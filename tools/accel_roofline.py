"""Roofline analysis of the batched (r, z) acceleration-search stage
(VERDICT r5 item 1a: "publish a roofline for the accel stage the way the
sweep has one — FLOPs+bytes per cell for the batched fft->multiply->ifft
+ stretch-gather, vs the measured 555-577M cells/s — so the gap is
known, not guessed").

The model walks the EXACT geometry the stage runners execute
(fourier/accelsearch._make_stage_runner_batch): for every harmonic stage
H and subharmonic ratio b/H it derives the bank height (rows = 2*Z*Wn
interleaved half-bin templates), the template half-width (zresponse.
zw_halfwidth of the ratio-scaled drift), and the power-of-two FFT length
L_b = fourier_chunk_len(segw*b/H + 4*hw_b) — then counts, per searched
(r, z) cell:

- FFT flops (the 5 L log2 L convention): one forward FFT of the slice
  per (spectrum, segment, bank) plus ``rows`` inverse FFTs — the inverse
  transforms dominate everything else by an order of magnitude;
- non-FFT flops: the broadcast complex multiply (6/elem), |.|^2
  (3/elem), and the stretch-gather + accumulate (2/cell/bank);
- HBM bytes under a no-fusion worst case and a fused best case, with the
  bank reads amortized over the batch (they are batch-invariant — the
  whole point of accel_search_batch).

Practical ceilings come from MEASURED on-chip rates, not datasheet peaks:
XLA's TPU FFT throughput on this v5e measured 121 GFLOP/s (batched
irfft) to 204 GFLOP/s (rfft) in the component probe (BENCHNOTES), and
the HBM roofline is 819 GB/s. The verdict this script prints — and
BENCHNOTES round 6 commits — is that the measured dispatch-level
555-577M cells/s sits AT the irfft-rate ceiling (~90-105% of it), i.e.
the batched stage is FFT-throughput-bound and the remaining CLI-level
gap (400M incl. I/O) is host/pipeline time, which the round-6 pipelined
driver attacks. 800M cells/s at the CLI is unreachable without a faster
FFT (smaller L padding, half-size real transforms, or a bf16 FFT), not
more overlap.

Usage: python tools/accel_roofline.py [--n 2097152] [--zmax 200]
           [--numharm 8] [--measured 577e6] [--json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pypulsar_tpu.fourier.zresponse import zw_halfwidth  # noqa: E402
from pypulsar_tpu.ops.fourier_dedisperse import fourier_chunk_len  # noqa: E402


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--n", type=int, default=1 << 21,
                    help="spectrum bins (default 2^21, the bench/configs4 "
                         "geometry)")
    ap.add_argument("--zmax", type=float, default=200.0)
    ap.add_argument("--dz", type=float, default=2.0)
    ap.add_argument("--numharm", type=int, default=8, choices=(1, 2, 4, 8))
    ap.add_argument("--segw", type=int, default=1 << 14,
                    help="fundamental bins per segment (default 2^14)")
    ap.add_argument("--min-halfwidth", type=int, default=24)
    ap.add_argument("--batch", type=int, default=32,
                    help="spectra per dispatch (amortizes bank reads)")
    ap.add_argument("--flo-bins", type=int, default=269,
                    help="lowest searched fundamental bin (rlo; default "
                         "269 = 1 Hz at the 2^21-bin configs4 spectrum)")
    ap.add_argument("--fft-gflops", type=float, default=204.0,
                    help="measured XLA FFT rate for the practical "
                         "ceiling (default 204 = the TOP of the "
                         "121-204 GFLOP/s band the component probe "
                         "measured for batched TPU FFTs; --fft-gflops-lo "
                         "sets the bottom)")
    ap.add_argument("--fft-gflops-lo", type=float, default=121.0,
                    help="bottom of the measured FFT-rate band "
                         "(121 = batched irfft probe)")
    ap.add_argument("--hbm-gbs", type=float, default=819.0,
                    help="HBM roofline GB/s (v5e: 819)")
    ap.add_argument("--measured", type=float, default=577e6,
                    help="measured cells/s to place on the roofline "
                         "(default 577M, BENCH r4/r5 dispatch-level; CLI "
                         "level with I/O measured 400M)")
    ap.add_argument("--fused", action="store_true",
                    help="model the SPECTRAL-FUSION stage (round 10, "
                         "parallel/specfuse.py): the per-trial forward "
                         "FFT of the prep (and the sweep-side inverse "
                         "that fed it) drop from the per-spectrum "
                         "budget, so the stage ceiling is restated "
                         "without the prep transforms")
    ap.add_argument("--json", action="store_true",
                    help="emit the analysis as one JSON line")
    return ap.parse_args(argv)


def analyze(n, zmax, dz, numharm, segw, min_halfwidth, batch, rlo,
            Wn: int = 1):
    """Per-stage and total (flops, bytes) per searched cell. Returns a
    dict of the full accounting."""
    Z = int(math.floor(2 * zmax / dz)) + 1
    rows = 2 * Z * Wn  # interleaved integer/half-bin template rows
    stages = [h for h in (1, 2, 4, 8) if h <= numharm]
    per_stage = []
    tot_cells = tot_fft = tot_other = 0.0
    tot_bytes_lo = tot_bytes_hi = 0.0
    for H in stages:
        top_lo, top_hi = H * rlo, min(H * (n - 1), n - 1)
        n_seg = -(-(top_hi - top_lo) // segw)
        cells_seg = Z * Wn * 2 * segw  # searched cells per segment
        fft_seg = other_seg = b_lo = b_hi = 0.0
        for b in range(1, H + 1):
            hw = zw_halfwidth(zmax * b / H, 0.0, min_halfwidth)
            L = fourier_chunk_len((segw * b) // H + 4 * hw)
            lg = math.log2(L)
            fft_seg += 5 * L * lg * (1 + rows)     # fwd slice + rows inv
            other_seg += (6 + 3) * rows * L        # multiply + |.|^2
            other_seg += 2 * cells_seg             # gather + accumulate
            # bytes, fused best case: slice read + bank read (amortized
            # over the batch) + plane accumulate; worst case adds the
            # cf/corr/power intermediates materialized
            b_lo += 8 * L + 8 * rows * L / batch + 8 * cells_seg
            b_hi += (8 * L + 16 * L + 8 * rows * L / batch
                     + 16 * rows * L + 4 * rows * L
                     + 4 * cells_seg + 8 * cells_seg)
        cells = n_seg * cells_seg
        per_stage.append(dict(
            H=H, n_seg=n_seg, cells=cells,
            fft_flops_per_cell=round(fft_seg / cells_seg, 1),
            other_flops_per_cell=round(other_seg / cells_seg, 1),
            bytes_per_cell_fused=round(b_lo / cells_seg, 1),
            bytes_per_cell_worst=round(b_hi / cells_seg, 1),
        ))
        tot_cells += cells
        tot_fft += n_seg * fft_seg
        tot_other += n_seg * other_seg
        tot_bytes_lo += n_seg * b_lo
        tot_bytes_hi += n_seg * b_hi
    return dict(
        Z=Z, rows=rows, stages=stages, per_stage=per_stage,
        total_cells=int(tot_cells),
        fft_flops_per_cell=round(tot_fft / tot_cells, 1),
        other_flops_per_cell=round(tot_other / tot_cells, 1),
        flops_per_cell=round((tot_fft + tot_other) / tot_cells, 1),
        bytes_per_cell_fused=round(tot_bytes_lo / tot_cells, 1),
        bytes_per_cell_worst=round(tot_bytes_hi / tot_cells, 1),
    )


def prep_flops_per_spectrum(n: int, fused: bool) -> float:
    """Per-spectrum transform cost of GETTING the normalized spectrum —
    the round-10 fusion target. The streamed handoff pays one forward
    rfft of the 2n-sample series in prep PLUS the sweep-side inverse
    that produced that series (the irfft->rfft pair specfuse elides);
    each real transform of length L is ~2.5*L*log2(L) flops under this
    file's 5*L*log2(L) complex-FFT convention. The fused path pays
    ZERO per-trial transforms (decimate regime; the stitched regime
    keeps the pair but off the host link — this model states the
    transform-count claim, which the specfuse telemetry counters
    verify at run time)."""
    if fused:
        return 0.0
    L = 2 * n
    return 2 * 2.5 * L * math.log2(L)


def main(argv=None):
    a = parse_args(argv)
    r = analyze(a.n, a.zmax, a.dz, a.numharm, a.segw, a.min_halfwidth,
                a.batch, a.flo_bins)
    prep = prep_flops_per_spectrum(a.n, a.fused)
    prep_per_cell = prep / r["total_cells"]
    fft_ceiling = a.fft_gflops * 1e9 / r["fft_flops_per_cell"]
    fft_floor = a.fft_gflops_lo * 1e9 / r["fft_flops_per_cell"]
    ceiling_with_prep = a.fft_gflops * 1e9 / (r["fft_flops_per_cell"]
                                              + prep_per_cell)
    hbm_ceiling_fused = a.hbm_gbs * 1e9 / r["bytes_per_cell_fused"]
    hbm_ceiling_worst = a.hbm_gbs * 1e9 / r["bytes_per_cell_worst"]
    implied_gflops = a.measured * r["fft_flops_per_cell"] / 1e9
    frac = a.measured / fft_ceiling
    rec = {
        **{k: v for k, v in r.items() if k != "per_stage"},
        "per_stage": r["per_stage"],
        "fft_rate_band_gflops": [a.fft_gflops_lo, a.fft_gflops],
        "hbm_gbs": a.hbm_gbs,
        "batch": a.batch,
        "fused": bool(a.fused),
        "prep_fft_flops_per_spectrum": round(prep, 1),
        "prep_fft_flops_per_cell": round(prep_per_cell, 4),
        "ceiling_fft_incl_prep_cells_per_sec": round(ceiling_with_prep, 1),
        "ceiling_fft_cells_per_sec": round(fft_ceiling, 1),
        "ceiling_fft_lo_cells_per_sec": round(fft_floor, 1),
        "ceiling_hbm_fused_cells_per_sec": round(hbm_ceiling_fused, 1),
        "ceiling_hbm_worst_cells_per_sec": round(hbm_ceiling_worst, 1),
        "measured_cells_per_sec": a.measured,
        "implied_fft_gflops": round(implied_gflops, 1),
        "measured_over_fft_ceiling": round(frac, 3),
        "bound": ("fft" if fft_ceiling < min(hbm_ceiling_worst, 1e18)
                  else "hbm"),
    }
    if a.json:
        print(json.dumps(rec))
        return 0
    print(f"# accel (r,z) roofline @ N={a.n}, zmax={a.zmax:.0f}, "
          f"dz={a.dz:g}, H<={a.numharm}, segw={a.segw}, batch={a.batch}")
    print(f"# Z={r['Z']} drift rows x2 interleave = {r['rows']} bank rows")
    print("# stage   n_seg   cells/spec    FFT fl/cell  other fl/cell  "
          "B/cell fused..worst")
    for s in r["per_stage"]:
        print(f"#  H={s['H']:<2d} {s['n_seg']:7d} {s['cells']:12d} "
              f"{s['fft_flops_per_cell']:12.1f} "
              f"{s['other_flops_per_cell']:14.1f}  "
              f"{s['bytes_per_cell_fused']:8.1f}.."
              f"{s['bytes_per_cell_worst']:.1f}")
    print(f"# TOTAL {r['total_cells']} cells/spectrum; "
          f"{r['fft_flops_per_cell']} FFT + {r['other_flops_per_cell']} "
          f"other flops/cell; {r['bytes_per_cell_fused']}.."
          f"{r['bytes_per_cell_worst']} bytes/cell")
    print(f"# ceilings: FFT-rate band ({a.fft_gflops_lo:.0f}-"
          f"{a.fft_gflops:.0f} GFLOP/s measured) -> "
          f"{fft_floor / 1e6:.0f}-{fft_ceiling / 1e6:.0f}M cells/s | "
          f"HBM ({a.hbm_gbs:.0f} GB/s) -> "
          f"{hbm_ceiling_fused / 1e9:.1f}G (fused) / "
          f"{hbm_ceiling_worst / 1e6:.0f}M (unfused)")
    print(f"# measured {a.measured / 1e6:.0f}M cells/s = "
          f"{100 * frac:.0f}% of the band-top FFT ceiling (implied FFT "
          f"rate {implied_gflops:.0f} GFLOP/s, inside the measured "
          f"band) -> the stage is {rec['bound'].upper()}-bound")
    if a.fused:
        print("# FUSED stage (round 10): per-trial prep transforms "
              "elided — 0 prep FFT flops/spectrum; the stage ceiling "
              "is the correlation-only number above")
    else:
        print(f"# prep (per-trial irfft+rfft pair the fused path "
              f"elides): {prep / 1e6:.1f}M flops/spectrum = "
              f"{prep_per_cell:.2f} flops/cell -> ceiling incl. prep "
              f"{ceiling_with_prep / 1e6:.0f}M cells/s "
              f"({100 * (1 - ceiling_with_prep / fft_ceiling):.1f}% "
              f"below correlation-only; compare --fused)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
