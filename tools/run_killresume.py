"""Hour-scale kill-and-resume proof (VERDICT r4 item 4), as one script.

Runs the checkpointed CLI sweep of the north-star file three ways:

1. uninterrupted reference -> {out}/seq.cands
2. the same command SIGKILLed at ~``--kill-frac`` of the file
3. resumed with --resume (seek-resume: the stream re-roots at the
   checkpoint cursor) -> {out}/kr.cands

and verifies kr.cands == seq.cands byte-for-byte, recording the wall
times (the resume wall measures the replay overhead). SIGKILL of a
client mid-transfer can wedge the axon tunnel for ~an hour (memory/
constraints), so this runs LAST in a round.

Usage: python tools/run_killresume.py [--trials 4096] [--kill-frac 0.45]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fil", default=os.path.join(REPO, "data",
                                                  "northstar_1hr.fil"))
    ap.add_argument("--trials", type=int, default=4096)
    ap.add_argument("--dm-max", type=float, default=500.0)
    ap.add_argument("--kill-frac", type=float, default=0.45)
    ap.add_argument("--checkpoint-every", type=int, default=None,
                    help="chunks between checkpoint saves (default: the "
                         "CLI's 16; toy rehearsals with fewer total "
                         "chunks need 1-2 or no checkpoint ever lands)")
    ap.add_argument("--workdir", default=os.path.join(REPO, "data",
                                                      "killresume"))
    ap.add_argument("--skip-seq", action="store_true",
                    help="reuse an existing {workdir}/seq.cands")
    ap.add_argument("--out", default=os.path.join(
        REPO, "BENCH_r05_killresume.json"))
    return ap.parse_args(argv)


def sweep_argv(a, outbase, ckpt=None, resume=False):
    dmstep = a.dm_max / max(a.trials - 1, 1)
    argv = [sys.executable, "-m", "pypulsar_tpu.cli.sweep", a.fil,
            "--lodm", "0", "--dmstep", f"{dmstep:.16g}",
            "--numdms", str(a.trials), "-s", "64", "--group-size", "32",
            "--threshold", "10", "-o", outbase]
    if ckpt:
        argv += ["--checkpoint", ckpt]
        if a.checkpoint_every is not None:
            argv += ["--checkpoint-every", str(a.checkpoint_every)]
    if resume:
        argv += ["--resume"]
    return argv


def wait_for_tunnel(max_wait=5400):
    code = ("import jax, jax.numpy as jnp; "
            "print(float(jnp.ones((8, 8)).sum()))")
    t0 = time.time()
    while time.time() - t0 < max_wait:
        try:
            p = subprocess.run([sys.executable, "-c", code], timeout=120,
                               capture_output=True, text=True)
            if "64.0" in p.stdout:
                return True
        except subprocess.TimeoutExpired:
            pass
        print(f"# tunnel down {time.time()-t0:.0f}s; retrying",
              flush=True)
        time.sleep(60)
    return False


def main(argv=None):
    a = parse_args(argv)
    os.makedirs(a.workdir, exist_ok=True)
    seq_out = os.path.join(a.workdir, "seq")
    kr_out = os.path.join(a.workdir, "kr")
    ckpt = os.path.join(a.workdir, "kr.ckpt")
    rec = {"metric": "killresume_resume_wall_seconds"}

    if not a.skip_seq or not os.path.exists(seq_out + ".cands"):
        t0 = time.time()
        subprocess.run(sweep_argv(a, seq_out), check=True)
        rec["seq_wall_seconds"] = round(time.time() - t0, 1)
        print(f"## uninterrupted: {rec['seq_wall_seconds']}s", flush=True)

    # killed run: poll the checkpoint cursor until past kill-frac
    from pypulsar_tpu.io.filterbank import FilterbankFile

    T = FilterbankFile(a.fil).number_of_samples
    for stale in (ckpt, ckpt + ".tmp.npz"):
        if os.path.exists(stale):
            os.remove(stale)
    t0 = time.time()
    proc = subprocess.Popen(sweep_argv(a, kr_out, ckpt=ckpt))
    cursor = 0
    while proc.poll() is None:
        time.sleep(5)
        if os.path.exists(ckpt):
            try:
                with np.load(ckpt) as z:
                    cursor = int(z["cursor"])
            except Exception:  # noqa: BLE001 - mid-replace read race
                continue
            if cursor >= a.kill_frac * T:
                break
    if proc.poll() is not None:
        raise RuntimeError("sweep finished before the kill point; "
                           "lower --kill-frac")
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    killed_at = time.time() - t0
    rec["killed_at_seconds"] = round(killed_at, 1)
    rec["killed_at_cursor"] = cursor
    rec["killed_at_frac"] = round(cursor / T, 3)
    print(f"## SIGKILLed at {killed_at:.0f}s, cursor {cursor} "
          f"({cursor/T*100:.0f}% of the file)", flush=True)

    # the SIGKILL may wedge the tunnel; wait it out before resuming
    if not wait_for_tunnel():
        raise RuntimeError("tunnel did not recover after the kill")
    t0 = time.time()
    subprocess.run(sweep_argv(a, kr_out, ckpt=ckpt, resume=True),
                   check=True)
    rec["resume_wall_seconds"] = round(time.time() - t0, 1)
    rec["value"] = rec["resume_wall_seconds"]

    seq = open(seq_out + ".cands", "rb").read()
    kr = open(kr_out + ".cands", "rb").read()
    rec["bit_identical"] = seq == kr
    rec["unit"] = (f"resume wall seconds after SIGKILL at "
                   f"{rec['killed_at_frac']*100:.0f}% of the "
                   f"{a.trials}-trial north-star sweep (seek-resume); "
                   f"candidate table bit-identical to the uninterrupted "
                   f"run: {rec['bit_identical']}")
    rec["vs_baseline"] = 0.0
    print(json.dumps(rec))
    with open(a.out, "w") as f:
        f.write(json.dumps(rec) + "\n")
    if not rec["bit_identical"]:
        print("## FAIL: resumed .cands differs from uninterrupted",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
