"""configs[4] END-TO-END on chip (VERDICT r4 item 1): retire the last
BASELINE projection by MEASURING the chain

    900-s window of the north-star file
      -> cli sweep --write-dats  (streamed two-stage writer, 512 DMs)
      -> cli accelsearch --batch (shared template banks, batched stages)
      -> cli sift

as one timed run with the per-stage wall split, and verify the injected
pulsar (P=262.144 ms => f0=3.814697 Hz at DM 70) comes out of the sift.
Writes BENCH_r05_configs4.json, which bench.py inlines into the driver's
streamed record (_configs4_reference).

Reference surface: formats/prestofft.py:76-195 + bin/plot_accelcands.py:
50-104 (the reference defers the search itself to PRESTO accelsearch on
one core; BASELINE configs[4]).

Usage: python tools/run_configs4.py [--trials 512] [--duration 900]
           [--downsamp 4] [--keep]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fil", default=os.path.join(REPO, "data",
                                                  "northstar_1hr.fil"))
    ap.add_argument("--trials", type=int, default=512)
    ap.add_argument("--duration", type=float, default=900.0)
    ap.add_argument("--dm-max", type=float, default=500.0)
    ap.add_argument("--downsamp", type=int, default=4,
                    help="dedispersed-series downsampling before the "
                         "accel search (256 us at the north-star's 64 us "
                         "raw rate: the benched N=2^21-scale spectrum)")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--device-prep", action="store_true",
                    help="pass --device-prep to the accelsearch stage "
                         "(device-side rfft + deredden; see "
                         "tools/run_accelprep_ab.py for the measured A/B)")
    ap.add_argument("--zmax", type=float, default=200.0)
    ap.add_argument("--coarse-dz", type=float, default=0.0,
                    help="coarse-to-fine z preselection step for the "
                         "accelsearch stage (cli accelsearch --coarse-dz; "
                         "0 = single pass). Used for the A/B record")
    ap.add_argument("--ab-coarse", type=float, default=0.0, metavar="DZ",
                    help="after the primary accelsearch stage, re-run "
                         "JUST that stage on the same .dats with "
                         "--coarse-dz DZ and record the A/B walls plus "
                         "whether the re-sifted candidates match "
                         "(VERDICT r4 item 1 stretch evidence at zero "
                         "extra sweep cost)")
    ap.add_argument("--workdir", default=os.path.join(REPO, "data",
                                                      "configs4"))
    ap.add_argument("--keep", action="store_true",
                    help="keep the .dat/.cand intermediates")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_r05_configs4.json"))
    ap.add_argument("--allow-miss", action="store_true",
                    help="exit 0 even when the injected pulsar is not "
                         "recovered (toy-scale rehearsals on other files)")
    return ap.parse_args(argv)


def slice_window(fil: str, out: str, seconds: float) -> int:
    """First ``seconds`` of a .fil as a standalone file (byte copy:
    header + whole spectra). When the source already IS the window
    (a file generated at exactly --duration), reuse it in place —
    no 14-GB copy, no double disk footprint."""
    from pypulsar_tpu.io.filterbank import FilterbankFile

    fb = FilterbankFile(fil)
    nsamp = min(int(round(seconds / fb.tsamp)), fb.number_of_samples)
    total = fb.number_of_samples
    if os.path.abspath(out) == os.path.abspath(fil):
        # the source IS the window artifact (re-run against a kept
        # window.fil): never remove it; slicing onto itself is a user error
        fb.close()
        if nsamp < total:
            raise ValueError(f"--fil and the window path are the same file "
                             f"({out}); cannot slice it onto itself")
        return nsamp
    if os.path.lexists(out):
        os.remove(out)  # never open through a stale symlink from a prior run
    if nsamp == fb.number_of_samples:
        fb.close()
        os.symlink(os.path.abspath(fil), out)
        return nsamp
    nbytes = nsamp * fb.bytes_per_spectrum
    with open(fil, "rb") as src, open(out, "wb") as dst:
        dst.write(src.read(fb.header_size))
        copied = 0
        while copied < nbytes:
            buf = src.read(min(1 << 24, nbytes - copied))
            if not buf:
                break
            dst.write(buf)
            copied += len(buf)
    fb.close()
    return nsamp


def run_stage(name, argv, log):
    print(f"## stage {name}: {' '.join(argv)}", flush=True)
    t0 = time.perf_counter()
    with open(log, "w") as lf:
        rc = subprocess.call(argv, stdout=lf, stderr=subprocess.STDOUT)
    el = time.perf_counter() - t0
    if rc != 0:
        tail = open(log).read()[-3000:]
        raise RuntimeError(f"stage {name} failed rc={rc}:\n{tail}")
    print(f"## stage {name}: {el:.1f}s", flush=True)
    return el


def main(argv=None):
    a = parse_args(argv)
    if a.device_prep and a.batch < 2:
        raise SystemExit("--device-prep only takes effect on the batched "
                         "accelsearch path; use --batch >= 2")
    os.makedirs(a.workdir, exist_ok=True)
    base = os.path.join(a.workdir, "c4")
    win_fil = os.path.join(a.workdir, "window.fil")
    stages = {}

    t_all = time.perf_counter()
    t0 = time.perf_counter()
    nsamp = slice_window(a.fil, win_fil, a.duration)
    stages["slice_window"] = round(time.perf_counter() - t0, 1)
    from pypulsar_tpu.io.filterbank import FilterbankFile

    _fb = FilterbankFile(win_fil)
    nchan, nbits, tsamp = _fb.nchans, _fb.nbits, float(_fb.tsamp)
    _fb.close()
    # actual covered span: the input can be SHORTER than the requested
    # window (slice_window clamps to the file), and every derived number
    # (trials/s, projections) must be read against the real coverage
    covered = nsamp * tsamp
    if covered < a.duration - 0.5 * tsamp:
        print(f"## WARNING: input covers only {covered:.1f}s of the "
              f"requested --duration {a.duration:.0f}s window; the "
              f"recorded metrics describe the shorter span",
              file=sys.stderr)
    print(f"## window: {nsamp} samples ({covered:.1f}s of the requested "
          f"{a.duration:.0f}s), {nchan} chans {nbits}-bit -> {win_fil}")

    dmstep = a.dm_max / max(a.trials - 1, 1)
    stages["sweep_write_dats"] = round(run_stage(
        "sweep+dats",
        [sys.executable, "-m", "pypulsar_tpu.cli.sweep", win_fil,
         "-o", base, "--lodm", "0", "--dmstep", f"{dmstep:.6f}",
         "--numdms", str(a.trials), "--downsamp", str(a.downsamp),
         "-s", "64", "--group-size", "32", "--threshold", "8",
         "--write-dats"],
        os.path.join(a.workdir, "sweep.log")), 1)

    dats = sorted(glob.glob(f"{base}_DM*.dat"))
    assert len(dats) == a.trials, (len(dats), a.trials)
    accel_argv = [sys.executable, "-m", "pypulsar_tpu.cli.accelsearch",
                  *dats, "--batch", str(a.batch), "-z", str(int(a.zmax)),
                  "--dz", "2", "-n", "8", "-s", "2"]
    if a.coarse_dz > 0:
        accel_argv += ["--coarse-dz", str(a.coarse_dz)]
    if a.device_prep:
        accel_argv += ["--device-prep"]
    stages["accelsearch_batch"] = round(run_stage(
        "accelsearch", accel_argv,
        os.path.join(a.workdir, "accel.log")), 1)

    cands = sorted(glob.glob(f"{base}_DM*_ACCEL_{int(a.zmax)}.cand"))
    assert cands, "no .cand outputs"
    sifted = base + ".sifted"
    stages["sift"] = round(run_stage(
        "sift",
        [sys.executable, "-m", "pypulsar_tpu.cli.sift", *cands,
         "-o", sifted, "-s", "4"],
        os.path.join(a.workdir, "sift.log")), 1)
    wall = time.perf_counter() - t_all

    # --- recovery check: the injected pulsar (or a harmonic) in the sift
    from pypulsar_tpu.io.accelcands import parse_candlist

    p0 = 4096 * 64e-6  # injected period 262.144 ms
    best = None
    for c in parse_candlist(sifted):
        for h in (1, 2, 3, 4, 8):
            if (abs(c.period * h - p0) < 0.01 * p0
                    and abs(c.dm - 70.0) < 5.0):
                if best is None or c.sigma > best["sigma"]:
                    best = {"dm": c.dm, "sigma": c.sigma,
                            "period_s": c.period, "harmonic": h,
                            "snr": c.snr}
    print(f"## injected pulsar recovery: {best}")

    # --- optional A/B: the coarse-to-fine accel stage on the SAME .dats
    ab = None
    if a.ab_coarse > 0:
        if a.coarse_dz > 0:
            raise SystemExit("--ab-coarse needs a single-pass primary run "
                             "(drop --coarse-dz)")
        for fn in cands + [sifted]:
            shutil.move(fn, fn + ".single")
        stages["accelsearch_batch_coarse"] = round(run_stage(
            "accelsearch-coarse",
            accel_argv + ["--coarse-dz", str(a.ab_coarse)],
            os.path.join(a.workdir, "accel_coarse.log")), 1)
        stages["sift_coarse"] = round(run_stage(
            "sift-coarse",
            [sys.executable, "-m", "pypulsar_tpu.cli.sift", *cands,
             "-o", sifted, "-s", "4"],
            os.path.join(a.workdir, "sift_coarse.log")), 1)
        with open(sifted + ".single", "rb") as f1, open(sifted, "rb") as f2:
            identical = f1.read() == f2.read()
        ab = {
            "coarse_dz": a.ab_coarse,
            "accel_wall_single": stages["accelsearch_batch"],
            "accel_wall_coarse": stages["accelsearch_batch_coarse"],
            "speedup": round(stages["accelsearch_batch"]
                             / max(stages["accelsearch_batch_coarse"],
                                   1e-9), 2),
            "sift_identical": identical,
        }
        print(f"## coarse-to-fine A/B: {ab}")

    # --- (r, z) cell accounting at the searched geometry (bench run_accel
    # formula) x trials / accel wall
    from pypulsar_tpu.fourier.accelsearch import AccelSearchConfig
    from pypulsar_tpu.fourier.zresponse import template_bank
    from pypulsar_tpu.io.infodata import InfoData

    inf = InfoData(dats[0][:-4] + ".inf")
    N = int(inf.N) // 2
    T = int(inf.N) * float(inf.dt)
    cfg = AccelSearchConfig(zmax=a.zmax, dz=2.0, numharm=8, sigma_min=2.0)
    Z = len(cfg.zs)
    rlo = max(int(np.ceil(cfg.flo * T)), 1)
    cells = sum(2 * Z * max((N - 1) - H * rlo, 0) for H in cfg.stages)
    cells_per_sec = cells * a.trials / stages["accelsearch_batch"]

    # single-core NumPy baseline for the search stage: one stage-1
    # segment's correlations with np.fft (the same generous baseline
    # bench.py run_accel measures), scaled linearly to the full count
    segw = cfg.seg_width
    tb, hw = template_bank(cfg.zs, numbetween=2)
    L = 1
    while L < segw + 4 * hw:
        L <<= 1
    padded = np.zeros((tb.shape[0], L), np.complex128)
    padded[:, : tb.shape[1]] = tb
    rev = np.zeros_like(padded)
    rev[:, 0] = padded[:, 0]
    rev[:, 1:] = padded[:, :0:-1]
    tf = np.fft.fft(rev, axis=1).astype(np.complex64)
    rng = np.random.RandomState(0)
    seg = (rng.standard_normal(L) + 1j * rng.standard_normal(L)) \
        .astype(np.complex64)

    def one_rep():
        tb0 = time.perf_counter()
        sl = np.fft.fft(seg)
        corr = np.fft.ifft(sl[None, :] * tf, axis=1)
        _ = (np.abs(corr) ** 2).astype(np.float32)
        return time.perf_counter() - tb0

    # the round-5 baseline protocol (bench.numpy_baseline): >=5
    # loadavg-gated reps + pinned-calibration cross-check
    import bench as bench_mod

    bl = bench_mod.numpy_baseline(one_rep)
    bl_cells_per_sec = (2 * Z * segw) / bl["seconds"]
    vs_baseline = cells_per_sec / bl_cells_per_sec

    rec = {
        "metric": "configs4_end_to_end_seconds",
        "value": round(wall, 1),
        "unit": (f"wall seconds, {a.duration:.0f}s x {nchan}-chan "
                 f"{nbits}-bit "
                 f"window -> sweep(+streamed .dats, ds={a.downsamp}) -> "
                 f"accelsearch --batch {a.batch} (zmax={a.zmax:.0f}, "
                 f"dz=2, H<=8, N={N} bins x {a.trials} trials"
                 + (f", coarse-dz={a.coarse_dz:g} prepass"
                    if a.coarse_dz > 0 else "")
                 + (", device-prep" if a.device_prep else "")
                 + ") -> sift; measured on one v5e through the axon "
                   "tunnel"),
        "vs_baseline": round(vs_baseline, 2),
        "numpy_cells_per_sec": round(bl_cells_per_sec, 1),
        **{k: v for k, v in bl.items() if k != "seconds"},
        "trials": a.trials,
        "covered_seconds": round(covered, 1),
        "requested_seconds": round(a.duration, 1),
        "coarse_dz": a.coarse_dz,
        "device_prep": a.device_prep,
        "wall_seconds": round(wall, 1),
        "stage_seconds": stages,
        "spectrum_bins": N,
        "cells_per_spectrum": cells,
        "cells_per_sec": round(cells_per_sec, 1),
        "injected_recovered": best,
        **({"ab_coarse": ab} if ab else {}),
        "per_spectrum_seconds": round(
            stages["accelsearch_batch"] / a.trials, 2),
        "projection_4096_trials_hours": round(
            4096 * stages["accelsearch_batch"] / a.trials / 3600.0, 2),
    }
    with open(a.out, "w") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    if not a.keep:
        shutil.rmtree(a.workdir, ignore_errors=True)
    if best is None and not a.allow_miss:
        print("## FAIL: injected pulsar NOT recovered by the sift",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
