"""configs[4] END-TO-END on chip (VERDICT r4 item 1 / r5 item 1): measure
the chain

    900-s window of the north-star file
      -> cli sweep --write-dats  (streamed two-stage writer, 512 DMs)
      -> cli accelsearch --batch (shared template banks, batched stages)
      -> cli sift

or, with --stream (round 6, the record path), the PIPELINED chain

    900-s window -> cli sweep --accel-search  (dedispersed series stream
      straight into the batched search: no .dat write + re-read, prep of
      batch N+1 overlapped with the search of batch N) -> cli sift

as one timed run with the per-stage wall split, and verify the injected
pulsar (P=262.144 ms => f0=3.814697 Hz at DM 70) comes out of the sift.
Writes BENCH_r06_configs4.json, which bench.py inlines into the driver's
streamed record (_configs4_reference). ``--ab-stream`` additionally runs
the classic .dat chain on the same window and records both walls plus
whether the sifted tables match (the handoff's parity evidence at the
production scale).

Reference surface: formats/prestofft.py:76-195 + bin/plot_accelcands.py:
50-104 (the reference defers the search itself to PRESTO accelsearch on
one core; BASELINE configs[4]).

Usage: python tools/run_configs4.py [--stream] [--trials 512]
           [--duration 900] [--downsamp 4] [--keep]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fil", default=os.path.join(REPO, "data",
                                                  "northstar_1hr.fil"))
    ap.add_argument("--trials", type=int, default=512)
    ap.add_argument("--duration", type=float, default=900.0)
    ap.add_argument("--dm-max", type=float, default=500.0)
    ap.add_argument("--downsamp", type=int, default=4,
                    help="dedispersed-series downsampling before the "
                         "accel search (256 us at the north-star's 64 us "
                         "raw rate: the benched N=2^21-scale spectrum)")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--stream", action="store_true",
                    help="round-6 pipelined path: ONE sweep invocation "
                         "streams the dedispersed series straight into "
                         "the batched accel search (--accel-search) — "
                         "no per-DM .dat write + re-read (745.9 s of "
                         "the round-5 chain)")
    ap.add_argument("--ab-stream", action="store_true",
                    help="with --stream: afterwards run the classic "
                         ".dat chain on the same window and record both "
                         "walls + sift parity in the JSON")
    ap.add_argument("--device-prep", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="device-side rfft + deredden for the accel "
                         "stage (default ON for --batch >= 2 since "
                         "round 6 under the matched-candidate contract; "
                         "--no-device-prep restores host prep)")
    ap.add_argument("--zmax", type=float, default=200.0)
    ap.add_argument("--coarse-dz", type=float, default=0.0,
                    help="coarse-to-fine z preselection step for the "
                         "accelsearch stage (cli accelsearch --coarse-dz; "
                         "0 = single pass). Used for the A/B record")
    ap.add_argument("--ab-coarse", type=float, default=0.0, metavar="DZ",
                    help="after the primary accelsearch stage, re-run "
                         "JUST that stage on the same .dats with "
                         "--coarse-dz DZ and record the A/B walls plus "
                         "whether the re-sifted candidates match "
                         "(VERDICT r4 item 1 stretch evidence at zero "
                         "extra sweep cost)")
    ap.add_argument("--workdir", default=os.path.join(REPO, "data",
                                                      "configs4"))
    ap.add_argument("--keep", action="store_true",
                    help="keep the .dat/.cand intermediates")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_r06_configs4.json"))
    ap.add_argument("--allow-miss", action="store_true",
                    help="exit 0 even when the injected pulsar is not "
                         "recovered (toy-scale rehearsals on other files)")
    return ap.parse_args(argv)


def slice_window(fil: str, out: str, seconds: float) -> int:
    """First ``seconds`` of a .fil as a standalone file (byte copy:
    header + whole spectra). When the source already IS the window
    (a file generated at exactly --duration), reuse it in place —
    no 14-GB copy, no double disk footprint."""
    from pypulsar_tpu.io.filterbank import FilterbankFile

    fb = FilterbankFile(fil)
    nsamp = min(int(round(seconds / fb.tsamp)), fb.number_of_samples)
    total = fb.number_of_samples
    if os.path.abspath(out) == os.path.abspath(fil):
        # the source IS the window artifact (re-run against a kept
        # window.fil): never remove it; slicing onto itself is a user error
        fb.close()
        if nsamp < total:
            raise ValueError(f"--fil and the window path are the same file "
                             f"({out}); cannot slice it onto itself")
        return nsamp
    if os.path.lexists(out):
        os.remove(out)  # never open through a stale symlink from a prior run
    if nsamp == fb.number_of_samples:
        fb.close()
        os.symlink(os.path.abspath(fil), out)
        return nsamp
    nbytes = nsamp * fb.bytes_per_spectrum
    with open(fil, "rb") as src, open(out, "wb") as dst:
        dst.write(src.read(fb.header_size))
        copied = 0
        while copied < nbytes:
            buf = src.read(min(1 << 24, nbytes - copied))
            if not buf:
                break
            dst.write(buf)
            copied += len(buf)
    fb.close()
    return nsamp


def run_stage(name, argv, log, env_extra=None):
    print(f"## stage {name}: {' '.join(argv)}", flush=True)
    env = None
    if env_extra:
        env = dict(os.environ, **env_extra)
    t0 = time.perf_counter()
    with open(log, "w") as lf:
        rc = subprocess.call(argv, stdout=lf, stderr=subprocess.STDOUT,
                             env=env)
    el = time.perf_counter() - t0
    if rc != 0:
        tail = open(log).read()[-3000:]
        raise RuntimeError(f"stage {name} failed rc={rc}:\n{tail}")
    print(f"## stage {name}: {el:.1f}s", flush=True)
    return el


def _span_seconds(jsonl: str) -> dict:
    """Per-span-name wall totals from a telemetry trace — the streamed
    chain is ONE CLI stage, so its internal sweep/prep/search split comes
    from the recorded spans (incl. noagg wrapper spans)."""
    from pypulsar_tpu.obs.summarize import load_records

    tot = {}
    for rec in load_records(jsonl):
        if rec.get("type") == "span":
            name = rec.get("name", "?")
            tot[name] = tot.get(name, 0.0) + float(rec.get("dur", 0.0))
    # round ONCE: per-record rounding floors sub-50ms spans to zero (a
    # toy-scale accel_search total would collapse to the 1e-9 guard)
    return {k: round(v, 3) for k, v in tot.items()}


def main(argv=None):
    a = parse_args(argv)
    if a.device_prep and a.batch < 2:
        raise SystemExit("--device-prep only takes effect on the batched "
                         "accelsearch path; use --batch >= 2")
    if a.device_prep is None:  # auto: on for the grouped path, like the CLI
        a.device_prep = a.batch >= 2
    if a.stream and (a.coarse_dz > 0 or a.ab_coarse > 0):
        raise SystemExit("--coarse-dz/--ab-coarse are classic-chain "
                         "options (the handoff runs single-pass)")
    if a.ab_stream and not a.stream:
        raise SystemExit("--ab-stream requires --stream")
    os.makedirs(a.workdir, exist_ok=True)
    base = os.path.join(a.workdir, "c4")
    win_fil = os.path.join(a.workdir, "window.fil")
    stages = {}

    t_all = time.perf_counter()
    t0 = time.perf_counter()
    nsamp = slice_window(a.fil, win_fil, a.duration)
    stages["slice_window"] = round(time.perf_counter() - t0, 1)
    from pypulsar_tpu.io.filterbank import FilterbankFile

    _fb = FilterbankFile(win_fil)
    nchan, nbits, tsamp = _fb.nchans, _fb.nbits, float(_fb.tsamp)
    _fb.close()
    # actual covered span: the input can be SHORTER than the requested
    # window (slice_window clamps to the file), and every derived number
    # (trials/s, projections) must be read against the real coverage
    covered = nsamp * tsamp
    if covered < a.duration - 0.5 * tsamp:
        print(f"## WARNING: input covers only {covered:.1f}s of the "
              f"requested --duration {a.duration:.0f}s window; the "
              f"recorded metrics describe the shorter span",
              file=sys.stderr)
    print(f"## window: {nsamp} samples ({covered:.1f}s of the requested "
          f"{a.duration:.0f}s), {nchan} chans {nbits}-bit -> {win_fil}")

    dmstep = a.dm_max / max(a.trials - 1, 1)
    sweep_base_argv = [
        sys.executable, "-m", "pypulsar_tpu.cli.sweep", win_fil,
        "-o", base, "--lodm", "0", "--dmstep", f"{dmstep:.6f}",
        "--numdms", str(a.trials), "--downsamp", str(a.downsamp),
        "-s", "64", "--group-size", "32", "--threshold", "8"]
    stream_tlm = os.path.join(a.workdir, "stream_tlm.jsonl")
    stream_spans = None
    if a.stream:
        # ONE invocation: sweep detection + dedispersed series streamed
        # straight into the batched accel search (no .dat round trip);
        # the internal split comes from the telemetry trace
        stream_argv = sweep_base_argv + [
            "--accel-search", "--accel-zmax", str(int(a.zmax)),
            "--accel-dz", "2", "--accel-numharm", "8",
            "--accel-sigma", "2", "--accel-batch", str(a.batch),
            "--telemetry", stream_tlm]
        if not a.device_prep:
            stream_argv += ["--no-accel-device-prep"]
        stages["sweep_accel_stream"] = round(run_stage(
            "sweep+accel-stream", stream_argv,
            os.path.join(a.workdir, "stream.log")), 1)
        stream_spans = _span_seconds(stream_tlm)
        print(f"## stream spans: {stream_spans}")
    else:
        # always the STREAMED .dat writer (prepsubband semantics — what
        # the full-scale window uses anyway, and the handoff's parity
        # partner), so toy-scale rehearsals measure the same path
        stages["sweep_write_dats"] = round(run_stage(
            "sweep+dats", sweep_base_argv + ["--write-dats"],
            os.path.join(a.workdir, "sweep.log"),
            env_extra={"PYPULSAR_TPU_DATS_RESIDENT_LIMIT": "0"}), 1)

        dats = sorted(glob.glob(f"{base}_DM*.dat"))
        assert len(dats) == a.trials, (len(dats), a.trials)
        accel_argv = [sys.executable, "-m", "pypulsar_tpu.cli.accelsearch",
                      *dats, "--batch", str(a.batch),
                      "-z", str(int(a.zmax)), "--dz", "2", "-n", "8",
                      "-s", "2"]
        if a.coarse_dz > 0:
            accel_argv += ["--coarse-dz", str(a.coarse_dz)]
        if not a.device_prep:
            accel_argv += ["--no-device-prep"]
        stages["accelsearch_batch"] = round(run_stage(
            "accelsearch", accel_argv,
            os.path.join(a.workdir, "accel.log")), 1)

    cands = sorted(glob.glob(f"{base}_DM*_ACCEL_{int(a.zmax)}.cand"))
    assert cands, "no .cand outputs"
    sifted = base + ".sifted"
    stages["sift"] = round(run_stage(
        "sift",
        [sys.executable, "-m", "pypulsar_tpu.cli.sift", *cands,
         "-o", sifted, "-s", "4"],
        os.path.join(a.workdir, "sift.log")), 1)
    wall = time.perf_counter() - t_all

    # --- recovery check: the injected pulsar (or a harmonic) in the sift
    from pypulsar_tpu.io.accelcands import parse_candlist

    p0 = 4096 * 64e-6  # injected period 262.144 ms
    best = None
    for c in parse_candlist(sifted):
        for h in (1, 2, 3, 4, 8):
            if (abs(c.period * h - p0) < 0.01 * p0
                    and abs(c.dm - 70.0) < 5.0):
                if best is None or c.sigma > best["sigma"]:
                    best = {"dm": c.dm, "sigma": c.sigma,
                            "period_s": c.period, "harmonic": h,
                            "snr": c.snr}
    print(f"## injected pulsar recovery: {best}")

    # --- optional A/B: the coarse-to-fine accel stage on the SAME .dats
    ab = None
    if a.ab_coarse > 0:
        if a.coarse_dz > 0:
            raise SystemExit("--ab-coarse needs a single-pass primary run "
                             "(drop --coarse-dz)")
        for fn in cands + [sifted]:
            shutil.move(fn, fn + ".single")
        stages["accelsearch_batch_coarse"] = round(run_stage(
            "accelsearch-coarse",
            accel_argv + ["--coarse-dz", str(a.ab_coarse)],
            os.path.join(a.workdir, "accel_coarse.log")), 1)
        stages["sift_coarse"] = round(run_stage(
            "sift-coarse",
            [sys.executable, "-m", "pypulsar_tpu.cli.sift", *cands,
             "-o", sifted, "-s", "4"],
            os.path.join(a.workdir, "sift_coarse.log")), 1)
        with open(sifted + ".single", "rb") as f1, open(sifted, "rb") as f2:
            identical = f1.read() == f2.read()
        ab = {
            "coarse_dz": a.ab_coarse,
            "accel_wall_single": stages["accelsearch_batch"],
            "accel_wall_coarse": stages["accelsearch_batch_coarse"],
            "speedup": round(stages["accelsearch_batch"]
                             / max(stages["accelsearch_batch_coarse"],
                                   1e-9), 2),
            "sift_identical": identical,
        }
        print(f"## coarse-to-fine A/B: {ab}")

    # --- optional A/B: the classic .dat chain on the same window
    ab_stream = None
    if a.ab_stream:
        for fn in cands + [sifted]:
            shutil.move(fn, fn + ".stream")
        # the classic chain's timings live INSIDE the A/B record, not in
        # the streamed record's stage_seconds (whose sum must match the
        # reported wall)
        dat_stages = {}
        dat_stages["sweep_write_dats"] = round(run_stage(
            "sweep+dats", sweep_base_argv + ["--write-dats"],
            os.path.join(a.workdir, "sweep_dat.log"),
            env_extra={"PYPULSAR_TPU_DATS_RESIDENT_LIMIT": "0"}), 1)
        dats = sorted(glob.glob(f"{base}_DM*.dat"))
        dat_accel_argv = [sys.executable, "-m",
                          "pypulsar_tpu.cli.accelsearch", *dats,
                          "--batch", str(a.batch), "-z", str(int(a.zmax)),
                          "--dz", "2", "-n", "8", "-s", "2"]
        if not a.device_prep:
            dat_accel_argv += ["--no-device-prep"]
        dat_stages["accelsearch_batch"] = round(run_stage(
            "accelsearch", dat_accel_argv,
            os.path.join(a.workdir, "accel_dat.log")), 1)
        dat_stages["sift"] = round(run_stage(
            "sift-dat",
            [sys.executable, "-m", "pypulsar_tpu.cli.sift", *cands,
             "-o", sifted, "-s", "4"],
            os.path.join(a.workdir, "sift_dat.log")), 1)
        with open(sifted + ".stream", "rb") as f1, open(sifted, "rb") as f2:
            identical = f1.read() == f2.read()
        dat_wall = sum(dat_stages.values())
        stream_wall = stages["sweep_accel_stream"] + stages["sift"]
        ab_stream = {
            "stream_wall": round(stream_wall, 1),
            "dat_chain_wall": round(dat_wall, 1),
            "speedup": round(dat_wall / max(stream_wall, 1e-9), 2),
            "sift_identical": identical,
            "dat_stage_seconds": dat_stages,
        }
        print(f"## stream-vs-dat A/B: {ab_stream}")

    # --- (r, z) cell accounting at the searched geometry (bench run_accel
    # formula) x trials / accel wall. The streamed chain has no separate
    # accel CLI stage, so its search wall comes from the recorded
    # accel_search spans (device dispatch + result drain; prep runs
    # overlapped on the pipeline thread and is reported separately)
    from pypulsar_tpu.fourier.accelsearch import AccelSearchConfig
    from pypulsar_tpu.fourier.zresponse import template_bank

    n_ds = nsamp // a.downsamp
    N = n_ds // 2
    T = n_ds * tsamp * a.downsamp
    cfg = AccelSearchConfig(zmax=a.zmax, dz=2.0, numharm=8, sigma_min=2.0)
    Z = len(cfg.zs)
    rlo = max(int(np.ceil(cfg.flo * T)), 1)
    cells = sum(2 * Z * max((N - 1) - H * rlo, 0) for H in cfg.stages)
    if a.stream:
        accel_wall = max(stream_spans.get("accel_search", 0.0), 1e-9)
    else:
        accel_wall = stages["accelsearch_batch"]
    cells_per_sec = cells * a.trials / accel_wall

    # single-core NumPy baseline for the search stage: one stage-1
    # segment's correlations with np.fft (the same generous baseline
    # bench.py run_accel measures), scaled linearly to the full count
    segw = cfg.seg_width
    tb, hw = template_bank(cfg.zs, numbetween=2)
    L = 1
    while L < segw + 4 * hw:
        L <<= 1
    padded = np.zeros((tb.shape[0], L), np.complex128)
    padded[:, : tb.shape[1]] = tb
    rev = np.zeros_like(padded)
    rev[:, 0] = padded[:, 0]
    rev[:, 1:] = padded[:, :0:-1]
    tf = np.fft.fft(rev, axis=1).astype(np.complex64)
    rng = np.random.RandomState(0)
    seg = (rng.standard_normal(L) + 1j * rng.standard_normal(L)) \
        .astype(np.complex64)

    def one_rep():
        tb0 = time.perf_counter()
        sl = np.fft.fft(seg)
        corr = np.fft.ifft(sl[None, :] * tf, axis=1)
        _ = (np.abs(corr) ** 2).astype(np.float32)
        return time.perf_counter() - tb0

    # the round-5 baseline protocol (bench.numpy_baseline): >=5
    # loadavg-gated reps + pinned-calibration cross-check
    import bench as bench_mod

    bl = bench_mod.numpy_baseline(one_rep)
    bl_cells_per_sec = (2 * Z * segw) / bl["seconds"]
    vs_baseline = cells_per_sec / bl_cells_per_sec

    # linear-extrapolation spot check (VERDICT r5 item 7): the same twin
    # on a 10x larger slice; ratio ~1 validates the scaling model behind
    # every scaled-baseline figure in the bench JSONs
    segs10 = [(rng.standard_normal(L) + 1j * rng.standard_normal(L))
              .astype(np.complex64) for _ in range(10)]

    def ten_rep():
        tb0 = time.perf_counter()
        for s10 in segs10:
            sl = np.fft.fft(s10)
            corr = np.fft.ifft(sl[None, :] * tf, axis=1)
            _ = (np.abs(corr) ** 2).astype(np.float32)
        return time.perf_counter() - tb0

    scale = bench_mod.baseline_scale_check(one_rep, ten_rep, factor=10)

    # per-spectrum fields keep the BENCH_r05 meaning (the ACCEL stage
    # per trial, comparable round over round): accel_wall is the
    # accelsearch CLI stage classically and the recorded accel_search
    # span total under --stream. The streamed chain's combined stage is
    # reported separately as stream_stage_per_spectrum_seconds.
    chain_stage = accel_wall
    rec = {
        "metric": "configs4_end_to_end_seconds",
        "value": round(wall, 1),
        "unit": (f"wall seconds, {a.duration:.0f}s x {nchan}-chan "
                 f"{nbits}-bit window -> "
                 + (f"sweep --accel-search (streamed handoff, "
                    f"ds={a.downsamp}, batch {a.batch}"
                    if a.stream else
                    f"sweep(+streamed .dats, ds={a.downsamp}) -> "
                    f"accelsearch --batch {a.batch}")
                 + f" (zmax={a.zmax:.0f}, "
                 f"dz=2, H<=8, N={N} bins x {a.trials} trials"
                 + (f", coarse-dz={a.coarse_dz:g} prepass"
                    if a.coarse_dz > 0 else "")
                 + (", device-prep" if a.device_prep else ", host-prep")
                 + ") -> sift; measured on one v5e through the axon "
                   "tunnel"),
        "vs_baseline": round(vs_baseline, 2),
        "numpy_cells_per_sec": round(bl_cells_per_sec, 1),
        **{k: v for k, v in bl.items() if k != "seconds"},
        **scale,
        "trials": a.trials,
        "covered_seconds": round(covered, 1),
        "requested_seconds": round(a.duration, 1),
        "streamed_handoff": a.stream,
        "coarse_dz": a.coarse_dz,
        "device_prep": a.device_prep,
        "wall_seconds": round(wall, 1),
        "stage_seconds": stages,
        **({"stream_span_seconds": stream_spans} if stream_spans else {}),
        "spectrum_bins": N,
        "cells_per_spectrum": cells,
        "accel_search_wall_seconds": round(accel_wall, 1),
        "cells_per_sec": round(cells_per_sec, 1),
        "injected_recovered": best,
        **({"ab_coarse": ab} if ab else {}),
        **({"ab_stream": ab_stream} if ab_stream else {}),
        "per_spectrum_seconds": round(chain_stage / a.trials, 2),
        "projection_4096_trials_hours": round(
            4096 * chain_stage / a.trials / 3600.0, 2),
        **({"stream_stage_per_spectrum_seconds": round(
            stages["sweep_accel_stream"] / a.trials, 2)}
           if a.stream else {}),
    }
    with open(a.out, "w") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    if not a.keep:
        shutil.rmtree(a.workdir, ignore_errors=True)
    if best is None and not a.allow_miss:
        print("## FAIL: injected pulsar NOT recovered by the sift",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
