"""One-command live-TPU validation of every device-facing engine.

Usage (repo root, axon tunnel up): ``python tools/tpu_smoke.py``

Runs each engine at small shapes with a correctness assertion and prints
one PASS/FAIL line per engine plus wall time — fast triage separating
"tunnel down" (liveness fails), "toolchain regression" (one engine
fails: e.g. a new complex-boundary or Mosaic limitation), and "all good"
(exit 0). The CPU test suite cannot catch axon-platform-only failures
(tests/conftest.py pins JAX_PLATFORMS=cpu); this can.
"""
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FAILED = []


def check(name, fn):
    t0 = time.perf_counter()
    try:
        fn()
        print(f"PASS  {name:28s} {time.perf_counter() - t0:6.1f}s")
    except Exception as e:  # noqa: BLE001 - report and continue
        FAILED.append(name)
        print(f"FAIL  {name:28s} {time.perf_counter() - t0:6.1f}s  "
              f"{type(e).__name__}: {str(e)[:120]}")
        traceback.print_exc(limit=3)


def liveness():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]  # psrlint: ignore[PL002] -- raw-inventory smoke: proves the backend exists BELOW the lease registry
    assert float(jnp.ones((128, 128)).sum()) == 128 * 128
    print(f"#     device: {dev} ({dev.platform})")


def sweep_chunk():
    import jax.numpy as jnp

    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.parallel.sweep import sweep_resident

    C, T, dt, dm = 128, 1 << 15, 64e-6, 120.0
    freqs = (1500.0 - 2.0 * np.arange(C)).astype(np.float64)
    rng = np.random.RandomState(0)
    data = rng.randn(C, T).astype(np.float32)
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for c in range(C):
        idx = 9000 + bins[c]
        if idx < T:
            data[c, idx] += 8.0
    dms = np.linspace(0.0, 240.0, 64)
    res = sweep_resident(Spectra(freqs, dt, jnp.asarray(data)), dms,
                         nsub=32, group_size=16, engine="fourier")
    best = res.best(1)[0]
    assert abs(best["dm"] - dm) <= 8.0 and best["snr"] > 6.0, best


def accel():
    from pypulsar_tpu.fourier.accelsearch import (
        AccelSearchConfig,
        accel_search,
    )
    from pypulsar_tpu.fourier.kernels import deredden

    N = 1 << 16
    dt = 1e-3
    T = 2 * N * dt
    t = np.arange(2 * N) * dt
    sig = np.random.RandomState(0).standard_normal(2 * N).astype(np.float32)
    sig += 6.0 * np.sin(2 * np.pi * 50.0 * t).astype(np.float32)
    fft = (np.fft.rfft(sig) / np.sqrt(2 * N)).astype(np.complex64)[:N]
    fft = deredden(fft)  # exercises the complex-plane jit boundary too
    cfg = AccelSearchConfig(zmax=8.0, dz=2.0, numharm=2, sigma_min=5.0,
                            seg_width=1 << 12)
    cands = accel_search(fft, T, cfg)
    best = max(cands, key=lambda c: c.sigma)
    assert abs(best.freq(T) - 50.0) < 0.1, best


def fold():
    import jax.numpy as jnp

    from pypulsar_tpu.fold.engine import fold_parts, phase_to_bins

    # nbins <= samples per rotation (50) so no phase bin is ever empty
    C, T, nbins, npart = 64, 1 << 17, 32, 8
    rng = np.random.RandomState(1)
    data = rng.standard_normal((C, T)).astype(np.float32)
    bi = phase_to_bins(np.arange(T) * 1e-3 / 0.05, nbins)
    data[:, bi == 10] += 1.0
    profs, counts = fold_parts(jnp.asarray(data), jnp.asarray(bi),
                               nbins, npart)
    prof = (np.asarray(profs).sum(axis=(0, 1))
            / np.asarray(counts).sum(axis=0) / C)
    assert prof[10] > 0.8 and abs(prof[11]) < 0.2, prof[9:12]


def rfi_stats():
    from pypulsar_tpu.ops.rfifind import rfifind

    rng = np.random.RandomState(2)
    data = rng.randn(32, 10 * 512).astype(np.float32)
    data[5] *= 20.0
    stats, flags, _ = rfifind(data, dt=1e-3, time=0.512,
                              hifreq_first=False)
    assert flags[:, 5].all()


def boxcar():
    import jax.numpy as jnp

    from pypulsar_tpu.ops.pallas_kernels import boxcar_stats

    import jax

    ts = jax.random.normal(jax.random.PRNGKey(0), (64, 8192), jnp.float32)
    s, ss, mb, ab = boxcar_stats(ts, (1, 2, 4, 8), 8000, backend="pallas")
    s2, ss2, mb2, ab2 = boxcar_stats(ts, (1, 2, 4, 8), 8000, backend="lax")
    np.testing.assert_allclose(np.asarray(mb), np.asarray(mb2),
                               rtol=1e-5, atol=1e-4)


def main():
    check("liveness", liveness)
    check("sweep (fourier, resident)", sweep_chunk)
    check("accel search + deredden", accel)
    check("fold_parts (one-hot MXU)", fold)
    check("rfifind block stats", rfi_stats)
    check("boxcar pallas-vs-lax", boxcar)
    if FAILED:
        print(f"\n{len(FAILED)} FAILED: {', '.join(FAILED)}")
        return 1
    print("\nALL ENGINES PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
