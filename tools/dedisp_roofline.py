"""Per-engine work accounting for the dedispersion sweep (round 16 —
sibling of tools/accel_roofline.py): adds/cell and bytes/cell for the
direct (two-stage gather/scan), fourier and tree engines at a given
(nchan, ndm, nsamp) geometry, so BENCHNOTES complexity claims are
TOOL-DERIVED, not hand-waved.

A "cell" is one (DM trial, output sample). The counts are STRUCTURAL —
the direct/naive numbers fall out of the plan shapes, the tree numbers
are the exact per-level merged-row counts of the host-built
ops/tree_dedisperse.py tables for the actual trial grid (dedup included;
no model), and the fourier numbers are flops (its work is transforms +
complex multiplies, a different currency than adds — reported under its
own key, never summed against the add counts).

What the accounting shows (committed in BENCH_r11_tree.json / BENCHNOTES
round 16):

- naive per-channel shifts pay ``C - 1`` adds/cell — linear in nchan;
- the two-stage direct engine pays ``(C - S)/g + (S - 1)`` adds/cell —
  affine in nchan with slope 1/group_size (DDplan's economics);
- the tree engine pays ``sum_l R_l / D`` adds/cell, bounded by
  ``~max(span, nchan) * log2(nchan) / D``: with the dispersion span and
  trial count held fixed it scales ~log2(nchan) (--scaling prints the
  sweep), and at production DM counts it undercuts the two-stage engine
  by the headline factor bench.py --dedisp-tree measures.

Usage: python tools/dedisp_roofline.py [--nchan 1024] [--ndm 1024]
           [--nsamp 16384] [--dm-max DIAG] [--nsub 64] [--group-size 32]
           [--scaling 256,512,1024,2048] [--json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pypulsar_tpu.core import psrmath  # noqa: E402
from pypulsar_tpu.ops.fourier_dedisperse import fourier_chunk_len  # noqa: E402
from pypulsar_tpu.ops.tree_dedisperse import plan_from_bins  # noqa: E402
from pypulsar_tpu.parallel.sweep import make_sweep_plan  # noqa: E402


def diagonal_dm(nchan: int, dt: float, f_hi: float, bw: float) -> float:
    """The FDMT-regime diagonal: the DM whose full-band delay spans
    ``nchan`` samples — where the tree's delay enumeration and the
    channel count coincide (PAPERS.md 1201.5380 §2)."""
    freqs_lo = f_hi - bw
    unit = psrmath.delay_from_DM(1.0, freqs_lo) - psrmath.delay_from_DM(
        1.0, f_hi)
    return nchan * dt / unit


def analyze(nchan: int, ndm: int, nsamp: int, dm_max: float,
            nsub: int = 64, group_size: int = 32, dt: float = 64e-6,
            f_hi: float = 1500.0, bw: float = 300.0) -> dict:
    """Structural (adds, bytes) per cell for every engine at one
    geometry. The tree numbers come from the ACTUAL merge tables."""
    nsub = min(nsub, nchan)
    group_size = min(group_size, ndm)
    freqs = (f_hi - bw / nchan * np.arange(nchan)).astype(np.float64)
    dms = np.linspace(0.0, dm_max, ndm)
    plan = make_sweep_plan(dms, freqs, dt, nsub=nsub,
                           group_size=group_size)
    G, g, S = plan.stage2_bins.shape
    C = nchan
    D = plan.n_trials  # padded to the group multiple, like the engines

    # direct two-stage (gather/scan): stage 1 sums `per` channels into
    # each subband per group, stage 2 sums S subbands per trial
    direct_adds = (G * (C - S) + D * (S - 1)) / D
    naive_adds = C - 1
    # f32 traffic, fused best case: stage 1 reads C rows + writes S per
    # group; stage 2 reads S + writes 1 per trial — per sample
    direct_bytes = 4.0 * (G * (C + S) + D * (S + 1)) / D

    # fourier: transforms + complex multiplies (flops, not adds). One
    # rfft per channel + one irfft per trial (~2.5 L log2 L real-FFT
    # flops under accel_roofline's 5 L log2 L complex convention), plus
    # the stage phase multiply-accumulates (8 flops per complex
    # multiply+add) over the F-bin spectra
    n_fft = fourier_chunk_len(nsamp + plan.min_overlap)
    F = n_fft // 2 + 1
    fft_flops = 2.5 * n_fft * math.log2(n_fft) * (C + D)
    mult_flops = 8.0 * F * (G * C + D * S)
    fourier_flops = (fft_flops + mult_flops) / (D * nsamp)
    fourier_bytes = (4 * C * n_fft + 8 * F * (C + G * (C + S) + D * (S + 1))
                     + 4 * D * n_fft) / (D * nsamp)

    # tree: exact per-level merged-row counts for THIS trial grid
    tplan = plan_from_bins(plan.stage1_bins, plan.stage2_bins)
    tree_adds = tplan.adds_per_sample / D
    total_rows = sum(tplan.rows_per_level)
    # each row: two gathered-row reads + one write, f32
    tree_bytes = 12.0 * total_rows / D

    return dict(
        nchan=C, ndm=ndm, n_trials_padded=D, nsamp=nsamp,
        dm_max=round(float(dm_max), 4),
        delay_span_bins=int(plan.max_total_shift),
        nsub=nsub, group_size=g,
        adds_per_cell=dict(
            naive=round(naive_adds, 2),
            direct_two_stage=round(direct_adds, 2),
            tree=round(tree_adds, 2),
        ),
        bytes_per_cell=dict(
            direct_two_stage=round(direct_bytes, 1),
            fourier=round(fourier_bytes, 1),
            tree=round(tree_bytes, 1),
        ),
        fourier_flops_per_cell=round(fourier_flops, 1),
        tree=dict(
            merge_levels=tplan.n_levels,
            rows_max=tplan.rows,
            rows_per_level=list(tplan.rows_per_level),
            adds_per_sample_all_trials=tplan.adds_per_sample,
        ),
        work_ratio_direct_over_tree=round(direct_adds / max(tree_adds,
                                                            1e-9), 2),
    )


def scaling_sweep(nchans, ndm, nsamp, dm_max, nsub, group_size, dt,
                  f_hi, bw) -> dict:
    """adds/cell vs nchan with the DM grid (and so the delay span) held
    FIXED — the complexity-claim table: tree grows ~log2(nchan), naive
    grows ~nchan, the two-stage engine grows affinely with slope 1/g."""
    rows = []
    for c in nchans:
        r = analyze(c, ndm, nsamp, dm_max, nsub=nsub,
                    group_size=group_size, dt=dt, f_hi=f_hi, bw=bw)
        rows.append(dict(nchan=c, **r["adds_per_cell"],
                         merge_levels=r["tree"]["merge_levels"]))
    lo, hi = rows[0], rows[-1]
    return dict(
        table=rows,
        nchan_range=[lo["nchan"], hi["nchan"]],
        growth=dict(
            naive=round(hi["naive"] / lo["naive"], 2),
            direct_two_stage=round(hi["direct_two_stage"]
                                   / lo["direct_two_stage"], 2),
            tree=round(hi["tree"] / lo["tree"], 2),
            log2_levels=round(hi["merge_levels"] / lo["merge_levels"], 2),
        ),
    )


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--nchan", type=int, default=1024)
    ap.add_argument("--ndm", type=int, default=1024)
    ap.add_argument("--nsamp", type=int, default=1 << 14)
    ap.add_argument("--dm-max", type=float, default=None,
                    help="highest trial DM (default: the FDMT-regime "
                         "diagonal where the full-band delay spans nchan "
                         "samples)")
    ap.add_argument("--nsub", type=int, default=64)
    ap.add_argument("--group-size", type=int, default=32)
    ap.add_argument("--dt", type=float, default=64e-6)
    ap.add_argument("--f-hi", type=float, default=1500.0)
    ap.add_argument("--bw", type=float, default=300.0)
    ap.add_argument("--scaling", default=None, metavar="C1,C2,...",
                    help="also sweep adds/cell over these channel counts "
                         "at the FIXED DM grid (the log2-vs-linear "
                         "demonstration)")
    ap.add_argument("--json", action="store_true",
                    help="emit the analysis as one JSON line")
    return ap.parse_args(argv)


def main(argv=None):
    a = parse_args(argv)
    dm_max = a.dm_max if a.dm_max is not None else diagonal_dm(
        a.nchan, a.dt, a.f_hi, a.bw)
    rec = analyze(a.nchan, a.ndm, a.nsamp, dm_max, nsub=a.nsub,
                  group_size=a.group_size, dt=a.dt, f_hi=a.f_hi, bw=a.bw)
    if a.scaling:
        nchans = [int(x) for x in a.scaling.split(",")]
        rec["scaling"] = scaling_sweep(nchans, a.ndm, a.nsamp, dm_max,
                                       a.nsub, a.group_size, a.dt,
                                       a.f_hi, a.bw)
    if a.json:
        print(json.dumps(rec))
        return 0
    ad = rec["adds_per_cell"]
    print(f"# dedispersion work roofline @ nchan={rec['nchan']}, "
          f"ndm={rec['ndm']} (padded {rec['n_trials_padded']}), "
          f"nsamp={rec['nsamp']}, DM 0-{rec['dm_max']:g} "
          f"(span {rec['delay_span_bins']} bins), nsub={rec['nsub']}, "
          f"g={rec['group_size']}")
    print(f"# adds/cell: naive {ad['naive']}  two-stage direct "
          f"{ad['direct_two_stage']}  tree {ad['tree']}  -> direct/tree "
          f"= {rec['work_ratio_direct_over_tree']}x")
    print(f"# fourier: {rec['fourier_flops_per_cell']} flops/cell "
          f"(transforms + complex multiplies — its own currency, not "
          f"comparable to add counts)")
    t = rec["tree"]
    print(f"# tree: {t['merge_levels']} merge levels, rows/level "
          f"{t['rows_per_level']} (max {t['rows_max']}), "
          f"{t['adds_per_sample_all_trials']} adds/sample for ALL "
          f"trials")
    bt = rec["bytes_per_cell"]
    print(f"# bytes/cell (fused best case): direct "
          f"{bt['direct_two_stage']}  fourier {bt['fourier']}  tree "
          f"{bt['tree']}")
    if "scaling" in rec:
        s = rec["scaling"]
        print("# scaling at FIXED DM grid (adds/cell):")
        print("#   nchan    naive   two-stage     tree   levels")
        for r in s["table"]:
            print(f"#   {r['nchan']:5d} {r['naive']:8.1f} "
                  f"{r['direct_two_stage']:11.1f} {r['tree']:8.2f} "
                  f"{r['merge_levels']:8d}")
        g = s["growth"]
        print(f"# growth over {s['nchan_range'][0]}->"
              f"{s['nchan_range'][1]} chans: naive {g['naive']}x "
              f"(~nchan), two-stage {g['direct_two_stage']}x, tree "
              f"{g['tree']}x (~log2: levels grew {g['log2_levels']}x)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
