"""A/B the batched accelsearch host-prep vs --device-prep on real .dats.

The round-5 configs[4] measurement showed the batched CLI spending more
wall in per-spectrum HOST prep (np.fft.rfft of a 3.5M-point series on
the 1-core host plus a deredden device round trip) than in the batched
device search itself. ``--device-prep`` (kernels.prep_spectra_batch)
fuses rfft + deredden into one device dispatch whose output feeds the
search without leaving HBM. This driver times both CLI paths over the
same .dat set and records walls + candidate-set parity.

Usage: python tools/run_accelprep_ab.py --dats 'data/configs4/c4_DM*.dat'
           [--batch 32] [--zmax 200] [--out BENCH_r05_accelprep.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--dats", required=True,
                    help="glob of input .dat files (with .inf siblings)")
    ap.add_argument("--workdir", default="/tmp/accelprep_ab")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--zmax", type=float, default=200.0)
    ap.add_argument("--numharm", type=int, default=8)
    ap.add_argument("--sigma", type=float, default=2.0)
    ap.add_argument("--coarse-dz", type=float, default=0.0,
                    help="also time coarse-to-fine legs at this coarse "
                         "step (single-pass vs --coarse-dz, each with "
                         "and without --device-prep): a clean-host "
                         "re-measurement of the configs[4] in-run A/B")
    ap.add_argument("--out", default=os.path.join(
        REPO, "BENCH_r05_accelprep.json"))
    return ap.parse_args(argv)


def run_cli(dats, a, extra, log):
    argv = [sys.executable, "-m", "pypulsar_tpu.cli.accelsearch", *dats,
            "--batch", str(a.batch), "-z", str(int(a.zmax)), "--dz", "2",
            "-n", str(a.numharm), "-s", str(a.sigma)] + extra
    t0 = time.perf_counter()
    with open(log, "w") as lf:
        rc = subprocess.call(argv, stdout=lf, stderr=subprocess.STDOUT)
    el = time.perf_counter() - t0
    if rc != 0:
        raise RuntimeError(f"accelsearch rc={rc}; see {log}")
    return el


def cand_sets(dats, a):
    from pypulsar_tpu.io.prestocand import read_rzwcands

    out = {}
    for d in dats:
        fn = os.path.splitext(d)[0] + f"_ACCEL_{int(a.zmax)}.cand"
        out[os.path.basename(d)] = sorted(
            ((round(c.r, 1), round(c.z, 1)) for c in read_rzwcands(fn)))
    return out


def main(argv=None):
    a = parse_args(argv)
    if a.batch < 2:
        raise SystemExit("--batch >= 2 required: the CLI only honors "
                         "--device-prep on its batched path, so a batch-1 "
                         "A/B would time identical host-prep legs")
    src = sorted(glob.glob(a.dats))
    if not src:
        raise SystemExit(f"no dats match {a.dats!r}")
    os.makedirs(a.workdir, exist_ok=True)
    dats = []
    for s in src:
        d = os.path.join(a.workdir, os.path.basename(s))
        if not os.path.exists(d):
            shutil.copy(s, d)
            shutil.copy(os.path.splitext(s)[0] + ".inf",
                        os.path.splitext(d)[0] + ".inf")
        dats.append(d)

    # device prep is default-on for the grouped path since round 6, so
    # the host leg must opt out explicitly
    legs = [("host", ["--no-device-prep"]), ("device", ["--device-prep"])]
    if a.coarse_dz > 0:
        cd = ["--coarse-dz", str(a.coarse_dz)]
        legs += [("coarse", cd + ["--no-device-prep"]),
                 ("coarse_device", cd + ["--device-prep"])]

    walls, sets = {}, {}
    for name, extra in legs:
        walls[name] = run_cli(dats, a, extra,
                              os.path.join(a.workdir, f"{name}.log"))
        sets[name] = cand_sets(dats, a)
        legdir = os.path.join(a.workdir, name)
        os.makedirs(legdir, exist_ok=True)
        for d in dats:  # exactly this run's outputs: no stale-file bleed
            fn = os.path.splitext(d)[0] + f"_ACCEL_{int(a.zmax)}.cand"
            shutil.copy(fn, legdir)
        print(f"# leg {name}: {walls[name]:.1f}s", flush=True)

    ref = sets["host"]
    parity = {name: sum(ref[k] == s[k] for k in ref)
              for name, s in sets.items() if name != "host"}
    all_same = all(v == len(dats) for v in parity.values())
    rec = {
        "metric": "accel_device_prep_speedup",
        "value": round(walls["host"] / walls["device"], 2),
        "unit": (f"host-prep wall / device-prep wall, cli accelsearch "
                 f"--batch {a.batch} over {len(dats)} x "
                 f"900-s .dats (zmax={a.zmax:.0f}, dz=2, "
                 f"H<={a.numharm}); candidate sets (r,z rounded to 0.1) "
                 f"vs host leg: "
                 + ", ".join(f"{n}={v}/{len(dats)}"
                             for n, v in parity.items())),
        "vs_baseline": 0.0,
        "wall_seconds_by_leg": {n: round(w, 1) for n, w in walls.items()},
        "per_spectrum_seconds_by_leg": {
            n: round(w / len(dats), 2) for n, w in walls.items()},
        "n_dats": len(dats),
        "coarse_dz": a.coarse_dz,
        "cand_parity_vs_host": parity,
        "cand_sets_identical": all_same,
    }
    print(json.dumps(rec))
    with open(a.out, "w") as f:
        f.write(json.dumps(rec) + "\n")
    return 0 if all_same else 1


if __name__ == "__main__":
    raise SystemExit(main())
