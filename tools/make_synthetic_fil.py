"""Synthesize a large on-disk SIGPROC filterbank with an injected pulsar.

The north-star workload (BASELINE.md) is a 1-hr x 1024-channel filterbank
swept over 4096 DM trials; this writes that dataset to disk blockwise
(~57.6 GB at 8 bits, never more than one block in RAM) so the streamed
sweep path — native prefetcher + sweep_stream — can be benchmarked on the
real chip with host I/O included (VERDICT r3 item 1).

Synthesis: uniform uint8 noise (0..noise_hi) plus a dispersed periodic
pulsar. The pulse period is an integer number of samples, so the injected
signal is one [period, nchan] pattern tiled over each block — generation
runs at memory bandwidth instead of evaluating per-sample phase math over
5.7e10 cells. Per-channel delays use the same ops.numpy_ref.bin_delays the
sweep parity tests use; the expected recovery (DM, boxcar width, period)
is printed and embedded in the header source name.

Reference treatment: the reference synthesizes no data (its test loop was
"compare with PRESTO" on real Arecibo files, SURVEY.md §4); the writer
layout follows formats/filterbank.py + sigproc header conventions.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pypulsar_tpu.io import sigproc  # noqa: E402
from pypulsar_tpu.ops import numpy_ref  # noqa: E402


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", required=True)
    ap.add_argument("--nchan", type=int, default=1024)
    ap.add_argument("--tsamp", type=float, default=64e-6)
    ap.add_argument("--duration", type=float, default=3600.0, help="seconds")
    ap.add_argument("--fch1", type=float, default=1500.0)
    ap.add_argument("--bw", type=float, default=300.0, help="total MHz, descending")
    ap.add_argument("--dm", type=float, default=70.0)
    ap.add_argument("--period-samples", type=int, default=4096,
                    help="pulse period in samples (integer => tileable)")
    ap.add_argument("--width", type=int, default=8, help="pulse width, samples")
    ap.add_argument("--nbits", type=int, default=8, choices=(8, 4, 2),
                    help="sample depth; 4/2 write PACKED sub-byte files "
                         "(io/filterbank.py layout) at half/quarter the "
                         "bytes. --amp/--noise-hi defaults scale to keep "
                         "the per-sample SNR of the 8-bit defaults")
    ap.add_argument("--amp", type=int, default=None,
                    help="pulse amplitude, counts (default 30 at 8-bit, "
                         "2 at 4-bit, 1 at 2-bit)")
    ap.add_argument("--noise-hi", type=int, default=None,
                    help="noise ~ Uniform{0..noise_hi-1} (default 200 at "
                         "8-bit, 14 at 4-bit, 3 at 2-bit)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--blocks-per-write", type=int, default=32,
                    help="periods per written block")
    ap.add_argument("--src-name", default=None,
                    help="header source_name (default: the injected-"
                         "signal tag SYNTH_DM{dm}_P{period}) — fleet "
                         "tests use this to generate distinguishable "
                         "observations")
    ap.add_argument("--start-mjd", type=float, default=60000.0,
                    help="header tstart MJD (default 60000.0)")
    ap.add_argument("--corrupt", default=None, metavar="KIND[:SEED]",
                    help="deterministically corrupt the written file "
                         "in place (resilience.dataguard.corrupt_file: "
                         "truncate|bitflip|dropblock|nanburst|dcjump|"
                         "header) — bench and tests generate corrupted "
                         "fixtures from this ONE code path instead of "
                         "hand-hexed files. nanburst/dcjump need an "
                         "f32 payload (this tool writes uint), so they "
                         "are rejected here; SEED defaults to 0")
    return ap.parse_args(argv)


def main(argv=None):
    a = parse_args(argv)
    if a.amp is None:
        a.amp = {8: 30, 4: 2, 2: 1}[a.nbits]
    if a.noise_hi is None:
        a.noise_hi = {8: 200, 4: 14, 2: 3}[a.nbits]
    if not 1 <= a.noise_hi <= 256:
        raise SystemExit("--noise-hi must be in [1, 256] (uint8 data; the "
                         "multiply-shift map overflows uint16 beyond that)")
    if a.noise_hi - 1 + a.amp >= (1 << a.nbits):
        raise SystemExit(f"noise_hi-1 + amp = {a.noise_hi - 1 + a.amp} "
                         f"overflows {a.nbits}-bit samples")
    C, P = a.nchan, a.period_samples
    nsamp = int(round(a.duration / a.tsamp))
    nsamp = max((nsamp // P) * P, P)  # whole periods; simplifies tiling only
    foff = -a.bw / C
    freqs = a.fch1 + foff * np.arange(C)
    delays = numpy_ref.bin_delays(a.dm, freqs, a.tsamp)  # [C] >= 0, int

    # one-period injection pattern [P, C]: channel c pulses at rows
    # (phase0 + delays[c]) % P .. +width (time-major, matching file order)
    pattern = np.zeros((P, C), np.uint8)
    rows = (np.arange(a.width)[:, None] + delays[None, :]) % P  # [width, C]
    pattern[rows, np.arange(C)[None, :]] = a.amp

    hdr = {
        "source_name": a.src_name or f"SYNTH_DM{a.dm:g}_P{P}",
        "fch1": a.fch1, "foff": foff, "nchans": C, "tsamp": a.tsamp,
        # the sample count lets readers cross-check the file size and
        # salvage (+ report) a truncated tail instead of silently
        # shortening the observation
        "nsamples": nsamp,
        "nbits": a.nbits, "nifs": 1, "tstart": a.start_mjd, "data_type": 1,
        "telescope_id": 0, "machine_id": 0, "barycentric": 0,
        "src_raj": 0.0, "src_dej": 0.0, "az_start": 0.0, "za_start": 0.0,
    }
    rng = np.random.Generator(np.random.SFC64(a.seed))
    B = P * a.blocks_per_write
    total_bytes = nsamp * C * a.nbits // 8
    t0 = time.time()
    with open(a.out, "wb") as f:
        f.write(sigproc.pack_header(hdr))
        written = 0
        while written < nsamp:
            n = min(B, nsamp - written)
            # raw bit-generator bytes + multiply-shift range map: ~10x the
            # throughput of bounded rng.integers (which Lemire-rejects per
            # byte); the map is near-uniform on {0..noise_hi-1}, which is
            # all synthetic noise needs
            raw = np.frombuffer(rng.bytes(n * C), np.uint8).reshape(n, C)
            block = ((raw.astype(np.uint16) * np.uint16(a.noise_hi))
                     >> np.uint16(8)).astype(np.uint8)
            block.reshape(n // P, P, C)[:] += pattern[None]
            if a.nbits < 8:
                from pypulsar_tpu.io.filterbank import pack_subbyte

                block = pack_subbyte(block, a.nbits)
            block.tofile(f)
            written += n
            if (written // B) % 8 == 0 or written == nsamp:
                el = time.time() - t0
                done = written * C * a.nbits // 8
                rate = done / el / 1e6 if el > 0 else 0.0
                print(f"\r{done/1e9:7.1f}/{total_bytes/1e9:.1f} GB "
                      f"({rate:.0f} MB/s)", end="", file=sys.stderr)
    print(file=sys.stderr)
    print(f"wrote {a.out}: {nsamp} samples x {C} chans, {a.nbits}-bit, "
          f"{total_bytes/1e9:.1f} GB in {time.time()-t0:.0f}s; injected "
          f"DM={a.dm} P={P*a.tsamp*1e3:.3f} ms ({P} samples) "
          f"width={a.width} amp={a.amp}")
    if a.corrupt:
        from pypulsar_tpu.resilience import dataguard

        kind, _, seed = a.corrupt.partition(":")
        if kind in ("nanburst", "dcjump"):
            raise SystemExit(f"--corrupt {kind} needs an f32 payload; "
                             f"this tool writes {a.nbits}-bit uints "
                             f"(use truncate/bitflip/dropblock/header)")
        desc = dataguard.corrupt_file(a.out, kind,
                                      seed=int(seed) if seed else 0)
        print(f"corrupted {a.out}: {desc}", file=sys.stderr)


if __name__ == "__main__":
    main()
