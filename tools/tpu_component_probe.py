"""Split timing of the Fourier sweep engine's components on the live TPU.

Run from the repo root with the axon tunnel up (`python
tools/tpu_component_probe.py`). Prints per-component wall times with the
~60 ms tunnel dispatch overhead calibrated out: batched rfft/irfft
throughput at the sweep's shapes, the stage-1/stage-2 phase-multiply
reduces, a gather-free LUT-factorized phase variant, boxcar backends, and
smaller FFT sizes — the data needed to decide where the next 10x comes
from (BENCHNOTES.md round-3 notes; the round-3 tunnel outage prevented
this run)."""
import sys, time
import numpy as np
import jax, jax.numpy as jnp
from functools import partial

key = jax.random.PRNGKey(0)
n = 1 << 17
F = n // 2 + 1
C, S, G, g = 1024, 64, 32, 32
D = G * g

def force(x):
    if isinstance(x, (tuple, list)):
        x = x[0]
    return float(jnp.asarray(x).ravel()[0])

def timeit(fn, *args):
    force(fn(*args))  # compile
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        force(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)

null = jax.jit(lambda x: x + 1.0)
xs = jnp.zeros((8,))
overhead = timeit(null, xs)
print(f"overhead {overhead*1e3:.1f} ms", file=sys.stderr)

data = jax.random.normal(key, (C, n), dtype=jnp.float32)
force(data[:1, :1])
t = timeit(jax.jit(lambda d: jnp.fft.rfft(d, axis=1).real), data) - overhead
print(f"rfft [{C},{n}]     {t*1e3:8.1f} ms  {C*2.5*n*17/t/1e9:6.1f} GFLOP/s", file=sys.stderr)

Xd = (jax.random.normal(key, (D, F)) + 1j*jax.random.normal(jax.random.PRNGKey(1), (D, F))).astype(jnp.complex64)
force(Xd.real[:1, :1])
t = timeit(jax.jit(lambda X: jnp.fft.irfft(X, n=n, axis=1)), Xd) - overhead
print(f"irfft [{D},{F}]   {t*1e3:8.1f} ms  {D*2.5*n*17/t/1e9:6.1f} GFLOP/s", file=sys.stderr)

Xc = (jax.random.normal(key, (C, F)) + 1j*jax.random.normal(jax.random.PRNGKey(2), (C, F))).astype(jnp.complex64)
force(Xc.real[:1, :1])
sh1 = jnp.asarray(np.random.RandomState(0).randint(0, 160, size=C), jnp.int32)
k = jnp.arange(F, dtype=jnp.int32)

@jax.jit
def stage1_one(X, sh):
    idx = (k * sh[:, None]) & jnp.int32(n - 1)
    ang = (2.0*jnp.pi/n) * idx.astype(jnp.float32)
    ph = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))
    return ((X * ph).reshape(S, C // S, F).sum(axis=1)).real

t = timeit(stage1_one, Xc, sh1) - overhead
print(f"stage1 x1 group    {t*1e3:8.1f} ms  -> x{G} = {t*G*1e3:8.1f} ms  ({C*F*8/t/1e9:5.1f} GB/s)", file=sys.stderr)

Xs = Xc[:S]
sh2 = jnp.asarray(np.random.RandomState(1).randint(0, 8000, size=(g, S)), jnp.int32)

@jax.jit
def stage2_one(X, sh):
    idx = (k[None, None, :] * sh[:, :, None]) & jnp.int32(n - 1)
    ang = (2.0*jnp.pi/n) * idx.astype(jnp.float32)
    ph = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))
    return ((X[None] * ph).sum(axis=1)).real

t = timeit(stage2_one, Xs, sh2) - overhead
print(f"stage2 x1 group    {t*1e3:8.1f} ms  -> x{G} = {t*G*1e3:8.1f} ms  ({g*S*F*8/t/1e9:5.1f} GB/s)", file=sys.stderr)

# no-transcendental stage2: phase from gathered per-shift row tables
t1 = jnp.exp(2j*jnp.pi*jnp.arange(128)[:, None]*k[None, :]*64.0/n).astype(jnp.complex64)  # W^(k*64*j)
t2 = jnp.exp(2j*jnp.pi*jnp.arange(64)[:, None]*k[None, :]/n).astype(jnp.complex64)
force(t1.real[:1, :1])

@jax.jit
def stage2_lut(X, sh):
    hi = sh // 64
    lo = sh % 64
    ph = t1[hi] * t2[lo]   # [g, S, F]
    return ((X[None] * ph).sum(axis=1)).real

t = timeit(stage2_lut, Xs, sh2) - overhead
print(f"stage2-lut x1      {t*1e3:8.1f} ms  -> x{G} = {t*G*1e3:8.1f} ms", file=sys.stderr)

from pypulsar_tpu.ops.pallas_kernels import boxcar_stats
ts_arr = jax.random.normal(key, (D, 123000), dtype=jnp.float32)
force(ts_arr[:1, :1])
for be in ("pallas", "lax"):
    try:
        # boxcar_stats is already jitted; re-wrapping would trace its
        # static kwargs as arguments
        fn = partial(boxcar_stats, widths=(1, 2, 4, 8, 16, 32),
                     stat_len=122850, backend=be)
        t = timeit(fn, ts_arr) - overhead
        print(f"boxcar-{be} [{D}]  {t*1e3:8.1f} ms "
              f"({2*4*D*123000/t/1e9:5.1f} GB/s)", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - pallas needs a real TPU
        print(f"boxcar-{be} unavailable: {type(e).__name__}", file=sys.stderr)
