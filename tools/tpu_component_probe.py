"""Split timing of the Fourier sweep engine's components on the live TPU.

Run from the repo root with the axon tunnel up (`python
tools/tpu_component_probe.py`). Prints per-component wall times with the
~60 ms tunnel dispatch overhead calibrated out: batched rfft/irfft
throughput at the sweep's shapes, the stage-1/stage-2 phase-multiply
reduces, a gather-free LUT-factorized phase variant, and boxcar backends
— the data needed to decide where the next speedup comes from
(BENCHNOTES.md round-3 tables).

Complex-boundary rule (ops/transfer.py): the axon platform cannot move
complex buffers across executable boundaries, so every timed program
takes float planes and combines them internally with lax.complex.
"""
import os, sys, time
import numpy as np
import jax, jax.numpy as jnp
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

key = jax.random.PRNGKey(0)
n = 1 << 17
F = n // 2 + 1
C, S, G, g = 1024, 64, 32, 32
D = G * g

def force(x):
    if isinstance(x, (tuple, list)):
        x = x[0]
    return float(jnp.asarray(x).ravel()[0])

def timeit(fn, *args):
    force(fn(*args))  # compile
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        force(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)

null = jax.jit(lambda x: x + 1.0)
xs = jnp.zeros((8,))
overhead = timeit(null, xs)
print(f"overhead {overhead*1e3:.1f} ms", file=sys.stderr)

data = jax.random.normal(key, (C, n), dtype=jnp.float32)
force(data[:1, :1])
t = timeit(jax.jit(lambda d: jnp.fft.rfft(d, axis=1).real), data) - overhead
print(f"rfft [{C},{n}]     {t*1e3:8.1f} ms  {C*2.5*n*17/t/1e9:6.1f} GFLOP/s", file=sys.stderr)

Xr = jax.random.normal(key, (D, F), dtype=jnp.float32)
Xi = jax.random.normal(jax.random.PRNGKey(1), (D, F), dtype=jnp.float32)
force(Xr[:1, :1])
t = timeit(jax.jit(lambda re, im: jnp.fft.irfft(
    jax.lax.complex(re, im), n=n, axis=1)), Xr, Xi) - overhead
print(f"irfft [{D},{F}]   {t*1e3:8.1f} ms  {D*2.5*n*17/t/1e9:6.1f} GFLOP/s", file=sys.stderr)

Cr = jax.random.normal(key, (C, F), dtype=jnp.float32)
Ci = jax.random.normal(jax.random.PRNGKey(2), (C, F), dtype=jnp.float32)
force(Cr[:1, :1])
sh1 = jnp.asarray(np.random.RandomState(0).randint(0, 160, size=C), jnp.int32)
k = jnp.arange(F, dtype=jnp.int32)

@jax.jit
def stage1_one(re, im, sh):
    X = jax.lax.complex(re, im)
    idx = (k * sh[:, None]) & jnp.int32(n - 1)
    ang = (2.0*jnp.pi/n) * idx.astype(jnp.float32)
    ph = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))
    return ((X * ph).reshape(S, C // S, F).sum(axis=1)).real

t = timeit(stage1_one, Cr, Ci, sh1) - overhead
print(f"stage1 x1 group    {t*1e3:8.1f} ms  -> x{G} = {t*G*1e3:8.1f} ms  ({C*F*8/t/1e9:5.1f} GB/s)", file=sys.stderr)

Sr, Si = Cr[:S], Ci[:S]
sh2 = jnp.asarray(np.random.RandomState(1).randint(0, 8000, size=(g, S)), jnp.int32)

@jax.jit
def stage2_one(re, im, sh):
    X = jax.lax.complex(re, im)
    idx = (k[None, None, :] * sh[:, :, None]) & jnp.int32(n - 1)
    ang = (2.0*jnp.pi/n) * idx.astype(jnp.float32)
    ph = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))
    return ((X[None] * ph).sum(axis=1)).real

t = timeit(stage2_one, Sr, Si, sh2) - overhead
print(f"stage2 x1 group    {t*1e3:8.1f} ms  -> x{G} = {t*G*1e3:8.1f} ms  ({g*S*F*8/t/1e9:5.1f} GB/s)", file=sys.stderr)

# no-transcendental stage2: phase from gathered per-shift row tables,
# built on device inside the jit (complex tables cannot transfer)
@jax.jit
def stage2_lut(re, im, sh):
    X = jax.lax.complex(re, im)
    j64 = jnp.arange(128, dtype=jnp.int32)
    # exact: W^(k*64*j) with (k*64*j) mod n via int32 wraparound
    idx1 = ((k[None, :] * (64*j64)[:, None]) & jnp.int32(n-1)).astype(jnp.float32)
    t1 = jax.lax.complex(jnp.cos((2.0*jnp.pi/n)*idx1),
                         jnp.sin((2.0*jnp.pi/n)*idx1))
    j2 = jnp.arange(64, dtype=jnp.int32)
    idx2 = ((k[None, :] * j2[:, None]) & jnp.int32(n-1)).astype(jnp.float32)
    t2 = jax.lax.complex(jnp.cos((2.0*jnp.pi/n)*idx2),
                         jnp.sin((2.0*jnp.pi/n)*idx2))
    ph = t1[sh // 64] * t2[sh % 64]   # [g, S, F]
    return ((X[None] * ph).sum(axis=1)).real

t = timeit(stage2_lut, Sr, Si, sh2) - overhead
print(f"stage2-lut x1      {t*1e3:8.1f} ms  -> x{G} = {t*G*1e3:8.1f} ms", file=sys.stderr)

from pypulsar_tpu.ops.pallas_kernels import boxcar_stats
ts_arr = jax.random.normal(key, (D, 123000), dtype=jnp.float32)
force(ts_arr[:1, :1])
for be in ("pallas", "lax"):
    try:
        # boxcar_stats is already jitted; re-wrapping would trace its
        # static kwargs as arguments
        fn = partial(boxcar_stats, widths=(1, 2, 4, 8, 16, 32),
                     stat_len=122850, backend=be)
        t = timeit(fn, ts_arr) - overhead
        print(f"boxcar-{be} [{D}]  {t*1e3:8.1f} ms "
              f"({2*4*D*123000/t/1e9:5.1f} GB/s)", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - pallas needs a real TPU
        print(f"boxcar-{be} unavailable: {type(e).__name__}", file=sys.stderr)

# full fourier chunk at the two-stage geometries the A/B grid covers
from pypulsar_tpu.parallel import make_sweep_plan
from pypulsar_tpu.parallel.sweep import sweep_chunk
dt = 64e-6
freqs = (1500.0 - 300.0 / C * np.arange(C)).astype(np.float64)
dms = np.linspace(0.0, 500.0, D)
for nsub2, group2 in ((64, 32), (32, 32), (64, 64)):
    plan = make_sweep_plan(dms, freqs, dt, nsub=nsub2, group_size=group2)
    chunk = n - plan.min_overlap
    out_len = chunk + max(plan.widths)
    need = out_len + plan.max_shift2 + plan.max_shift1
    d2 = jax.random.normal(key, (C, need), dtype=jnp.float32)
    s1 = jnp.asarray(plan.stage1_bins)
    s2 = jnp.asarray(plan.stage2_bins)
    force(d2[:1, :1])
    fn = lambda: sweep_chunk(d2, s1, s2, plan.nsub, out_len,
                             plan.max_shift2, plan.widths, chunk,
                             engine="fourier")
    force(fn())
    t0 = time.perf_counter(); force(fn()); el = time.perf_counter() - t0
    print(f"chunk-fourier s{nsub2} g{group2}  {el*1e3:8.1f} ms "
          f"({D/el:7.1f} trials/s/chunk)", file=sys.stderr)

# accelsearch subharmonic stretch-gather at the batched stage geometry
# (VERDICT r5 item 5, accel slice): the stage runner's plane build ends in
# `jnp.take(p, idx, axis=2)` with a STATIC index vector shared by every
# (spectrum, z-row) — unlike the per-element generic gather that measured
# ~70M elem/s on this chip (the shift_channels 'rotate' cliff, BENCHNOTES
# r5), a shared last-axis index can lower as a vectorizable copy pattern.
# This measures which lowering the real shape actually gets; the verdict
# lands in the BENCHNOTES gather-audit table.
segw_a, La, Za, Ba = 1 << 14, 1 << 15, 201, 8
p_planes = jax.random.normal(key, (Ba, Za, 2 * La), dtype=jnp.float32)
for rho_num, rho_den in ((1, 2), (7, 8)):
    rf = rho_num / rho_den
    rel = np.floor(rf * np.arange(2 * segw_a) + 0.5).astype(np.int64)
    idx_a = jnp.asarray(((rel % 2) * La + rel // 2).astype(np.int32))
    force(p_planes[:1, :1, :1])
    t = timeit(jax.jit(lambda p, i: jnp.take(p, i, axis=2)),
               p_planes, idx_a) - overhead
    elems = Ba * Za * 2 * segw_a
    print(f"accel stretch-gather rho={rho_num}/{rho_den} "
          f"[{Ba},{Za},2x{segw_a} of {2*La}] {t*1e3:8.1f} ms  "
          f"{elems/t/1e6:8.1f}M elem/s", file=sys.stderr)
# reference point: the generic per-element gather formulation of the same
# stretch (index varies per row -> the cliff lowering), for the A/B
idx_rows = jnp.asarray(np.stack([
    ((np.floor(0.5 * (np.arange(2 * segw_a) + rr % 3) + 0.5)
      .astype(np.int64) % 2) * La
     + np.floor(0.5 * (np.arange(2 * segw_a) + rr % 3) + 0.5)
     .astype(np.int64) // 2).astype(np.int32)
    for rr in range(Za)]))
t = timeit(jax.jit(lambda p, i: jnp.take_along_axis(p, i[None], axis=2)),
           p_planes, idx_rows) - overhead
print(f"accel stretch per-row gather (cliff formulation)  {t*1e3:8.1f} ms  "
      f"{Ba*Za*2*segw_a/t/1e6:8.1f}M elem/s", file=sys.stderr)
