"""Acceleration-search engine tests: z-response physics, significance
calibration, injection recovery (tone, drifting tone, pulse train, binary
orbit -> (P, Pdot)), and the CLI end-to-end loop into plot_accelcands.

Ground truth is direct synthesis (DFT of chirps / folded orbits), not
PRESTO: the reference repo contains no search engine to compare against
(it consumes PRESTO accelsearch output, bin/plot_accelcands.py:50-71)."""

import os

import numpy as np
import pytest

from pypulsar_tpu.fourier.accelsearch import (
    AccelSearchConfig,
    accel_search,
    candidate_sigma,
    equivalent_gaussian_sigma,
    power_threshold,
)
from pypulsar_tpu.fourier.zresponse import template_bank, z_halfwidth, z_response


# ---------------------------------------------------------------------------
# z-response physics
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("z", [0.0, 3.0, 17.0, 60.0, -25.0])
def test_z_response_matches_direct_dft(z):
    """The Fresnel-integral response reproduces the DFT of a chirp."""
    N = 1 << 14
    r0 = 3000.25
    t = np.arange(N) / N
    sig = np.exp(2j * np.pi * (r0 * t + z * t * t / 2))
    X = np.fft.fft(sig)
    offs = np.arange(-80, 80, dtype=float)
    bins = (np.round(r0) + offs).astype(int)
    pred = N * z_response(z, bins - r0)
    err = np.abs(pred - X[bins]).max() / np.abs(X[bins]).max()
    assert err < 2e-3


def test_template_bank_unit_energy_and_matched_peak():
    """Templates are unit-energy; correlating a chirp spectrum with the
    matched template peaks at the mid-drift frequency and recovers >80%
    of the total signal power."""
    N = 1 << 16
    z = 60.0
    r0 = 20000.3
    t = np.arange(N) / N
    sig = np.exp(2j * np.pi * (r0 * t + z * t * t / 2))
    X = np.fft.fft(sig) / np.sqrt(N)  # total signal power N -> sum|X|^2 = N
    tb, hw = template_bank(np.array([z]), numbetween=2)
    np.testing.assert_allclose(
        np.sum(np.abs(tb) ** 2, axis=1), 1.0, rtol=1e-9)
    row = tb[0]
    rhats = np.arange(19990, 20070)
    C = np.array([np.sum(X[rh - hw:rh + hw] * row) for rh in rhats])
    P = np.abs(C) ** 2
    r_mid = r0 + z / 2
    assert abs(rhats[P.argmax()] - r_mid) <= 1.0
    # matched filter recovers most of the power (integer-grid sampling of
    # a fractional-bin signal costs ~25%; interbinning recovers it in the
    # real search)
    assert P.max() > 0.7 * N


def test_z_halfwidth_covers_support():
    for z in (0.0, 50.0, 200.0, -120.0):
        hw = z_halfwidth(z)
        offs = np.arange(-hw, hw, dtype=float) + z / 2
        resp = z_response(z, offs)
        assert np.sum(np.abs(resp) ** 2) > 0.95 * max(abs(z) / 2, 1.0) * (
            2.0 / max(abs(z), 2.0))  # most of the energy is inside


# ---------------------------------------------------------------------------
# significance calibration
# ---------------------------------------------------------------------------


def test_equivalent_gaussian_sigma_roundtrip():
    from scipy.special import log_ndtr

    for sigma in (1.0, 3.0, 8.0, 20.0, 38.0):
        logp = float(log_ndtr(-sigma))
        assert abs(equivalent_gaussian_sigma(logp) - sigma) < 1e-6


def test_power_threshold_inverts_candidate_sigma():
    for numsum in (1, 2, 4, 8):
        for sigma in (2.0, 5.0):
            p = power_threshold(sigma, numsum, numindep=1e5)
            back = candidate_sigma(p, numsum, numindep=1e5)
            assert abs(back - sigma) < 1e-3


def test_noise_false_alarm_rate():
    """Pure noise yields ~no candidates above 4 sigma."""
    rng = np.random.RandomState(42)
    N = 1 << 15
    ts = rng.standard_normal(N)
    fft = np.fft.rfft(ts) / np.sqrt(N)
    cands = accel_search(fft, 30.0, AccelSearchConfig(
        zmax=20.0, dz=2.0, numharm=2, sigma_min=4.0, seg_width=1 << 12))
    assert len(cands) <= 1  # P(any 4-sigma FA) is a few percent


def test_batched_search_matches_serial():
    """accel_search_batch == [accel_search(f) for f] candidate-for-
    candidate (VERDICT r3 item 2): the template banks are DM-independent,
    so batching B spectra into one dispatch per stage must change no
    result."""
    from pypulsar_tpu.fourier.accelsearch import accel_search_batch

    rng = np.random.RandomState(7)
    N = 1 << 14
    T = N * 2 * 128e-6
    cfg = AccelSearchConfig(zmax=20.0, dz=2.0, numharm=4, sigma_min=2.5,
                            seg_width=1 << 12)
    ffts = []
    for b in range(3):
        ts = rng.standard_normal(2 * N).astype(np.float32)
        ts += 0.15 * np.sin(2 * np.pi * (40.0 + 13.0 * b)
                            * np.arange(2 * N) * 128e-6)
        ffts.append((np.fft.rfft(ts) / np.sqrt(2 * N))
                    .astype(np.complex64)[:N])
    serial = [accel_search(f, T, cfg) for f in ffts]
    batch = accel_search_batch(np.stack(ffts), T, cfg)
    assert [len(s) for s in serial] == [len(b) for b in batch]
    for s, bt in zip(serial, batch):
        assert s, "injection not detected"
        for cs, cb in zip(s, bt):
            assert abs(cs.r - cb.r) < 1e-6
            assert abs(cs.z - cb.z) < 1e-6
            assert abs(cs.power - cb.power) < 1e-3
            assert cs.numharm == cb.numharm


def test_batched_search_chunked_matches_unchunked():
    """A tiny HBM budget forces accel_search_batch to process the batch
    in per-stage chunks (the axon worker hard-crashes on oversized
    allocations, so the budget is enforced analytically up front);
    chunking must change no candidate."""
    from pypulsar_tpu.fourier.accelsearch import accel_search_batch

    rng = np.random.RandomState(11)
    N = 1 << 13
    T = N * 2 * 128e-6
    cfg = AccelSearchConfig(zmax=20.0, dz=2.0, numharm=2, sigma_min=2.5,
                            seg_width=1 << 11)
    ffts = []
    for b in range(3):
        ts = rng.standard_normal(2 * N).astype(np.float32)
        ts += 0.2 * np.sin(2 * np.pi * (60.0 + 11.0 * b)
                           * np.arange(2 * N) * 128e-6)
        ffts.append((np.fft.rfft(ts) / np.sqrt(2 * N))
                    .astype(np.complex64)[:N])
    ffts = np.stack(ffts)
    whole = accel_search_batch(ffts, T, cfg)
    chunked = accel_search_batch(ffts, T, cfg, hbm_budget_bytes=1)  # chunk=1
    assert [len(w) for w in whole] == [len(c) for c in chunked]
    for w, c in zip(whole, chunked):
        for cw, cc in zip(w, c):
            # chunk-size-dependent XLA fusion moves powers by last-ulp
            # amounts, which the parabola refinement amplifies to ~1e-6
            # in (r, z) — physically meaningless at dz=2
            assert abs(cw.r - cc.r) < 1e-5
            assert abs(cw.z - cc.z) < 1e-5
            assert abs(cw.power - cc.power) < 1e-3


def test_batched_search_sharded_matches_unsharded():
    """The shard_map'd batch runner (batch axis over the 'dm' mesh axis)
    reproduces the single-device batched result on the virtual CPU mesh."""
    import jax

    from pypulsar_tpu.fourier.accelsearch import accel_search_batch

    if len(jax.devices()) < 4:
        import pytest

        pytest.skip("needs >= 4 virtual devices")
    rng = np.random.RandomState(8)
    N = 1 << 13
    T = N * 2 * 128e-6
    cfg = AccelSearchConfig(zmax=20.0, dz=2.0, numharm=2, sigma_min=2.5,
                            seg_width=1 << 11)
    ffts = []
    for b in range(4):
        ts = rng.standard_normal(2 * N).astype(np.float32)
        ts += 0.2 * np.sin(2 * np.pi * (50.0 + 9.0 * b)
                           * np.arange(2 * N) * 128e-6)
        ffts.append((np.fft.rfft(ts) / np.sqrt(2 * N))
                    .astype(np.complex64)[:N])
    ffts = np.stack(ffts)
    plain = accel_search_batch(ffts, T, cfg)
    sharded = accel_search_batch(ffts, T, cfg, mesh_devices=4)
    assert [len(p) for p in plain] == [len(s) for s in sharded]
    for p, s in zip(plain, sharded):
        for cp, cs in zip(p, s):
            assert abs(cp.r - cs.r) < 1e-5
            assert abs(cp.power - cs.power) < 1e-2


# ---------------------------------------------------------------------------
# injection recovery
# ---------------------------------------------------------------------------


def test_recover_constant_tone():
    rng = np.random.RandomState(0)
    N = 1 << 16
    T = 32.0
    t = np.arange(N) * (T / N)
    f0 = 37.61
    ts = rng.standard_normal(N) + 0.12 * np.cos(2 * np.pi * f0 * t)
    fft = np.fft.rfft(ts) / np.sqrt(N)
    cands = accel_search(fft, T, AccelSearchConfig(
        zmax=20.0, dz=2.0, numharm=1, sigma_min=4.0, seg_width=1 << 12))
    assert cands, "tone not detected"
    best = cands[0]
    assert abs(best.freq(T) - f0) < 0.5 / T
    assert abs(best.z) <= 2.0


def test_recover_drifting_tone_r_and_z():
    rng = np.random.RandomState(1)
    N = 1 << 17
    T = 64.0
    t = np.arange(N) * (T / N)
    f0 = 113.37
    z_true = 60.0
    fdot = z_true / T ** 2
    ts = rng.standard_normal(N) + 0.1 * np.cos(
        2 * np.pi * (f0 * t + 0.5 * fdot * t * t))
    fft = np.fft.rfft(ts) / np.sqrt(N)
    cands = accel_search(fft, T, AccelSearchConfig(
        zmax=100.0, dz=2.0, numharm=1, sigma_min=4.0, seg_width=1 << 13))
    assert cands
    best = cands[0]
    r_mid = (f0 + 0.5 * fdot * T) * T
    assert abs(best.r - r_mid) < 1.0
    assert abs(best.z - z_true) <= 2.0
    # a zero-drift search at the same threshold must do worse on this signal
    c0 = accel_search(fft, T, AccelSearchConfig(
        zmax=0.0, dz=2.0, numharm=1, sigma_min=2.0, seg_width=1 << 13))
    p0 = max((c.power for c in c0 if abs(c.r - r_mid) < 40), default=0.0)
    assert best.power > 2.0 * p0


def test_harmonic_summing_beats_fundamental():
    """A narrow pulse train is found at higher significance by the H=8
    stage than by the fundamental alone, at the right frequency."""
    rng = np.random.RandomState(2)
    N = 1 << 17
    T = 64.0
    t = np.arange(N) * (T / N)
    P = 0.0737
    phase = (t / P) % 1.0
    prof = np.exp(-0.5 * ((phase - 0.3) / 0.02) ** 2)
    ts = rng.standard_normal(N) + 0.22 * prof
    fft = np.fft.rfft(ts) / np.sqrt(N)
    cands = accel_search(fft, T, AccelSearchConfig(
        zmax=20.0, dz=2.0, numharm=8, sigma_min=4.0, seg_width=1 << 13))
    assert cands
    best = cands[0]
    assert best.numharm == 8
    assert abs(best.freq(T) - 1.0 / P) < 1.0 / T
    f1 = [c for c in cands if c.numharm == 1
          and abs(c.freq(T) - 1.0 / P) < 2.0 / T]
    best_f1 = max((c.sigma for c in f1), default=0.0)
    assert best.sigma > best_f1


def test_recover_binary_p_and_pdot():
    """Inject a pulsar in a (locally linear) binary orbit; recover its
    apparent spin period and period derivative from (r, z)."""
    rng = np.random.RandomState(3)
    N = 1 << 17
    T = 512.0  # long integration so the drift spans many Fourier bins
    t = np.arange(N) * (T / N)
    f0 = 97.3  # Hz (Nyquist here is 128 Hz)
    # orbital line-of-sight acceleration: fdot = -f0 * a / c
    a_los = 500.0  # m/s^2 (tight compact binary near periastron)
    c = 299792458.0
    fdot = -f0 * a_los / c  # -1.62e-4 Hz/s -> z = fdot*T^2 = -42.5
    z_true = fdot * T * T
    ts = rng.standard_normal(N) + 0.1 * np.cos(
        2 * np.pi * (f0 * t + 0.5 * fdot * t * t))
    fft = np.fft.rfft(ts) / np.sqrt(N)
    cands = accel_search(fft, T, AccelSearchConfig(
        zmax=100.0, dz=2.0, numharm=1, sigma_min=4.0, seg_width=1 << 13))
    assert cands
    best = cands[0]
    f_mid_true = f0 + 0.5 * fdot * T
    f_rec = best.freq(T)
    fdot_rec = best.fdot(T)
    assert abs(f_rec - f_mid_true) < 0.5 / T
    assert abs(best.z - z_true) <= 2.0
    # period and period derivative: P = 1/f, Pdot = -fdot/f^2
    P_rec = 1.0 / f_rec
    Pdot_rec = -fdot_rec / f_rec ** 2
    P_true = 1.0 / f_mid_true
    Pdot_true = -fdot / f_mid_true ** 2
    assert abs(P_rec - P_true) / P_true < 1e-4
    assert abs(Pdot_rec - Pdot_true) / abs(Pdot_true) < 0.05
    # implied line-of-sight acceleration comes back out
    a_rec = -fdot_rec * c / f_rec
    assert abs(a_rec - a_los) / a_los < 0.05


# ---------------------------------------------------------------------------
# CLI end-to-end: accelsearch -> .cand -> plot_accelcands
# ---------------------------------------------------------------------------


def _write_fake_dat(base, ts, dt, obj="FAKE", dm=None):
    """One .dat + .inf pair with the standard fake-observatory header —
    the single place the CLI tests' fixture schema lives."""
    from pypulsar_tpu.io.datfile import write_dat
    from pypulsar_tpu.io.infodata import InfoData

    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = dt
    inf.N = len(ts)
    if dm is not None:
        inf.DM = dm
    inf.telescope = "Fake"
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 1
    inf.chan_width = 100.0
    inf.object = obj
    write_dat(base, ts, inf)
    return base


def test_cli_accelsearch_to_plot_accelcands(tmp_path, monkeypatch):
    import matplotlib

    matplotlib.use("Agg", force=True)
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.cli import plot_accelcands as cli_plot
    from pypulsar_tpu.io.prestocand import read_rzwcands

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(4)
    N = 1 << 16
    dt = 5e-4
    T = N * dt
    t = np.arange(N) * dt
    f0 = 43.21
    inffns = []
    for ii in range(3):
        ts = rng.standard_normal(N).astype(np.float32)
        ts += 0.15 * np.cos(2 * np.pi * f0 * t).astype(np.float32)
        base = _write_fake_dat(str(tmp_path / f"beam{ii}"), ts, dt)
        inffns.append(base + ".inf")
        rc = cli_accel.main([base + ".dat", "-z", "0", "-n", "1",
                             "-s", "4"])
        assert rc == 0
        cands = read_rzwcands(base + "_ACCEL_0.cand")
        assert cands, "no candidates written"
        assert abs(cands[0].r / T - f0) < 1.0 / T
        assert os.path.exists(base + "_ACCEL_0.txtcand")

    # the clustering tool consumes our own pipeline's candidate files
    out = str(tmp_path / "cands.png")
    rc = cli_plot.main(inffns + ["-o", out, "--min-hits", "2"])
    assert rc == 0
    assert os.path.exists(out)


# ---------------------------------------------------------------------------
# jerk (w) search
# ---------------------------------------------------------------------------


def test_numeric_template_matches_analytic_at_w0():
    """FFT-synthesized templates reproduce the Fresnel-integral responses
    (independent validation paths agree)."""
    from pypulsar_tpu.fourier.zresponse import _numeric_response

    offs = np.arange(-60, 60, 0.5)
    for z in (0.0, 10.0, 60.0, -30.0):
        a = z_response(z, offs + z / 2.0)
        b = _numeric_response(z, 0.0, offs)
        assert np.abs(a - b).max() < 2e-3


def test_recover_jerk_signal_w_dimension():
    """A signal with second-order drift is recovered at the right (r, z, w)
    by the jerk search, and at much higher power than the z-only search."""
    rng = np.random.RandomState(9)
    N = 1 << 17
    T = 64.0
    t = np.arange(N) * (T / N)
    f0 = 151.31
    z_true, w_true = 20.0, 120.0
    fdot = z_true / T ** 2
    fddot = w_true / T ** 3
    ts = rng.standard_normal(N) + 0.12 * np.cos(
        2 * np.pi * (f0 * t + fdot * t * t / 2 + fddot * t ** 3 / 6))
    fft = np.fft.rfft(ts) / np.sqrt(N)

    cfg_w = AccelSearchConfig(zmax=40.0, dz=2.0, numharm=1, sigma_min=4.0,
                              seg_width=1 << 13, wmax=160.0, dw=40.0)
    cands = accel_search(fft, T, cfg_w)
    assert cands
    best = cands[0]
    f_mean_true = f0 + fdot * T / 2 + fddot * T * T / 6
    assert abs(best.freq(T) - f_mean_true) < 1.0 / T
    assert abs(best.z - z_true) <= cfg_w.dz + 1.0
    assert abs(best.w - w_true) <= cfg_w.dw
    assert abs(best.fddot(T) - fddot) <= cfg_w.dw / T ** 3

    cfg_z = AccelSearchConfig(zmax=40.0, dz=2.0, numharm=1, sigma_min=3.0,
                              seg_width=1 << 13)
    c_z = accel_search(fft, T, cfg_z)
    p_z = max((c.power for c in c_z
               if abs(c.freq(T) - f_mean_true) < 60.0 / T), default=0.0)
    assert best.power > 1.5 * p_z  # jerk templates recover what z-only loses


def test_cli_sift_clusters_across_dms(tmp_path, monkeypatch):
    """Per-DM accelsearch outputs sift into one .accelcands candidate that
    peaks at the injected DM, parseable by the reference-format reader."""
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.cli import sift as cli_sift
    from pypulsar_tpu.io.accelcands import parse_candlist

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(17)
    N, dt = 1 << 15, 1e-3
    T = N * dt
    t = np.arange(N) * dt
    f0 = 29.17
    candfns = []
    # simulate three DM trials: signal strongest at the middle one
    for dm, amp in ((38.0, 0.12), (40.0, 0.3), (42.0, 0.12)):
        ts = rng.standard_normal(N).astype(np.float32)
        ts += amp * np.cos(2 * np.pi * f0 * t).astype(np.float32)
        base = _write_fake_dat(str(tmp_path / f"s_DM{dm:.2f}"), ts, dt,
                               obj="SIFT", dm=dm)
        rc = cli_accel.main([base + ".dat", "-z", "0", "-n", "1", "-s", "4"])
        assert rc == 0
        candfns.append(base + "_ACCEL_0.cand")

    out = str(tmp_path / "sifted.accelcands")
    rc = cli_sift.main(candfns + ["-o", out, "--min-hits", "2"])
    assert rc == 0
    cands = parse_candlist(out)
    assert cands, "no sifted candidates"
    best = cands[0]
    assert abs(1.0 / best.period - f0) < 1.0 / T
    assert best.dm == 40.0  # strongest trial wins the cluster
    assert len(best.dmhits) == 3
    hit_dms = sorted(h.dm for h in best.dmhits)
    assert hit_dms == [38.0, 40.0, 42.0]


def test_full_pipeline_fil_to_sifted_accelcands(tmp_path, monkeypatch):
    """The complete periodicity pipeline on one synthetic observation:
    .fil -> DM sweep (--write-dats) -> per-DM accelsearch -> sift ->
    .accelcands, recovering the injected (period, DM)."""
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.cli import sift as cli_sift
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.io.accelcands import parse_candlist
    from pypulsar_tpu.ops import numpy_ref

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(23)
    C, T, dt = 32, 1 << 15, 1e-3
    dm_true, f0 = 40.0, 23.31
    freqs = 1500.0 - 4.0 * np.arange(C)
    tsec = np.arange(T) * dt
    delays = numpy_ref.bin_delays(dm_true, freqs, dt) * dt
    data = rng.randn(T, C).astype(np.float32)
    for c in range(C):
        data[:, c] += 0.35 * np.cos(
            2 * np.pi * f0 * (tsec - delays[c])).astype(np.float32)
    hdr = dict(nchans=C, tsamp=dt, fch1=1500.0, foff=-4.0, tstart=55000.0,
               nbits=32, nifs=1, source_name="PIPE")
    filterbank.write_filterbank("obs.fil", hdr, data)

    rc = cli_sweep.main(["obs.fil", "-o", "obs", "--lodm", "32",
                         "--dmstep", "4", "--numdms", "5", "-s", "8",
                         "--group-size", "4", "--write-dats"])
    assert rc == 0
    candfns = []
    for dm in (32.0, 36.0, 40.0, 44.0, 48.0):
        datfn = f"obs_DM{dm:.2f}.dat"
        assert os.path.exists(datfn)
        rc = cli_accel.main([datfn, "-z", "0", "-n", "4", "-s", "3"])
        assert rc == 0
        candfns.append(f"obs_DM{dm:.2f}_ACCEL_0.cand")
    rc = cli_sift.main(candfns + ["-o", "obs.accelcands", "--min-hits", "2"])
    assert rc == 0
    cands = parse_candlist("obs.accelcands")
    assert cands
    best = cands[0]
    Tobs = T * dt
    assert abs(1.0 / best.period - f0) < 1.5 / Tobs
    assert abs(best.dm - dm_true) <= 4.0  # cluster peaks at the true DM
    assert len(best.dmhits) >= 3  # seen across neighbouring trials


# ---------------------------------------------------------------------------
# coarse-to-fine z search (VERDICT r4 item 1 stretch)
# ---------------------------------------------------------------------------


def test_coarse_grid_power_retention():
    """Calibration behind AccelSearchConfig.coarse_power_frac: a template
    one fine step (dz=2) off in z keeps ~95% of the matched power and one
    coarse step (2*dz -> worst mismatch 2 bins) keeps ~80%, independent
    of z — so a coarse pass thresholded at 0.7x the fine threshold
    cannot lose a fine-grid detection."""
    for z in (0.0, 50.0, 200.0):
        ret = []
        for dz in (1.0, 2.0):
            tb, _hw = template_bank(np.array([z, z + dz]), numbetween=2)
            a, b = tb[0], tb[2]  # integer-phase rows at z and z+dz
            num = np.abs(np.vdot(b, a)) ** 2
            den = np.vdot(a, a).real * np.vdot(b, b).real
            ret.append(num / den)
        assert ret[0] > 0.93  # fine-grid worst case (|dz/2| = 1 mismatch)
        assert ret[1] > 0.78  # coarse-grid worst case (2-bin mismatch)


def _drifting_train(rng, N, T, f0, z_true, amp=1.2, width_frac=0.05):
    """Noisy pulse train whose fundamental drifts z_true bins over T."""
    t = np.arange(N) * (T / N)
    fdot = z_true / T ** 2
    phase = (f0 * t + 0.5 * fdot * t * t) % 1.0
    ts = rng.standard_normal(N) + amp * (phase < width_frac)
    return (np.fft.rfft(ts) / np.sqrt(N)).astype(np.complex64)


def _cand_key(cands):
    return [(round(c.r, 4), round(c.z, 4), round(c.power, 2), c.numharm)
            for c in cands]


def test_coarse_fine_matches_full_serial():
    """coarse_dz preselection returns the identical candidate list: the
    fine pass re-evaluates selected segments with the same compiled
    stage program, so any difference would mean a segment was missed.
    z_true sits mid-between coarse grid points (worst mismatch)."""
    rng = np.random.RandomState(3)
    N = 1 << 16
    T = 64.0
    fft = _drifting_train(rng, N, T, f0=87.31, z_true=22.0)
    cfg = AccelSearchConfig(zmax=40.0, dz=2.0, numharm=4, sigma_min=3.0,
                            seg_width=1 << 12)
    full = accel_search(fft, T, cfg)
    cf = accel_search(
        fft, T, AccelSearchConfig(
            zmax=40.0, dz=2.0, numharm=4, sigma_min=3.0,
            seg_width=1 << 12, coarse_dz=4.0))
    assert full, "injection not detected"
    assert _cand_key(cf) == _cand_key(full)
    best = cf[0]
    assert abs(best.z - 22.0) <= 2.0


def test_coarse_fine_matches_full_batch():
    """The batched driver's coarse pass (hit-segment union over the
    batch) also reproduces the single-pass batched result."""
    from pypulsar_tpu.fourier.accelsearch import accel_search_batch

    rng = np.random.RandomState(5)
    N = 1 << 14
    T = 32.0
    ffts = np.stack([
        _drifting_train(rng, N, T, f0=61.0 + 7.0 * b, z_true=10.0)
        for b in range(3)])
    base = dict(zmax=20.0, dz=2.0, numharm=2, sigma_min=3.0,
                seg_width=1 << 12)
    full = accel_search_batch(ffts, T, AccelSearchConfig(**base))
    cf = accel_search_batch(
        ffts, T, AccelSearchConfig(**base, coarse_dz=4.0))
    assert any(full), "injection not detected"
    for f, c in zip(full, cf):
        assert _cand_key(c) == _cand_key(f)


def test_coarse_config_validation():
    """Out-of-regime coarse settings warn (no-op grid, uncalibrated
    spacing) or raise (bad threshold fraction) instead of silently
    degrading recall."""
    with pytest.warns(UserWarning, match="no effect"):
        AccelSearchConfig(dz=2.0, coarse_dz=2.0)
    with pytest.warns(UserWarning, match="no effect"):
        AccelSearchConfig(dz=2.0, coarse_dz=-4.0)  # sign slip
    with pytest.warns(UserWarning, match="retention"):
        AccelSearchConfig(dz=2.0, coarse_dz=8.0)
    with pytest.raises(ValueError):
        AccelSearchConfig(coarse_power_frac=0.0)


def test_coarse_fine_sharded_matches_sharded_single_pass():
    """coarse_dz composes with mesh sharding: the coarse pass and the
    refine pass both shard_map over the 'dm' axis and the result matches
    the sharded single-pass search."""
    import jax

    from pypulsar_tpu.fourier.accelsearch import accel_search_batch

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 virtual devices")
    rng = np.random.RandomState(6)
    N = 1 << 13
    T = 16.0
    ffts = np.stack([
        _drifting_train(rng, N, T, f0=71.0 + 5.0 * b, z_true=6.0)
        for b in range(4)])
    base = dict(zmax=12.0, dz=2.0, numharm=2, sigma_min=3.0,
                seg_width=1 << 11)
    single = accel_search_batch(ffts, T, AccelSearchConfig(**base),
                                mesh_devices=4)
    cf = accel_search_batch(ffts, T,
                            AccelSearchConfig(**base, coarse_dz=4.0),
                            mesh_devices=4)
    assert any(single), "injection not detected"
    for s, c in zip(single, cf):
        assert _cand_key(c) == _cand_key(s)


# ---------------------------------------------------------------------------
# device-side batched spectrum prep (rfft + deredden fused on device)
# ---------------------------------------------------------------------------


def test_prep_spectra_batch_matches_host_prep():
    """kernels.prep_spectra_batch (f32 device rfft + vmapped deredden)
    reproduces the CLI host path (f64 np.fft.rfft -> kernels.deredden)
    within the documented 2e-6 relative SNR contract, and
    accel_search_batch consumes the plane tuple directly with the same
    candidates as the host-prepped complex batch."""
    from pypulsar_tpu.fourier.accelsearch import accel_search_batch
    from pypulsar_tpu.fourier.kernels import deredden, prep_spectra_batch

    rng = np.random.RandomState(11)
    n = 1 << 15
    dt = 2.5e-4
    T = n * dt
    series = []
    for b in range(3):
        ts = rng.standard_normal(n).astype(np.float32)
        ts += 0.2 * np.sin(2 * np.pi * (37.0 + 9.0 * b)
                           * np.arange(n) * dt).astype(np.float32)
        series.append(ts)
    series = np.stack(series)

    re, im = prep_spectra_batch(series)
    dev = np.asarray(re) + 1j * np.asarray(im)
    host = np.stack([
        np.asarray(deredden(np.fft.rfft(s).astype(np.complex64)))
        for s in series])
    assert dev.shape == host.shape == (3, n // 2 + 1)
    # normalized-spectrum agreement away from the (unit-set) DC bin
    scale = np.abs(host).max()
    assert np.abs(dev - host).max() / scale < 2e-5

    cfg = AccelSearchConfig(zmax=20.0, dz=2.0, numharm=4, sigma_min=3.0,
                            seg_width=1 << 12)
    from_host = accel_search_batch(host, T, cfg)
    from_dev = accel_search_batch((re, im), T, cfg)
    assert [len(c) for c in from_host] == [len(c) for c in from_dev]
    for hs, ds in zip(from_host, from_dev):
        assert hs, "injection not detected"
        for ch, cd in zip(hs, ds):
            # r/z are sub-grid refined continuous values: f32-vs-f64 prep
            # noise moves them at the ~1e-7 level, not the grid cell
            assert abs(ch.r - cd.r) < 1e-3
            assert abs(ch.z - cd.z) < 1e-3
            assert ch.numharm == cd.numharm
            assert abs(ch.sigma - cd.sigma) <= 1e-3


def test_prep_spectra_batch_large_mean_parity():
    """A +1000-count DC offset (8-bit data sits far above zero) must not
    degrade the device prep: the per-series mean is subtracted on device
    before the f32 rfft (deredden overwrites bin 0 anyway, so the exact
    result is unchanged), keeping the f32 butterflies at fluctuation
    scale — same tolerance as the zero-mean parity test (ADVICE r5)."""
    from pypulsar_tpu.fourier.kernels import deredden, prep_spectra_batch

    rng = np.random.RandomState(17)
    n = 1 << 14
    dt = 2.5e-4
    series = []
    for b in range(2):
        ts = rng.standard_normal(n).astype(np.float32)
        ts += 0.2 * np.sin(2 * np.pi * (23.0 + 11.0 * b)
                           * np.arange(n) * dt).astype(np.float32)
        ts += 1000.0  # the large-mean regime the fix targets
        series.append(ts)
    series = np.stack(series)

    re, im = prep_spectra_batch(series)
    dev = np.asarray(re) + 1j * np.asarray(im)
    # host reference: f64 rfft (no DC-rounding problem) -> deredden
    host = np.stack([
        np.asarray(deredden(np.fft.rfft(s.astype(np.float64))
                            .astype(np.complex64)))
        for s in series])
    assert dev.shape == host.shape == (2, n // 2 + 1)
    scale = np.abs(host[:, 1:]).max()
    assert np.abs(dev[:, 1:] - host[:, 1:]).max() / scale < 2e-5
    assert np.allclose(dev[:, 0], 1.0)  # deredden's unit DC bin


def test_cli_device_prep_requires_batch(tmp_path):
    """--device-prep with --batch < 2 is a hard CLI error instead of a
    silent no-op (device prep only exists on the grouped batch path)."""
    import pytest

    from pypulsar_tpu.cli import accelsearch as cli_accel

    with pytest.raises(SystemExit) as exc:
        cli_accel.main([str(tmp_path / "x.dat"), "--device-prep"])
    assert exc.value.code == 2  # argparse error exit
    with pytest.raises(SystemExit) as exc:
        cli_accel.main([str(tmp_path / "x.dat"), "--device-prep",
                        "--batch", "1"])
    assert exc.value.code == 2


def test_cli_device_prep_matches_host_prep(tmp_path, monkeypatch):
    """cli accelsearch --batch --device-prep finds the same candidates
    as the default host-prep batch path on the same .dats."""
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.io.prestocand import read_rzwcands

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(12)
    N = 1 << 15
    dt = 5e-4
    bases = []
    for ii in range(3):
        ts = rng.standard_normal(N).astype(np.float32)
        ts += 0.2 * np.cos(2 * np.pi * (41.0 + 7.0 * ii)
                           * np.arange(N) * dt).astype(np.float32)
        bases.append(_write_fake_dat(str(tmp_path / f"dp{ii}"), ts, dt))

    dats = [b + ".dat" for b in bases]
    # --no-device-prep: device prep is DEFAULT-ON for --batch >= 2 since
    # round 6, so the host-prep reference side must opt out explicitly
    rc = cli_accel.main(dats + ["--batch", "3", "-z", "20", "-n", "2",
                                "-s", "3", "--no-device-prep"])
    assert rc == 0
    host_cands = {b: read_rzwcands(b + "_ACCEL_20.cand") for b in bases}
    for b in bases:
        os.remove(b + "_ACCEL_20.cand")
    rc = cli_accel.main(dats + ["--batch", "3", "-z", "20", "-n", "2",
                                "-s", "3", "--device-prep"])
    assert rc == 0
    for b in bases:
        dev = read_rzwcands(b + "_ACCEL_20.cand")
        host = host_cands[b]
        assert host, "no candidates from host prep"
        assert len(dev) == len(host)
        for ch, cd in zip(host, dev):
            assert abs(ch.r - cd.r) < 1e-3
            assert abs(ch.z - cd.z) < 1e-3
            assert abs(ch.sig - cd.sig) < 1e-3


def test_cli_device_prep_hbm_cap_chunks_prep(tmp_path, monkeypatch):
    """A tiny PYPULSAR_TPU_ACCEL_HBM forces the device-prep flush to prep
    the group in budget-bounded slices (cap = budget // (24 * n)); the
    candidates must not change. Guards the review fix that stops a large
    --batch from out-allocating the search's own HBM budget during prep."""
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.io.prestocand import read_rzwcands

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(13)
    N = 1 << 14
    dt = 5e-4
    bases = []
    for ii in range(4):
        ts = rng.standard_normal(N).astype(np.float32)
        ts += 0.25 * np.cos(2 * np.pi * (29.0 + 5.0 * ii)
                            * np.arange(N) * dt).astype(np.float32)
        bases.append(_write_fake_dat(str(tmp_path / f"cap{ii}"), ts, dt))
    dats = [b + ".dat" for b in bases]
    argv = dats + ["--batch", "4", "-z", "10", "-n", "1", "-s", "3",
                   "--device-prep"]

    # count prep dispatches through the symbol the CLI resolves at call
    # time, so the test FAILS if the cap slicing is removed
    from pypulsar_tpu.fourier import kernels as _k

    calls = []
    real_prep = _k.prep_spectra_batch

    def spy(series, *a, **kw):
        calls.append(np.asarray(series).shape[0])
        return real_prep(series, *a, **kw)

    monkeypatch.setattr(_k, "prep_spectra_batch", spy)

    monkeypatch.delenv("PYPULSAR_TPU_ACCEL_HBM", raising=False)
    assert cli_accel.main(argv) == 0
    assert calls == [4], calls  # unbounded budget: one whole-group prep
    whole = {b: [(round(c.r, 3), round(c.z, 3))
                 for c in read_rzwcands(b + "_ACCEL_10.cand")]
             for b in bases}
    for b in bases:
        os.remove(b + "_ACCEL_10.cand")
    # budget small enough that cap = max(1, budget // (24 * N)) == 1:
    # every spectrum preps in its own slice
    calls.clear()
    monkeypatch.setenv("PYPULSAR_TPU_ACCEL_HBM", str(24 * N))
    assert cli_accel.main(argv) == 0
    assert calls == [1, 1, 1, 1], calls
    for b in bases:
        got = [(round(c.r, 3), round(c.z, 3))
               for c in read_rzwcands(b + "_ACCEL_10.cand")]
        assert got == whole[b]


def test_cli_device_prep_batch_failure_falls_back_serial(tmp_path,
                                                         monkeypatch):
    """A failing device-prep batched dispatch degrades to per-file serial
    HOST-prep searches (re-reading each .dat) instead of failing the
    group — the poison-spectrum contract of the batched CLI, extended to
    series-kind groups."""
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.io.prestocand import read_rzwcands

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(14)
    N = 1 << 14
    dt = 5e-4
    bases = []
    for ii in range(3):
        ts = rng.standard_normal(N).astype(np.float32)
        ts += 0.25 * np.cos(2 * np.pi * (31.0 + 4.0 * ii)
                            * np.arange(N) * dt).astype(np.float32)
        bases.append(_write_fake_dat(str(tmp_path / f"pf{ii}"), ts, dt))
    dats = [b + ".dat" for b in bases]

    from pypulsar_tpu.fourier import accelsearch as _accel_mod

    real_batch = _accel_mod.accel_search_batch
    boom = {"n": 0}

    def failing_batch(*a, **kw):
        boom["n"] += 1
        raise RuntimeError("synthetic batch failure")

    # the CLI imports accel_search_batch into its main() closure at call
    # time via `from ... import`, so patch the module attribute BEFORE
    # main() runs
    monkeypatch.setattr(_accel_mod, "accel_search_batch", failing_batch)
    rc = cli_accel.main(dats + ["--batch", "3", "-z", "10", "-n", "1",
                                "-s", "3", "--device-prep"])
    monkeypatch.setattr(_accel_mod, "accel_search_batch", real_batch)
    assert rc == 0 and boom["n"] >= 1
    fallback = {b: [(round(c.r, 3), round(c.z, 3))
                    for c in read_rzwcands(b + "_ACCEL_10.cand")]
                for b in bases}
    for b in bases:
        os.remove(b + "_ACCEL_10.cand")

    # reference: the healthy serial path on the same inputs
    rc = cli_accel.main(dats + ["-z", "10", "-n", "1", "-s", "3"])
    assert rc == 0
    for b in bases:
        got = [(round(c.r, 3), round(c.z, 3))
               for c in read_rzwcands(b + "_ACCEL_10.cand")]
        assert got == fallback[b], b


# ---------------------------------------------------------------------------
# the device-prep matched-candidate contract (VERDICT r5 item 2)
# ---------------------------------------------------------------------------


def _assert_candidate_contract(host_cands, dev_cands, floor, margin,
                               dr, dz, dsig):
    """The matched-candidate contract, as BENCHNOTES round-5 states it in
    prose for 53/64 files: every candidate above ``floor + margin`` on
    EITHER side has a partner on the other within (dr, dz, dsig), and no
    unpartnered candidate on either side exceeds ``floor + margin`` —
    i.e. device prep may flicker threshold-floor candidates but can
    neither gain nor lose an above-floor detection."""
    def matches(c, pool):
        return any(abs(c.r - o.r) < dr and abs(c.z - o.z) < dz
                   and abs(c.sigma - o.sigma) < dsig for o in pool)

    for a, b, side in ((host_cands, dev_cands, "host"),
                       (dev_cands, host_cands, "device")):
        for c in a:
            if not matches(c, b):
                assert c.sigma <= floor + margin, (
                    f"unmatched {side}-prep candidate above the "
                    f"floor+margin contract bound: r={c.r:.2f} "
                    f"z={c.z:.2f} sigma={c.sigma:.2f} "
                    f"(bound {floor + margin:.2f})")


def test_device_prep_candidate_contract():
    """Device-prep vs host-prep accel over a battery of synthetic
    spectra — constant tones, drifting tones, strong/weak/near-threshold
    amplitudes — asserting the matched-candidate contract that justifies
    flipping --device-prep default-on (VERDICT r5 item 2; documented in
    README next to the 2e-6 SNR contract)."""
    from pypulsar_tpu.fourier.accelsearch import accel_search_batch
    from pypulsar_tpu.fourier.kernels import (deredden, deredden_schedule,
                                              prep_spectra_batch)

    rng = np.random.RandomState(42)
    n = 1 << 15
    dt = 2.5e-4
    T = n * dt
    floor, margin = 3.0, 0.5
    cfg = AccelSearchConfig(zmax=20.0, dz=2.0, numharm=4, sigma_min=floor,
                            seg_width=1 << 12)
    t = np.arange(n) * dt
    battery = []
    # (f0 Hz, z bins over T, amplitude): strong, moderate, drifting both
    # ways, WEAK near the detection floor, and pure noise
    specs = [(37.0, 0.0, 0.30), (61.0, 0.0, 0.18),
             (43.0, 8.0, 0.25), (29.0, -12.0, 0.25),
             (53.0, 4.0, 0.10), (71.0, 0.0, 0.07),
             (47.0, 0.0, 0.0)]
    for f0, z, amp in specs:
        ts = rng.standard_normal(n).astype(np.float32)
        if amp > 0:
            fdot = z / (T * T)
            ts += amp * np.cos(2 * np.pi * (f0 * t
                                            + 0.5 * fdot * t * t)
                               ).astype(np.float32)
        battery.append(ts)
    series = np.stack(battery)

    schedule = deredden_schedule(n // 2 + 1)
    host = np.stack([
        np.asarray(deredden(np.fft.rfft(s).astype(np.complex64),
                            schedule=schedule))
        for s in series])
    host_out = accel_search_batch(host, T, cfg)
    dev_out = accel_search_batch(prep_spectra_batch(series, schedule),
                                 T, cfg)

    n_detecting = 0
    for hs, ds in zip(host_out, dev_out):
        _assert_candidate_contract(hs, ds, floor, margin,
                                   dr=0.5, dz=1.0, dsig=0.5)
        # count SPECTRA with an above-floor detection, not candidates:
        # one strong tone's harmonics must not mask the drifting/weak
        # spectra all going dark
        n_detecting += any(c.sigma > floor + margin for c in hs)
    assert n_detecting >= len(specs) - 2, \
        "battery too weak to exercise the contract"
