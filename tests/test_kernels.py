"""Golden-parity tests: JAX kernels vs NumPy twins (SURVEY.md §4 strategy 1).

The twins in ops/numpy_ref.py mirror reference formats/spectra.py semantics in
float64; the kernels run in float32 on device. Pure index-permutation ops must
match exactly; reduction-based ops to float32 tolerances.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pypulsar_tpu.ops import kernels, numpy_ref
from pypulsar_tpu.core.spectra import Spectra

RNG = np.random.RandomState(42)


def make_data(C=16, T=128):
    return RNG.randn(C, T).astype(np.float32)


def make_freqs(C=16, fch1=1500.0, foff=-1.0):
    return (fch1 + foff * np.arange(C)).astype(np.float64)


@pytest.mark.parametrize("padval", [0, 3.5, "mean", "median", "rotate"])
def test_shift_channels_parity(padval):
    data = make_data()
    bins = RNG.randint(-50, 50, size=16)
    ref = numpy_ref.shift_channels(data, bins, padval)
    got = np.asarray(kernels.shift_channels(jnp.asarray(data), jnp.asarray(bins), padval))
    if padval == "rotate" or isinstance(padval, (int, float)):
        # pure permutation + constant fill: exact
        np.testing.assert_array_equal(got.astype(np.float64), ref)
    else:
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("dm", [0.0, 12.3, 100.0, 496.9])
def test_dedisperse_parity(dm):
    data = make_data()
    freqs = make_freqs()
    ref = numpy_ref.dedisperse(data, freqs, 64e-6, dm)
    got = np.asarray(
        kernels.dedisperse_with_bins(
            jnp.asarray(data), jnp.asarray(numpy_ref.bin_delays(dm, freqs, 64e-6))
        )
    )
    np.testing.assert_array_equal(got.astype(np.float64), ref)


def test_bin_delays_device_vs_host():
    # device f32 delay math must agree with host f64 for realistic params
    freqs = make_freqs(1024, 1500.0, -0.3)
    for dm in [0.0, 3.7, 56.8, 212.0, 499.5]:
        host = numpy_ref.bin_delays(dm, freqs, 64e-6)
        dev = np.asarray(kernels.bin_delays(dm, jnp.asarray(freqs, jnp.float32), 64e-6))
        # f32 rounding can flip a bin near .5 boundaries; allow <=1 bin on <1% of chans
        diff = np.abs(host - dev)
        assert diff.max() <= 1
        assert (diff > 0).mean() < 0.01


@pytest.mark.parametrize("subdm", [None, 50.0])
def test_subband_parity(subdm):
    data = make_data(16, 128)
    freqs = make_freqs(16)
    ref, ref_ctr = numpy_ref.subband(data, freqs, 64e-6, 4, subdm)
    got, ctr = kernels.subband(jnp.asarray(data), jnp.asarray(freqs), 64e-6, 4, subdm)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ctr), ref_ctr, rtol=1e-6)


@pytest.mark.parametrize("factor", [1, 2, 5])
def test_downsample_parity(factor):
    data = make_data(4, 103)
    ref = numpy_ref.downsample(data, factor)
    got = np.asarray(kernels.downsample(jnp.asarray(data), factor))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("padval", [0, "mean", "median", "wrap"])
@pytest.mark.parametrize("width", [1, 4, 7])
def test_smooth_parity(width, padval):
    data = make_data(4, 64)
    ref = numpy_ref.smooth(data, width, padval)
    got = np.asarray(kernels.smooth(jnp.asarray(data), width, padval))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("indep", [False, True])
def test_scaled_parity(indep):
    data = make_data()
    np.testing.assert_allclose(
        np.asarray(kernels.scaled(jnp.asarray(data), indep)),
        numpy_ref.scaled(data, indep),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(kernels.scaled2(jnp.asarray(data), indep)),
        numpy_ref.scaled2(data, indep),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.parametrize("maskval", ["median", "mean", "median-mid80", 7.0])
def test_masked_parity(maskval):
    data = make_data(8, 100)
    mask = RNG.rand(8, 100) > 0.8
    ref = numpy_ref.masked(data, mask, maskval)
    got = np.asarray(kernels.masked(jnp.asarray(data), jnp.asarray(mask), maskval))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_zero_dm_parity():
    data = make_data()
    np.testing.assert_allclose(
        np.asarray(kernels.zero_dm(jnp.asarray(data))),
        numpy_ref.zero_dm(data),
        rtol=1e-5, atol=1e-5,
    )


def test_boxcar_snr_parity():
    ts = RNG.randn(512).astype(np.float32)
    ts[100:104] += 8.0
    widths = (1, 2, 4, 8)
    ref_snr, ref_idx = numpy_ref.boxcar_snr(ts, widths)
    snr, idx = kernels.boxcar_snr(jnp.asarray(ts), widths)
    np.testing.assert_allclose(np.asarray(snr), ref_snr, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(idx), ref_idx)


def test_dedispersed_timeseries_recovers_pulse():
    # inject a dispersed pulse; dedispersing at the true DM must align it
    C, T, dt, dm = 64, 2048, 64e-6, 30.0
    freqs = make_freqs(C, 1500.0, -2.0)
    data = RNG.randn(C, T).astype(np.float32) * 0.1
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    t0 = 300
    for c in range(C):
        data[c, (t0 + bins[c]) % T] += 5.0
    ts = np.asarray(kernels.dedispersed_timeseries(jnp.asarray(data), jnp.asarray(bins)))
    assert ts.argmax() == t0
    ref_ts = numpy_ref.dedispersed_timeseries(data, bins)
    np.testing.assert_allclose(ts, ref_ts, rtol=1e-4, atol=1e-3)


class TestSpectra:
    def _spec(self, C=16, T=128):
        data = make_data(C, T)
        return data, Spectra(make_freqs(C), 64e-6, data)

    def test_constructor_honors_dm(self):
        # reference defect spectra.py:37 fixed: dm argument kept
        s = Spectra(make_freqs(4), 1e-3, make_data(4, 16), dm=12.5)
        assert s.dm == 12.5

    def test_dedisperse_roundtrip(self):
        data, s = self._spec()
        d = s.dedisperse(40.0, padval="rotate")
        assert d.dm == 40.0
        back = d.dedisperse(0.0, padval="rotate")
        np.testing.assert_allclose(back.to_numpy(), data, atol=1e-6)

    def test_dedisperse_trim(self):
        data, s = self._spec()
        d = s.dedisperse(100.0, trim=True)
        maxdel = int(numpy_ref.bin_delays(100.0, make_freqs(16), 64e-6).max())
        assert d.numspectra == 128 - maxdel

    def test_downsample_updates_dt(self):
        _, s = self._spec()
        d = s.downsample(4)
        assert d.dt == pytest.approx(4 * 64e-6)
        assert d.numspectra == 32

    def test_trim_negative_moves_starttime(self):
        _, s = self._spec()
        t = s.trim(-10)
        assert t.numspectra == 118
        assert t.starttime == pytest.approx(10 * 64e-6)

    def test_subband(self):
        data, s = self._spec()
        sb = s.subband(4, subdm=25.0)
        ref, ctr = numpy_ref.subband(data, make_freqs(16), 64e-6, 4, 25.0)
        np.testing.assert_allclose(sb.to_numpy(), ref, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(sb.freqs), ctr, rtol=1e-6)

    def test_pytree(self):
        import jax

        _, s = self._spec(4, 16)
        leaves, treedef = jax.tree_util.tree_flatten(s)
        s2 = jax.tree_util.tree_unflatten(treedef, leaves)
        np.testing.assert_array_equal(s2.to_numpy(), s.to_numpy())
        assert s2.dt == s.dt


def test_shift_channels_fourier_matches_gather():
    """The TPU fourier shift backend (round 5: the gather path measured
    ~70M elem/s on chip, BENCHNOTES) agrees with the bit-exact gather
    formulation to FFT f32 rounding for every padval mode, including
    negative shifts and fully-vacated rows (|s| >= T)."""
    from pypulsar_tpu.ops.kernels import shift_channels

    rng = np.random.RandomState(8)
    C, T = 16, 1000
    data = rng.randn(C, T).astype(np.float32)
    bins = np.array([0, 1, -1, 7, -7, 500, -500, 999, -999, 1000, -1000,
                     1500, -1500, 3, 250, -250], dtype=np.int32)
    for padval in (0, 5.0, "mean", "median"):
        a = np.asarray(shift_channels(data, jnp.asarray(bins), padval,
                                      backend="gather"))
        b = np.asarray(shift_channels(data, jnp.asarray(bins), padval,
                                      backend="fourier"))
        np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-4,
                                   err_msg=f"padval={padval}")
