"""Batch-broker tests (round 24): the fleet-level coalescing plane
must fuse same-key dispatches from concurrent observations into single
device calls and demux rows back BYTE-IDENTICALLY to the un-brokered
path; a batchmate's failure or injected fault must never poison its
peers; a kill mid-coalesce must resume re-running only unvalidated
stages; and ``PYPULSAR_TPU_BROKER=0`` must restore the pre-round-24
dispatch tree exactly."""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.parallel import broker as broker_mod
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
from pypulsar_tpu.survey.scheduler import FleetScheduler
from pypulsar_tpu.survey.state import status_rows

from tests.test_accel_pipeline import _pulsar_fil
from tests.test_survey import (
    ARTIFACT_PATTERNS,
    CFG_KW,
    _artifact_bytes,
    _fleet_obs,
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    faultinject.reset()
    broker_mod.reset()
    yield
    faultinject.reset()
    broker_mod.reset()


# ---------------------------------------------------------------------------
# broker unit semantics (no device, numpy payloads)
# ---------------------------------------------------------------------------


def _np_hooks():
    """Stage hooks for a toy 'multiply rows by 2' dispatch."""
    calls = []

    def concat(payloads):
        return np.concatenate(payloads)

    def dispatch(fused, n):
        calls.append(int(n))
        return np.asarray(fused) * 2.0

    def demux(out, lo, hi):
        return out[lo:hi]

    return calls, concat, dispatch, demux


KEY = ("accel", (64,), ("cfg",), ("host",), "digest")
PARTY = ("accel", ("host",))


def test_solo_submit_dispatches_immediately_no_wait():
    """Zero registered parties (standalone CLI): a submission must
    dispatch at once — the broker never adds latency outside lanes."""
    bk = broker_mod.BatchBroker()
    calls, concat, dispatch, demux = _np_hooks()
    t0 = time.monotonic()
    out = bk.submit(KEY, PARTY, np.arange(4.0), 4, tag="a",
                    concat=concat, dispatch=dispatch, demux=demux)
    assert time.monotonic() - t0 < 1.0
    assert calls == [4]
    np.testing.assert_array_equal(out, np.arange(4.0) * 2)


def test_two_parties_fuse_one_dispatch_rows_demuxed(monkeypatch):
    """Two registered parties submitting the same key fuse into ONE
    dispatch; each gets exactly its own rows back, in order."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER_WAIT_MS", "30000")
    bk = broker_mod.BatchBroker()
    calls, concat, dispatch, demux = _np_hooks()
    results = {}

    def worker(name, payload):
        results[name] = bk.submit(
            KEY, PARTY, payload, len(payload), tag=name,
            concat=concat, dispatch=dispatch, demux=demux)

    a, b = np.arange(3.0), np.arange(10.0, 15.0)
    t0 = time.monotonic()
    # parties registered BEFORE any submit, as the scheduler's lane
    # does — the leader's early close waits for full attendance
    with bk.party(PARTY), bk.party(PARTY):
        ts = [threading.Thread(target=worker, args=("a", a)),
              threading.Thread(target=worker, args=("b", b))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
    # early close on full party attendance: nobody waited out 30s
    assert time.monotonic() - t0 < 10.0
    assert calls == [8], "expected ONE fused dispatch of 3+5 rows"
    np.testing.assert_array_equal(results["a"], a * 2)
    np.testing.assert_array_equal(results["b"], b * 2)


def test_row_budget_closes_batch_and_opens_fresh_one(monkeypatch):
    """A unit that would bust the fused row budget must not ride the
    open batch: the batch closes and the unit leads a fresh one."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER_WAIT_MS", "200")
    bk = broker_mod.BatchBroker()
    calls, concat, dispatch, demux = _np_hooks()
    results = {}

    def worker(name, payload):
        results[name] = bk.submit(
            KEY, PARTY, payload, len(payload), tag=name,
            concat=concat, dispatch=dispatch, demux=demux,
            budget_rows=6)

    with bk.party(PARTY), bk.party(PARTY), bk.party(PARTY):
        ts = [threading.Thread(target=worker,
                               args=(f"m{i}", np.arange(4.0) + 10 * i))
              for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
    assert sorted(calls) == [4, 4, 4], calls  # 4+4 rows bust budget 6
    for i in range(3):
        np.testing.assert_array_equal(results[f"m{i}"],
                                      (np.arange(4.0) + 10 * i) * 2)


def test_slo_pressure_collapses_coalesce_window(monkeypatch):
    """After note_pressure() a lone-member batch dispatches immediately
    even though a second party is registered but absent — SLO burn
    gates window widening."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER_WAIT_MS", "30000")
    bk = broker_mod.BatchBroker()
    calls, concat, dispatch, demux = _np_hooks()
    bk.note_pressure("test")
    with bk.party(PARTY), bk.party(PARTY):  # 2 parties, 1 shows up
        t0 = time.monotonic()
        out = bk.submit(KEY, PARTY, np.arange(4.0), 4, tag="a",
                        concat=concat, dispatch=dispatch, demux=demux)
    assert time.monotonic() - t0 < 5.0, "pressure did not collapse wait"
    assert calls == [4]
    np.testing.assert_array_equal(out, np.arange(4.0) * 2)


def test_departed_party_never_stalls_the_leader(monkeypatch):
    """A party that exits (stage finished) while a leader waits must
    wake the leader: trailing uneven batches dispatch without the
    departed peer."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER_WAIT_MS", "30000")
    bk = broker_mod.BatchBroker()
    calls, concat, dispatch, demux = _np_hooks()
    bk._party_enter(PARTY)
    bk._party_enter(PARTY)
    out = {}

    def leader():
        out["r"] = bk.submit(KEY, PARTY, np.arange(2.0), 2, tag="a",
                             concat=concat, dispatch=dispatch,
                             demux=demux)
        bk._party_exit(PARTY)

    t = threading.Thread(target=leader)
    t0 = time.monotonic()
    t.start()
    time.sleep(0.3)
    bk._party_exit(PARTY)  # the absent peer departs
    t.join(timeout=30)
    assert not t.is_alive()
    assert time.monotonic() - t0 < 10.0
    np.testing.assert_array_equal(out["r"], np.arange(2.0) * 2)


def test_member_fault_isolated_from_batchmates(monkeypatch):
    """An injected per-member fault fails ONLY that member; its
    batchmate still rides a (now solo) dispatch and gets bytes
    identical to an unfused run."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER_WAIT_MS", "30000")
    faultinject.configure("io:broker.member.bad:1")
    bk = broker_mod.BatchBroker()
    calls, concat, dispatch, demux = _np_hooks()
    results, errors = {}, {}

    def worker(name, payload):
        try:
            results[name] = bk.submit(
                KEY, PARTY, payload, len(payload), tag=name,
                concat=concat, dispatch=dispatch, demux=demux)
        except Exception as e:  # noqa: BLE001
            errors[name] = e

    good = np.arange(5.0)
    with bk.party(PARTY), bk.party(PARTY):
        ts = [threading.Thread(target=worker,
                               args=("bad", np.arange(3.0))),
              threading.Thread(target=worker, args=("good", good))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
    assert isinstance(errors["bad"], faultinject.InjectedIOError)
    assert "good" not in errors
    np.testing.assert_array_equal(results["good"], good * 2)


def test_fused_fault_retries_each_unit_alone(monkeypatch):
    """A transient failure of the FUSED dispatch retries every unit
    solo: no member inherits a batchmate's error, and each solo retry
    is the exact dispatch it would have run un-brokered."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER_WAIT_MS", "30000")
    bk = broker_mod.BatchBroker()
    calls = []

    def concat(payloads):
        return np.concatenate(payloads)

    def dispatch(fused, n):
        calls.append(int(n))
        if n > 4:  # the fused call fails; solo retries succeed
            raise RuntimeError("transient fused failure")
        return np.asarray(fused) * 2.0

    results = {}

    def worker(name, payload):
        results[name] = bk.submit(
            KEY, PARTY, payload, len(payload), tag=name,
            concat=concat, dispatch=dispatch,
            demux=lambda out, lo, hi: out[lo:hi])

    a, b = np.arange(3.0), np.arange(10.0, 14.0)
    with bk.party(PARTY), bk.party(PARTY):
        ts = [threading.Thread(target=worker, args=("a", a)),
              threading.Thread(target=worker, args=("b", b))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
    assert calls[0] == 7 and sorted(calls[1:]) == [3, 4]
    np.testing.assert_array_equal(results["a"], a * 2)
    np.testing.assert_array_equal(results["b"], b * 2)


def test_device_fault_in_fused_dispatch_propagates_to_all(monkeypatch):
    """A chip-indicting fault is about the DEVICE, not a member: the
    broker must NOT absorb it with per-unit retries (that would hide
    the strike from device-health accounting) — every member sees it."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER_WAIT_MS", "30000")
    bk = broker_mod.BatchBroker()

    def dispatch(fused, n):
        raise faultinject.InjectedDeviceFault("injected: chip down")

    errors = {}

    def worker(name, payload):
        try:
            bk.submit(KEY, PARTY, payload, len(payload), tag=name,
                      concat=lambda ps: np.concatenate(ps),
                      dispatch=dispatch,
                      demux=lambda out, lo, hi: out[lo:hi])
        except Exception as e:  # noqa: BLE001
            errors[name] = e

    with bk.party(PARTY), bk.party(PARTY):
        ts = [threading.Thread(target=worker, args=(n, np.arange(2.0)))
              for n in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
    assert all(isinstance(errors[n], faultinject.InjectedDeviceFault)
               for n in ("a", "b"))


def test_different_keys_never_fuse(monkeypatch):
    """Units whose geometry/config/scope keys differ must dispatch
    separately even when submitted concurrently."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER_WAIT_MS", "200")
    bk = broker_mod.BatchBroker()
    calls, concat, dispatch, demux = _np_hooks()
    other_key = ("accel", (128,), ("cfg",), ("host",), "digest")
    results = {}

    def worker(name, key, payload):
        results[name] = bk.submit(key, PARTY, payload, len(payload),
                                  tag=name, concat=concat,
                                  dispatch=dispatch, demux=demux)

    ts = [threading.Thread(target=worker, args=("a", KEY, np.arange(3.0))),
          threading.Thread(target=worker,
                           args=("b", other_key, np.arange(4.0)))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert sorted(calls) == [3, 4]


# ---------------------------------------------------------------------------
# multi-series fold kernel parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("T", [100, None])
def test_fold_parts_multi_matches_per_series_fold(T):
    """The fused fold kernel (a stack of series + per-candidate series
    index) is bitwise-equal to folding each candidate against its own
    series with the round-7 batch kernel — blocked and short paths."""
    from pypulsar_tpu.fold import engine

    if T is None:
        T = int(engine._FOLD_BLOCK * 2.5)  # exercise the blocked path
    rng = np.random.default_rng(7)
    nbins, npart = 16, 4
    stack = rng.standard_normal((3, T)).astype(np.float32)
    ks = [2, 1, 3]  # candidates per series
    sidx = np.concatenate([np.full(k, g, np.int32)
                           for g, k in enumerate(ks)])
    K = int(sidx.size)
    bins = rng.integers(0, nbins, size=(K, T)).astype(np.int32)
    profs, counts = engine.fold_parts_multi(stack, sidx, bins,
                                            nbins, npart)
    profs, counts = np.asarray(profs), np.asarray(counts)
    lo = 0
    for g, k in enumerate(ks):
        rp, rc = engine.fold_parts_batch(stack[g], bins[lo:lo + k],
                                         nbins, npart)
        np.testing.assert_array_equal(profs[lo:lo + k], np.asarray(rp),
                                      err_msg=f"series {g} profiles")
        np.testing.assert_array_equal(counts[lo:lo + k], np.asarray(rc),
                                      err_msg=f"series {g} counts")
        lo += k


# ---------------------------------------------------------------------------
# full-chain parity, fault isolation, kill+resume (slow: real fleets)
# ---------------------------------------------------------------------------

OBS = dict(C=16, T=8192)
NOMASK_KW = dict(CFG_KW, mask=False)


def _run_fleet(fils, outdir, cfg_kw, trace=None, **sched_kw):
    obs = _fleet_obs(fils, outdir)
    cfg = SurveyConfig(**cfg_kw)
    if trace is not None:
        with telemetry.session(trace):
            result = FleetScheduler(obs, cfg, max_host_workers=2,
                                    **sched_kw).run()
    else:
        result = FleetScheduler(obs, cfg, max_host_workers=2,
                                **sched_kw).run()
    return obs, result


@pytest.fixture(scope="module")
def duo(tmp_path_factory):
    """Two same-geometry toy observations plus the BROKER=0 reference
    artifacts (the pre-round-24 dispatch tree, pinned byte-identical
    to the serial chain by test_survey)."""
    root = tmp_path_factory.mktemp("broker")
    fils = [_pulsar_fil(root, name=f"psr{i}.fil", seed=5 + i, **OBS)
            for i in range(2)]
    refdir = str(root / "ref")
    os.environ["PYPULSAR_TPU_BROKER"] = "0"
    try:
        _, result = _run_fleet(fils, refdir, NOMASK_KW)
    finally:
        os.environ.pop("PYPULSAR_TPU_BROKER", None)
    assert result.ok
    ref = {f"psr{i}": _artifact_bytes(refdir, f"psr{i}")
           for i in range(2)}
    assert all(ref.values())
    return {"root": root, "fils": fils, "ref": ref}


def _assert_ref_parity(duo_dict, outdir):
    for stem, want in duo_dict["ref"].items():
        got = _artifact_bytes(outdir, stem)
        assert got.keys() == want.keys(), stem
        for name, data in want.items():
            assert got[name] == data, f"{stem}: {name} diverged"


def test_brokered_fleet_byte_identical_and_actually_coalesces(duo):
    """Acceptance: with the broker ON and batch lanes enabled, a
    2-observation fleet really fuses cross-obs dispatches (coalesced
    units > 0, fused dispatches < total submissions) and every final
    artifact is byte-identical to the BROKER=0 reference."""
    outdir = str(duo["root"] / "brokered")
    trace = str(duo["root"] / "brokered.jsonl")
    _, result = _run_fleet(duo["fils"], outdir, NOMASK_KW, trace=trace)
    assert result.ok
    _assert_ref_parity(duo, outdir)
    from pypulsar_tpu.obs.summarize import load_records, summarize

    s = summarize(load_records(trace))
    subs = s.counters.get("broker.submissions", 0)
    disp = s.counters.get("broker.dispatches", 0)
    assert disp > 0 and subs > disp, (subs, disp)
    assert s.counters.get("broker.coalesced_units", 0) >= 2
    assert s.counters.get("broker.lane_grants", 0) >= 1
    assert s.events.get("survey.lane_decision", 0) >= 1
    # and tlmsum renders the roll-up
    import io

    from pypulsar_tpu.obs.summarize import render

    buf = io.StringIO()
    render(s, buf)
    assert "# batch broker:" in buf.getvalue()


def test_broker_off_restores_pre_broker_dispatch_tree(duo, monkeypatch):
    """PYPULSAR_TPU_BROKER=0 must be byte-identical AND
    dispatch-identical to the pre-round-24 path: zero broker traffic,
    zero lane grants, and the same per-stage dispatch counters as the
    reference leg."""
    monkeypatch.setenv("PYPULSAR_TPU_BROKER", "0")
    outdir = str(duo["root"] / "off")
    trace = str(duo["root"] / "off.jsonl")
    _, result = _run_fleet(duo["fils"], outdir, NOMASK_KW, trace=trace)
    assert result.ok
    _assert_ref_parity(duo, outdir)
    from pypulsar_tpu.obs.summarize import load_records, summarize

    s = summarize(load_records(trace))
    for key in ("broker.submissions", "broker.dispatches",
                "broker.lane_grants", "broker.coalesced_units"):
        assert not s.counters.get(key), key
    assert not s.events.get("survey.lane_decision")


def test_batchmate_fault_leaves_peer_artifacts_byte_identical(duo):
    """One observation's injected broker-member fault must cost ONLY
    that observation a stage retry: its batchmate's artifacts stay
    byte-identical and the fleet completes."""
    outdir = str(duo["root"] / "memfault")
    trace = str(duo["root"] / "memfault.jsonl")
    faultinject.configure("io:broker.member.psr0:1")
    _, result = _run_fleet(duo["fils"], outdir, NOMASK_KW, trace=trace)
    assert result.ok and result.retried >= 1
    _assert_ref_parity(duo, outdir)
    from pypulsar_tpu.obs.summarize import load_records, summarize

    s = summarize(load_records(trace))
    assert s.counters.get("broker.member_faults", 0) >= 1


def test_kill_mid_coalesce_resume_reruns_only_unvalidated(duo):
    """kill -9 semantics at the fused-dispatch boundary: resume must
    re-run exactly the stages the manifests do not validate, and the
    artifacts still match the BROKER=0 reference."""
    outdir = str(duo["root"] / "kill")
    cfg = SurveyConfig(**NOMASK_KW)
    all_stages = {s.name for s in build_dag(cfg)}
    obs = _fleet_obs(duo["fils"], outdir)
    faultinject.configure("kill:broker.dispatch:3")
    with pytest.raises(faultinject.InjectedKill):
        FleetScheduler(obs, cfg, max_host_workers=2).run()
    faultinject.reset()
    broker_mod.reset()
    recorded = {(r["obs"], s)
                for r in status_rows([o.manifest for o in obs])
                for s in r["done"]}
    result = FleetScheduler(obs, cfg, max_host_workers=2,
                            resume=True).run()
    assert result.ok
    assert set(result.skipped) == recorded
    assert set(result.ran) == (
        {(o.name, s) for o in obs for s in all_stages} - recorded)
    _assert_ref_parity(duo, outdir)
    # a fully validated fleet resumes to zero stages re-run
    result2 = FleetScheduler(_fleet_obs(duo["fils"], outdir), cfg,
                             max_host_workers=2, resume=True).run()
    assert result2.ok and not result2.ran


# ---------------------------------------------------------------------------
# observability: tlmsum roll-up + statusd exposition
# ---------------------------------------------------------------------------


def test_tlmsum_renders_batch_broker_rollup(tmp_path):
    import io

    from pypulsar_tpu.obs.summarize import load_records, render, summarize

    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path):
        telemetry.counter("broker.submissions", 12)
        telemetry.counter("broker.dispatches", 4)
        telemetry.counter("broker.fused_rows", 4096)
        telemetry.counter("broker.lane_grants", 3)
        telemetry.counter("broker.unit_retries", 2)
        telemetry.gauge("broker.coalesce_factor", 3.0)
        with telemetry.span("broker.wait", key="accel"):
            pass
    buf = io.StringIO()
    render(summarize(load_records(path)), buf)
    out = buf.getvalue()
    assert "# batch broker:" in out
    for bit in ("fused dispatches=4", "units=12 (coalesce factor 3.00)",
                "rows fused=4096", "lane grants=3", "unit retries=2",
                "wait p50/p99=", "peak batch occupancy=3"):
        assert bit in out, bit


def test_statusd_metrics_exposes_broker_counters(tmp_path):
    import urllib.request

    from pypulsar_tpu.obs import statusd

    with telemetry.session():
        telemetry.counter("broker.dispatches", 7)
        telemetry.gauge("broker.coalesce_factor", 2.0)
        with statusd.StatusServer(str(tmp_path), 0) as srv:
            with urllib.request.urlopen(srv.url + "/metrics",
                                        timeout=5) as resp:
                text = resp.read().decode()
    assert 'pypulsar_counter{name="broker.dispatches"} 7' in text
    assert 'pypulsar_gauge{name="broker.coalesce_factor"' in text
