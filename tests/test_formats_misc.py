"""Tests for accelcands, fbobs, wapp, and datafile format modules."""

import io
import struct

import numpy as np
import pytest

from pypulsar_tpu.io import accelcands
from pypulsar_tpu.io.fbobs import FilterbankObs
from pypulsar_tpu.io.filterbank import write_filterbank
from pypulsar_tpu.io.psrfits import write_psrfits


# ---------------------------------------------------------------------------
# accelcands
# ---------------------------------------------------------------------------

def _make_cand(i):
    c = accelcands.Candidate(
        accelfile="obs_DM%05.2f_ACCEL_50" % (i * 1.5), candnum=i + 1,
        dm=i * 1.5, snr=10.0 + i, sigma=5.0 + i, numharm=1 << (i % 4),
        ipow=100.0 + i, cpow=110.0 + i, period=0.033 * (i + 1),
        r=1234.5 + i, z=-2.0 * i)
    c.add_dmhit(i * 1.5, 10.0 + i, 5.0 + i)
    c.add_dmhit(i * 1.5 + 0.5, 8.0 + i)
    return c


def test_accelcands_roundtrip():
    cands = [_make_cand(i) for i in range(5)]
    buf = io.StringIO()
    accelcands.write_candlist(cands, buf)
    text = buf.getvalue()
    back = accelcands.parse_candlist(io.StringIO(text))
    assert len(back) == 5
    # writer sorts by sigma descending
    sigmas = [c.sigma for c in back]
    assert sigmas == sorted(sigmas, reverse=True)
    orig = {c.candnum: c for c in cands}
    for c in back:
        o = orig[c.candnum]
        assert c.accelfile == o.accelfile
        assert c.dm == pytest.approx(o.dm)
        assert c.snr == pytest.approx(o.snr)
        assert c.numharm == o.numharm
        assert c.period == pytest.approx(o.period, rel=1e-6)
        assert len(c.dmhits) == len(o.dmhits)
        assert c.dmhits[0].sigma is not None
        assert c.dmhits[1].sigma is None

    # second write of the parsed list is byte-identical (format is stable)
    buf2 = io.StringIO()
    accelcands.write_candlist(back, buf2)
    assert buf2.getvalue() == text


def test_accelcands_file_roundtrip(tmp_path):
    fn = str(tmp_path / "test.accelcands")
    accelcands.write_candlist([_make_cand(0)], fn)
    back = accelcands.parse_candlist(fn)
    assert len(back) == 1 and back[0].candnum == 1


def test_accelcands_bad_line():
    with pytest.raises(accelcands.AccelcandsError):
        accelcands.parse_candlist(io.StringIO("utter nonsense\n"))


# ---------------------------------------------------------------------------
# fbobs
# ---------------------------------------------------------------------------

@pytest.fixture
def fil_pair(tmp_path):
    """Two contiguous filterbank files of 100 + 60 samples, 4 channels."""
    rng = np.random.RandomState(42)
    nchan, tsamp = 4, 1e-3
    hdr = dict(fch1=1500.0, foff=-1.0, nchans=nchan, tsamp=tsamp, nbits=32)
    d1 = rng.rand(100, nchan).astype(np.float32)
    d2 = rng.rand(60, nchan).astype(np.float32)
    fn1 = str(tmp_path / "part1.fil")
    fn2 = str(tmp_path / "part2.fil")
    write_filterbank(fn1, dict(hdr, tstart=55000.0), d1)
    write_filterbank(fn2, dict(hdr, tstart=55000.0 + 100 * tsamp / 86400.0), d2)
    # deliberately pass out of order; fbobs must sort by tstart
    return [fn2, fn1], np.concatenate([d1, d2])


def test_fbobs_index_and_read(fil_pair):
    fns, full = fil_pair
    with FilterbankObs(fns) as obs:
        assert obs.numfiles == 2
        assert obs.number_of_samples == 160
        assert obs.filenames[0].endswith("part1.fil")
        # interval within first file
        np.testing.assert_allclose(obs.get_sample_interval(10, 50), full[10:50])
        # interval spanning the boundary
        np.testing.assert_allclose(obs.get_sample_interval(90, 130), full[90:130])
        # interval in second file
        np.testing.assert_allclose(obs.get_sample_interval(110, 160), full[110:160])
        # clipping
        np.testing.assert_allclose(obs.get_sample_interval(-5, 1000), full)
        with pytest.raises(ValueError):
            obs.get_sample_interval(50, 10)


def test_fbobs_time_interval_and_spectra(fil_pair):
    fns, full = fil_pair
    with FilterbankObs(fns) as obs:
        d = obs.get_time_interval(0.09, 0.13)  # samples 90..130
        np.testing.assert_allclose(d, full[90:130])
        spec = obs.get_spectra(95, 20)
        assert spec.data.shape == (4, 20)
        np.testing.assert_allclose(np.asarray(spec.data), full[95:115].T)
        assert spec.starttime == pytest.approx(95 * obs.tsamp)


def test_fbobs_iter_blocks(fil_pair):
    fns, full = fil_pair
    with FilterbankObs(fns) as obs:
        blocks = list(obs.iter_blocks(block_len=64, overlap=16))
        assert blocks[0][0] == 0 and blocks[1][0] == 48
        # overlap region is re-read
        np.testing.assert_allclose(
            np.asarray(blocks[0][1].data)[:, 48:64],
            np.asarray(blocks[1][1].data)[:, :16])
        # full coverage
        last_start, last_spec = blocks[-1]
        assert last_start + last_spec.data.shape[1] == 160


# ---------------------------------------------------------------------------
# wapp
# ---------------------------------------------------------------------------

WAPP_HDR_SRC = """
#define NAMELEN 12
struct WAPP_HEADER {
    char src_name[NAMELEN];
    char obs_date[12];
    char start_time[12];
    double samp_time;
    double bandwidth;
    double cent_freq;
    int num_lags;
    int lagformat;
    int nifs;
    long timeoff;
    double alfa_az[7];
};
"""


def _write_wapp(fn, nsamp=16, num_lags=8, lagformat=0, timeoff=0):
    packed = b"".join([
        struct.pack("12s", b"J0000+0000"),
        struct.pack("12s", b"20100910"),
        struct.pack("12s", b"12:34:56"),
        struct.pack("d", 64.0),       # samp_time (us)
        struct.pack("d", 100.0),      # bandwidth
        struct.pack("d", 1420.0),     # cent_freq
        struct.pack("i", num_lags),
        struct.pack("i", lagformat),
        struct.pack("i", 1),
        struct.pack("l", timeoff),
        struct.pack("7d", *np.linspace(100.0, 106.0, 7)),
    ])
    dtype = np.int16 if lagformat == 0 else np.int32
    lags = np.arange(nsamp * num_lags, dtype=dtype)
    with open(fn, "wb") as f:
        f.write(WAPP_HDR_SRC.encode("ascii") + b"\0")
        f.write(packed)
        lags.tofile(f)
    return lags


def test_wapp_header_parse(tmp_path):
    from pypulsar_tpu.io.wapp import WappFile

    fn = str(tmp_path / "test.wapp")
    lags = _write_wapp(fn)
    with WappFile(fn) as w:
        assert w.header["src_name"] == "J0000+0000"
        assert w.header["samp_time"] == 64.0
        assert w.header["num_lags"] == 8
        assert w.header["nifs"] == 1
        assert len(w.header["alfa_az"]) == 7
        assert w.header["alfa_az"][0] == pytest.approx(100.0)
        assert w.bytes_per_lag == 2
        assert w.number_of_samples == 16
        assert w.obs_time == pytest.approx(64e-6 * 16)
        got = w.read_lags(2, 3)
        np.testing.assert_array_equal(got, lags.reshape(16, 8)[2:5])


def test_wapp_32bit_lags(tmp_path):
    """lagformat=1 works (reference wapp.py:86 typo made this path raise)."""
    from pypulsar_tpu.io.wapp import WappFile

    fn = str(tmp_path / "test32.wapp")
    _write_wapp(fn, lagformat=1)
    with WappFile(fn) as w:
        assert w.bytes_per_lag == 4
        assert w.number_of_samples == 16


def test_wapp_preprocessor():
    from pypulsar_tpu.io.wapp import preprocess_c

    out = preprocess_c("#define N 4\n/* c */ struct S { int a[N]; }; // x\n")
    assert "4" in out and "#" not in out and "/*" not in out and "//" not in out


# ---------------------------------------------------------------------------
# datafile
# ---------------------------------------------------------------------------

def _write_mock_fits(tmp_path, name):
    rng = np.random.RandomState(0)
    nchan = 8
    freqs = 1400.0 + np.arange(nchan)
    data = rng.randint(0, 255, size=(nchan, 128)).astype(np.float32)
    fn = str(tmp_path / name)
    write_psrfits(fn, data, freqs, tsamp=6.4e-5, nsamp_per_subint=64,
                  nbits=8, start_mjd=55500.25, src_name="FAKE",
                  extra_primary={"IBEAM": 3})
    return fn


def test_datafile_autogen_mock(tmp_path):
    from pypulsar_tpu.io import datafile

    fn = _write_mock_fits(
        tmp_path, "4bit-p2030.20101105.FAKE.b3s1g0.00100.fits")
    data = datafile.autogen_dataobj([fn])
    assert isinstance(data, datafile.MockPsrfitsData)
    assert data.beam_id == 3
    assert data.scan_num == "00100"
    assert data.num_channels_per_record == 8
    assert data.sample_time == pytest.approx(64.0)  # microseconds
    assert data.obs_name.startswith("TEST.FAKE.55500")
    # header coords fall through (no coords table, MJD > 54651)
    assert data.ra_deg == pytest.approx(data.orig_ra_deg)


def test_datafile_autogen_merged(tmp_path):
    from pypulsar_tpu.io import datafile

    fn = _write_mock_fits(
        tmp_path, "4bit-p2030.20101105.FAKE.b5g0.merged.00100_0001.fits")
    data = datafile.autogen_dataobj([fn])
    assert isinstance(data, datafile.MergedMockPsrfitsData)
    assert data.beam_id == 5  # from filename, not IBEAM
    assert data.num_ifs == 2


def test_datafile_rejects_unknown(tmp_path):
    from pypulsar_tpu.io import datafile

    with pytest.raises(ValueError):
        datafile.autogen_dataobj(["garbage.xyz"])


def test_accelcands_write_does_not_mutate():
    c = _make_cand(0)
    c.dmhits = c.dmhits[::-1]  # deliberately out of DM order
    before = list(c.dmhits)
    accelcands.write_candlist([c], io.StringIO())
    assert c.dmhits == before


def test_datafile_regex_anchored():
    from pypulsar_tpu.io import datafile

    assert datafile.MockPsrfitsData.fnmatch(
        "4bit-p2030.20101105.FAKE.b3s1g0X00100.fits") is None
    assert datafile.MockPsrfitsData.fnmatch(
        "4bit-p2030.20101105.FAKE.b3s1g0.00100.fitsJUNK") is None


def test_datafile_filename_dispatch():
    from pypulsar_tpu.io import datafile

    assert datafile.MultiplexedWappData.is_correct_filetype(
        ["p2030.FAKE.wapp1.55000.0003"])
    assert datafile.DumpOfWappData.is_correct_filetype(
        ["p2030_55000_00010_0003_FAKE_1.w4bit.wapp_hdr"])
    assert datafile.WappPsrfitsData.is_correct_filetype(
        ["p2030_55000_00010_0003_FAKE_1.w4bit.fits"])
    assert not datafile.MockPsrfitsData.is_correct_filetype(["x.fil"])
