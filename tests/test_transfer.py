"""Complex-boundary transfer helpers (ops/transfer.py): the contract
that lets the framework run on backends that cannot move complex
buffers across executable boundaries."""

import jax
import jax.numpy as jnp
import numpy as np

from pypulsar_tpu.ops.transfer import (
    join_planes,
    split_complex,
    to_host_complex,
)


def test_split_host_complex_roundtrip():
    a = (np.arange(6) + 1j * np.arange(6)[::-1]).astype(np.complex64)
    re, im = split_complex(a)
    assert isinstance(re, np.ndarray) and re.dtype == np.float32
    np.testing.assert_array_equal(re, a.real)
    np.testing.assert_array_equal(im, a.imag)
    back = to_host_complex(re, im)
    assert back.dtype == np.complex64
    np.testing.assert_array_equal(back, a)


def test_split_host_real_gets_zero_imag():
    re, im = split_complex(np.arange(4, dtype=np.float64))
    np.testing.assert_array_equal(im, np.zeros(4))
    assert re.dtype == np.float32


def test_split_complex128_downcasts():
    a = np.array([1.5 + 2.5j], dtype=np.complex128)
    re, im = split_complex(a)
    assert re.dtype == np.float32 and float(re[0]) == 1.5


def test_split_device_array():
    dev = jnp.asarray(np.array([1.0, 2.0], np.float32))
    cx = jax.jit(lambda x: x + 1j * x)(dev)
    re, im = split_complex(cx)
    assert isinstance(re, jax.Array)
    np.testing.assert_array_equal(np.asarray(re), [1.0, 2.0])
    np.testing.assert_array_equal(np.asarray(im), [1.0, 2.0])


def test_split_noncontiguous_input():
    a = (np.arange(12).reshape(3, 4) * (1 + 1j)).astype(np.complex64)
    re, im = split_complex(a[:, ::2])  # strided view
    assert re.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(re, a[:, ::2].real)


def test_join_planes_inside_jit():
    re = np.array([3.0, 0.0], np.float32)
    im = np.array([4.0, 1.0], np.float32)
    mag = jax.jit(lambda r, i: jnp.abs(join_planes(r, i)))(re, im)
    np.testing.assert_allclose(np.asarray(mag), [5.0, 1.0], rtol=1e-6)
