"""Tests for the analysis CLI tools: autozap, plot_accelcands, shapiro,
pbdot, massfunc, pfdinfo, coordconv, prestocand IO."""

import os

import matplotlib
import numpy as np
import pytest

matplotlib.use("Agg", force=True)

from pypulsar_tpu.core.psrmath import Tsun
from pypulsar_tpu.io.infodata import InfoData
from pypulsar_tpu.io.prestocand import (FOURIERPROPS_DTYPE, read_rzwcands,
                                        write_rzwcands)


def _make_inf(N=32768, dt=1e-3):
    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = dt
    inf.N = N
    inf.telescope = "Fake"
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 1
    inf.chan_width = 100.0
    inf.object = "FAKE"
    return inf


def _make_ffts(tmp_path, nfiles=3, N=32768, dt=1e-3, rfi_freq=60.0):
    """Write .fft files of white noise + a strong persistent RFI tone."""
    from pypulsar_tpu.fourier.prestofft import write_fft

    fns = []
    for ii in range(nfiles):
        rng = np.random.RandomState(ii)
        data = rng.randn(N).astype(np.float32)
        t = np.arange(N) * dt
        data += 20.0 * np.sin(2 * np.pi * rfi_freq * t)
        # full rfft: N/2+1 coefficients (our write_fft layout); autozap
        # must size by the on-disk count, not inf.N//2
        fft = np.fft.rfft(data).astype(np.complex64)
        fn = str(tmp_path / ("beam%d.fft" % ii))
        inf = _make_inf(N, dt)
        inf.basenm = "beam%d" % ii
        write_fft(fn, fft, inf)
        fns.append(fn)
    return fns


def test_autozap_finds_rfi_tone(tmp_path, monkeypatch):
    from pypulsar_tpu.cli import autozap

    monkeypatch.chdir(tmp_path)
    fns = _make_ffts(tmp_path, rfi_freq=60.0)
    rc = autozap.main(fns + ["-o", str(tmp_path / "zap"), "--no-plot"])
    assert rc == 0
    zap = np.atleast_2d(np.loadtxt(str(tmp_path / "zap.zaplist")))
    assert zap.shape[0] >= 1
    # the 60 Hz tone must be inside one of the zapped intervals
    hit = any(lo - w <= 60.0 <= lo + w for lo, w in zap)
    assert hit, f"60 Hz tone not zapped: {zap}"


def test_rzwcands_roundtrip(tmp_path):
    fn = str(tmp_path / "test_ACCEL_0.cand")
    cands = [dict(r=1234.5, rerr=0.1, z=-3.0, zerr=0.5, sig=12.0,
                  pow=50.0),
             dict(r=888.0, rerr=0.2, z=0.0, zerr=0.1, sig=8.0, pow=25.0)]
    write_rzwcands(fn, cands)
    assert os.path.getsize(fn) == 2 * FOURIERPROPS_DTYPE.itemsize
    back = read_rzwcands(fn)
    assert len(back) == 2
    assert back[0].r == pytest.approx(1234.5)
    assert back[0].zerr == pytest.approx(0.5)
    assert back[1].sig == pytest.approx(8.0)


def test_plot_accelcands(tmp_path, monkeypatch, capsys):
    from pypulsar_tpu.cli import plot_accelcands

    monkeypatch.chdir(tmp_path)
    N, dt = 32768, 1e-3
    T = N * dt
    # 10 files, all containing a candidate at the same frequency (60 Hz)
    inffns = []
    for ii in range(10):
        base = str(tmp_path / ("file%02d" % ii))
        inf = _make_inf(N, dt)
        inf.basenm = os.path.basename(base)
        inf.to_file(base + ".inf")
        # jitter the 60 Hz candidate slightly per file so the intervals
        # overlap (strict-inequality merge, reference :24-31)
        write_rzwcands(base + "_ACCEL_0.cand",
                       [dict(r=(60.0 + 0.001 * ii) * T, rerr=0.5 + 0.1 * ii,
                             z=0, zerr=0.1, sig=10.0),
                        dict(r=(20.0 + ii) * T, rerr=0.5, z=0, zerr=0.1,
                             sig=6.0)])
        inffns.append(base + ".inf")
    out = str(tmp_path / "cands.png")
    rc = plot_accelcands.main(inffns + ["-o", out])
    assert rc == 0
    printed = capsys.readouterr().out
    # the persistent 60 Hz interval (10 hits) is reported; scattered ones not
    rows = [ln for ln in printed.splitlines() if ln.startswith("\t")]
    assert len(rows) == 1
    assert float(rows[0].split()[0]) == pytest.approx(60.0, abs=0.1)
    assert os.path.getsize(out) > 1000


def test_shapiro_math():
    from pypulsar_tpu.cli.shapiro import measurable_shapiro_delay, sini

    # edge-on equal-mass system: sini = (f(2m)^2)^(1/3)/m
    mf, mp, mc = 0.15, 1.4, 1.4
    s = sini(mp, mc, mf)
    assert s == pytest.approx((mf * (mp + mc) ** 2) ** (1 / 3) / mc)
    # measurable delay is finite
    d = measurable_shapiro_delay(1.4, 1.4, mf, phi=np.pi / 2)
    assert np.isfinite(d)
    # higher mass function (at fixed masses) -> higher inclination ->
    # larger measurable harmonic content
    d2 = measurable_shapiro_delay(1.4, 1.4, 0.05, phi=np.pi / 2)
    assert abs(d) > abs(d2)


def test_shapiro_cli(tmp_path):
    from pypulsar_tpu.cli import shapiro

    out = str(tmp_path / "shapiro.png")
    assert shapiro.main(["-o", out]) == 0
    assert os.path.getsize(out) > 1000


def test_pbdot_hulse_taylor():
    from pypulsar_tpu.cli.pbdot import pbdot

    # PSR B1913+16: Pb=0.322997 d, e=0.6171, mp=1.441, mc=1.387
    # GR prediction: Pb-dot = -2.40e-12 s/s
    pb = 0.322997448918 * 86400
    val = pbdot(1.4398, 1.3886, pb, 0.6171340)
    assert val == pytest.approx(-2.402e-12, rel=0.01)


def test_pbdot_cli(tmp_path):
    from pypulsar_tpu.cli import pbdot

    out = str(tmp_path / "pbdot.png")
    assert pbdot.main(["-o", out]) == 0
    assert os.path.getsize(out) > 1000


def test_massfunc():
    from pypulsar_tpu.cli.massfunc import min_companion_mass
    from pypulsar_tpu.core.psrmath import mass_funct

    # consistency: mass function of the returned minimum mass reproduces f
    mp, inc = 1.4, 90.0
    for mf in (0.001, 0.15, 1.0):
        roots = min_companion_mass(mf, mp, inc)
        assert roots.size >= 1
        mc = roots.max()
        f_back = mc ** 3 / (mp + mc) ** 2
        assert f_back == pytest.approx(mf, rel=1e-8)


def test_massfunc_cli(capsys):
    from pypulsar_tpu.cli import massfunc

    assert massfunc.main(["-f", "0.15"]) == 0
    out = capsys.readouterr().out
    assert "Minimum companion mass" in out


def test_pfdinfo(tmp_path, capsys):
    from pypulsar_tpu.cli import pfdinfo
    from pypulsar_tpu.io.prestopfd import make_pfd

    profs = np.random.RandomState(0).rand(4, 8, 32)
    pfd = make_pfd(profs, dt=1e-3, lofreq=1400.0, chan_wid=1.0,
                   fold_p1=0.033, bestdm=25.0, candnm="TESTCAND")
    fn = str(tmp_path / "test.pfd")
    pfd.write(fn)
    rc = pfdinfo.main([fn, "-a", "candnm,bestdm", "--header",
                       "name,dm"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "TESTCAND\t25.0" in out
    assert "# name\tdm" in out


def test_coordconv_cli(capsys):
    from pypulsar_tpu.cli import coordconv

    assert coordconv.main(["192.25", "27.4"]) == 0
    out = capsys.readouterr().out
    # (192.25, 27.4) deg is close to the galactic north pole definition
    assert out.strip()
    assert coordconv.main(["1"]) == 1
