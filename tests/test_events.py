"""Event grouping (parallel/events.py): friends-of-friends association
of the sweep's per-(DM, width, chunk) events into pulse candidates."""

import numpy as np

from pypulsar_tpu.parallel.events import group_events


def ev(dm, snr, t, sample=0, width=1, ds=1):
    return dict(dm=dm, snr=snr, time_sec=t, sample=sample,
                width_bins=width, downsamp=ds)


def test_one_pulse_many_trials_collapses_to_one_group():
    # a bright pulse detected across 20 adjacent DM trials and 3 widths
    events = [ev(30 + 0.5 * i, 10 - 0.1 * i, 5.0 + 1e-4 * i, width=w)
              for i in range(20) for w in (1, 2, 4)]
    groups = group_events(events)
    assert len(groups) == 1
    g = groups[0]
    assert g["n_hits"] == 60
    assert g["snr"] == 10.0 and g["dm"] == 30.0  # peak member kept
    assert g["dm_lo"] == 30.0 and g["dm_hi"] == 39.5


def test_pulses_separated_in_time_stay_apart():
    events = [ev(30, 9, 5.0), ev(30.5, 8, 5.001),
              ev(31, 12, 50.0), ev(30, 7, 50.005)]
    groups = group_events(events)
    assert len(groups) == 2
    assert groups[0]["snr"] == 12 and groups[0]["n_hits"] == 2
    assert groups[1]["snr"] == 9 and groups[1]["n_hits"] == 2


def test_coincident_but_dm_distant_events_stay_apart():
    # same instant, wildly different DM: different phenomena
    events = [ev(5, 9, 5.0), ev(400, 8, 5.0)]
    groups = group_events(events, dm_tol=10.0)
    assert len(groups) == 2


def test_transitive_time_chaining():
    # each event within tol of its neighbor, ends far apart: one group
    events = [ev(20, 5 + i, 1.0 + 0.015 * i) for i in range(10)]
    groups = group_events(events, time_tol=0.02)
    assert len(groups) == 1
    assert groups[0]["time_hi"] - groups[0]["time_lo"] > 0.1


def test_empty_and_ordering():
    assert group_events([]) == []
    groups = group_events([ev(10, 6, 1.0), ev(50, 9, 30.0)])
    assert [g["snr"] for g in groups] == [9, 6]  # descending peak SNR


def test_bridging_event_merges_open_groups():
    """True friends-of-friends: an event within tolerance of TWO open
    groups fuses them into one (greedy first-match would report one
    physical pulse as two rows)."""
    events = [ev(30, 9, 5.0000), ev(50, 8, 5.0001), ev(40, 7, 5.0002)]
    groups = group_events(events, time_tol=0.02, dm_tol=10.0)
    assert len(groups) == 1
    g = groups[0]
    assert g["n_hits"] == 3
    assert (g["dm_lo"], g["dm_hi"]) == (30, 50)
    assert g["snr"] == 9  # peak survives the merge
