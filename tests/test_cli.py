"""Smoke + behavior tests for the CLI tools (batch 1: data-plane tools)."""

import os

import matplotlib
import numpy as np
import pytest

matplotlib.use("Agg", force=True)

from pypulsar_tpu.io.datfile import Datfile, write_dat
from pypulsar_tpu.io.filterbank import FilterbankFile, write_filterbank
from pypulsar_tpu.io.infodata import InfoData
from pypulsar_tpu.ops import numpy_ref


def _make_fil(tmp_path, name="test.fil", C=16, T=512, dt=1e-3, dm=None,
              tstart=55000.0, fch1=1500.0, foff=-2.0, seed=0, offset=100.0):
    rng = np.random.RandomState(seed)
    data = (rng.randn(T, C) + offset).astype(np.float32)
    if dm:
        freqs = fch1 + foff * np.arange(C)
        bins = numpy_ref.bin_delays(dm, freqs, dt)
        for c in range(C):
            data[(T // 3 + bins[c]) % T, c] += 40.0
    fn = str(tmp_path / name)
    write_filterbank(fn, dict(fch1=fch1, foff=foff, nchans=C, tsamp=dt,
                              nbits=32, tstart=tstart), data)
    return fn, data


def _make_dat(tmp_path, name="test", N=4096, dt=1e-3, epoch=55000.0,
              freq=20.0, seed=0):
    rng = np.random.RandomState(seed)
    t = np.arange(N) * dt
    data = (rng.randn(N) + 3 * np.sin(2 * np.pi * freq * t)).astype(np.float32)
    inf = InfoData()
    inf.epoch = epoch
    inf.dt = dt
    inf.N = N
    inf.telescope = "Fake"
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 1
    inf.chan_width = 100.0
    inf.object = "FAKE"
    basefn = str(tmp_path / name)
    write_dat(basefn, data, inf)
    return basefn + ".dat", data


def test_waterfaller(tmp_path):
    from pypulsar_tpu.cli import waterfaller

    fn, _ = _make_fil(tmp_path, dm=30.0)
    out = str(tmp_path / "wf.png")
    rc = waterfaller.main([fn, "-T", "0.05", "-t", "0.3", "-d", "30.0",
                           "-s", "8", "--downsamp", "2", "--width-bins", "2",
                           "--sweep-dm", "30.0", "-o", out])
    assert rc == 0 and os.path.getsize(out) > 1000


def test_waterfaller_requires_duration(tmp_path):
    from pypulsar_tpu.cli import waterfaller

    fn, _ = _make_fil(tmp_path)
    assert waterfaller.main([fn, "-T", "0"]) == 1


def test_zero_dm_filter(tmp_path):
    from pypulsar_tpu.cli import zero_dm_filter

    fn, data = _make_fil(tmp_path)
    out = str(tmp_path / "zdm.fil")
    rc = zero_dm_filter.main([fn, "-o", out])
    assert rc == 0
    with FilterbankFile(out) as fb:
        got = fb.get_samples(0, fb.nspec)
        assert fb.header["nchans"] == 16
    expect = data - data.mean(axis=1, keepdims=True)
    np.testing.assert_allclose(got, expect, atol=2e-4)


def test_spectrogram_cli(tmp_path):
    from pypulsar_tpu.cli import spectrogram

    datfn, _ = _make_dat(tmp_path)
    out = str(tmp_path / "sg.png")
    rc = spectrogram.main([datfn, "-t", "0.512", "-l", "-o", out])
    assert rc == 0 and os.path.getsize(out) > 1000


def test_spectrogram_get_spectra_matches_numpy(tmp_path):
    from pypulsar_tpu.cli.spectrogram import get_spectra

    datfn, data = _make_dat(tmp_path, N=2048)
    spectra, times, freqs = get_spectra(Datfile(datfn), time=0.256)
    spb = 256
    expect = np.abs(np.fft.rfft(data[:2048 // spb * spb]
                                .reshape(-1, spb), axis=1)) ** 2
    np.testing.assert_allclose(spectra, expect, rtol=2e-4)
    assert freqs[0] == 0.0 and times[0] == 0.0


def test_freq_time(tmp_path):
    from pypulsar_tpu.cli import freq_time

    fn, _ = _make_fil(tmp_path, dm=30.0, T=1024)
    out = str(tmp_path / "ft.png")
    rc = freq_time.main([fn, "--dm", "30.0", "--downsamp", "2", "-w", "2",
                         "-s", "0.0", "-e", "0.9", "-o", out])
    assert rc == 0 and os.path.getsize(out) > 1000


def test_freq_time_no_dm(tmp_path):
    """Reference bin/freq_time.py:118 crashed without --dm; ours must not."""
    from pypulsar_tpu.cli import freq_time

    fn, _ = _make_fil(tmp_path, T=512)
    out = str(tmp_path / "ft2.png")
    assert freq_time.main([fn, "-o", out]) == 0


def test_combinefil(tmp_path):
    from pypulsar_tpu.cli import combinefil

    # two adjacent 8-channel bands: 1500..1486 and 1484..1470 (foff=-2)
    fn_hi, d_hi = _make_fil(tmp_path, "hi.fil", C=8, T=300, fch1=1500.0)
    fn_lo, d_lo = _make_fil(tmp_path, "lo.fil", C=8, T=300, fch1=1484.0,
                            seed=1)
    out = str(tmp_path / "comb.fil")
    rc = combinefil.main([fn_lo, fn_hi, "-o", out])
    assert rc == 0
    with FilterbankFile(out) as fb:
        assert fb.header["nchans"] == 16
        assert fb.header["fch1"] == 1500.0
        got = fb.get_samples(0, 300)
    np.testing.assert_allclose(got, np.hstack([d_hi, d_lo]))


def test_combinefil_rejects_overlap(tmp_path):
    from pypulsar_tpu.cli.combinefil import combine_fil

    fn1, _ = _make_fil(tmp_path, "a.fil", C=8, fch1=1500.0)
    fn2, _ = _make_fil(tmp_path, "b.fil", C=8, fch1=1499.0)
    with pytest.raises(ValueError):
        combine_fil([fn1, fn2], str(tmp_path / "x.fil"))


def test_stitchdat(tmp_path):
    from pypulsar_tpu.cli import stitchdat

    dt = 1e-3
    fn1, d1 = _make_dat(tmp_path, "a", N=1000, epoch=55000.0)
    # second file starts 1.5 s after the first begins -> 500-sample gap
    fn2, d2 = _make_dat(tmp_path, "b", N=800,
                        epoch=55000.0 + 1.5 / 86400.0, seed=1)
    out = str(tmp_path / "stitched")
    rc = stitchdat.main([fn1, fn2, "-o", out])
    assert rc == 0
    combined = np.fromfile(out + ".dat", dtype=np.float32)
    assert combined.size == 1000 + 500 + 800
    np.testing.assert_allclose(combined[:1000], d1)
    np.testing.assert_allclose(combined[1500:], d2)
    np.testing.assert_allclose(combined[1000:1500], np.median(d1))
    inf = InfoData(out + ".inf")
    assert inf.N == 2300


def test_mockspecfil2subbands(tmp_path):
    from pypulsar_tpu.cli import mockspecfil2subbands

    fn, data = _make_fil(tmp_path, C=4, T=200)
    out = str(tmp_path / "subbands")
    rc = mockspecfil2subbands.main([fn, "-o", out])
    assert rc == 0
    # foff < 0: sub0000 is the lowest-frequency channel = last data column
    sub0 = np.fromfile(out + ".sub0000", dtype=np.float32)
    np.testing.assert_allclose(sub0, data[:, 3])
    sub3 = np.fromfile(out + ".sub0003", dtype=np.float32)
    np.testing.assert_allclose(sub3, data[:, 0])
    inf = InfoData(out + ".sub.inf")
    assert inf.numchan == 4
    assert inf.lofreq == pytest.approx(1500.0 - 8.0)


def test_cli_unknown_tool_exits_2_with_suggestion(capsys):
    """A typo'd tool name is a usage error (exit 2, distinguishable from
    a tool that ran and failed) with a closest-match hint."""
    from pypulsar_tpu.cli.__main__ import main as cli_main

    assert cli_main(["swep"]) == 2
    err = capsys.readouterr().err
    assert "unknown tool 'swep'" in err
    assert "did you mean 'sweep'?" in err
    # gibberish with no close match: still exit 2, no bogus hint
    assert cli_main(["zzqqxx"]) == 2
    err = capsys.readouterr().err
    assert "unknown tool" in err and "did you mean" not in err


def test_cli_survey_tool_registered():
    from pypulsar_tpu.cli.__main__ import TOOLS

    assert "survey" in TOOLS and "tlmsum" in TOOLS
