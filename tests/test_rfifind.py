"""Native RFI-mask generator tests (ops/rfifind.py): device-vs-NumPy
stat parity, sigma-clip detection of injected interference, mask-file
round-trip through the reference binary layout, and the CLI."""

import numpy as np
import pytest

from pypulsar_tpu.io.filterbank import write_filterbank
from pypulsar_tpu.io.rfimask import RfifindMask
from pypulsar_tpu.ops.rfifind import (
    RfiStats,
    block_stats,
    block_stats_numpy,
    clip_stats,
    mask_products,
    rfifind,
)

RNG = np.random.RandomState(11)


def make_rfi_data(C=64, nint=20, pts=512):
    """Unit-noise data with three injected interference modes:
    channel 37 loud (20x std), intervals 5-6 broadband (offset +30),
    channel 50 carrying a strong coherent tone (periodic RFI)."""
    T = nint * pts
    data = RNG.randn(C, T).astype(np.float32)
    data[37 % C] *= 20.0
    data[:, 5 * pts : 7 * pts] += 30.0
    t = np.arange(T)
    data[50 % C] += 12.0 * np.sin(2 * np.pi * t / 16.0).astype(np.float32)
    return data, pts


def test_block_stats_matches_numpy_twin():
    data = RNG.randn(8, 4 * 100).astype(np.float32)
    m, s, p = (np.asarray(x) for x in block_stats(data, 100))
    mr, sr, pr = block_stats_numpy(data, 100)
    assert m.shape == (4, 8)
    np.testing.assert_allclose(m, mr, atol=1e-5)
    np.testing.assert_allclose(s, sr, atol=1e-5)
    np.testing.assert_allclose(p, pr, rtol=2e-3)


def test_clip_flags_injected_rfi():
    data, pts = make_rfi_data()
    # hifreq_first=False: treat rows as already being in mask channel
    # order so the injected row indices map straight onto flag columns
    stats, flags, _ = rfifind(data, dt=1e-3, time=pts * 1e-3,
                              hifreq_first=False)
    assert stats.nint == 20 and stats.nchan == 64
    # loud channel: every interval's std is a bandpass outlier
    assert flags[:, 37].all()
    # broadband intervals: most channels' means are timeline outliers
    assert flags[5].mean() > 0.8 and flags[6].mean() > 0.8
    # coherent tone: Fourier max-power detector fires in every interval
    assert flags[:, 50].all()
    # clean cells stay clean (well under the whole-channel threshold)
    clean = np.delete(flags, [37, 50], axis=1)
    clean = np.delete(clean, [5, 6], axis=0)
    assert clean.mean() < 0.05


def test_mask_products_thresholds():
    flags = np.zeros((10, 16), dtype=bool)
    flags[:, 3] = True  # always-bad channel
    flags[7, :10] = True  # mostly-bad interval
    flags[2, 8] = True  # isolated block
    zc, zi, per_int = mask_products(flags, chanfrac=0.7, intfrac=0.3,
                                    extra_zap_chans=[12])
    assert zc == [3, 12]
    assert zi == [7]
    assert per_int[2] == [8]
    assert per_int[7] == []  # covered by the interval zap
    # globally zapped channels are excluded from per-interval lists
    assert all(3 not in chans for chans in per_int)
    # out-of-range extra zaps are rejected (a mask with them would crash
    # every consumer at load)
    with pytest.raises(ValueError):
        mask_products(flags, extra_zap_chans=[16])
    with pytest.raises(ValueError):
        mask_products(flags, extra_zap_chans=[-1])
    with pytest.raises(ValueError):
        mask_products(flags, extra_zap_ints=[10])


def test_end_to_end_mask_file(tmp_path):
    data, pts = make_rfi_data(C=32, nint=12, pts=256)
    dt = 64e-6
    hdr = dict(telescope_id=1, machine_id=2, source_name="FAKE",
               src_raj=0.0, src_dej=0.0, tstart=59000.0, tsamp=dt,
               fch1=1500.0, foff=-0.5, nchans=32, nbits=32, nifs=1)
    # SIGPROC foff<0 stores high-frequency-first: data here IS file order
    fn = str(tmp_path / "rfi.fil")
    write_filterbank(fn, hdr, data.T)

    from pypulsar_tpu.cli.rfifind import main as rfifind_main

    out = str(tmp_path / "test")
    assert rfifind_main([fn, "-o", out, "-t", str(pts * dt),
                         "--zapchan", "2"]) == 0

    mask = RfifindMask(out + "_rfifind.mask")
    assert mask.nchan == 32 and mask.nint == 12
    assert mask.ptsperint == pts
    assert mask.dtint == pytest.approx(pts * dt)
    assert mask.lofreq == pytest.approx(1500.0 - 0.5 * 31)
    # the .fil is foff<0 (file order = high-first); mask channels are
    # low-first, so loud data row 5 is mask channel 32-1-5 = 26
    assert {2, 31 - 37 % 32} <= mask.mask_zap_chans_set
    # the sample-mask expansion covers the broadband intervals
    chan_mask = mask.get_sample_mask(5 * pts, pts)
    assert chan_mask.all()
    stats = RfiStats.load(out + "_rfifind.stats.npz")
    assert stats.mean.shape == (12, 32)


def test_rfifind_psrfits_reader(tmp_path):
    """Mask generation from a PSRFITS file: the get_spectra fallback path
    (always flipped to low-first) finds the same loud channel."""
    from pypulsar_tpu.io import psrfits
    from pypulsar_tpu.ops.rfifind import rfifind as run_rfifind

    C, T = 16, 8 * 256
    rng = np.random.RandomState(4)
    # write_psrfits takes [chan, time] with ascending freqs (file order)
    data = rng.randn(C, T).astype(np.float32) * 2.0 + 10.0
    data[3] *= 25.0  # loud channel, file order = mask channel 3
    freqs = 1400.0 + 1.0 * np.arange(C)
    fn = str(tmp_path / "rfi.fits")
    psrfits.write_psrfits(fn, data, freqs, tsamp=1e-3,
                          nsamp_per_subint=256, nbits=32)
    with psrfits.PsrfitsFile(fn) as pf:
        stats, flags, _ = run_rfifind(pf, time=0.256)
    assert stats.nchan == C and stats.nint == 8
    assert flags[:, 3].all()
    clean = np.delete(flags, 3, axis=1)
    assert clean.mean() < 0.1


def test_rfifind_fbobs_multifile(tmp_path):
    """Mask generation across a multi-file observation (fbobs reader)."""
    from pypulsar_tpu.io.fbobs import FilterbankObs
    from pypulsar_tpu.io.filterbank import write_filterbank
    from pypulsar_tpu.ops.rfifind import rfifind as run_rfifind

    C, Tpart, dt = 16, 1024, 1e-3
    rng = np.random.RandomState(5)
    hdr = dict(telescope_id=1, machine_id=2, source_name="MULTI",
               src_raj=0.0, src_dej=0.0, tsamp=dt, fch1=1500.0,
               foff=-2.0, nchans=C, nbits=32, nifs=1)
    fns = []
    for i in range(3):
        data = rng.randn(Tpart, C).astype(np.float32)
        data[:, 2] *= 25.0  # loud in file order (hi-first row 2)
        fn = str(tmp_path / f"part{i}.fil")
        write_filterbank(fn, dict(hdr, tstart=56000.0 + i * Tpart * dt
                                  / 86400.0), data)
        fns.append(fn)
    obs = FilterbankObs(fns)
    stats, flags, _ = run_rfifind(obs, time=0.256)
    assert stats.nint == 12  # 3 files x 1024 samples / 256
    # file order hi-first: loud row 2 -> mask channel C-1-2
    assert flags[:, C - 1 - 2].all()
    assert stats.mjd == 56000.0


def test_partial_tail_interval_padding():
    # 3 full intervals + 60% of one more: the tail becomes interval 4
    data = RNG.randn(8, 3 * 200 + 120).astype(np.float32)
    stats, flags, _ = rfifind(data, dt=1e-3, time=0.2)
    assert stats.nint == 4
    # under half an interval is dropped instead
    data = RNG.randn(8, 3 * 200 + 50).astype(np.float32)
    stats, _, _ = rfifind(data, dt=1e-3, time=0.2)
    assert stats.nint == 3


def test_sweep_with_mask_suppresses_rfi():
    """rfifind mask -> sweep --mask loop: a loud RFI channel that drowns
    an injected dispersed pulse is masked out and the pulse recovers."""
    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.io.rfimask import RfifindMask, write_mask
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.parallel.staged import sweep_flat

    C, T, dt, dm_true = 32, 6144, 1e-3, 40.0
    rng = np.random.RandomState(3)
    freqs = (1500.0 - 4.0 * np.arange(C)).astype(np.float64)
    data = rng.randn(C, T).astype(np.float32)
    bins = numpy_ref.bin_delays(dm_true, freqs, dt)
    for c in range(C):
        idx = 900 + bins[c]
        if idx < T:
            data[c, idx] += 10.0
    # bursty RFI in channel 6 (hi-first): strong enough to dominate the
    # zero-DM end of the trial grid and inflate every trial's variance
    data[6, ::37] += 60.0

    stats, flags, _ = rfifind(data, dt=dt, time=512 * dt)
    lo_idx = C - 1 - 6
    assert flags[:, lo_idx].all()

    import tempfile, os
    with tempfile.TemporaryDirectory() as td:
        maskfn = os.path.join(td, "t.mask")
        zc, zi, per_int = mask_products(flags)
        write_mask(maskfn, nchan=stats.nchan, nint=stats.nint,
                   ptsperint=stats.ptsperint, zap_chans=zc, zap_ints=zi,
                   zap_chans_per_int=per_int)
        mask = RfifindMask(maskfn)

    spec = Spectra(freqs, dt, data)
    dms = np.arange(0.0, 80.0, 2.0)
    res_masked = sweep_flat(spec, dms, nsub=8, group_size=8,
                            rfimask=mask).best(1)[0]
    assert abs(res_masked["dm"] - dm_true) <= 4.0
    assert res_masked["snr"] > 7.0
    # unmasked control: the RFI channel's spikes beat the pulse
    res_raw = sweep_flat(spec, dms, nsub=8, group_size=8).best(1)[0]
    assert res_raw["snr"] < res_masked["snr"] or \
        abs(res_raw["dm"] - dm_true) > 4.0


def test_mask_tag_distinguishes_masks(tmp_path):
    """Checkpoint contexts must change when the applied mask changes —
    else a resume could mix masked and unmasked chunk results."""
    from pypulsar_tpu.io.rfimask import RfifindMask, write_mask
    from pypulsar_tpu.parallel.staged import _mask_tag

    assert _mask_tag(None) == ""
    fn1 = str(tmp_path / "a.mask")
    fn2 = str(tmp_path / "b.mask")
    write_mask(fn1, nchan=8, nint=4, ptsperint=100, zap_chans=[1])
    write_mask(fn2, nchan=8, nint=4, ptsperint=100, zap_chans=[2])
    t1 = _mask_tag(RfifindMask(fn1))
    t2 = _mask_tag(RfifindMask(fn2))
    assert t1.startswith("/mask=") and t1 != t2


def test_clip_stats_is_iterative():
    """A strong outlier block must not mask a moderate one: with a single
    pass the strong block inflates the IQR-scale; iteration re-judges."""
    nint, C = 30, 4
    mean = np.zeros((nint, C))
    mean[:, 0] = np.linspace(-0.01, 0.01, nint)
    mean[3, 0] = 1000.0
    mean[4, 0] = 0.2  # ~moderate outlier vs the 0.01-scale spread
    stats = RfiStats(mean=mean, std=np.ones((nint, C)),
                     maxpow=np.full((nint, C), 5.0), ptsperint=256,
                     dtint=1.0, lofreq=1400.0, df=1.0)
    flags = clip_stats(stats, time_sigma=10.0)
    assert flags[3, 0] and flags[4, 0]
    assert not flags[10, 0]
