"""Survey-orchestrator tests (round 9): the fleet scheduler must add
CONCURRENCY, never a second implementation — a 2-observation toy fleet's
artifacts are byte-identical to the serial per-tool chain; kill+resume
at every stage boundary re-runs exactly the unjournaled stages; a
persistently failing observation quarantines while the other completes;
the device lease serializes device-bound stages while host stages
overlap."""

import glob
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.survey.dag import StageSpec, SurveyConfig, build_dag
from pypulsar_tpu.survey.scheduler import FleetScheduler
from pypulsar_tpu.survey.state import (
    Observation,
    format_status,
    status_rows,
)

from tests.test_accel_pipeline import _pulsar_fil


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.reset()
    yield
    faultinject.reset()


# toy fleet geometry: small enough that a full 5-stage chain runs in a
# few seconds warm, strong enough that the accel search recovers the
# injected pulsar through sift into real .pfd archives
OBS = dict(C=16, T=8192)
CFG_KW = dict(mask=True, mask_time=1.0, lodm=0.0, dmstep=10.0, numdms=6,
              nsub=8, group_size=2, threshold=8.0,
              accel_zmax=20.0, accel_numharm=2, accel_sigma=3.0,
              accel_batch=4, sift_sigma=5.0, sift_min_hits=2,
              fold_nbins=32, fold_npart=8)
SURVEY_FLAGS = ["--lodm", "0", "--dmstep", "10", "--numdms", "6",
                "-s", "8", "--group-size", "2", "--threshold", "8",
                "--mask-time", "1.0",
                "--accel-zmax", "20", "--accel-numharm", "2",
                "--accel-sigma", "3", "--accel-batch", "4",
                "--sift-sigma", "5", "--sift-min-hits", "2",
                "--fold-nbins", "32", "--fold-npart", "8"]
ARTIFACT_PATTERNS = (".cands", "_DM*_ACCEL_*.cand", "_DM*_ACCEL_*.txtcand",
                     ".accelcands", "_cand*.pfd")


def _fleet_obs(fils, outdir):
    os.makedirs(outdir, exist_ok=True)
    return [Observation(os.path.splitext(os.path.basename(f))[0], f,
                        os.path.join(outdir,
                                     os.path.splitext(
                                         os.path.basename(f))[0]))
            for f in fils]


def _serial_chain(fil, outbase):
    """The exact per-tool chain the orchestrator composes, run serially
    by hand — the parity reference. Note: NO --journal on the sweep (the
    orchestrated stage passes one); artifact bytes must not depend on
    it."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch
    from pypulsar_tpu.cli import pfd_snr as cli_pfd_snr
    from pypulsar_tpu.cli import rfifind as cli_rfifind
    from pypulsar_tpu.cli import sift as cli_sift
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_rfifind.main([fil, "-o", outbase, "-t", "1.0"]) == 0
    assert cli_sweep.main(
        [fil, "-o", outbase, "--lodm", "0", "--dmstep", "10",
         "--numdms", "6", "-s", "8", "--group-size", "2",
         "--threshold", "8", "--write-dats", "--accel-search",
         "--accel-zmax", "20", "--accel-dz", "2.0",
         "--accel-numharm", "2", "--accel-sigma", "3",
         "--accel-batch", "4",
         "--mask", outbase + "_rfifind.mask"]) == 0
    cands = sorted(glob.glob(outbase + "_DM*_ACCEL_*.cand"))
    assert cli_sift.main(cands + ["-s", "5", "--min-hits", "2",
                                  "-o", outbase + ".accelcands"]) == 0
    assert cli_foldbatch.main(
        ["--cands", outbase + ".accelcands", "--datbase", outbase,
         "-o", outbase, "-n", "32", "--npart", "8", "--batch", "32"]) == 0
    pfds = sorted(glob.glob(outbase + "_cand*.pfd"))
    assert pfds, "sift kept no candidates; the toy fleet is too weak"
    assert cli_pfd_snr.main(pfds + ["--json", outbase + "_snr.json"]) == 0


def _artifact_bytes(outdir, stem):
    out = {}
    for pat in ARTIFACT_PATTERNS:
        for f in sorted(glob.glob(os.path.join(outdir, stem + pat))):
            out[os.path.basename(f)] = open(f, "rb").read()
    return out


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """Two distinguishable toy observations + the serial-chain reference
    artifacts, computed once per module (the parity target for the
    orchestrated and kill/resume runs, and the jit warmup)."""
    root = tmp_path_factory.mktemp("survey")
    fils = [_pulsar_fil(root, name=f"psr{i}.fil", seed=5 + i, **OBS)
            for i in range(2)]
    refdir = str(root / "serial")
    os.makedirs(refdir)
    ref = {}
    for i, fil in enumerate(fils):
        stem = f"psr{i}"
        _serial_chain(fil, os.path.join(refdir, stem))
        ref[stem] = _artifact_bytes(refdir, stem)
        assert ref[stem], stem
    return {"root": root, "fils": fils, "refdir": refdir, "ref": ref}


def _assert_matches_reference(fleet_dict, outdir, stems=("psr0", "psr1")):
    for stem in stems:
        got = _artifact_bytes(outdir, stem)
        assert got.keys() == fleet_dict["ref"][stem].keys(), stem
        for name, data in fleet_dict["ref"][stem].items():
            assert got[name] == data, f"{stem}: {name} diverged"


# ---------------------------------------------------------------------------
# end-to-end parity
# ---------------------------------------------------------------------------


def test_fleet_end_to_end_byte_identical_to_serial_chain(fleet):
    """The acceptance contract: the orchestrated fleet's candidate
    tables and archives are byte-identical to running the serial chain
    per observation, and the SNR summaries carry the same science."""
    from pypulsar_tpu.cli import survey as cli_survey

    outdir = str(fleet["root"] / "orch")
    tlmdir = str(fleet["root"] / "tlm")
    rc = cli_survey.main(fleet["fils"] + ["-o", outdir,
                                          "--telemetry-dir", tlmdir,
                                          *SURVEY_FLAGS])
    assert rc == 0
    _assert_matches_reference(fleet, outdir)
    for stem in ("psr0", "psr1"):
        a = json.load(open(os.path.join(fleet["refdir"],
                                        stem + "_snr.json")))
        b = json.load(open(os.path.join(outdir, stem + "_snr.json")))
        assert [(r["name"], r["best_dm"], r["snr"]) for r in a] \
            == [(r["name"], r["best_dm"], r["snr"]) for r in b]
    # one trace per observation + one fleet trace, all tlmsum-readable
    traces = sorted(os.path.basename(f)
                    for f in glob.glob(os.path.join(tlmdir, "*.jsonl")))
    assert traces == ["fleet.jsonl", "psr0.jsonl", "psr1.jsonl"]
    from pypulsar_tpu.obs.summarize import load_records, summarize

    obs_sum = summarize(load_records(os.path.join(tlmdir, "psr0.jsonl")))
    assert "survey.stage.sweep" in obs_sum.stages
    fleet_sum = summarize(load_records(os.path.join(tlmdir,
                                                    "fleet.jsonl")))
    assert fleet_sum.counters.get("survey.stages_run") == 10
    # --status renders both observations complete
    rc = cli_survey.main(["--status", "-o", outdir])
    assert rc == 0


# ---------------------------------------------------------------------------
# kill + resume at every stage boundary
# ---------------------------------------------------------------------------


def test_kill_resume_every_stage_boundary_bit_identical(fleet):
    """Kill the fleet at EVERY stage's completion boundary (artifacts
    written, manifest record pending — the torn window) plus one
    start boundary; ``--resume`` must re-run exactly the stages the
    manifests do not validate, and every final artifact is
    byte-identical to the serial chain."""
    cfg = SurveyConfig(**CFG_KW)
    all_stages = {s.name for s in build_dag(cfg)}
    points = [f"survey.stage_done.{s}"
              for s in ("mask", "sweep", "sift", "fold", "snr")]
    points.append("survey.stage_start.sweep")
    for ki, point in enumerate(points):
        outdir = str(fleet["root"] / f"kill{ki}")
        obs = _fleet_obs(fleet["fils"], outdir)
        faultinject.configure(f"kill:{point}:1")
        with pytest.raises(faultinject.InjectedKill):
            FleetScheduler(obs, cfg, max_host_workers=2).run()
        faultinject.reset()
        # what the manifests recorded done at the kill is what resume
        # must skip; everything else must re-run
        recorded = {(r["obs"], s)
                    for r in status_rows([o.manifest for o in obs])
                    for s in r["done"]}
        result = FleetScheduler(obs, cfg, max_host_workers=2,
                                resume=True).run()
        assert result.ok, point
        assert set(result.skipped) == recorded, point
        assert set(result.ran) == (
            {(o.name, s) for o in obs for s in all_stages} - recorded), \
            point
        _assert_matches_reference(fleet, outdir)


def test_kill9_subprocess_exit_then_resume(fleet):
    """The literal kill -9 semantics (os._exit(137): no finally blocks,
    no flushing) in a real subprocess, mid-fleet; a --resume completes
    the fleet without re-running validated stages."""
    outdir = str(fleet["root"] / "kill9")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = (repo_root + os.pathsep
                         + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pypulsar_tpu.cli", "survey",
         *fleet["fils"], "-o", outdir, *SURVEY_FLAGS,
         "--fault-inject", "exit:survey.stage_done.sweep:1"],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 137, proc.stderr[-2000:]
    obs = _fleet_obs(fleet["fils"], outdir)
    recorded = {(r["obs"], s)
                for r in status_rows([o.manifest for o in obs])
                for s in r["done"]}
    # the killed subprocess completed (and journaled) at least one stage
    assert recorded, "kill fired before any stage completed"
    from pypulsar_tpu.cli import survey as cli_survey

    rc = cli_survey.main(fleet["fils"] + ["-o", outdir, "--resume",
                                          *SURVEY_FLAGS])
    assert rc == 0
    _assert_matches_reference(fleet, outdir)


def test_resume_skips_whole_validated_fleet_and_redoes_corruption(fleet):
    """Resuming a COMPLETE fleet runs nothing; corrupting one artifact
    re-runs exactly that stage chainward (size/sha256 validation)."""
    cfg = SurveyConfig(**CFG_KW)
    outdir = str(fleet["root"] / "revalidate")
    obs = _fleet_obs(fleet["fils"], outdir)
    assert FleetScheduler(obs, cfg).run().ok
    result = FleetScheduler(obs, cfg, resume=True).run()
    assert result.ran == [] and len(result.skipped) == 10
    # truncate one observation's sifted list: its sift stage (only) is
    # redone; the other observation still skips everything
    victim = os.path.join(outdir, "psr0.accelcands")
    ref = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(ref[: len(ref) // 2])
    result = FleetScheduler(obs, cfg, resume=True).run()
    assert result.ok
    assert ("psr0", "sift") in result.ran
    assert all(o == "psr0" for o, _ in result.ran)
    assert open(victim, "rb").read() == ref
    _assert_matches_reference(fleet, outdir)


def test_changed_config_restarts_manifest(tmp_path):
    """A resume under different stage parameters must restart the
    manifest (fingerprint mismatch) instead of trusting stale
    artifacts — the sweep-journal contract at fleet scope."""
    stages = _stub_stages()
    obs = [Observation("a", str(tmp_path / "a.raw"),
                       str(tmp_path / "a"))]
    cfg = SurveyConfig(numdms=8)
    assert FleetScheduler(obs, cfg, stages=stages).run().ok
    r = FleetScheduler(obs, cfg, stages=stages, resume=True).run()
    assert r.ran == [] and len(r.skipped) == 2
    r = FleetScheduler(obs, SurveyConfig(numdms=16), stages=stages,
                       resume=True).run()
    assert r.skipped == [] and len(r.ran) == 2


def test_replaced_input_file_restarts_manifest(tmp_path):
    """A regenerated raw file — even at the SAME size — restarts the
    manifest (the fingerprint includes mtime): resuming against
    artifacts derived from the old input would report stale science."""
    stages = _stub_stages()
    raw = str(tmp_path / "a.raw")
    with open(raw, "wb") as f:
        f.write(b"A" * 64)
    obs = [Observation("a", raw, str(tmp_path / "a"))]
    cfg = SurveyConfig()
    assert FleetScheduler(obs, cfg, stages=stages).run().ok
    assert FleetScheduler(obs, cfg, stages=stages,
                          resume=True).run().ran == []
    time.sleep(0.01)  # distinct mtime even on coarse filesystems
    with open(raw, "wb") as f:
        f.write(b"B" * 64)  # same size, new content
    r = FleetScheduler(obs, cfg, stages=stages, resume=True).run()
    assert r.skipped == [] and len(r.ran) == 2


def test_multi_device_leases_bind_distinct_jax_devices(tmp_path):
    """--devices N pins each device worker to its own JAX device
    (thread-local default_device), so N leases are N chips — not N-fold
    oversubscription of device 0. conftest forces an 8-device CPU mesh,
    so the binding is observable."""
    import jax
    import jax.numpy as jnp

    used = []

    def dev_run(obs, cfg):
        d, = jnp.ones(4).sum().devices()
        with _conc_lock:
            used.append(d.id)
        with open(f"{obs.outbase}.dev1.out", "w") as f:
            f.write("x")
        return 0

    stages = [StageSpec("dev1", "stub", True, (), lambda o, c: [],
                        _stub_outputs("dev1"), run=dev_run)]
    obs = [Observation(f"o{i}", str(tmp_path / f"o{i}.raw"),
                       str(tmp_path / f"o{i}")) for i in range(6)]
    assert FleetScheduler(obs, SurveyConfig(), stages=stages,
                          devices=2).run().ok
    assert len(used) == 6
    assert set(used) <= {d.id for d in jax.local_devices()[:2]}
    # with one lease (the default) nothing is pinned: process default
    used.clear()
    assert FleetScheduler(obs, SurveyConfig(), stages=stages,
                          devices=1).run().ok
    assert set(used) == {jax.local_devices()[0].id}


def test_obs_trace_appends_on_resume(tmp_path):
    """A resumed fleet appends to the per-observation trace instead of
    truncating the killed run's recorded spans."""
    from pypulsar_tpu.obs.summarize import load_records, summarize
    from pypulsar_tpu.survey.state import ObsTrace

    path = str(tmp_path / "o.jsonl")
    t = ObsTrace(path, "o")
    t.span("survey.stage.mask", 0.0, 1.0)
    t.close()
    t = ObsTrace(path, "o", append=True)  # the --resume run
    t.span("survey.stage.sweep", 0.0, 2.0)
    t.close()
    s = summarize(load_records(path))
    assert set(s.stages) == {"survey.stage.mask", "survey.stage.sweep"}
    # a fresh (non-resume) run still truncates
    t = ObsTrace(path, "o")
    t.close()
    assert summarize(load_records(path)).stages == {}


# ---------------------------------------------------------------------------
# quarantine + retry
# ---------------------------------------------------------------------------


def test_reconfigured_rerun_scrubs_stale_artifacts(fleet):
    """Rerunning a SMALLER configuration into the same outdir must not
    let the previous grid's files leak into the glob-driven stage
    inputs (sift would cluster old-grid .cand trails): a fresh manifest
    scrubs every stage's enumerable artifacts first, so the rerun
    matches a clean-dir run byte for byte."""
    cfg6 = SurveyConfig(**CFG_KW)
    cfg4 = SurveyConfig(**{**CFG_KW, "numdms": 4})
    fil = fleet["fils"][0]
    shared = str(fleet["root"] / "reconf")
    assert FleetScheduler(_fleet_obs([fil], shared), cfg6).run().ok
    assert glob.glob(os.path.join(shared, "psr0_DM50.00_ACCEL_*.cand"))
    assert FleetScheduler(_fleet_obs([fil], shared), cfg4).run().ok
    # old-grid trails (DM 40/50) are gone, not globbed into the sift
    assert not glob.glob(os.path.join(shared, "psr0_DM[45]0*"))
    clean = str(fleet["root"] / "reconf_clean")
    assert FleetScheduler(_fleet_obs([fil], clean), cfg4).run().ok
    got = _artifact_bytes(shared, "psr0")
    want = _artifact_bytes(clean, "psr0")
    assert got.keys() == want.keys()
    for name, data in want.items():
        assert got[name] == data, name


def test_retry_timer_does_not_resurrect_quarantined_stage(tmp_path):
    """The backoff timer's requeue must drop a task whose observation
    was quarantined (or whose fleet stopped) while it waited."""
    sched = FleetScheduler(
        [Observation("a", str(tmp_path / "a.raw"), str(tmp_path / "a"))],
        SurveyConfig(), stages=_stub_stages())
    task = sched._tasks[(0, "host1")]
    task.state = 4  # _QUARANTINED
    sched._requeue_retry(task)
    assert sched._host_q.empty()
    task.state = 2  # _RUNNING (normal backing-off state)
    sched._requeue_retry(task)
    assert not sched._host_q.empty()
    # a stopped fleet also drops the requeue
    task2 = sched._tasks[(0, "dev1")]
    sched._stop = True
    sched._requeue_retry(task2)
    assert sched._device_q.empty()


def test_quarantine_keeps_other_observation_complete(fleet):
    """A persistently failing observation (unreadable input) is
    quarantined after bounded retries; the OTHER observation's chain
    completes with byte-identical artifacts and the verdict lands in
    the manifest + --status."""
    from pypulsar_tpu.cli import survey as cli_survey

    bad = str(fleet["root"] / "bad.fil")
    with open(bad, "wb") as f:
        f.write(b"this is not a filterbank")
    outdir = str(fleet["root"] / "quarantine")
    rc = cli_survey.main([fleet["fils"][0], bad, "-o", outdir,
                          "--retries", "1", *SURVEY_FLAGS])
    assert rc == 1
    _assert_matches_reference(fleet, outdir, stems=("psr0",))
    assert os.path.exists(os.path.join(outdir, "psr0_snr.json"))
    rows = {r["obs"]: r for r in status_rows(
        sorted(glob.glob(os.path.join(outdir, "*.survey.jsonl"))))}
    assert rows["bad"]["quarantine"] is not None
    assert rows["bad"]["quarantine"]["stage"] == "mask"
    assert rows["psr0"]["quarantine"] is None
    assert len(rows["psr0"]["done"]) == 5
    table = format_status(rows.values())
    assert "QUARANTINED" in table and "complete" in table
    # --status over the same manifests
    assert cli_survey.main(["--status", "-o", outdir]) == 0


def test_stage_retry_recovers_from_transient_fault(tmp_path):
    """An injected transient IO fault at a stage boundary is retried
    (bounded backoff) and the fleet completes — visible as a
    survey.stage_retry telemetry event."""
    stages = _stub_stages()
    obs = [Observation("a", str(tmp_path / "a.raw"), str(tmp_path / "a"))]
    faultinject.configure("io:survey.stage_start.host1:1")
    with telemetry.session() as tlm:
        result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                                retries=2).run()
        assert tlm.event_counts.get("survey.stage_retry") == 1
        assert tlm.event_counts.get("survey.stage_failed") == 1
    assert result.ok and result.retried == 1
    assert ("a", "host1") in result.ran


def test_retries_exhausted_quarantines_not_aborts(tmp_path):
    """A stage that fails every attempt quarantines its observation;
    the scheduler returns (no exception) and the other observation
    completes."""

    # only observation 'a' fails; 'b' runs the normal stub body
    def selective_fail(o, c):
        if o.name == "a":
            raise OSError("persistent read failure")
        return _stub_body("host1")(o, c)

    stages = [_stub("dev1", True, ()),
              StageSpec("host1", "stub", False, ("dev1",),
                        lambda o, c: [], _stub_outputs("host1"),
                        run=selective_fail)]
    obs = [Observation(n, str(tmp_path / f"{n}.raw"), str(tmp_path / n))
           for n in ("a", "b")]
    with telemetry.session() as tlm:
        result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                                retries=1).run()
        assert tlm.event_counts.get("survey.quarantine") == 1
    assert not result.ok
    assert set(result.quarantined) == {"a"}
    assert result.quarantined["a"]["stage"] == "host1"
    assert ("b", "host1") in result.ran
    assert os.path.exists(str(tmp_path / "b") + ".host1.out")


# ---------------------------------------------------------------------------
# scheduler semantics (synthetic stages; no pipeline cost)
# ---------------------------------------------------------------------------

_conc_lock = threading.Lock()


def _stub_body(name, sleep=0.0, conc=None, key=None, order=None):
    def run(obs, cfg):
        if conc is not None:
            with _conc_lock:
                conc[key] += 1
                conc[key + "_max"] = max(conc[key + "_max"], conc[key])
        if order is not None:
            with _conc_lock:
                order.append((obs.name, name))
        if sleep:
            time.sleep(sleep)
        if conc is not None:
            with _conc_lock:
                conc[key] -= 1
        with open(f"{obs.outbase}.{name}.out", "w") as f:
            f.write(f"{name} {obs.name}\n")
        return 0
    return run


def _stub_outputs(name):
    def outputs(obs, cfg):
        return [f"{obs.outbase}.{name}.out"]
    return outputs


def _stub(name, device, deps, **kw):
    return StageSpec(name, "stub", device, deps, lambda o, c: [],
                     _stub_outputs(name), run=_stub_body(name, **kw))


def _stub_stages():
    return [_stub("dev1", True, ()), _stub("host1", False, ("dev1",))]


def test_device_lease_exclusive_host_pool_overlaps(tmp_path):
    """Device-bound stages never overlap (one lease); host-bound stages
    from different observations DO overlap on the worker pool — the
    wall-clock mechanism the bench A/B measures."""
    conc = {"dev": 0, "dev_max": 0, "host": 0, "host_max": 0}
    stages = [
        _stub("dev1", True, (), sleep=0.02, conc=conc, key="dev"),
        _stub("host1", False, ("dev1",), sleep=0.15, conc=conc,
              key="host"),
    ]
    obs = [Observation(f"o{i}", str(tmp_path / f"o{i}.raw"),
                       str(tmp_path / f"o{i}")) for i in range(4)]
    result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                            max_host_workers=2, devices=1).run()
    assert result.ok and len(result.ran) == 8
    assert conc["dev_max"] == 1          # exclusive lease
    assert conc["host_max"] >= 2         # B's post overlaps A's device time


def test_device_queue_prefers_deeper_stages(tmp_path):
    """Priority + FIFO on the device lease: when a later-chain stage
    becomes ready it runs before an earlier-chain stage of another
    observation (drain observations toward completion)."""
    order = []
    stages = [
        _stub("dev1", True, (), order=order),
        _stub("dev2", True, ("dev1",), order=order),
    ]
    obs = [Observation(f"o{i}", str(tmp_path / f"o{i}.raw"),
                       str(tmp_path / f"o{i}")) for i in range(2)]
    result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                            devices=1).run()
    assert result.ok
    # o0.dev1 runs first; its dev2 (deeper) then outranks o1.dev1
    assert order[0] == ("o0", "dev1")
    assert order[1] == ("o0", "dev2")


def test_scheduler_rejects_bad_dags_and_duplicate_names(tmp_path):
    with pytest.raises(ValueError, match="unknown stage"):
        FleetScheduler([], SurveyConfig(),
                       stages=[_stub("a", True, ("missing",))])
    obs = [Observation("x", "x.raw", str(tmp_path / "x")),
           Observation("x", "y.raw", str(tmp_path / "y"))]
    with pytest.raises(ValueError, match="duplicate"):
        FleetScheduler(obs, SurveyConfig(), stages=_stub_stages())


# ---------------------------------------------------------------------------
# gang leases (multi-chip single-observation scale-out)
# ---------------------------------------------------------------------------

# cached capability gate shared with the sharded-handoff tests (same
# pattern as test_distributed's CPU-collectives probe)
from tests.test_accel_pipeline import require_virtual_mesh as \
    _require_virtual_mesh


def _gang_stub(name, deps=(), devices_max=4, body=None):
    def run(obs, cfg):
        if body is not None:
            body(obs, cfg)
        with open(f"{obs.outbase}.{name}.out", "w") as f:
            f.write(f"{name} {obs.name}\n")
        return 0

    return StageSpec(name, "stub", True, deps, lambda o, c: [],
                     _stub_outputs(name), run=run,
                     devices_max=devices_max)


def test_gang_lease_pins_k_distinct_devices(tmp_path):
    """A gang-leased stage sees its k chips through the thread-local
    lease (parallel.mesh.device_lease / lease_devices) — the resolver
    every mesh-building call site goes through, so `sweep --mesh k`
    inside the stage can only address the leased chips."""
    _require_virtual_mesh(2)
    import jax

    from pypulsar_tpu.parallel import mesh as mesh_mod

    seen = []

    def body(obs, cfg):
        lease = mesh_mod.current_lease()
        devs = mesh_mod.lease_devices(2)
        with _conc_lock:
            seen.append((tuple(d.id for d in lease),
                         tuple(d.id for d in devs)))

    stages = [_gang_stub("gangdev", devices_max=2, body=body)]
    obs = [Observation(f"o{i}", str(tmp_path / f"o{i}.raw"),
                       str(tmp_path / f"o{i}")) for i in range(3)]
    assert FleetScheduler(obs, SurveyConfig(), stages=stages,
                          devices=2, gang=2).run().ok
    assert len(seen) == 3
    local = [d.id for d in jax.local_devices()]
    for lease_ids, resolved_ids in seen:
        assert len(set(lease_ids)) == 2          # two DISTINCT chips
        assert resolved_ids == lease_ids         # resolver == the lease
        assert set(lease_ids) <= set(local)


def test_gang_auto_places_both_shapes(tmp_path):
    """The placement policy demonstrably picks BOTH shapes: a deep
    fleet stays fleet-parallel (k obs x 1 chip), a lone observation
    widens onto the idle chips (1 obs x k chips) — and each decision is
    recorded with its reason (survey.gang_decision)."""
    _require_virtual_mesh(2)

    def decisions(n_obs, subdir):
        path = str(tmp_path / f"{subdir}.jsonl")
        stages = [_gang_stub("gangable", devices_max=2)]
        obs = [Observation(f"o{i}", str(tmp_path / f"{subdir}{i}.raw"),
                           str(tmp_path / f"{subdir}_o{i}"))
               for i in range(n_obs)]
        with telemetry.session(path):
            assert FleetScheduler(obs, SurveyConfig(), stages=stages,
                                  devices=2, gang="auto").run().ok
        recs = [json.loads(l) for l in open(path)]
        return [r["attrs"] for r in recs
                if r.get("type") == "event"
                and r.get("name") == "survey.gang_decision"]

    deep = decisions(4, "deep")
    assert len(deep) == 4
    # with 4 ready observations on 2 chips at least the contended
    # decisions stay fleet-parallel, with the reason recorded
    assert any(d["k"] == 1 and "fleet-parallel" in d["reason"]
               for d in deep)
    lone = decisions(1, "lone")
    assert len(lone) == 1
    assert lone[0]["k"] == 2 and len(lone[0]["chips"]) == 2
    assert "idle" in lone[0]["reason"]


def test_gang_auto_cost_gate():
    """The measured-cost gate: a gang-able stage that owns a sliver of
    the measured device chain runs 1-chip even with idle chips; the
    dominant stage gangs. (Unit-level: the policy reads the same
    per-stage costs the obs traces record.)"""
    stages = [_gang_stub("cheap", devices_max=4),
              _gang_stub("dominant", devices_max=4)]
    sched = FleetScheduler(
        [Observation("a", "a.raw", "/tmp/unused_a")],
        SurveyConfig(), stages=stages, devices=4, gang="auto")
    sched._stage_cost = {"cheap": [0.1, 1], "dominant": [9.9, 1]}
    k, reason = sched._gang_size(sched._tasks[(0, "cheap")])
    assert k == 1 and "not worth" in reason
    k, reason = sched._gang_size(sched._tasks[(0, "dominant")])
    assert k == 4 and "99%" in reason


def test_gang_oversubscribed_pool_distinct_devices(tmp_path):
    """An oversubscribed lease pool (--devices > real chips) is legal
    for 1-chip fleet placement, but a gang mesh must hold DISTINCT
    chips: colliding lease ids (e.g. 0 and 0+n) are bumped to free
    devices, and auto-gang width is capped at the real device count."""
    _require_virtual_mesh(2)
    import jax
    n = len(jax.local_devices())
    sched = FleetScheduler(
        [Observation("a", "a.raw", str(tmp_path / "a"))],
        SurveyConfig(), stages=[_gang_stub("s", devices_max=4 * n)],
        devices=4 * n, gang="auto")
    # lease ids that wrap modulo n and collide: [0, n] both map to dev 0
    gang = sched._jax_gang([0, n])
    assert len(set(gang)) == 2
    # a full-width gang over the whole oversubscribed pool is impossible
    with pytest.raises(ValueError, match="distinct devices"):
        sched._jax_gang(list(range(n + 1)))
    # ...and the placement policy never asks for one: k caps at n
    k, _reason = sched._gang_size(sched._tasks[(0, "s")])
    assert k <= n


def test_gang_acquisition_fifo_no_starvation(tmp_path):
    """Device-pool acquisition is FIFO with reservation: a waiting wide
    gang reserves freed chips, so 1-chip traffic cannot starve it."""
    sched = FleetScheduler(
        [Observation("a", "a.raw", str(tmp_path / "a"))],
        SurveyConfig(), stages=_stub_stages(), devices=2)
    one = sched._acquire_devices(1)
    assert one == [0]
    got = []
    t = threading.Thread(target=lambda: got.append(
        sched._acquire_devices(2)))
    t.start()
    time.sleep(0.05)
    assert not got                       # gang waits: only 1 chip free
    # a younger 1-chip claim must NOT overtake the waiting gang's
    # reservation once the first chip frees
    sched._release_devices(one)
    t.join(timeout=5.0)
    assert got and sorted(got[0]) == [0, 1]
    sched._release_devices(got[0])
    assert sched._acquire_devices(1) is not None


def test_gang_lease_kill_resume_byte_identical(fleet):
    """Kill a gang-leased fleet at the sweep completion boundary; a
    --resume under the same gang shape completes with artifacts
    byte-identical to the serial 1-chip chain — placement is not
    science, so the manifest resumes across ANY gang shape."""
    _require_virtual_mesh(4)
    cfg = SurveyConfig(**CFG_KW)
    outdir = str(fleet["root"] / "gangkill")
    obs = _fleet_obs(fleet["fils"][:1], outdir)
    faultinject.configure("kill:survey.stage_done.sweep:1")
    with pytest.raises(faultinject.InjectedKill):
        FleetScheduler(obs, cfg, devices=4, gang="auto").run()
    faultinject.reset()
    result = FleetScheduler(obs, cfg, devices=4, gang="auto",
                            resume=True).run()
    assert result.ok
    assert ("psr0", "sweep") in result.ran   # the torn stage redone
    _assert_matches_reference(fleet, outdir, stems=("psr0",))


def test_gang_fleet_byte_identical_and_per_device_rollup(fleet):
    """One observation spanning 4 chips end to end produces artifacts
    byte-identical to the serial chain, and the traces carry per-chip
    attribution tlmsum's per-device roll-up renders."""
    _require_virtual_mesh(4)
    from pypulsar_tpu.cli import survey as cli_survey
    from pypulsar_tpu.obs.summarize import load_records, summarize

    outdir = str(fleet["root"] / "gangfleet")
    tlmdir = str(fleet["root"] / "gangtlm")
    rc = cli_survey.main([fleet["fils"][0], "-o", outdir,
                          "--devices", "4", "--gang", "4",
                          "--telemetry-dir", tlmdir, *SURVEY_FLAGS])
    assert rc == 0
    _assert_matches_reference(fleet, outdir, stems=("psr0",))
    s = summarize(load_records(os.path.join(tlmdir, "fleet.jsonl")))
    assert s.events.get("survey.gang_decision")
    # the sharded sweep/accel spans stamped all 4 leased chips
    assert len(s.device_busy) == 4
    for _d, (busy, nsp) in sorted(s.device_busy.items()):
        assert busy > 0 and nsp > 0
    assert s.counters.get("device0.dedisperse.chunks", 0) >= 1
    import io

    from pypulsar_tpu.obs.summarize import render

    buf = io.StringIO()
    render(s, buf)
    assert "# per-device:" in buf.getvalue()
    assert "device 3" in buf.getvalue()


def test_gang_rejects_more_than_devices():
    with pytest.raises(ValueError, match="exceeds"):
        FleetScheduler([], SurveyConfig(), stages=_stub_stages(),
                       devices=2, gang=4)


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------


def _load_make_synthetic_fil():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "make_synthetic_fil.py")
    spec = importlib.util.spec_from_file_location("make_synthetic_fil",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_make_synthetic_fil_src_name_and_start_mjd(tmp_path):
    """--src-name/--start-mjd land in the header (round-trip through the
    reader); defaults unchanged."""
    from pypulsar_tpu.io.filterbank import FilterbankFile

    mod = _load_make_synthetic_fil()
    common = ["--nchan", "8", "--duration", "0.5", "--tsamp", "1e-3",
              "--period-samples", "128", "--width", "2"]
    fn = str(tmp_path / "beam7.fil")
    mod.main(["--out", fn, *common,
              "--src-name", "FLEET_BEAM7", "--start-mjd", "58765.5"])
    with FilterbankFile(fn) as fb:
        assert fb.header["source_name"] == "FLEET_BEAM7"
        assert fb.header["tstart"] == 58765.5
    fn2 = str(tmp_path / "default.fil")
    mod.main(["--out", fn2, *common])
    with FilterbankFile(fn2) as fb:
        assert fb.header["source_name"].startswith("SYNTH_DM")
        assert fb.header["tstart"] == 60000.0


def test_status_rows_and_render_from_raw_manifests(tmp_path):
    """--status reads manifests fingerprint-agnostically, tolerating a
    torn trailing line, and renders progress/quarantine states."""
    p1 = str(tmp_path / "a.survey.jsonl")
    with open(p1, "w") as f:
        f.write(json.dumps({"type": "journal", "tool": "survey",
                            "fingerprint": "zzz"}) + "\n")
        f.write(json.dumps({"type": "note", "event": "plan", "obs": "a",
                            "stages": ["s1", "s2", "s3"]}) + "\n")
        f.write(json.dumps({"type": "done", "unit": "stage:s1",
                            "outputs": []}) + "\n")
        f.write('{"type": "done", "unit": "stage:s2", "outp')  # torn
    p2 = str(tmp_path / "b.survey.jsonl")
    with open(p2, "w") as f:
        f.write(json.dumps({"type": "journal", "tool": "survey",
                            "fingerprint": "zzz"}) + "\n")
        f.write(json.dumps({"type": "note", "event": "plan", "obs": "b",
                            "stages": ["s1", "s2"]}) + "\n")
        f.write(json.dumps({"type": "note", "event": "quarantine",
                            "stage": "s1", "error": "boom"}) + "\n")
    rows = status_rows([p1, p2])
    assert rows[0]["done"] == ["s1"] and rows[0]["quarantine"] is None
    assert rows[1]["quarantine"]["stage"] == "s1"
    table = format_status(rows)
    assert "1/3" in table and "next: s2" in table
    assert "QUARANTINED at s1 (boom)" in table
    # a LATER done record for the quarantined stage (a resume got past
    # it) supersedes the verdict — --status must not say QUARANTINED
    # about a completed observation
    with open(p2, "a") as f:
        f.write(json.dumps({"type": "done", "unit": "stage:s1",
                            "outputs": []}) + "\n")
        f.write(json.dumps({"type": "done", "unit": "stage:s2",
                            "outputs": []}) + "\n")
    rows = status_rows([p1, p2])
    assert rows[1]["quarantine"] is None
    assert "complete" in format_status([rows[1]])


# ---------------------------------------------------------------------------
# fleet health (round 12): watchdog, device strikes, admission, chaos
# ---------------------------------------------------------------------------


def test_stalled_stage_interrupted_and_retried(tmp_path, monkeypatch):
    """Acceptance: a stage that stops heartbeating is detected within
    its bound, its worker is interrupted, the lease is reclaimed and
    the observation RETRIES — the fleet completes, the verdict is a
    survey.stage_stalled event, and the other observation is never
    stalled behind the wedged one."""
    monkeypatch.setenv(faultinject.ENV_HANG_S, "30")  # hang >> stall
    # the stub pipeline trips a fault point per loop like the real hot
    # paths do; the armed hang wedges attempt 1 of ONE observation
    faultinject.configure("hang:stub.step:1")

    def body(obs, cfg):
        for _ in range(3):
            faultinject.trip("stub.step")
            telemetry.counter("stub.steps")  # heartbeat
        with open(f"{obs.outbase}.dev1.out", "w") as f:
            f.write(f"dev1 {obs.name}\n")
        return 0

    stages = [StageSpec("dev1", "stub", True, (), lambda o, c: [],
                        _stub_outputs("dev1"), run=body)]
    obs = [Observation(n, str(tmp_path / f"{n}.raw"), str(tmp_path / n))
           for n in ("a", "b")]
    t0 = time.monotonic()
    with telemetry.session() as tlm:
        result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                                retries=1, stall_s=0.5).run()
        assert tlm.event_counts.get("survey.stage_stalled") == 1
        assert tlm.event_counts.get("survey.stage_retry") == 1
        assert tlm.counters.get("survey.watchdog_interrupts") == 1
    took = time.monotonic() - t0
    assert result.ok and result.timeouts == 1 and result.retried == 1
    assert took < 20.0  # interrupted within the bound, not HANG_S
    for n in ("a", "b"):
        assert os.path.exists(str(tmp_path / n) + ".dev1.out")
    # the retry verdict (attempt + stall excerpt) landed in the
    # manifest for --status
    from pypulsar_tpu.survey.state import status_rows

    rows = status_rows(sorted(glob.glob(str(tmp_path / "*.survey.jsonl"))))
    stalled = [r for r in rows if r["retries"]]
    assert len(stalled) == 1
    assert stalled[0]["retries"]["dev1"]["attempts"] == 1
    assert "StageStalled" in stalled[0]["retries"]["dev1"]["error"]


def test_deadline_exceeded_quarantines_without_stalling_fleet(tmp_path):
    """A stage that heartbeats but outruns its declared deadline is
    interrupted every attempt and the observation quarantines; the
    other observation completes and the fleet returns promptly."""

    def slow_body(obs, cfg):
        if obs.name == "a":
            for _ in range(100):  # ~5 s, beating the whole way
                time.sleep(0.05)
                telemetry.counter("stub.steps")
        with open(f"{obs.outbase}.dev1.out", "w") as f:
            f.write(f"dev1 {obs.name}\n")
        return 0

    stages = [StageSpec("dev1", "stub", True, (), lambda o, c: [],
                        _stub_outputs("dev1"), run=slow_body,
                        deadline_s=0.4)]
    obs = [Observation(n, str(tmp_path / f"{n}.raw"), str(tmp_path / n))
           for n in ("a", "b")]
    with telemetry.session() as tlm:
        result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                                retries=1, stall_s=30.0).run()
        assert tlm.event_counts.get("survey.deadline_exceeded") == 2
        assert not tlm.event_counts.get("survey.stage_stalled")
    assert not result.ok
    assert set(result.quarantined) == {"a"}
    assert "StageDeadlineExceeded" in result.quarantined["a"]["error"]
    assert result.timeouts == 2  # first attempt + the retry
    assert ("b", "dev1") in result.ran
    assert os.path.exists(str(tmp_path / "b") + ".dev1.out")


def test_stage_deadline_per_mb_and_uniform_override(tmp_path):
    """deadline_for composes the flat and size-derived terms; the
    scheduler-level --stage-deadline overrides both."""
    raw = tmp_path / "o.raw"
    raw.write_bytes(b"\0" * 2_000_000)  # 2 MB
    obs = Observation("o", str(raw), str(tmp_path / "o"))
    s = StageSpec("x", "stub", True, (), lambda o, c: [],
                  _stub_outputs("x"), deadline_s=10.0,
                  deadline_per_mb=2.0)
    assert s.deadline_for(obs) == pytest.approx(14.0)
    s2 = StageSpec("x", "stub", True, (), lambda o, c: [],
                   _stub_outputs("x"), deadline_per_mb=3.0)
    assert s2.deadline_for(obs) == pytest.approx(6.0)
    # unstatable input contributes nothing (the stage reports it)
    gone = Observation("g", str(tmp_path / "gone.raw"),
                       str(tmp_path / "g"))
    assert s.deadline_for(gone) == pytest.approx(10.0)
    assert s2.deadline_for(gone) is None
    s3 = StageSpec("x", "stub", True, (), lambda o, c: [],
                   _stub_outputs("x"))
    assert s3.deadline_for(obs) is None
    sched = FleetScheduler([obs], SurveyConfig(), stages=[s],
                           stage_deadline=99.0)
    assert sched._deadline_for(s, obs) == 99.0


def test_device_fault_strikes_evict_lease_mid_fleet(tmp_path):
    """A lease past K strikes is quarantined OUT of the pool mid-fleet:
    the fleet completes on the survivors, the verdict is mirrored to
    _fleet_health.json, and survey --status renders it."""
    from pypulsar_tpu.survey.state import (
        format_status,
        read_fleet_health,
        status_rows,
    )

    flaky = {"n": 0}

    def body(obs, cfg):
        if obs.name == "a" and flaky["n"] < 1:
            flaky["n"] += 1
            raise faultinject.InjectedDeviceFault("stub.dispatch")
        with open(f"{obs.outbase}.dev1.out", "w") as f:
            f.write(f"dev1 {obs.name}\n")
        return 0

    stages = [StageSpec("dev1", "stub", True, (), lambda o, c: [],
                        _stub_outputs("dev1"), run=body)]
    obs = [Observation(n, str(tmp_path / f"{n}.raw"), str(tmp_path / n))
           for n in ("a", "b", "c")]
    with telemetry.session() as tlm:
        result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                                devices=2, retries=2,
                                strike_limit=1).run()
        assert tlm.event_counts.get("survey.device_evicted") == 1
        assert tlm.event_counts.get("mesh.device_quarantined") == 1
    assert result.ok and len(result.evicted_devices) == 1
    evicted = result.evicted_devices[0]
    health = read_fleet_health(str(tmp_path))
    assert health is not None and health["strike_limit"] == 1
    dev = health["devices"][str(evicted)]
    assert dev["quarantined"] and dev["strikes"] >= 1
    assert "DEVICE_FAULT" in dev["last_error"]
    rendered = format_status(
        status_rows(sorted(glob.glob(str(tmp_path / "*.survey.jsonl")))),
        health=health)
    assert "QUARANTINED" in rendered and f"device {evicted}" in rendered
    for n in ("a", "b", "c"):
        assert os.path.exists(str(tmp_path / n) + ".dev1.out")


def test_last_healthy_lease_never_evicted(tmp_path):
    """Strikes on the only healthy lease are counted but the verdict is
    deferred: an empty pool is a hung fleet, strictly worse than a
    flaky one."""
    flaky = {"n": 0}

    def body(obs, cfg):
        if flaky["n"] < 2:
            flaky["n"] += 1
            raise faultinject.InjectedDeviceFault("stub.dispatch")
        with open(f"{obs.outbase}.dev1.out", "w") as f:
            f.write(f"dev1 {obs.name}\n")
        return 0

    stages = [StageSpec("dev1", "stub", True, (), lambda o, c: [],
                        _stub_outputs("dev1"), run=body)]
    obs = [Observation("a", str(tmp_path / "a.raw"), str(tmp_path / "a"))]
    result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                            devices=1, retries=3, strike_limit=1).run()
    assert result.ok and result.evicted_devices == []
    assert result.retried == 2


def test_admission_gate_pauses_scheduling_not_inflight(tmp_path):
    """Backpressure (a pending_depth gauge above --max-pending) pauses
    LAUNCHING new stages; when the gauge drains the fleet resumes and
    completes. One paused + one resumed event per episode."""
    stages = _stub_stages()
    obs = [Observation(f"o{i}", str(tmp_path / f"o{i}.raw"),
                       str(tmp_path / f"o{i}")) for i in range(2)]
    with telemetry.session() as tlm:
        telemetry.gauge("stub.pending_depth", 10)
        sched = FleetScheduler(obs, SurveyConfig(), stages=stages,
                               max_pending=5)
        t = threading.Thread(target=sched.run)
        t.start()
        for _ in range(100):
            if tlm.event_counts.get("survey.admission_paused"):
                break
            time.sleep(0.05)
        assert tlm.event_counts.get("survey.admission_paused") == 1
        assert not sched.result.ran  # nothing launched while paused
        telemetry.gauge("stub.pending_depth", 0)  # the consumer drained
        t.join(timeout=30.0)
        assert not t.is_alive()
        assert tlm.event_counts.get("survey.admission_resumed") == 1
    assert sched.result.ok and len(sched.result.ran) == 4


def test_tlmsum_renders_fleet_health_rollup(tmp_path):
    """The fleet-health verdicts are visible in tlmsum: watchdog
    interrupts, deadline/stall events, device strikes/quarantines and
    injected-fault counts roll up into one `fleet health:` line."""
    import io

    from pypulsar_tpu.obs.summarize import load_records, render, summarize

    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path):
        telemetry.counter("survey.watchdog_interrupts", 2)
        telemetry.event("survey.deadline_exceeded", obs="a", stage="sweep")
        telemetry.event("survey.stage_stalled", obs="b", stage="fold")
        telemetry.event("mesh.device_strike", dev=1, kind="oom", strikes=1)
        telemetry.event("mesh.device_quarantined", dev=1, strikes=3)
        telemetry.event("survey.device_evicted", devs=[1], stage="sweep")
        telemetry.counter("resilience.faults_injected", 4)
    buf = io.StringIO()
    render(summarize(load_records(path)), buf)
    out = buf.getvalue()
    assert "fleet health:" in out
    for bit in ("watchdog interrupts=2", "deadlines exceeded=1",
                "stalls=1", "device strikes=1", "devices quarantined=1",
                "lease evictions=1", "injected faults=4"):
        assert bit in out, bit


def test_gang_shrinks_after_eviction_byte_identical(fleet):
    """Acceptance: a chip-indicting fault mid-gang evicts the struck
    lease and the retried gang SHRINKS to the survivors — with the
    final artifacts byte-identical to the serial 1-chip chain, because
    placement is excluded from every fingerprint."""
    _require_virtual_mesh(2)
    cfg = SurveyConfig(**CFG_KW)
    outdir = str(fleet["root"] / "shrink")
    obs = _fleet_obs(fleet["fils"][:1], outdir)
    # the device fault escapes the accel batch dispatch mid-sweep (the
    # no_degrade contract forbids the serial fallback from absorbing
    # it), indicts the whole gang, and the strike evicts one lease
    faultinject.configure("device:accel.batch_dispatch:1")
    trace = str(fleet["root"] / "shrink_trace.jsonl")
    with telemetry.session(trace) as tlm:
        result = FleetScheduler(obs, cfg, devices=2, gang=2,
                                retries=2, strike_limit=1).run()
        assert tlm.event_counts.get("survey.device_evicted") == 1
    assert result.ok and result.retried >= 1
    assert len(result.evicted_devices) == 1
    # the sweep gang ran wide first, then retried shrunk (the decision
    # trail is in the trace, attrs and all)
    decisions = [r["attrs"] for r in map(json.loads, open(trace))
                 if r.get("type") == "event"
                 and r.get("name") == "survey.gang_decision"]
    sweep_ks = [d["k"] for d in decisions if d["stage"] == "sweep"]
    assert sweep_ks[0] == 2 and sweep_ks[-1] == 1
    # the shrunk retry ran on the SURVIVING chip, and said why
    last = [d for d in decisions if d["stage"] == "sweep"][-1]
    assert result.evicted_devices[0] not in last["chips"]
    assert "healthy" in last["reason"]
    _assert_matches_reference(fleet, outdir, stems=("psr0",))


@pytest.mark.slow
def test_seeded_chaos_fleet_recovers_byte_identical(fleet, monkeypatch):
    """The chaos harness's contract at pytest scale (bench.py --chaos is
    the committed record): a seeded probabilistic fault spray across
    every registered point, plus armed kill/hang faults in the nastiest
    windows, resumed until the fleet completes — with every artifact
    byte-identical to the serial chain. Marked slow: tier-1 runs with
    -m 'not slow'; `make test-chaos` runs the bench harness."""
    import random

    monkeypatch.setenv(faultinject.ENV_HANG_S, "12")
    cfg = SurveyConfig(**CFG_KW)
    outdir = str(fleet["root"] / "chaos")
    obs = _fleet_obs(fleet["fils"], outdir)
    faultinject.configure_chaos("3:0.004")
    faultinject.configure("kill:survey.stage_done.sweep:1,"
                          "hang:sweep.chunk_dispatch:2")
    result = None
    rounds = kills = 0
    while rounds < 15:
        rounds += 1
        sched = FleetScheduler(obs, cfg, max_host_workers=2,
                               retries=2, resume=(rounds > 1),
                               stall_s=8.0,
                               jitter_rng=random.Random(rounds))
        try:
            result = sched.run()
        except faultinject.InjectedKill:
            kills += 1
            continue
        if result.ok:
            break
    fired = faultinject.fired_counts()
    assert result is not None and result.ok, (rounds, fired)
    assert fired.get("kill", 0) >= 1 and fired.get("hang", 0) >= 1
    # the final no-chaos resume validates everything and runs NOTHING
    faultinject.reset()
    final = FleetScheduler(obs, cfg, max_host_workers=2,
                           resume=True).run()
    assert final.ok and len(final.ran) == 0
    _assert_matches_reference(fleet, outdir)
