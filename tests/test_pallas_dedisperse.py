"""shifted_gather_sum: lax vs interpret-mode Pallas parity."""

import numpy as np
import pytest

from pypulsar_tpu.ops.pallas_dedisperse import shifted_gather_sum


def _ref(data, rows, shifts, out_len):
    O, K = rows.shape
    return np.stack([
        sum(data[rows[o, k], shifts[o, k]:shifts[o, k] + out_len]
            for k in range(K))
        for o in range(O)])


@pytest.mark.parametrize("O,K,out_len", [(6, 4, 700), (3, 16, 1024),
                                         (1, 1, 130)])
def test_gather_sum_backends_agree(O, K, out_len):
    rng = np.random.RandomState(0)
    R, L = 32, out_len + 5000
    data = rng.randn(R, L).astype(np.float32)
    rows = rng.randint(0, R, size=(O, K)).astype(np.int32)
    shifts = rng.randint(0, L - out_len, size=(O, K)).astype(np.int32)
    ref = _ref(data, rows, shifts, out_len)
    for backend in ("lax", "interpret", "auto"):
        got = np.asarray(shifted_gather_sum(data, rows, shifts, out_len,
                                            backend=backend))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_gather_sum_is_dedispersion():
    """Sanity: using dispersion bin delays recovers an injected pulse."""
    from pypulsar_tpu.ops import numpy_ref

    rng = np.random.RandomState(1)
    C, T, dt, dm = 32, 4096, 1e-3, 20.0
    freqs = 1500.0 - 4.0 * np.arange(C)
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    data = rng.randn(C, T + bins.max() + 1).astype(np.float32)
    for c in range(C):
        data[c, 1000 + bins[c]] += 30.0
    rows = np.arange(C, dtype=np.int32)[None, :]
    shifts = bins.astype(np.int32)[None, :]
    ts = np.asarray(shifted_gather_sum(data, rows, shifts, T,
                                       backend="interpret"))[0]
    assert int(np.argmax(ts)) == 1000
