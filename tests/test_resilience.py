"""Resilience-layer tests (round 7): every recovery path proven under
DETERMINISTIC injected failure, with the acceptance bar that candidate
tables stay BIT-IDENTICAL to an unfaulted run — under injected device
OOM (the dispatch auto-halves and completes), injected transient read
errors (the worker retries), and kill+resume at every journal
kill-point of the streamed ``sweep --accel-search`` chain — and each
recovery emits a telemetry event visible in tlmsum."""

import glob
import json
import os
import threading

import numpy as np
import pytest

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.resilience.journal import (
    RunJournal,
    atomic_write_text,
    candfile_complete,
    file_digest,
)
from pypulsar_tpu.resilience.retry import halving_dispatch, is_oom_error

from tests.test_accel_pipeline import (
    ACCEL_ARGS,
    HANDOFF_ARGS,
    SWEEP_ARGS,
    _pulsar_fil,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    """Armed faults and hit counters never leak between tests."""
    faultinject.reset()
    yield
    faultinject.reset()


# ---------------------------------------------------------------------------
# fault injection core
# ---------------------------------------------------------------------------


def test_fault_spec_parsing():
    spec = faultinject.parse_spec("oom:sweep.chunk_dispatch:2, io:x.produce")
    assert spec == {("oom", "sweep.chunk_dispatch"): 2, ("io", "x.produce"): 1}
    for bad in ("boom:x:1", "oom:x:0", "oom:x:1:2"):  # psrlint: ignore[PL005] -- grammar-rejection fixtures, never armed
        with pytest.raises(ValueError):
            faultinject.parse_spec(bad)


def test_fault_trip_fires_on_nth_hit_once():
    faultinject.configure("oom:p:3")
    faultinject.trip("p")
    faultinject.trip("p")
    with pytest.raises(faultinject.InjectedOOM) as ei:
        faultinject.trip("p")
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    # fired once: further hits pass (and with nothing left armed the
    # no-op fast path stops even counting)
    faultinject.trip("p")
    assert faultinject.hits("p") == 3

    faultinject.configure("io:q")
    with pytest.raises(OSError):
        faultinject.trip("q")
    faultinject.configure("kill:r")
    with pytest.raises(BaseException) as ei:
        faultinject.trip("r")
    assert isinstance(ei.value, faultinject.InjectedKill)
    assert not isinstance(ei.value, Exception)  # unswallowable by handlers


def test_fault_injection_emits_telemetry_event():
    faultinject.configure("io:t")
    with telemetry.session() as tlm:
        with pytest.raises(OSError):
            faultinject.trip("t")
        assert tlm.event_counts.get("resilience.fault_injected") == 1


def test_is_oom_error_classifier():
    assert is_oom_error(faultinject.InjectedOOM("x"))
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: alloc failed"))
    assert is_oom_error(RuntimeError("Out of memory allocating 5GB"))
    assert not is_oom_error(RuntimeError("INVALID_ARGUMENT"))
    assert not is_oom_error(KeyboardInterrupt())  # BaseException stays fatal


# ---------------------------------------------------------------------------
# halving dispatch
# ---------------------------------------------------------------------------


def test_halving_dispatch_splits_only_oom_slices(monkeypatch):
    monkeypatch.setattr("pypulsar_tpu.resilience.retry.BACKOFF_BASE_S", 0.0)
    calls = []

    def run(lo, hi):
        calls.append((lo, hi))
        if hi - lo > 2:
            raise faultinject.InjectedOOM("big")
        return list(range(lo, hi))

    out = halving_dispatch(run, 8, what="t")
    # results cover [0, 8) in order with no overlap
    assert [x for _, _, r in out for x in r] == list(range(8))
    assert all(hi - lo <= 2 for lo, hi, _ in out)
    assert (0, 8) in calls  # the whole dispatch was attempted first


def test_halving_dispatch_min_size_and_reraise(monkeypatch):
    monkeypatch.setattr("pypulsar_tpu.resilience.retry.BACKOFF_BASE_S", 0.0)

    def always_oom(lo, hi):
        raise faultinject.InjectedOOM("p")

    with pytest.raises(faultinject.InjectedOOM):
        halving_dispatch(always_oom, 8, min_size=4, what="t")

    def not_oom(lo, hi):
        raise ValueError("unrelated")

    with pytest.raises(ValueError):
        halving_dispatch(not_oom, 8, what="t")

    # min_size multiples: a mesh-constrained axis never splits off-grid
    sizes = []

    def run(lo, hi):
        sizes.append(hi - lo)
        if hi - lo > 4:
            raise faultinject.InjectedOOM("p")
        return hi - lo

    out = halving_dispatch(run, 12, min_size=4, what="t")
    assert all((hi - lo) % 4 == 0 for lo, hi, _ in out)
    assert sum(r for _, _, r in out) == 12


def test_halving_dispatch_emits_backoff_event(monkeypatch):
    monkeypatch.setattr("pypulsar_tpu.resilience.retry.BACKOFF_BASE_S", 0.0)
    state = {"failed": False}

    def run(lo, hi):
        if not state["failed"]:
            state["failed"] = True
            raise faultinject.InjectedOOM("p")
        return hi - lo

    with telemetry.session() as tlm:
        halving_dispatch(run, 4, what="t")
        assert tlm.event_counts.get("resilience.oom_backoff") == 1
        assert tlm.counter_totals().get("resilience.oom_backoffs") == 1


# ---------------------------------------------------------------------------
# journal + artifact integrity
# ---------------------------------------------------------------------------


def test_journal_roundtrip_and_validation(tmp_path):
    art = str(tmp_path / "a.bin")
    with open(art, "wb") as f:
        f.write(b"payload")
    jp = str(tmp_path / "run.jsonl")
    with RunJournal(jp, "fp1") as j:
        j.done("unit:a", [art])
        j.note(event="milestone")
    j2 = RunJournal(jp, "fp1")
    assert j2.completed() == {"unit:a"}

    # truncated artifact -> unit is redone, not trusted
    with open(art, "wb") as f:
        f.write(b"pay")
    with telemetry.session() as tlm:
        assert RunJournal(jp, "fp1").completed() == set()
        assert tlm.event_counts.get("resilience.journal_invalid") == 1
    # same size, different bytes -> checksum catches it
    with open(art, "wb") as f:
        f.write(b"paYload")
    assert RunJournal(jp, "fp1").completed() == set()
    # restored content revalidates
    with open(art, "wb") as f:
        f.write(b"payload")
    assert RunJournal(jp, "fp1").completed() == {"unit:a"}
    # deleted artifact -> redone
    os.remove(art)
    assert RunJournal(jp, "fp1").completed() == set()


def test_journal_torn_trailing_line_and_fingerprint(tmp_path):
    art = str(tmp_path / "a.bin")
    with open(art, "wb") as f:
        f.write(b"x" * 64)
    jp = str(tmp_path / "run.jsonl")
    j = RunJournal(jp, "fp1")
    j.done("u1", [art])
    j.close()
    # a kill mid-append leaves a torn trailing line: tolerated
    with open(jp, "a") as f:
        f.write('{"type": "done", "unit": "u2", "outp')
    assert RunJournal(jp, "fp1").completed() == {"u1"}
    # appending to the recovered journal keeps it parseable
    j3 = RunJournal(jp, "fp1")
    j3.done("u3", [art])
    j3.close()
    # NOTE: the torn line is superseded, u1/u3 survive
    assert RunJournal(jp, "fp1").completed() == {"u1", "u3"}
    # a different run fingerprint ignores everything
    assert RunJournal(jp, "OTHER").completed() == set()


def test_candfile_complete(tmp_path):
    cand = str(tmp_path / "x_ACCEL_20.cand")
    txt = str(tmp_path / "x_ACCEL_20.txtcand")
    # missing -> incomplete
    assert not candfile_complete(cand, txt)
    # zero-byte .cand WITHOUT its txt twin: killed-run debris
    open(cand, "wb").close()
    assert not candfile_complete(cand, txt)
    # legitimately empty result: 0 records + header-only txt
    atomic_write_text(txt, "# cand   sigma\n")
    assert candfile_complete(cand, txt)
    # row-count mismatch -> incomplete
    atomic_write_text(txt, "# cand   sigma\n1  5.0\n")
    assert not candfile_complete(cand, txt)
    # whole records + matching rows -> complete
    with open(cand, "wb") as f:
        f.write(b"\0" * 88)
    assert candfile_complete(cand, txt)
    # torn record -> incomplete regardless of the txt
    with open(cand, "wb") as f:
        f.write(b"\0" * 87)
    assert not candfile_complete(cand, txt)


def test_atomic_write_leaves_no_partial(tmp_path):
    p = str(tmp_path / "out.txt")
    atomic_write_text(p, "hello")
    assert open(p).read() == "hello"
    assert not os.path.exists(p + ".tmp")
    size, digest = file_digest(p)
    assert size == 5


# ---------------------------------------------------------------------------
# prefetch worker retry + consumer deadline
# ---------------------------------------------------------------------------


def test_prefetch_retries_transient_io_error():
    from pypulsar_tpu.parallel.prefetch import prefetch

    faultinject.configure("io:rt.produce:2")
    with telemetry.session() as tlm:
        out = list(prefetch(iter(range(6)), depth=2, name="rt",
                            transform=lambda x: x * 10, retries=2,
                            retry_backoff=0.01))
        assert out == [x * 10 for x in range(6)]  # value + order unchanged
        assert tlm.event_counts.get("resilience.worker_retry") == 1
        assert tlm.counter_totals().get("resilience.worker_retries") == 1


def test_prefetch_retry_exhaustion_reraises():
    from pypulsar_tpu.parallel import prefetch as prefetch_mod
    from pypulsar_tpu.parallel.prefetch import prefetch

    def flaky(x):
        raise OSError("persistent disk failure")

    it = prefetch(iter(range(3)), depth=2, name="rx", transform=flaky,
                  retries=1, retry_backoff=0.01)
    with pytest.raises(OSError, match="persistent"):
        list(it)

    # retries=0 (the default) keeps the old fail-fast contract
    it = prefetch(iter(range(3)), depth=2, name="rx0", transform=flaky)
    with pytest.raises(OSError):
        list(it)
    assert prefetch_mod.RETRY_BACKOFF_MAX_S >= 1.0  # backoff is bounded


def test_retry_transient_never_retries_permanent_errors():
    """A typo'd path (FileNotFoundError) or bad permission fails on the
    FIRST attempt — retrying a configuration error only delays it and
    mislabels it as IO weather."""
    from pypulsar_tpu.resilience.retry import retry_transient

    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError("no such file: typo.dat")

    with pytest.raises(FileNotFoundError):
        retry_transient(missing, retries=3, backoff=0.01, what="t")
    assert len(calls) == 1


def test_prefetch_consumer_deadline_fails_loudly():
    """A wedged producer must raise a TimeoutError naming the pipeline,
    promptly, and the generator cleanup must not inherit the wedge."""
    from pypulsar_tpu.parallel.prefetch import prefetch

    release = threading.Event()

    def wedge(x):
        release.wait(30.0)  # simulates a hung read/ship
        return x

    it = prefetch(iter(range(3)), depth=1, name="wedged",
                  transform=wedge, timeout=0.3)
    with pytest.raises(TimeoutError, match="wedged"):
        list(it)
    release.set()  # let the daemon worker exit


def test_prefetch_inline_mode_applies_same_retry(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_SHIP_AHEAD", "0")
    from pypulsar_tpu.parallel.prefetch import prefetch

    faultinject.configure("io:inl.produce:1")
    out = list(prefetch(iter(range(4)), name="inl", retries=1,
                        retry_backoff=0.01))
    assert out == list(range(4))


# ---------------------------------------------------------------------------
# telemetry sink hardening
# ---------------------------------------------------------------------------


def test_telemetry_sink_unwritable_path_never_crashes(tmp_path, capsys):
    bad = str(tmp_path / "no" / "such" / "dir" / "t.jsonl")
    with telemetry.session(bad) as tlm:
        telemetry.counter("c", 2)
        telemetry.event("e", k=1)
        with telemetry.span("s"):
            pass
        assert tlm.counter_totals()["c"] == 2  # memory side still works
    err = capsys.readouterr().err
    assert err.count("telemetry: sink") == 1  # warned exactly once


def test_telemetry_sink_dies_midrun_drops_quietly(tmp_path, capsys):
    path = str(tmp_path / "t.jsonl")

    class _Dying:
        def __init__(self, fh):
            self._fh = fh
            self.writes = 0

        def write(self, s):
            self.writes += 1
            if self.writes > 1:
                raise OSError(28, "No space left on device")
            return self._fh.write(s)

        def flush(self):
            pass

        def close(self):
            self._fh.close()

        def fileno(self):
            return self._fh.fileno()

    with telemetry.session(path) as tlm:
        tlm._fh = _Dying(tlm._fh)
        telemetry.event("first")   # hits the dying write
        telemetry.event("second")  # sink is gone: must not raise
        telemetry.counter("c")
        assert tlm.counter_totals()["c"] == 1
    err = capsys.readouterr().err
    assert err.count("telemetry: sink") == 1


# ---------------------------------------------------------------------------
# OOM-adaptive pipelines: bit-identical recovery
# ---------------------------------------------------------------------------


def test_sweep_oom_backoff_bit_identical(tmp_path):
    """Injected device OOM on a sweep chunk dispatch: the trial-group
    axis halves, the run completes, and the result is BIT-identical."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_flat

    fil = _pulsar_fil(tmp_path, T=8192)
    dms = np.arange(12) * 10.0
    clean = sweep_flat(filterbank.FilterbankFile(fil), dms, nsub=8,
                       group_size=4, chunk_payload=2048).steps[0].result

    faultinject.configure("oom:sweep.chunk_dispatch:2")
    with telemetry.session() as tlm:
        faulted = sweep_flat(filterbank.FilterbankFile(fil), dms, nsub=8,
                             group_size=4,
                             chunk_payload=2048).steps[0].result
        assert tlm.event_counts.get("resilience.oom_backoff") == 1
        assert tlm.event_counts.get("resilience.fault_injected") == 1
    np.testing.assert_array_equal(faulted.snr, clean.snr)
    np.testing.assert_array_equal(faulted.peak_sample, clean.peak_sample)
    np.testing.assert_array_equal(faulted.mean, clean.mean)


def test_accel_stage_oom_bit_identical(tmp_path):
    """Injected OOM inside the batched stage runner: the HBM chunk
    halves and the per-spectrum candidates are unchanged."""
    from pypulsar_tpu.fourier.accelsearch import (
        AccelSearchConfig,
        accel_search_batch,
    )

    rng = np.random.RandomState(11)
    N, T = 1 << 12, 8.0
    ffts = (rng.standard_normal((4, N)) + 1j * rng.standard_normal((4, N))
            ).astype(np.complex64)
    ffts /= np.sqrt(2.0)
    cfg = AccelSearchConfig(zmax=10.0, numharm=2, sigma_min=2.5,
                            seg_width=1 << 10)
    clean = accel_search_batch(ffts, T, cfg)
    faultinject.configure("oom:accel.stage_dispatch:1")
    with telemetry.session() as tlm:
        faulted = accel_search_batch(ffts, T, cfg)
        assert tlm.event_counts.get("resilience.oom_backoff", 0) >= 1
    assert len(clean) == len(faulted)
    for a, b in zip(clean, faulted):
        assert [(c.r, c.z, c.power, c.sigma) for c in a] \
            == [(c.r, c.z, c.power, c.sigma) for c in b]


def test_accel_batch_oom_bit_identical_no_fallback(tmp_path, monkeypatch):
    """Injected OOM on a streamed-handoff batch dispatch: the batch
    halves (NOT the serial fallback — candidates must come from the
    batched path) and every table is byte-identical."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "c", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    ref = {os.path.basename(f)[1:]: open(f, "rb").read()
           for f in sorted(glob.glob("c_DM*_ACCEL_20.cand"))}
    assert len(ref) == 8

    tlm_path = str(tmp_path / "oom.jsonl")
    assert cli_sweep.main([fil, "-o", "o", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", "--telemetry", tlm_path,
                           "--fault-inject",
                           "oom:accel.batch_dispatch:1"]) == 0
    got = {os.path.basename(f)[1:]: open(f, "rb").read()
           for f in sorted(glob.glob("o_DM*_ACCEL_20.cand"))}
    assert got == ref

    # the recovery is visible in the tlmsum view of the trace, and the
    # serial fallback never engaged
    from pypulsar_tpu.obs.summarize import load_records, summarize

    s = summarize(load_records(tlm_path))
    assert s.events.get("resilience.oom_backoff", 0) >= 1
    assert s.events.get("resilience.fault_injected") == 1
    assert "accel.batch_serial_fallback" not in s.events


# ---------------------------------------------------------------------------
# kill + resume at every journal kill-point
# ---------------------------------------------------------------------------

KILL_POINTS = [
    ("dats.append:2", True),          # mid-stream .dat tee write
    ("accel.after_stream:1", False),  # series buffered, nothing searched
    ("accel.before_cand_write:3", False),
    ("accel.after_cand_write:2", False),  # written but not journaled
    ("accel.after_journal:2", False),     # journaled, next trial pending
]


def test_kill_resume_every_kill_point_bit_identical(tmp_path, monkeypatch):
    """Kill the streamed sweep->accel chain at EVERY journal kill-point;
    a --journal resume redoes exactly the unfinished units and every
    final artifact is byte-identical to an uninterrupted run."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    # --chunk 4096: the 16384-sample file streams as FOUR chunks, so the
    # mid-stream kill-points actually sit mid-stream
    assert cli_sweep.main([fil, "-o", "r", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--chunk", "4096", "--write-dats",
                           "--journal", "r.jsonl"]) == 0
    ref_cands = {os.path.basename(f)[1:]: open(f, "rb").read()
                 for f in sorted(glob.glob("r_DM*_ACCEL_20.cand"))}
    ref_dats = {os.path.basename(f)[1:]: open(f, "rb").read()
                for f in sorted(glob.glob("r_DM*.dat"))}
    ref_sp = open("r.cands", "rb").read()
    assert len(ref_cands) == 8 and len(ref_dats) == 8

    for ki, (spec, _tee_kill) in enumerate(KILL_POINTS):
        tag = f"k{ki}"
        argv = [fil, "-o", tag, *SWEEP_ARGS, *HANDOFF_ARGS,
                "--chunk", "4096", "--write-dats",
                "--journal", f"{tag}.jsonl"]
        with pytest.raises(faultinject.InjectedKill):
            cli_sweep.main(argv + ["--fault-inject", "kill:" + spec])
        faultinject.reset()
        # no published artifact may be a truncation: every .dat that made
        # it to its final name is byte-complete (atomic tmp + replace)
        for f in glob.glob(f"{tag}_DM*.dat"):
            name = os.path.basename(f)[len(tag):]
            assert open(f, "rb").read() == ref_dats[name], (spec, f)
        # resume: same command, no fault
        assert cli_sweep.main(argv) == 0, spec
        got = {os.path.basename(f)[len(tag):]: open(f, "rb").read()
               for f in sorted(glob.glob(f"{tag}_DM*_ACCEL_20.cand"))}
        assert got == ref_cands, spec
        dats = {os.path.basename(f)[len(tag):]: open(f, "rb").read()
                for f in sorted(glob.glob(f"{tag}_DM*.dat"))}
        assert dats == ref_dats, spec
        assert open(f"{tag}.cands", "rb").read() == ref_sp, spec


def test_kill_mid_tee_leaves_no_truncated_dat(tmp_path, monkeypatch):
    """A kill between .dat chunk appends leaves only .tmp staging files —
    a published .dat name is never a truncation."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    with pytest.raises(faultinject.InjectedKill):
        cli_sweep.main([fil, "-o", "t", *SWEEP_ARGS, *HANDOFF_ARGS,
                        "--chunk", "4096", "--write-dats",
                        "--fault-inject", "kill:dats.append:2"])
    faultinject.reset()
    assert glob.glob("t_DM*.dat") == []  # nothing published
    assert glob.glob("t_DM*.dat.tmp")   # staging debris only


def test_journal_resume_skips_completed_sweep_pass(tmp_path, monkeypatch):
    """A resumed --journal run whose sweep:cands unit validates skips the
    single-pulse sweep pass entirely (and redoes it if the artifact was
    corrupted)."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.parallel import staged as staged_mod

    calls = []
    real = staged_mod.sweep_flat

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(staged_mod, "sweep_flat", spy)
    argv = [fil, "-o", "j", *SWEEP_ARGS, "--journal", "j.jsonl"]
    assert cli_sweep.main(argv) == 0
    assert len(calls) == 1
    ref = open("j.cands", "rb").read()
    assert cli_sweep.main(argv) == 0
    assert len(calls) == 1  # second run resumed from the manifest
    assert open("j.cands", "rb").read() == ref
    # corrupt the artifact: the checksum catches it and the pass reruns
    with open("j.cands", "ab") as f:
        f.write(b"garbage\n")
    assert cli_sweep.main(argv) == 0
    assert len(calls) == 2
    assert open("j.cands", "rb").read() == ref


def test_journal_different_outbase_does_not_skip(tmp_path, monkeypatch):
    """The journal fingerprint includes the outbase: rerunning with a
    different -o against the same journal file must produce the new
    artifacts, not skip against the old ones."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "a", *SWEEP_ARGS,
                           "--journal", "j.jsonl"]) == 0
    assert cli_sweep.main([fil, "-o", "b", *SWEEP_ARGS,
                           "--journal", "j.jsonl"]) == 0
    assert os.path.exists("b.cands")
    assert open("b.cands", "rb").read() == open("a.cands", "rb").read()


def test_journal_refuses_foreign_tool_manifest(tmp_path, monkeypatch):
    """Pointing one stage's --journal at another stage's manifest raises
    instead of silently truncating it (the chain journal survives)."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sift as cli_sift
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "f", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", "--journal",
                           "chain.jsonl"]) == 0
    chain = open("chain.jsonl").read()
    cands = sorted(glob.glob("f_DM*_ACCEL_20.cand"))
    with pytest.raises(ValueError, match="different tool"):
        cli_sift.main(cands + ["-s", "3", "--min-hits", "1",
                               "-o", "f.accelcands",
                               "--journal", "chain.jsonl"])
    assert open("chain.jsonl").read() == chain  # manifest untouched


def test_journal_detects_truncated_cand_on_resume(tmp_path, monkeypatch):
    """A journaled trial whose .cand was truncated after the fact is
    re-searched on resume (size/sha256 validation), restoring the exact
    bytes."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    argv = [fil, "-o", "v", *SWEEP_ARGS, *HANDOFF_ARGS, "--accel-only",
            "--journal", "v.jsonl"]
    assert cli_sweep.main(argv) == 0
    victim = sorted(glob.glob("v_DM*_ACCEL_20.cand"))[3]
    ref = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(ref[:44])  # torn mid-record
    assert cli_sweep.main(argv) == 0
    assert open(victim, "rb").read() == ref


def test_skip_existing_revalidates_zero_byte_cand(tmp_path, monkeypatch):
    """--accel-skip-existing re-searches a zero-byte .cand (killed-run
    debris) instead of treating it as done — the pre-round-7 behavior
    permanently wedged such trials."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    argv = [fil, "-o", "z", *SWEEP_ARGS, *HANDOFF_ARGS, "--accel-only"]
    assert cli_sweep.main(argv) == 0
    fulls = sorted(glob.glob("z_DM*_ACCEL_20.cand"))
    assert len(fulls) == 8
    victim = fulls[2]
    ref = open(victim, "rb").read()
    open(victim, "wb").close()            # zero-byte debris
    os.remove(victim[:-5] + ".txtcand")   # and no txt twin
    assert cli_sweep.main(argv + ["--accel-skip-existing"]) == 0
    assert open(victim, "rb").read() == ref


def test_cli_accelsearch_skip_existing_revalidates(tmp_path, monkeypatch):
    """The .dat-file CLI's --skip-existing applies the same validation."""
    monkeypatch.chdir(tmp_path)
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from tests.test_accelsearch import _write_fake_dat

    rng = np.random.RandomState(31)
    N, dt = 1 << 13, 5e-4
    bases = []
    for ii in range(3):
        ts = rng.standard_normal(N).astype(np.float32)
        ts += 0.3 * np.cos(2 * np.pi * (40.0 + 5 * ii)
                           * np.arange(N) * dt).astype(np.float32)
        bases.append(_write_fake_dat(str(tmp_path / f"sk{ii}"), ts, dt))
    dats = [b + ".dat" for b in bases]
    argv = dats + ["-z", "10", "-n", "2", "-s", "3"]
    assert cli_accel.main(argv) == 0
    ref = {b: open(b + "_ACCEL_10.cand", "rb").read() for b in bases}
    # one zero-byte debris + one valid file left alone
    open(bases[1] + "_ACCEL_10.cand", "wb").close()
    os.remove(bases[1] + "_ACCEL_10.txtcand")
    before = os.path.getmtime(bases[0] + "_ACCEL_10.cand")
    assert cli_accel.main(argv + ["--skip-existing"]) == 0
    for b in bases:
        assert open(b + "_ACCEL_10.cand", "rb").read() == ref[b]
    assert os.path.getmtime(bases[0] + "_ACCEL_10.cand") == before


def test_sift_journal_and_truncated_input(tmp_path, monkeypatch):
    """cli/sift skips truncated .cand inputs with a warning and its
    --journal unit makes a rerun a validated no-op."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sift as cli_sift
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "s", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    cands = sorted(glob.glob("s_DM*_ACCEL_20.cand"))
    argv = cands + ["-s", "3", "--min-hits", "1", "-o", "s.accelcands",
                    "--journal", "sift.jsonl"]
    assert cli_sift.main(argv) == 0
    ref = open("s.accelcands").read()
    mtime = os.path.getmtime("s.accelcands")
    assert cli_sift.main(argv) == 0  # journaled no-op
    assert os.path.getmtime("s.accelcands") == mtime
    rec = json.loads(open("sift.jsonl").readline())
    assert rec["type"] == "journal" and rec["tool"] == "sift"
    # truncated input .cand: skipped with a warning, not read short
    data = open(cands[0], "rb").read()
    assert len(data) >= 88
    with open(cands[0], "wb") as f:
        f.write(data[:-40])
    assert cli_sift.collect([cands[0]]) == []
    # AND the content-hashed fingerprint makes the journaled rerun
    # re-sift (a changed input is a different run, not a no-op): the
    # journal restarts under a new fingerprint
    assert cli_sift.main(argv) == 0
    rec2 = json.loads(open("sift.jsonl").readline())
    assert rec2["fingerprint"] != rec["fingerprint"]
    assert ref  # sanity: the sift produced output


# ---------------------------------------------------------------------------
# fleet health primitives (round 12): heartbeats, deadlines, strikes,
# admission, jittered backoff, seeded chaos
# ---------------------------------------------------------------------------


def test_backoff_delay_jitter_range_and_determinism():
    import random

    from pypulsar_tpu.resilience.retry import backoff_delay

    # seeded rng -> reproducible delays, each in [0.5*d, d) of the
    # deterministic schedule min(base * 2^(attempt-1), cap)
    for attempt, full in ((1, 0.25), (2, 0.5), (3, 1.0), (10, 5.0)):
        a = backoff_delay(0.25, attempt, 5.0, random.Random(7))
        b = backoff_delay(0.25, attempt, 5.0, random.Random(7))
        assert a == b
        assert 0.5 * full <= a < full
    # different seeds decorrelate: N leases that failed together must
    # NOT come back in lockstep (the satellite's whole point)
    d1 = [backoff_delay(0.25, 2, 5.0, random.Random(s)) for s in range(16)]
    assert len(set(d1)) > 8
    # default (process) rng path stays in range too
    assert 0.25 <= backoff_delay(0.25, 2, 5.0) < 0.5


def test_chaos_spec_parsing():
    good = faultinject.parse_chaos_spec("42:0.1")
    assert good == (42, 0.1, faultinject.CHAOS_KINDS)
    assert "exit" not in faultinject.CHAOS_KINDS  # never self-kill the harness
    seed, rate, kinds = faultinject.parse_chaos_spec("7:0.5:oom+io")
    assert (seed, rate, kinds) == (7, 0.5, ("oom", "io"))
    for bad in ("42", "x:0.1", "42:1.5", "42:-0.1", "42:0.1:boom",
                "42:0.1:oom:extra", "42:0.1:exit"):
        with pytest.raises(ValueError):
            faultinject.parse_chaos_spec(bad)


def test_chaos_roll_deterministic_and_rate_bounded():
    """The chaos decision is a pure function of (seed, point, hit):
    thread interleaving cannot change it, and a re-rolled retry draws a
    FRESH decision (the cumulative hit index keeps counting)."""
    faultinject.configure_chaos("11:0.3:oom")
    fired_at = []
    for i in range(1, 201):
        try:
            faultinject.trip("chaos.point")
        except faultinject.InjectedOOM:
            fired_at.append(i)
    # seeded: the exact same firing pattern on a fresh armed state
    faultinject.reset()
    faultinject.configure_chaos("11:0.3:oom")
    fired_again = []
    for i in range(1, 201):
        try:
            faultinject.trip("chaos.point")
        except faultinject.InjectedOOM:
            fired_again.append(i)
    assert fired_at == fired_again
    # rate ~0.3 over 200 rolls: some fired, most did not
    assert 20 <= len(fired_at) <= 120
    assert faultinject.fired_counts() == {"oom": len(fired_at)}
    # a different seed draws a different pattern
    faultinject.reset()
    faultinject.configure_chaos("12:0.3:oom")
    other = []
    for i in range(1, 201):
        try:
            faultinject.trip("chaos.point")
        except faultinject.InjectedOOM:
            other.append(i)
    assert other != fired_at
    # rate 0 never fires; disarm clears
    faultinject.reset()
    faultinject.configure_chaos("11:0.0")
    for _ in range(50):
        faultinject.trip("chaos.point")
    assert faultinject.fired_counts() == {}


def test_chaos_composes_with_armed_and_device_kind():
    """The deterministic armed set wins at its exact (point, N); the
    injected device fault classifies as chip-indicting."""
    from pypulsar_tpu.resilience import health

    faultinject.configure_chaos("1:0.0")  # chaos armed but silent
    faultinject.configure("device:p:2")
    # arming a deterministic fault must NOT disarm the chaos spray
    # (bench --chaos arms one guaranteed fault per family on top of it)
    assert faultinject.chaos_active()
    faultinject.trip("p")
    with pytest.raises(faultinject.InjectedDeviceFault) as ei:
        faultinject.trip("p")
    assert health.is_device_fault(ei.value)
    assert health.no_degrade(ei.value)
    assert faultinject.fired_counts() == {"device": 1}


def test_injected_hang_is_bounded_and_interruptible(monkeypatch):
    """An unwatched hang ends on its own (PYPULSAR_TPU_HANG_S bound) —
    and sleeps in small slices so a watchdog interrupt can land."""
    import time as _time

    monkeypatch.setenv(faultinject.ENV_HANG_S, "0.3")
    faultinject.configure("hang:h:1")
    t0 = _time.monotonic()
    faultinject.trip("h")  # returns (no exception): progress resumed
    took = _time.monotonic() - t0
    assert 0.2 <= took < 2.0
    assert faultinject.fired_counts() == {"hang": 1}


def test_heartbeat_registry_deadline_and_stall():
    from pypulsar_tpu.resilience import health

    reg = health.HeartbeatRegistry()
    e_dl = reg.start("a", thread_id=1, deadline_s=10.0)
    e_st = reg.start("b", thread_id=2, stall_s=5.0)
    now = e_dl.started
    assert reg.expired(now + 1.0) == []
    # stall fires on heartbeat silence; a beat resets the clock
    e_st.last_beat = now  # pin, then advance past the bound
    out = reg.expired(now + 6.0)
    assert [(e.label, r) for e, r in out] == [("b", "stall")]
    # fired entries are returned AT MOST once (no re-interrupt)
    assert reg.expired(now + 7.0) == []
    # deadline fires from start time regardless of beats
    reg.beat_thread(1)
    out = reg.expired(now + 11.0)
    assert [(e.label, r) for e, r in out] == [("a", "deadline")]
    reg.finish(e_dl)
    reg.finish(e_st)
    assert reg.active() == []


def test_interrupt_thread_lands_mid_sleep():
    from pypulsar_tpu.resilience import health

    caught = []
    started = threading.Event()

    def victim():
        started.set()
        try:
            for _ in range(600):  # ~30 s of interruptible sleeping
                __import__("time").sleep(0.05)
        except health.StageStalled as e:
            caught.append(e)

    t = threading.Thread(target=victim)
    t.start()
    started.wait(5.0)
    assert health.interrupt_thread(t.ident, health.StageStalled)
    t.join(timeout=10.0)
    assert not t.is_alive() and len(caught) == 1
    # a gone thread is reported, not raised
    assert not health.interrupt_thread(t.ident, health.StageStalled) \
        or True  # CPython may reuse idents; only the call contract matters


def test_device_health_strikes_and_quarantine():
    from pypulsar_tpu.resilience import health

    dh = health.DeviceHealth(limit=2)
    assert not dh.strike(3, kind="oom", error="RESOURCE_EXHAUSTED hbm")
    assert dh.strikes(3) == 1 and not dh.is_quarantined(3)
    # allow_quarantine=False counts but defers the verdict (the
    # scheduler's last-healthy-lease protection)
    assert not dh.strike(3, kind="device", allow_quarantine=False)
    assert dh.strikes(3) == 2 and not dh.is_quarantined(3)
    # next allowed strike quarantines (>= limit), exactly once
    assert dh.strike(3, kind="device", error="DEVICE_FAULT: chip 3")
    assert dh.is_quarantined(3) and dh.quarantined() == {3}
    assert not dh.strike(3)  # already quarantined: not "newly"
    snap = dh.snapshot()
    assert snap[3]["quarantined"] and snap[3]["strikes"] == 4
    assert "DEVICE_FAULT" in snap[3]["last_error"]
    dh.reset()
    assert dh.snapshot() == {} and not dh.is_quarantined(3)


def test_is_device_fault_classification():
    from pypulsar_tpu.resilience import health

    assert health.is_device_fault(faultinject.InjectedDeviceFault("p"))
    assert health.is_device_fault(
        RuntimeError("collective operation failed on slice"))
    # OOMs are accounted separately; ordinary errors never cost a strike
    assert not health.is_device_fault(RuntimeError("RESOURCE_EXHAUSTED"))
    assert not health.is_device_fault(ValueError("bad dm"))
    # BaseExceptions (kills) are unwinding, not chip verdicts
    assert not health.is_device_fault(faultinject.InjectedKill("p"))


def test_must_propagate_and_no_degrade():
    from pypulsar_tpu.resilience import health

    assert health.must_propagate(health.StageDeadlineExceeded("late"))
    assert health.must_propagate(health.StageStalled("silent"))
    assert health.must_propagate(faultinject.InjectedDeviceFault("p"))
    assert not health.must_propagate(faultinject.InjectedOOM("p"))
    # no_degrade adds EVERY injected fault: byte-divergent degrade
    # paths must not absorb what the chaos harness asserts recovers
    # byte-identically
    assert health.no_degrade(faultinject.InjectedOOM("p"))
    assert health.no_degrade(faultinject.InjectedIOError("p"))
    assert not health.no_degrade(ValueError("poison spectrum"))


def test_resource_guard_disk_and_backpressure(tmp_path, monkeypatch):
    from pypulsar_tpu.resilience import health

    g = health.ResourceGuard(str(tmp_path), min_free_bytes=64e6,
                             max_pending=4)
    monkeypatch.setattr(health.ResourceGuard, "free_bytes",
                        lambda self: 32e6)
    reason = g.admit()
    assert reason is not None and "low disk" in reason
    monkeypatch.setattr(health.ResourceGuard, "free_bytes",
                        lambda self: 128e6)
    assert g.admit() is None
    # a live pending_depth gauge above the bound pauses admission
    with telemetry.session():
        telemetry.gauge("accel.pending_depth", 9)
        reason = g.admit()
        assert reason is not None and "backpressure" in reason
        telemetry.gauge("accel.pending_depth", 1)
        assert g.admit() is None
    # disabled floor + no session: always admits
    g2 = health.ResourceGuard(str(tmp_path), min_free_bytes=0,
                              max_pending=None)
    assert g2.admit() is None
    # an unstatable root is not a reason to pause
    g3 = health.ResourceGuard(str(tmp_path / "missing"),
                              min_free_bytes=64e6)
    assert g3.free_bytes() is None or g3.admit() is None


def test_env_float_tolerates_garbage(monkeypatch):
    from pypulsar_tpu.resilience import health

    monkeypatch.setenv("X_KNOB", "not-a-float")
    assert health.env_float("X_KNOB", 3.0) == 3.0
    monkeypatch.setenv("X_KNOB", "1.5")
    assert health.env_float("X_KNOB", 3.0) == 1.5
    monkeypatch.delenv("X_KNOB")
    assert health.env_float("X_KNOB", None) is None


def test_survey_manifest_torn_tail_on_done_and_quarantine(tmp_path):
    """Satellite: RunJournal torn-tail recovery on SURVEY manifests — a
    kill mid-append of a `done` or `quarantine` note leaves a torn
    trailing line that resume and --status must treat as never written
    (the chaos harness's kill faults land exactly in these windows)."""
    from pypulsar_tpu.survey.state import (
        ObsManifest,
        Observation,
        status_rows,
    )

    art = str(tmp_path / "obs0_rfifind.mask")
    with open(art, "wb") as f:
        f.write(b"m" * 128)
    obs = Observation("obs0", str(tmp_path / "obs0.fil"),
                      str(tmp_path / "obs0"))
    mpath = obs.manifest

    m = ObsManifest(mpath, "fp-torn")
    m.plan(obs, ["mask", "sweep", "sift"])
    m.mark_done("mask", [art])
    m.close()

    # kill mid-append of the NEXT stage's done record: torn tail
    with open(mpath, "a") as f:
        f.write('{"type": "done", "unit": "stage:sweep", "outpu')
    m2 = ObsManifest(mpath, "fp-torn")
    assert m2.done_stages() == {"mask"}  # sweep's torn done: not done
    # the recovered journal stays appendable and the torn line is
    # superseded, not resurrected
    m2.mark_done("sweep", [art])
    m2.close()
    assert ObsManifest(mpath, "fp-torn").done_stages() == {"mask", "sweep"}

    # kill mid-append of a QUARANTINE note: --status must not show a
    # phantom quarantine (nor crash on the torn record)
    with open(mpath, "a") as f:
        f.write('{"type": "note", "event": "quarantine", "stage": "si')
    rows = status_rows([mpath])
    assert rows[0]["quarantine"] is None
    assert rows[0]["done"] == ["mask", "sweep"]
    # a whole quarantine note written after recovery IS the verdict
    m3 = ObsManifest(mpath, "fp-torn")
    m3.quarantine("sift", "boom")
    m3.close()
    rows = status_rows([mpath])
    assert rows[0]["quarantine"] == {"stage": "sift", "error": "boom"}


def test_survey_manifest_torn_retry_note(tmp_path):
    """A torn retry note (the new --status annotation channel) is
    dropped like any torn tail; whole notes accumulate attempts."""
    from pypulsar_tpu.survey.state import (
        ObsManifest,
        Observation,
        status_rows,
    )

    obs = Observation("obs1", str(tmp_path / "obs1.fil"),
                      str(tmp_path / "obs1"))
    m = ObsManifest(obs.manifest, "fp-r")
    m.plan(obs, ["mask"])
    m.note_retry("mask", 1, "InjectedOOM: injected oom at 'x'")
    m.close()
    with open(obs.manifest, "a") as f:
        f.write('{"type": "note", "event": "retry", "stage": "mask", "att')
    rows = status_rows([obs.manifest])
    assert rows[0]["retries"]["mask"]["attempts"] == 1
    m2 = ObsManifest(obs.manifest, "fp-r")
    m2.note_retry("mask", 2, "StageStalled: no heartbeat for 8.0s")
    m2.close()
    rows = status_rows([obs.manifest])
    assert rows[0]["retries"]["mask"]["attempts"] == 2
    assert "StageStalled" in rows[0]["retries"]["mask"]["error"]


def test_atomic_open_success_and_failure(tmp_path):
    """The streaming atomic-write helper: on clean exit the artifact
    appears whole and the tmp is gone; on ANY exception (including
    BaseException kills) the target is untouched and no tmp debris
    survives."""
    from pypulsar_tpu.resilience.journal import atomic_open

    out = tmp_path / "obs.dat"
    with atomic_open(str(out), "wb") as f:
        f.write(b"abc")
        assert not out.exists()  # nothing visible until the rename
    assert out.read_bytes() == b"abc"
    assert not (tmp_path / "obs.dat.tmp").exists()

    class _Kill(BaseException):
        pass

    with pytest.raises(_Kill):
        with atomic_open(str(out), "wb") as f:
            f.write(b"torn")
            raise _Kill()
    assert out.read_bytes() == b"abc"  # old artifact untouched
    assert not (tmp_path / "obs.dat.tmp").exists()

    # append/read/update modes would silently REPLACE the artifact
    # with just the tmp's bytes: refused at the entry point
    for bad_mode in ("ab", "a", "r+b", "rb"):
        with pytest.raises(ValueError):
            with atomic_open(str(out), bad_mode):
                pass
    assert out.read_bytes() == b"abc"
