"""Timing stack tests: parfile, polycos, FFTFIT-equivalent TOAs, fold
engine (parity targets: reference utils/mypolycos.py, bin/dissect.py
measure_phase/write_toa, external parfile/fftfit/psr_utils deps)."""

import numpy as np
import pytest

from pypulsar_tpu.core import psrmath
from pypulsar_tpu.fold import (
    Polycos,
    create_polycos_from_spindown,
    cprof,
    fftfit,
    measure_phase,
    format_princeton_toa,
    fold_bins,
    fold_numpy,
    fold_timeseries,
    phases_from_polycos,
    phase_to_bins,
)
from pypulsar_tpu.io.parfile import PsrPar, write_par


@pytest.fixture
def simple_par(tmp_path):
    fn = str(tmp_path / "fake.par")
    write_par(fn, {
        "PSRJ": "J0123+4567",
        "RAJ": "01:23:00.0",
        "DECJ": "45:67:00.0".replace("67", "40"),
        "F0": 2.5,
        "F1": -1e-12,
        "PEPOCH": 56000.0,
        "DM": 30.0,
    })
    return fn


class TestParfile:
    def test_basic_parse(self, simple_par):
        par = PsrPar(simple_par)
        assert par.PSRJ == "J0123+4567"
        assert par.F0 == 2.5
        assert par.P0 == pytest.approx(0.4)
        # P1 = -F1/F0^2 = +1.6e-13 for F1 = -1e-12
        assert par.P1 == pytest.approx(1.6e-13, abs=1e-16)
        assert par.DM == 30.0
        assert par.name == "J0123+4567"
        assert par.RA_RAD == pytest.approx((1 + 23 / 60.0) / 24.0 * 2 * np.pi)

    def test_fit_flags_and_errors(self, tmp_path):
        fn = str(tmp_path / "f.par")
        with open(fn, "w") as f:
            f.write("PSR  B1937+21\nF0 641.9282 1 0.0001\nPEPOCH 55000\n")
            f.write("P1-alias-check 0\n")
        par = PsrPar(fn)
        assert par.F0 == pytest.approx(641.9282)
        assert par.F0_FIT == 1
        assert par.F0_ERR == pytest.approx(1e-4)
        assert par.name == "B1937+21"

    def test_p0_to_f0(self, tmp_path):
        fn = str(tmp_path / "p.par")
        write_par(fn, {"PSR": "J0000+0000", "P0": 0.5, "PEPOCH": 56000.0})
        par = PsrPar(fn)
        assert par.F0 == pytest.approx(2.0)


class TestPolycos:
    def test_native_generation_matches_spindown(self, simple_par):
        par = PsrPar(simple_par)
        pcs = create_polycos_from_spindown(par, 56000.0, 56000.1)
        assert len(pcs) >= 2
        # phase at PEPOCH+t must equal the analytic spin-down phase
        for mjd in (56000.01, 56000.04, 56000.09):
            mjdi, mjdf = int(mjd), mjd - int(mjd)
            dt = (mjd - 56000.0) * psrmath.SECPERDAY
            expected = par.F0 * dt + 0.5 * par.F1 * dt * dt
            got = pcs.get_rotation(mjdi, mjdf)
            assert got == pytest.approx(expected, abs=1e-6)
            f_expected = par.F0 + par.F1 * dt
            assert pcs.get_freq(mjdi, mjdf) == pytest.approx(f_expected, rel=1e-12)

    def test_roundtrip_through_file(self, simple_par, tmp_path):
        pcs = create_polycos_from_spindown(PsrPar(simple_par), 56000.0, 56000.05)
        fn = str(tmp_path / "polyco.dat")
        pcs.write(fn)
        pcs2 = Polycos(fn)
        assert len(pcs2) == len(pcs)
        mjd = 56000.02
        r1 = pcs.get_rotation(int(mjd), mjd - int(mjd))
        r2 = pcs2.get_rotation(int(mjd), mjd - int(mjd))
        assert r2 == pytest.approx(r1, abs=1e-4)

    def test_out_of_range_raises(self, simple_par):
        from pypulsar_tpu.fold import PolycoError

        pcs = create_polycos_from_spindown(PsrPar(simple_par), 56000.0, 56000.05)
        with pytest.raises(PolycoError):
            pcs.get_phase(56010, 0.0)

    def test_f2_cross_term_and_small_numcoeffs(self, tmp_path):
        # F2 != 0 with PEPOCH far from TMID: the dt^2 coefficient must use
        # f'(TMID), not F1 alone
        fn = str(tmp_path / "f2.par")
        write_par(fn, {"PSRJ": "J0", "F0": 10.0, "F1": -1e-12, "F2": 1e-20,
                       "PEPOCH": 55900.0, "DM": 0.0})
        par = PsrPar(fn)
        pcs = create_polycos_from_spindown(par, 56000.0, 56000.05)
        mjd = 56000.03
        dt = (mjd - 55900.0) * psrmath.SECPERDAY
        expected = (par.F0 * dt + 0.5 * par.F1 * dt**2 + par.F2 * dt**3 / 6.0
                    - (par.F0 * 100 * psrmath.SECPERDAY
                       + 0.5 * par.F1 * (100 * psrmath.SECPERDAY) ** 2
                       + par.F2 * (100 * psrmath.SECPERDAY) ** 3 / 6.0))
        got = (pcs.get_rotation(int(mjd), mjd - int(mjd))
               - pcs.get_rotation(56000, 0.0))
        assert got == pytest.approx(expected, abs=1e-5)
        # numcoeffs <= 3 must not crash
        pcs3 = create_polycos_from_spindown(par, 56000.0, 56000.01, numcoeffs=3)
        assert len(pcs3) >= 1
        pcs2 = create_polycos_from_spindown(par, 56000.0, 56000.01, numcoeffs=2)
        assert len(pcs2) >= 1

    def test_rotation_batch_matches_scalar(self, simple_par):
        pcs = create_polycos_from_spindown(PsrPar(simple_par), 56000.0, 56000.05)
        p = pcs.polycos[0]
        mjdfs = np.linspace(0.0, 0.02, 50)
        batch = p.rotation_batch(56000, mjdfs)
        scalar = np.array([p.rotation(56000, f) for f in mjdfs])
        np.testing.assert_allclose(batch, scalar, rtol=0, atol=1e-9)


class TestFFTFit:
    def _template(self, n=128, fwhm=0.05):
        return psrmath.gaussian_profile(n, 0.25, fwhm)

    def test_zero_shift(self):
        t = self._template()
        shift, eshift, snr, esnr, b, errb, ngood = fftfit(
            t * 3.0 + 1.0, *cprof(t)[1:]
        )
        assert abs(shift) < 1e-6
        assert b == pytest.approx(3.0, rel=1e-6)

    @pytest.mark.parametrize("s", [3, 17, -11, 60])
    def test_integer_shift_recovery(self, s):
        t = self._template()
        prof = np.roll(t, s) * 2.0
        shift, eshift, *_ = fftfit(prof, *cprof(t)[1:])
        n = len(t)
        expected = (s + n / 2) % n - n / 2
        assert shift == pytest.approx(expected, abs=1e-6)

    def test_fractional_shift_with_noise(self):
        rng = np.random.RandomState(42)
        n = 256
        # build a fractionally shifted pulse directly in the Fourier domain
        t = psrmath.gaussian_profile(n, 0.3, 0.04)
        true_shift = 7.35
        T = np.fft.rfft(t)
        k = np.arange(len(T))
        shifted = np.fft.irfft(T * np.exp(-2j * np.pi * k * true_shift / n), n)
        prof = 5.0 * shifted + rng.randn(n) * 0.05
        shift, eshift, snr, esnr, b, errb, ngood = fftfit(prof, *cprof(t)[1:])
        assert shift == pytest.approx(true_shift, abs=3 * max(eshift, 0.05))
        assert eshift < 1.0
        assert snr > 10

    def test_measure_phase_surface(self):
        t = self._template()
        out = measure_phase(np.roll(t, 5), t)
        assert len(out) == 8
        shift = out[0]
        # template was rotated to put fundamental at zero phase; shift must
        # still locate the pulse displacement modulo the rotation
        assert np.isfinite(shift)


class TestPrincetonTOA:
    def test_format_with_dm(self):
        line = format_princeton_toa(56123, 0.25, 1.5, 1400.0, 30.0, obs="3")
        assert line.startswith("3")
        assert "1400.000" in line
        assert "56123.2500000000000" in line
        assert line.rstrip().endswith("30.0000")

    def test_format_without_dm(self):
        line = format_princeton_toa(56123, 0.75, 2.0, 350.0, 0.0, obs="@")
        assert "350.000" in line
        assert "30.0000" not in line


class TestFoldEngine:
    def test_fold_parity_numpy_vs_jax(self):
        rng = np.random.RandomState(0)
        data = rng.randn(1000).astype(np.float32)
        bins = rng.randint(0, 32, 1000).astype(np.int32)
        jp, jc = fold_bins(data, bins, 32)
        np_, nc = fold_numpy(data, bins, 32)
        np.testing.assert_allclose(np.asarray(jp), np_, rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(jc), nc)

    def test_fold_2d(self):
        rng = np.random.RandomState(1)
        data = rng.randn(4, 500).astype(np.float32)
        bins = rng.randint(0, 16, 500).astype(np.int32)
        jp, _ = fold_bins(data, bins, 16)
        np_, _ = fold_numpy(data, bins, 16)
        # device accumulates f32; twin f64
        np.testing.assert_allclose(np.asarray(jp), np_, rtol=1e-4, atol=1e-5)

    def test_fold_parts_matches_per_partition_folds(self):
        from pypulsar_tpu.fold.engine import fold_parts

        rng = np.random.RandomState(2)
        C, T, nbins, npart = 4, 1030, 16, 8  # remainder of 6 dropped
        data = rng.randn(C, T).astype(np.float32)
        bins = rng.randint(0, nbins, T).astype(np.int32)
        profs, counts = fold_parts(data, bins, nbins, npart)
        assert profs.shape == (npart, C, nbins)
        part_len = T // npart
        for pi in range(npart):
            sl = slice(pi * part_len, (pi + 1) * part_len)
            ref_p, ref_c = fold_numpy(data[:, sl], bins[sl], nbins)
            np.testing.assert_allclose(np.asarray(profs[pi]), ref_p,
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_array_equal(np.asarray(counts[pi]), ref_c)

    def test_fold_stats_matches_numpy_twin(self):
        from pypulsar_tpu.fold.engine import (
            bestprof_offsets, fold_stats, fold_stats_numpy)

        rng = np.random.RandomState(3)
        C, T, nbins, npart = 8, 4096, 16, 8
        data = rng.randn(C, T).astype(np.float32)
        bins = rng.randint(0, nbins, T).astype(np.int32)
        _, off = bestprof_offsets(npart, T * 1e-3, 0.05, ntrial=9)
        dev = [np.asarray(x, np.float64)
               for x in fold_stats(data, bins, nbins, npart, off)]
        ref = list(fold_stats_numpy(data, bins, nbins, npart, off))
        for d, r, tol in zip(dev, ref, (1e-4,) * 3 + (2e-4,) * 3):
            np.testing.assert_allclose(d, r, rtol=tol, atol=1e-2)

    def test_fold_snr_stats_recovers_snr_and_period(self):
        """The fused device fold+stats path (VERDICT r3 item 4) detects an
        injected pulsar and refines a deliberately-off fold period back to
        the true one."""
        from pypulsar_tpu.fold.engine import fold_snr_stats, phase_to_bins

        rng = np.random.RandomState(4)
        C, T, nbins, npart = 16, 200_000, 64, 25
        dt = 1e-3
        p_true = 0.512  # seconds
        p_fold = p_true * (1 + 2.0e-5)  # off by ~8 ms drift over the obs
        t = np.arange(T) * dt
        data = rng.randn(C, T).astype(np.float32)
        pulse = (np.abs(((t / p_true) % 1.0) - 0.5) < 0.02)
        data += 0.6 * pulse[None, :].astype(np.float32)
        bins = phase_to_bins(t / p_fold, nbins)
        out = fold_snr_stats(data, bins, nbins, npart, dt, p_fold)
        assert out["snr"] > 10.0, out["snr"]
        # refined period within a quarter of the trial-grid spacing
        dgrid = out["dp_trials"][1] - out["dp_trials"][0]
        assert abs(out["best_period"] - p_true) <= (p_fold - p_true) * 0.3 \
            + dgrid, (out["best_period"], p_true)
        assert out["part_profs"].shape == (npart, nbins)
        assert out["chan_profs"].shape == (C, nbins)

    def test_constant_period_fold_recovers_pulse(self):
        dt, period, nbins = 1e-3, 0.1, 50
        n = 100_000
        t = np.arange(n) * dt
        phase = (t / period) % 1.0
        data = np.where(np.abs(phase - 0.5) < 0.02, 10.0, 0.0).astype(np.float32)
        prof, counts = fold_timeseries(data, dt, nbins, period=period,
                                       normalize=True)
        assert prof.argmax() == nbins // 2
        assert counts.sum() == n

    def test_polyco_fold_recovers_drifting_pulse(self, simple_par):
        # F1 != 0: a constant-period fold would smear; polyco fold must not
        par = PsrPar(simple_par)
        fn_par = par
        # drift 0.5*|F1|*T^2 = 0.4 rotations over the 400 s obs: enough to
        # smear a constant-period fold across ~40% of phase
        f0, f1, pepoch = par.F0, -5e-6, 56000.0
        par.F1 = f1
        pcs = create_polycos_from_spindown(par, 56000.0, 56000.02)
        dt = 1e-3
        n = 400_000
        mjdstart = 56000.0
        tsec = np.arange(n) * dt
        true_phase = f0 * tsec + 0.5 * f1 * tsec**2
        data = (np.abs((true_phase % 1.0) - 0.5) < 0.02).astype(np.float32) * 8
        nbins = 64
        prof, counts = fold_timeseries(data, dt, nbins, polycos=pcs,
                                       mjdstart=mjdstart, normalize=True)
        # pulse occupies phases [0.48, 0.52) -> bins 30-33
        assert abs(prof.argmax() - nbins // 2) <= 2
        # smeared control: constant-period fold spreads the pulse
        prof_c, _ = fold_timeseries(data, dt, nbins, period=1.0 / f0,
                                    normalize=True)
        peak_frac = prof.max() / prof.sum()
        peak_frac_c = prof_c.max() / prof_c.sum()
        assert peak_frac > peak_frac_c

    def test_phases_from_polycos_spans_blocks(self, simple_par):
        pcs = create_polycos_from_spindown(PsrPar(simple_par), 56000.0, 56000.1)
        dt = 0.5
        n = int(0.09 * psrmath.SECPERDAY / dt)
        phases = phases_from_polycos(pcs, 56000.0, n, dt)
        # must be monotonic and continuous across block seams
        d = np.diff(phases)
        assert (d > 0).all()
        assert np.allclose(d, d[0], rtol=1e-6)
