"""Round-21 observability plane: causal trace propagation (span ids,
kill+resume continuity, cross-host adoption on ONE trace), the log2
latency histograms + SLO burn accounting through tlmsum, the crash
flight recorder's postmortem capsules, the tlmtrace stitcher/--check
CLI, heartbeat trace-attribution, and the live status/metrics
endpoint."""

import glob
import io
import json
import os
import threading
import time
import urllib.request

import pytest

from pypulsar_tpu.obs import flightrec, statusd, summarize, telemetry, tracing
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.resilience.health import HeartbeatRegistry
from pypulsar_tpu.survey.dag import StageSpec, SurveyConfig
from pypulsar_tpu.survey.fleet import FleetPlane
from pypulsar_tpu.survey.scheduler import FleetScheduler
from pypulsar_tpu.survey.state import Observation


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.reset()
    yield
    faultinject.reset()


def _read_jsonl(path):
    return [json.loads(line) for line in open(path) if line.strip()]


def _stub_outputs(name):
    def outputs(obs, cfg):
        return [f"{obs.outbase}.{name}.out"]
    return outputs


def _mk_stage(name, deps=(), body=None, device=None, **kw):
    def run(o, c, _n=name):
        if body is not None:
            rc = body(o, c)
            if rc:
                return rc
        with open(f"{o.outbase}.{_n}.out", "w") as f:
            f.write(_n + o.name)
        return 0

    return StageSpec(name, "stub", device if device is not None
                     else name.startswith("dev"), tuple(deps),
                     lambda o, c: [], _stub_outputs(name), run=run, **kw)


def _mk_obs(td, n):
    obs = []
    for i in range(n):
        raw = os.path.join(str(td), f"o{i}.raw")
        with open(raw, "wb") as f:
            f.write(b"x" * 64)
        obs.append(Observation(f"o{i}", raw,
                               os.path.join(str(td), f"o{i}")))
    return obs


# ---------------------------------------------------------------------------
# causal trace context: ids on spans
# ---------------------------------------------------------------------------


def test_trace_context_stamps_span_ids(tmp_path):
    """Spans inside a trace_context carry trace_id/span_id and parent
    onto the enclosing span; spans outside carry no ids at all (old
    traces stay byte-stable)."""
    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path, tool="test"):
        with telemetry.span("bare"):
            pass
        with telemetry.trace_context(trace_id="t" * 16, obs="o0",
                                     stage="dev1"):
            with telemetry.span("root") as sp:
                with telemetry.span("child"):
                    pass
            assert sp.sid
    recs = _read_jsonl(path)
    spans = {r["name"]: r for r in recs if r["type"] == "span"}
    assert "trace_id" not in spans["bare"]
    assert "span_id" not in spans["bare"]
    root, child = spans["root"], spans["child"]
    assert root["trace_id"] == child["trace_id"] == "t" * 16
    # the context root has no parent (it IS the trace root)
    assert "parent_id" not in root
    assert child["parent_id"] == root["span_id"]


def test_prefetch_worker_adopts_stage_trace(tmp_path):
    """The ship-ahead worker thread re-enters the consumer's trace
    context (the PR 7 attribution caveat, closed): telemetry it records
    lands on the stage's trace_id."""
    from pypulsar_tpu.parallel.prefetch import prefetch

    seen = []

    def xf(x):
        ctx = telemetry.current_context()
        seen.append(ctx.trace_id if ctx else None)
        return x * 2

    with telemetry.session(str(tmp_path / "t.jsonl")):
        with telemetry.trace_context(trace_id="feed" * 4, obs="o0",
                                     stage="dev1"):
            out = list(prefetch(range(4), transform=xf, name="tst"))
    assert out == [0, 2, 4, 6]
    assert seen == ["feed" * 4] * 4  # worker thread, stage's trace


# ---------------------------------------------------------------------------
# histograms: bucket math, percentiles, tlmsum rendering
# ---------------------------------------------------------------------------


def test_hist_bucket_and_percentile_math():
    assert telemetry.hist_bucket(0) == 0
    assert telemetry.hist_bucket(1) == 1
    assert telemetry.hist_bucket(2) == 2        # [2, 4) -> bucket 2
    assert telemetry.hist_bucket(1023) == 10
    assert telemetry.hist_bucket(1 << 60) == telemetry.HIST_BUCKETS - 1
    buckets = [0] * telemetry.HIST_BUCKETS
    for v in (3, 3, 3, 1000):  # three in [2,4), one in [512,1024)
        buckets[telemetry.hist_bucket(v)] += 1
    assert summarize.hist_percentile(buckets, 0.5) == 4.0   # upper edge
    assert summarize.hist_percentile(buckets, 0.99) == 1024.0
    assert summarize.hist_percentile([0] * 4, 0.5) == 0.0  # empty hist
    merged = summarize.hist_merge([1, 2], [0, 1, 5])
    assert merged == [1, 3, 5]


def test_span_hists_roundtrip_tlmsum(tmp_path):
    """Span durations land in log2 µs histograms, serialize with the
    counters record, and tlmsum renders p50/p95/p99 for them."""
    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path) as tlm:
        for ms in (1, 1, 1, 30):
            telemetry.record_span("stage.x", ms / 1000.0)
        telemetry.gauge("pipe.pending_depth", 3)
        snap = tlm.hist_snapshot()
    assert sum(snap["spans"]["stage.x"]) == 4
    assert sum(snap["gauges"]["pipe.pending_depth"]) == 1
    buf = io.StringIO()
    summarize.render(
        summarize.summarize(summarize.load_records(path)), buf)
    out = buf.getvalue()
    assert "latency percentiles" in out
    assert "stage.x" in out
    assert "gauge watermarks" in out


# ---------------------------------------------------------------------------
# SLO burn accounting
# ---------------------------------------------------------------------------


def test_slo_burn_event_and_tlmsum_section(tmp_path):
    """A stage that consumes >80% of its watchdog budget WITHOUT
    tripping it emits survey.slo_burn, and tlmsum's SLO section
    accounts the burn against the stage's budget."""
    def slow(o, c):
        time.sleep(0.45)
        return 0

    stages = [_mk_stage("dev1", body=slow, deadline_s=0.5)]
    obs = _mk_obs(tmp_path, 1)
    tpath = str(tmp_path / "t.jsonl")
    with telemetry.session(tpath) as tlm:
        result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                                stall_s=30.0).run()
        assert tlm.event_counts.get("survey.slo_burn") == 1
        assert tlm.counters.get("survey.slo_burns") == 1
    assert result.ok and result.timeouts == 0  # watchdog never fired
    buf = io.StringIO()
    summarize.render(
        summarize.summarize(summarize.load_records(tpath)), buf)
    out = buf.getvalue()
    assert "SLO burn" in out
    assert "dev1" in out and "burns>80%: 1" in out
    # the span carried the budget so the trace alone can account it
    recs = _read_jsonl(tpath)
    span = next(r for r in recs if r["type"] == "span"
                and r["name"] == "survey.stage.dev1")
    assert span["attrs"]["budget_s"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# flight recorder: capsules at failure edges
# ---------------------------------------------------------------------------


def test_quarantine_dumps_postmortem_capsule(tmp_path):
    """A quarantined observation leaves a capsule under
    _fleet/postmortem/ (recorder on even with --telemetry off), the
    capsule round-trips through tlmsum, and --status maps it to the
    QUARANTINED row."""
    def boom(o, c):
        if o.name == "o0":
            raise RuntimeError("injected stage failure")
        return 0

    stages = [_mk_stage("dev1", body=boom)]
    obs = _mk_obs(tmp_path, 2)
    flightrec.configure(64)
    # a live session's meta record in the ring must not masquerade as
    # the capsule's own header when tlmsum reads it back
    flightrec.record({"type": "meta", "tool": "?", "argv": ["stale"]})
    try:
        result = FleetScheduler(obs, SurveyConfig(), stages=stages,
                                retries=0).run()
    finally:
        flightrec.configure(None)
    assert not result.ok and set(result.quarantined) == {"o0"}
    caps = flightrec.capsule_paths(statusd.postmortem_dir(str(tmp_path)))
    assert caps, "no postmortem capsule written"
    cap = json.load(open(caps[0]))
    assert cap["type"] == "postmortem" and cap["reason"] == "quarantine"
    assert cap["obs"] == "o0"
    assert cap["extra"]["stage"] == "dev1"
    assert any(r.get("type") for r in cap["records"])
    # tlmsum accepts the capsule directly
    buf = io.StringIO()
    summarize.render(
        summarize.summarize(summarize.load_records(caps[0])), buf)
    assert "postmortem" in buf.getvalue()
    # --status knows which row it explains
    by_obs = statusd.capsules_by_obs(str(tmp_path))
    assert "o0" in by_obs and by_obs["o0"]


def test_flightrec_dump_never_raises(tmp_path):
    flightrec.configure(0)
    try:
        assert flightrec.dump(str(tmp_path), "x") is None  # disabled
    finally:
        flightrec.configure(None)
    # unwritable dir: returns None instead of raising
    assert flightrec.dump("/dev/null/nope", "x") is None


# ---------------------------------------------------------------------------
# trace continuity: kill+resume, cross-host adoption
# ---------------------------------------------------------------------------


def test_kill_resume_is_one_trace(tmp_path):
    """The trace_id persists in the manifest: spans from the run that
    died and the resume stitch into ONE trace with no dangling
    parents."""
    stages = [_mk_stage("dev1"), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 1)
    tdir = str(tmp_path / "tlm")
    faultinject.configure("kill:survey.stage_start.host1:1")
    with pytest.raises(faultinject.InjectedKill):
        FleetScheduler(obs, SurveyConfig(), stages=stages,
                       telemetry_dir=tdir).run()
    faultinject.reset()
    r = FleetScheduler(obs, SurveyConfig(), stages=stages,
                       telemetry_dir=tdir, resume=True).run()
    assert r.ok and r.ran == [("o0", "host1")]
    recs = _read_jsonl(os.path.join(tdir, "o0.jsonl"))
    spans = [x for x in recs if x["type"] == "span"]
    tids = {x.get("trace_id") for x in spans}
    assert len(tids) == 1 and None not in tids, tids
    # both stage spans are on the trace, and the stitcher agrees
    names = {x["name"] for x in spans}
    assert {"survey.stage.dev1", "survey.stage.host1"} <= names
    assert tracing.check([os.path.join(tdir, "o0.jsonl")]) == []
    doc = tracing.stitch([os.path.join(tdir, "o0.jsonl")])
    assert len(doc["otherData"]["traces"]) == 1


def test_adoption_continues_the_trace_across_hosts(tmp_path):
    """Cross-host adoption: the adopter reuses the trace_id the dead
    host minted (it lives in the manifest), stamps adopted_from on its
    first span, and the stitched timeline shows the lane handover on
    one trace."""
    stages = [_mk_stage("dev1"), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 1)
    tdir = str(tmp_path / "tlm")
    faultinject.configure("kill:survey.stage_start.host1:1")
    pa = FleetPlane(str(tmp_path), host_id="hA", lease_s=1.0,
                    settle_s=0.02)
    with pytest.raises(faultinject.InjectedKill):
        FleetScheduler(obs, SurveyConfig(), stages=stages, plane=pa,
                       telemetry_dir=tdir).run()
    faultinject.reset()
    pb = FleetPlane(str(tmp_path), host_id="hB", lease_s=1.0,
                    settle_s=0.02)
    with telemetry.session() as tlm:
        r = FleetScheduler(obs, SurveyConfig(), stages=stages, plane=pb,
                           telemetry_dir=tdir).run()
        # the claim's terminal state is an event on the trace too
        assert tlm.event_counts.get("survey.claim_terminal") == 1
    assert r.ok and r.adopted == ["o0"]
    recs = _read_jsonl(os.path.join(tdir, "o0.jsonl"))
    spans = [x for x in recs if x["type"] == "span"]
    tids = {x.get("trace_id") for x in spans}
    assert len(tids) == 1 and None not in tids, tids
    hosts = {x["attrs"].get("host") for x in spans}
    assert hosts == {"hA", "hB"}  # the handover happened on one trace
    hb_span = next(x for x in spans if x["attrs"].get("host") == "hB")
    assert hb_span["attrs"]["adopted_from"] == "hA"
    # manifest trace note survives and matches
    notes = [x for x in _read_jsonl(obs[0].manifest)
             if x.get("type") == "note" and x.get("event") == "trace"]
    assert len(notes) == 1  # adoption reused it, never re-minted
    assert notes[0]["trace_id"] == tids.pop()
    assert tracing.check([os.path.join(tdir, "o0.jsonl")]) == []
    doc = tracing.stitch([os.path.join(tdir, "o0.jsonl")])
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"hA", "hB"} <= lanes


# ---------------------------------------------------------------------------
# tlmtrace stitcher + --check
# ---------------------------------------------------------------------------


def test_tlmtrace_stitch_and_check_cli(tmp_path, capsys):
    from pypulsar_tpu.cli import tlmtrace

    good = str(tmp_path / "good.jsonl")
    with open(good, "w") as f:
        f.write(json.dumps({"type": "meta", "tool": "survey",
                            "host": "hA", "t_unix": 100.0}) + "\n")
        f.write(json.dumps({"type": "span", "name": "a", "t": 1.0,
                            "dur": 0.5, "trace_id": "T1",
                            "span_id": "s1", "attrs": {}}) + "\n")
        f.write(json.dumps({"type": "span", "name": "b", "t": 1.1,
                            "dur": 0.2, "trace_id": "T1", "span_id": "s2",
                            "parent_id": "s1", "attrs": {}}) + "\n")
    out = str(tmp_path / "trace.json")
    assert tlmtrace.main([good, "-o", out]) == 0
    doc = json.load(open(out))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 2
    assert all(e["args"]["trace_id"] == "T1" for e in xs)
    assert tlmtrace.main([good, "--check"]) == 0
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps({"type": "span", "name": "orphan", "t": 1.0,
                            "dur": 0.1, "trace_id": "T2",
                            "span_id": "s9", "parent_id": "GONE",
                            "attrs": {}}) + "\n")
    assert tlmtrace.main([bad, "--check"]) == 1
    assert "GONE" in capsys.readouterr().err
    assert tlmtrace.main([str(tmp_path / "missing.jsonl")]) == 1


def test_check_tolerates_torn_tail_of_adopted_trace(tmp_path, capsys):
    """A SIGKILL'd host never flushes its in-flight stage span, so its
    completed children dangle — tolerated ONLY when the trace carries
    an adoption receipt (an ``adopted_from`` attr somewhere in the
    stitch set); the same shape without the receipt stays fatal."""
    from pypulsar_tpu.cli import tlmtrace

    victim = str(tmp_path / "fleet.h0.jsonl")
    with open(victim, "w") as f:
        f.write(json.dumps({"type": "meta", "tool": "survey",
                            "host": "h0", "t_unix": 100.0}) + "\n")
        # a prefetch child whose parent (the hung stage span) was
        # never written — h0 died by SIGKILL mid-stage
        f.write(json.dumps({"type": "span", "name": "block_source",
                            "t": 1.0, "dur": 0.1, "trace_id": "T1",
                            "span_id": "c1", "parent_id": "LOST",
                            "attrs": {"obs": "o0"}}) + "\n")
    adopter = str(tmp_path / "fleet.h1.jsonl")
    with open(adopter, "w") as f:
        f.write(json.dumps({"type": "meta", "tool": "survey",
                            "host": "h1", "t_unix": 100.0}) + "\n")
        f.write(json.dumps({"type": "span", "name": "survey.stage.dev1",
                            "t": 9.0, "dur": 1.0, "trace_id": "T1",
                            "span_id": "s2",
                            "attrs": {"obs": "o0",
                                      "adopted_from": "h0"}}) + "\n")
    # without the adoption receipt the dangle is a hard failure
    assert len(tracing.check([victim])) == 1
    # with it: no failures, the torn span reported as tolerated
    torn = []
    assert tracing.check([victim, adopter], tolerated=torn) == []
    assert len(torn) == 1 and "LOST" in torn[0]
    assert tlmtrace.main(["--check", victim, adopter]) == 0
    out = capsys.readouterr()
    assert "tolerated" in out.err and "LOST" in out.err
    # an adoption EVENT (plane flavor: obs attr, no trace context)
    # resolves onto the trace via the obs name too
    ev_adopter = str(tmp_path / "fleet.h2.jsonl")
    with open(ev_adopter, "w") as f:
        f.write(json.dumps({"type": "meta", "tool": "survey",
                            "host": "h2", "t_unix": 100.0}) + "\n")
        f.write(json.dumps({"type": "event",
                            "name": "survey.obs_adopted", "t": 9.0,
                            "attrs": {"obs": "o0", "host": "h2",
                                      "adopted_from": "h0"}}) + "\n")
    assert tracing.check([victim, ev_adopter]) == []


def test_stitch_dedups_echoed_spans(tmp_path):
    """The obs-trace echo of a fleet span (same trace_id+span_id) is
    folded into one event, keeping the host-attributed record."""
    fleet = str(tmp_path / "fleet.hA.jsonl")
    with open(fleet, "w") as f:
        f.write(json.dumps({"type": "meta", "tool": "survey",
                            "host": "hA", "t_unix": 100.0}) + "\n")
        f.write(json.dumps({"type": "span", "name": "survey.stage.d",
                            "t": 1.0, "dur": 0.5, "trace_id": "T1",
                            "span_id": "s1",
                            "attrs": {"host": "hA"}}) + "\n")
    echo = str(tmp_path / "o0.jsonl")
    with open(echo, "w") as f:
        f.write(json.dumps({"type": "meta", "tool": "survey-obs",
                            "obs": "o0", "t_unix": 100.0}) + "\n")
        f.write(json.dumps({"type": "span", "name": "survey.stage.d",
                            "t": 1.0, "dur": 0.5, "trace_id": "T1",
                            "span_id": "s1", "attrs": {}}) + "\n")
    doc = tracing.stitch([echo, fleet])
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 1
    assert xs[0]["args"].get("host") == "hA"


# ---------------------------------------------------------------------------
# heartbeat trace attribution (the PR 7 caveat, closed)
# ---------------------------------------------------------------------------


def test_heartbeat_beats_attribute_per_trace_then_thread():
    reg = HeartbeatRegistry()
    entry = reg.start("o0:dev1", stall_s=60.0, obs="o0", stage="dev1",
                      trace_id="T1")
    entry.last_beat = 0.0
    # a helper thread beating with the trace id refreshes the entry
    t = threading.Thread(target=lambda: reg.beat("T1"))
    t.start()
    t.join()
    assert entry.last_beat > 0.0
    # beat(None) from the OWNING thread falls back to thread identity
    entry.last_beat = 0.0
    reg.beat(None)
    assert entry.last_beat > 0.0
    # ...but from a foreign thread with no trace id it is a no-op
    entry.last_beat = 0.0
    t2 = threading.Thread(target=lambda: reg.beat(None))
    t2.start()
    t2.join()
    assert entry.last_beat == 0.0
    reg.finish(entry)
    assert entry.obs == "o0" and entry.stage == "dev1"
    assert entry.trace_id == "T1"


def test_activity_hook_receives_trace_id(tmp_path):
    got = []
    telemetry.add_activity_hook(got.append)
    try:
        with telemetry.session(str(tmp_path / "t.jsonl")):
            telemetry.counter("c")  # outside any trace -> None
            with telemetry.trace_context(trace_id="T9"):
                telemetry.counter("c")
    finally:
        telemetry.remove_activity_hook(got.append)
    assert None in got and "T9" in got


# ---------------------------------------------------------------------------
# live status/metrics endpoint
# ---------------------------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_status_server_serves_json_and_prometheus(tmp_path):
    """StatusServer on an ephemeral port serves the --status snapshot
    as JSON and the live collector as Prometheus text."""
    stages = [_mk_stage("dev1")]
    obs = _mk_obs(tmp_path, 1)
    assert FleetScheduler(obs, SurveyConfig(), stages=stages).run().ok
    with telemetry.session() :
        telemetry.counter("survey.stages_run", 3)
        telemetry.record_span("survey.stage.dev1", 0.01)
        with statusd.StatusServer(str(tmp_path), 0) as srv:
            assert srv.port > 0
            code, body = _get(srv.url + "/status.json")
            assert code == 200
            snap = json.loads(body)
            assert snap["rows"] and snap["rows"][0]["obs"] == "o0"
            assert snap["rows"][0]["state"] == "done"
            code, text = _get(srv.url + "/metrics")
            assert code == 200
            assert 'pypulsar_counter{name="survey.stages_run"} 3' in text
            assert "pypulsar_span_seconds_bucket" in text
            assert 'le="+Inf"' in text
            assert 'pypulsar_obs_state{state="done"} 1' in text
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.url + "/nope")
            assert ei.value.code == 404


def test_survey_status_follow_and_port_flags(tmp_path):
    """CLI wiring: `survey --status` renders the endpoint's snapshot
    when --status-port names a live server."""
    from pypulsar_tpu.cli import survey as cli_survey

    stages = [_mk_stage("dev1")]
    obs = _mk_obs(tmp_path, 1)
    assert FleetScheduler(obs, SurveyConfig(), stages=stages).run().ok
    with statusd.StatusServer(str(tmp_path), 0) as srv:
        text = cli_survey._status_text(str(tmp_path), port=srv.port)
    assert text and "o0" in text and "complete" in text
    # and without a port it reads the manifests directly
    text2 = cli_survey._status_text(str(tmp_path))
    assert text2 and "o0" in text2
