"""Staged DDplan execution + sweep CLI tests (VERDICT round-1 item 4:
configs[2] end-to-end from the command line)."""

import os

import numpy as np
import pytest

from pypulsar_tpu.core.spectra import Spectra
from pypulsar_tpu.io import filterbank
from pypulsar_tpu.ops import numpy_ref
from pypulsar_tpu.plan.ddplan import Observation


def synth_fil(tmp_path, C=64, T=8192, dt=1e-3, dm=60.0, t0=900, amp=7.0,
              seed=2, name="synth.fil"):
    rng = np.random.RandomState(seed)
    freqs = (1500.0 - 2.0 * np.arange(C)).astype(np.float64)
    data = rng.randn(T, C).astype(np.float32) + 50.0  # DC offset on purpose
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for c in range(C):
        idx = t0 + bins[c]
        for k, a in ((0, amp), (1, amp * 0.6)):
            if idx + k < T:
                data[idx + k, c] += a
    fn = str(tmp_path / name)
    hdr = dict(filterbank.DEFAULT_HEADER)
    hdr.update(nchans=C, fch1=freqs[0], foff=freqs[1] - freqs[0], tsamp=dt,
               nbits=32)
    filterbank.write_filterbank(fn, hdr, data)
    return fn, freqs, data


def test_sweep_ddplan_staged_recovers_injection(tmp_path):
    from pypulsar_tpu.parallel.staged import sweep_ddplan

    dm_true, t0, dt = 60.0, 900, 1e-3
    fn, freqs, _ = synth_fil(tmp_path, dm=dm_true, t0=t0, dt=dt)
    fil = filterbank.FilterbankFile(fn)
    bw = abs(freqs[0] - freqs[-1]) + 2.0
    obs = Observation(dt=dt, fctr=float(freqs.mean()), BW=bw,
                      numchan=len(freqs))
    plan = obs.gen_ddplan(0.0, 120.0)
    assert len(plan.DDsteps) >= 1
    staged = sweep_ddplan(fil, plan, nsub=16, group_size=8)
    # every step ran with its own downsampling
    assert [s.downsamp for s in staged.steps] == \
        [st.downsamp for st in plan.DDsteps][: len(staged.steps)]
    best = staged.best(1)[0]
    assert abs(best["dm"] - dm_true) <= 2 * plan.DDsteps[0].dDM + 1.0
    assert abs(best["time_sec"] - t0 * dt) <= 0.005
    assert best["snr"] > 10.0


def test_staged_step_equals_flat_sweep(tmp_path):
    """A one-step staged run must equal sweep_spectra on the same DMs."""
    from pypulsar_tpu.parallel import sweep_spectra
    from pypulsar_tpu.parallel.staged import sweep_ddplan

    fn, freqs, data = synth_fil(tmp_path, T=4096)
    fil = filterbank.FilterbankFile(fn)
    obs = Observation(dt=1e-3, fctr=float(freqs.mean()),
                      BW=abs(freqs[0] - freqs[-1]) + 2.0, numchan=len(freqs))
    plan = obs.gen_ddplan(0.0, 30.0)
    step0 = plan.DDsteps[0]
    staged = sweep_ddplan(fil, plan, nsub=16, group_size=8)
    if step0.downsamp == 1:
        spec = Spectra(freqs, 1e-3, np.ascontiguousarray(data.T))
        flat = sweep_spectra(spec, step0.DMs, nsub=16, group_size=8)
        np.testing.assert_allclose(staged.steps[0].result.snr, flat.snr,
                                   rtol=5e-6, atol=1e-4)


def test_staged_chunked_consistency(tmp_path):
    from pypulsar_tpu.parallel.staged import sweep_ddplan

    fn, freqs, _ = synth_fil(tmp_path, T=8192)
    fil = filterbank.FilterbankFile(fn)
    obs = Observation(dt=1e-3, fctr=float(freqs.mean()),
                      BW=abs(freqs[0] - freqs[-1]) + 2.0, numchan=len(freqs))
    plan = obs.gen_ddplan(0.0, 80.0)
    whole = sweep_ddplan(fil, plan, nsub=16, group_size=8)
    chunked = sweep_ddplan(fil, plan, nsub=16, group_size=8,
                           chunk_payload=2048)
    for a, b in zip(whole.steps, chunked.steps):
        # baseline comes from the first block (chunk-dependent), so the
        # guarantee here is detection-level consistency, not ulp parity
        np.testing.assert_allclose(a.result.snr, b.result.snr,
                                   rtol=1e-3, atol=5e-3)


def test_ship_ahead_disabled_matches_enabled(tmp_path, monkeypatch):
    """PYPULSAR_TPU_SHIP_AHEAD=0 (inline ship, single-threaded debugging
    path) produces bit-identical sweep results to the default background
    ship thread — threading must only move WHEN blocks ship, never what
    arrives or in what order."""
    from pypulsar_tpu.parallel.staged import sweep_flat

    fn, freqs, _ = synth_fil(tmp_path, T=8192, name="ship.fil")
    dms = np.linspace(0.0, 80.0, 16)
    fil = filterbank.FilterbankFile(fn)
    default = sweep_flat(fil, dms, nsub=16, group_size=8,
                         chunk_payload=2048)
    monkeypatch.setenv("PYPULSAR_TPU_SHIP_AHEAD", "0")
    inline = sweep_flat(filterbank.FilterbankFile(fn), dms, nsub=16,
                        group_size=8, chunk_payload=2048)
    a, b = default.steps[0].result, inline.steps[0].result
    np.testing.assert_array_equal(a.snr, b.snr)
    np.testing.assert_array_equal(a.peak_sample, b.peak_sample)


def test_ship_ahead_propagates_worker_errors():
    """An exception in the block producer (disk error, bad header)
    surfaces in the consumer instead of hanging or being swallowed by
    the ship thread."""
    import pytest

    from pypulsar_tpu.parallel.staged import _ship_ahead

    def bad_blocks():
        yield 0, np.zeros((4, 16), np.float32)
        raise OSError("disk pulled")

    it = _ship_ahead(bad_blocks())
    pos, _ = next(it)
    assert pos == 0
    with pytest.raises(OSError, match="disk pulled"):
        for _ in it:
            pass


def test_ship_ahead_abandoned_consumer_stops_worker():
    """Breaking out of the stream signals the ship thread to stop
    instead of shipping the remaining blocks (review r4: an abandoned
    57 GB sweep must not spend minutes shipping the rest of the file)."""
    import threading
    import time

    from pypulsar_tpu.parallel.staged import _ship_ahead

    produced = []

    def blocks():
        for i in range(1000):
            produced.append(i)
            yield i, np.zeros((4, 16), np.float32)

    it = _ship_ahead(blocks(), depth=2)
    next(it)
    it.close()  # GeneratorExit -> stop event + drain
    deadline = time.time() + 5.0
    while time.time() < deadline and any(
            t.name == "pypulsar-ship-ahead" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert not any(t.name == "pypulsar-ship-ahead" and t.is_alive()
                   for t in threading.enumerate())
    assert len(produced) < 20  # worker stopped early, not after 1000


def test_sweep_cli_flat_writes_cands(tmp_path, capsys):
    from pypulsar_tpu.cli import sweep as sweep_cli

    dm_true, t0, dt = 60.0, 900, 1e-3
    fn, _, _ = synth_fil(tmp_path, dm=dm_true, t0=t0, dt=dt)
    out = str(tmp_path / "out")
    rc = sweep_cli.main([fn, "-o", out, "--lodm", "0", "--dmstep", "2.5",
                         "--numdms", "48", "-s", "16", "--group-size", "8",
                         "--threshold", "8"])
    assert rc == 0
    cands = out + ".cands"
    assert os.path.exists(cands)
    rows = [ln.split() for ln in open(cands) if not ln.startswith("#")]
    assert rows, "threshold crossings expected for a 7-sigma injection"
    stdout = capsys.readouterr().out
    assert "DM" in stdout
    dms = [float(r[0]) for r in rows]
    snrs = [float(r[1]) for r in rows]
    assert any(abs(d - dm_true) <= 5.0 for d in dms)
    assert max(snrs) > 10.0


def test_sweep_cli_ddplan_mode(tmp_path):
    from pypulsar_tpu.cli import sweep as sweep_cli

    fn, _, _ = synth_fil(tmp_path, T=8192)
    out = str(tmp_path / "plan_out")
    rc = sweep_cli.main([fn, "-o", out, "--ddplan", "--lodm", "0",
                         "--hidm", "100", "-s", "16", "--group-size", "8"])
    assert rc == 0
    assert os.path.exists(out + ".cands")


def test_sweep_cli_write_dats(tmp_path):
    from pypulsar_tpu.cli import sweep as sweep_cli
    from pypulsar_tpu.io.datfile import Datfile

    fn, freqs, data = synth_fil(tmp_path, T=4096)
    out = str(tmp_path / "dats")
    rc = sweep_cli.main([fn, "-o", out, "--lodm", "0", "--dmstep", "30",
                         "--numdms", "2", "-s", "16", "--group-size", "8",
                         "--write-dats"])
    assert rc == 0
    for dm in (0.0, 30.0):
        base = f"{out}_DM{dm:.2f}"
        assert os.path.exists(base + ".dat") and os.path.exists(base + ".inf")
        df = Datfile(base + ".dat")
        ts = df.read_all()
        assert len(ts) == 4096
        if dm == 0.0:
            # DM 0: series is the plain channel sum
            np.testing.assert_allclose(ts, data.sum(axis=1), rtol=1e-5)


def test_sweep_cli_sharded_mesh(tmp_path):
    import jax

    from pypulsar_tpu.cli import sweep as sweep_cli

    assert len(jax.devices()) == 8
    fn, _, _ = synth_fil(tmp_path)
    out = str(tmp_path / "mesh_out")
    rc = sweep_cli.main([fn, "-o", out, "--lodm", "0", "--dmstep", "2.5",
                         "--numdms", "48", "-s", "16", "--group-size", "8",
                         "--mesh", "4"])
    assert rc == 0
    assert os.path.exists(out + ".cands")


def test_sweep_ddplan_2d_matches_1d(tmp_path):
    """The {dm, time} 2-D mesh staged execution reproduces the streamed
    1-D staged sweep (halo exchange over ppermute == host overlap-save)."""
    import jax

    from pypulsar_tpu.parallel import make_mesh
    from pypulsar_tpu.parallel.staged import sweep_ddplan, sweep_ddplan_2d

    assert len(jax.devices()) == 8
    rng = np.random.RandomState(21)
    C, T, dt = 32, 16384, 1e-3
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    spec = Spectra(freqs, dt, data)
    obs = Observation(dt=dt, fctr=float(freqs.mean()),
                      BW=float(freqs.max() - freqs.min() + 4.0), numchan=C)
    plan = obs.gen_ddplan(0.0, 300.0)
    mesh = make_mesh([4, 2], ("dm", "time"))

    ref = sweep_ddplan(spec, plan, nsub=8, group_size=4)
    got = sweep_ddplan_2d(spec, plan, mesh, nsub=8, group_size=4)
    assert len(got.steps) == len(ref.steps)
    for sa, sb in zip(got.steps, ref.steps):
        # trial counts match (2d pads groups to the mesh; finalize trims)
        assert len(sa.result.dms) == len(sb.result.dms)
        np.testing.assert_allclose(sa.result.snr, sb.result.snr,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(sa.result.peak_sample,
                                      sb.result.peak_sample)


def test_windowed_source_rejects_unaligned_window(tmp_path):
    """ADVICE r4: an interior window that is not a whole payload multiple
    would double-count seam samples in merged statistics — the source must
    fail loudly, not corrupt silently."""
    from pypulsar_tpu.parallel.staged import _ReaderSource

    fn, _, _ = synth_fil(tmp_path, T=8192)
    fil = filterbank.FilterbankFile(fn)
    src = _ReaderSource(fil, start=0, end=3000)  # interior, 3000 % 2048 != 0
    with pytest.raises(ValueError, match="whole multiple of payload"):
        next(src.chan_major_blocks(payload=2048, overlap=64))
    # tail windows may be ragged: the file end is the natural boundary
    src2 = _ReaderSource(fil, start=4096, end=8192)
    tail = _ReaderSource(fil, start=6144)  # end defaults to total
    assert sum(1 for _ in src2.chan_major_blocks(2048, 64)) == 2
    assert sum(1 for _ in tail.chan_major_blocks(2048, 64)) == 1


def test_masked_block_interval_lookup_past_int32(tmp_path):
    """ADVICE r4: the zap-interval lookup must be exact for file-absolute
    sample positions past 2^31 (int32 arange would overflow and index the
    wrong intervals)."""
    from pypulsar_tpu.parallel.staged import _masked_block

    rng = np.random.RandomState(5)
    C, L, pts = 8, 512, 1000
    # past int32, constructed so rem=800 and the block crosses into the
    # next interval at j=200
    pos = (2**31 // pts + 1) * pts + 800
    assert pos > 2**31 and pos % pts == 800
    nint = pos // pts + 2
    data = rng.randn(C, L).astype(np.float32)
    table = np.zeros((nint, C), dtype=bool)
    table[pos // pts + 1, 3] = True  # zap only the block's SECOND interval
    import jax.numpy as jnp
    base = min(pos // pts, nint - 1)
    got = np.asarray(_masked_block(jnp.asarray(data), jnp.asarray(table),
                                   base, pos % pts, pts))
    assert not np.array_equal(got, data)  # the zap actually landed
    # int64 host reference of the same clamped lookup + median-mid80 fill
    iv = np.minimum((pos + np.arange(L, dtype=np.int64)) // pts, nint - 1)
    mask = table[iv].T  # [C, L]
    from pypulsar_tpu.ops import numpy_ref
    ref = numpy_ref.masked(data, mask)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("nbits", [4, 2])
def test_sweep_packed_subbyte_matches_expanded_8bit(tmp_path, nbits):
    """VERDICT r4 item 2: a 4-bit (or 2-bit) PACKED file swept through
    the streamed path (device-side unpack in _ingest_tc) produces
    bit-identical results to the same values pre-expanded into an 8-bit
    file — while shipping 1/2 (1/4) of the bytes."""
    from pypulsar_tpu.parallel.staged import sweep_flat

    rng = np.random.RandomState(17)
    C, T, dt, dm_true = 64, 16384, 1e-3, 60.0
    freqs = (1500.0 - 2.0 * np.arange(C)).astype(np.float64)
    noise_hi, amp = (14, 2) if nbits == 4 else (3, 1)
    vals = rng.randint(0, noise_hi, size=(T, C)).astype(np.uint8)
    bins = numpy_ref.bin_delays(dm_true, freqs, dt)
    for c in range(C):
        for k in range(8):
            i = 900 + k + bins[c]
            if i < T:
                vals[i, c] += amp
    hdr = dict(filterbank.DEFAULT_HEADER)
    hdr.update(nchans=C, fch1=freqs[0], foff=-2.0, tsamp=dt)
    fn4 = str(tmp_path / "p4.fil")
    fn8 = str(tmp_path / "p8.fil")
    filterbank.write_filterbank(fn4, dict(hdr, nbits=nbits), vals)
    filterbank.write_filterbank(fn8, dict(hdr, nbits=8), vals)
    assert (os.stat(fn4).st_size - FilterbankFileHeaderSize(fn4)
            ) * (8 // nbits) == (os.stat(fn8).st_size
                                 - FilterbankFileHeaderSize(fn8))
    dms = np.linspace(0.0, 120.0, 16)
    r4 = sweep_flat(filterbank.FilterbankFile(fn4), dms, nsub=16,
                    group_size=8, chunk_payload=4096)
    r8 = sweep_flat(filterbank.FilterbankFile(fn8), dms, nsub=16,
                    group_size=8, chunk_payload=4096)
    a, b = r4.steps[0].result, r8.steps[0].result
    np.testing.assert_array_equal(a.snr, b.snr)
    np.testing.assert_array_equal(a.peak_sample, b.peak_sample)
    np.testing.assert_array_equal(a.mean, b.mean)
    # and the sweep still finds the injection
    best = r4.best(1)[0]
    assert abs(best["dm"] - dm_true) < 10.0 and best["snr"] > 8.0


def FilterbankFileHeaderSize(fn):
    return filterbank.FilterbankFile(fn).header_size


def test_write_dats_streamed_basic_and_windows(tmp_path):
    """Streamed .dat writer (VERDICT r4 items 1/3): DM-0 series equals
    the exact channel sum; window segments concatenate bit-exactly to
    the whole-file stream; .inf sidecars carry the full length."""
    from pypulsar_tpu.io.datfile import Datfile
    from pypulsar_tpu.parallel.staged import (write_dat_infs,
                                              write_dats_streamed)

    fn, freqs, data = synth_fil(tmp_path, T=8192)
    out = str(tmp_path / "sd")
    fil = filterbank.FilterbankFile(fn)
    # single-DM grids: the group centers on the trial itself, so the
    # two-stage series is the EXACT per-channel dedisperse (a multi-DM
    # group carries the engine's documented subband smearing instead)
    write_dats_streamed(out, fil, [0.0], nsub=16, group_size=8,
                        chunk_payload=2048)
    ts0 = Datfile(f"{out}_DM0.00.dat").read_all()
    assert len(ts0) == 8192
    np.testing.assert_allclose(ts0, data.sum(axis=1), rtol=1e-5, atol=1e-2)
    write_dats_streamed(out, fil, [60.0], nsub=16, group_size=8,
                        chunk_payload=2048)
    ts60 = Datfile(f"{out}_DM60.00.dat").read_all()
    # the injected pulse (t0=900 in synth_fil) dominates the series
    assert abs(int(np.argmax(ts60)) - 900) <= 2
    dms = np.array([0.0, 60.0])
    write_dats_streamed(out, fil, dms, nsub=16, group_size=8,
                        chunk_payload=2048)
    whole = np.fromfile(f"{out}_DM60.00.dat", np.float32)
    # two half-windows, written as segments, concatenate to the whole
    out2 = str(tmp_path / "sw")
    for rank, win in enumerate([(0, 4096), (4096, 8192)]):
        write_dats_streamed(out2, filterbank.FilterbankFile(fn), dms,
                            nsub=16, group_size=8, chunk_payload=2048,
                            window=win, suffix=f".w{rank}",
                            write_inf=False)
    parts = [np.fromfile(f"{out2}_DM60.00.w{r}.dat", np.float32)
             for r in (0, 1)]
    np.testing.assert_array_equal(np.concatenate(parts), whole)
    write_dat_infs(out2, fil, dms, 8192, fil.tsamp)
    from pypulsar_tpu.io.infodata import InfoData
    inf = InfoData(f"{out2}_DM60.00.inf")
    assert int(inf.N) == 8192


def test_sweep_flat_seek_resume_bit_exact(tmp_path, monkeypatch):
    """Kill-and-resume through sweep_flat's SEEK path (round 5): the
    resumed run re-roots the block stream at the checkpoint cursor
    instead of replaying (and re-shipping) the whole file, and the final
    result is bit-identical to the uninterrupted sweep."""
    from pypulsar_tpu.parallel import staged as staged_mod
    from pypulsar_tpu.parallel.staged import sweep_flat
    from pypulsar_tpu.parallel.sweep import SweepCheckpoint

    fn, freqs, _ = synth_fil(tmp_path, T=16384, name="seek.fil")
    dms = np.linspace(0.0, 80.0, 16)
    ckpt = str(tmp_path / "seek.ckpt")

    whole = sweep_flat(filterbank.FilterbankFile(fn), dms, nsub=16,
                       group_size=8, chunk_payload=2048).steps[0].result

    # crash once >= 4 chunks have drained (burst draining accounts whole
    # batches per on_drained call, so the count lives on the checkpoint)
    real = SweepCheckpoint.on_drained

    def dying(self, *a, **k):
        real(self, *a, **k)
        if self._drained >= 4:
            raise KeyboardInterrupt("simulated SIGKILL")

    monkeypatch.setattr(SweepCheckpoint, "on_drained", dying)
    with pytest.raises(KeyboardInterrupt):
        sweep_flat(filterbank.FilterbankFile(fn), dms, nsub=16,
                   group_size=8, chunk_payload=2048,
                   checkpoint_path=ckpt, checkpoint_every=1)
    monkeypatch.setattr(SweepCheckpoint, "on_drained", real)
    assert os.path.exists(ckpt)
    with np.load(ckpt) as z:
        saved_cursor = int(z["cursor"])
    assert saved_cursor >= 4 * 2048  # the crash point's drained coverage

    # resume: the re-rooted source must start AT the cursor, not 0
    seeks = []
    real_reroot = staged_mod._reroot_source

    def spying(src, start_raw):
        seeks.append(start_raw)
        return real_reroot(src, start_raw)

    monkeypatch.setattr(staged_mod, "_reroot_source", spying)
    resumed = sweep_flat(filterbank.FilterbankFile(fn), dms, nsub=16,
                         group_size=8, chunk_payload=2048,
                         checkpoint_path=ckpt,
                         checkpoint_every=1).steps[0].result
    assert seeks == [saved_cursor]
    np.testing.assert_array_equal(resumed.snr, whole.snr)
    np.testing.assert_array_equal(resumed.peak_sample, whole.peak_sample)
    np.testing.assert_array_equal(resumed.mean, whole.mean)
    assert not os.path.exists(ckpt)  # cleaned up on completion
