"""prepfold-equivalent CLI: fold an observation at (P, Pdot, DM) into a
.pfd archive readable by io/prestopfd and analysable by pfd_snr — the
candidate-verification loop (reference defers folding to external PRESTO
prepfold; bin/pfd_snr.py:19 consumes its output)."""

import os

import numpy as np

from pypulsar_tpu.io import filterbank
from pypulsar_tpu.io.prestopfd import PfdFile
from pypulsar_tpu.ops import numpy_ref


def synth_pulsar_fil(path, C=32, T=1 << 15, dt=1e-3, period=0.0517,
                     dm=35.0, amp=1.2, seed=3):
    freqs = 1500.0 - 4.0 * np.arange(C)
    rng = np.random.RandomState(seed)
    data = rng.randn(T, C).astype(np.float32)
    tsec = np.arange(T) * dt
    delays = numpy_ref.bin_delays(dm, freqs, dt) * dt
    for c in range(C):
        phase = ((tsec - delays[c]) / period) % 1.0
        data[:, c] += amp * np.exp(
            -0.5 * ((phase - 0.5) / 0.04) ** 2).astype(np.float32)
    hdr = dict(nchans=C, tsamp=dt, fch1=1500.0, foff=-4.0, tstart=55000.0,
               nbits=32, nifs=1, source_name="FOLDME")
    filterbank.write_filterbank(path, hdr, data)
    return freqs


def test_prepfold_fil_to_pfd_and_snr(tmp_path, monkeypatch, capsys):
    import matplotlib

    matplotlib.use("Agg", force=True)
    from pypulsar_tpu.cli import pfd_snr as cli_snr
    from pypulsar_tpu.cli import prepfold as cli_fold

    monkeypatch.chdir(tmp_path)
    period, dm = 0.0517, 35.0
    synth_pulsar_fil("psr.fil", period=period, dm=dm)
    rc = cli_fold.main(["psr.fil", "-p", str(period), "--dm", str(dm),
                        "-n", "32", "--npart", "8", "--nsub", "8",
                        "-o", "psr.pfd"])
    assert rc == 0

    pfd = PfdFile("psr.pfd")
    assert pfd.profs.shape == (8, 8, 32)
    assert pfd.bestdm == dm
    # before dedispersion the summed profile is smeared; after, sharp
    blurred = pfd.sumprof.copy()
    pfd.dedisperse()
    sharp = pfd.sumprof
    def contrast(p):
        return (p.max() - np.median(p)) / max(p.std(), 1e-9)
    assert contrast(sharp) > contrast(blurred)
    # the pulse sits at the folded phase and repeats coherently per part
    tvp = pfd.time_vs_phase()
    peaks = tvp.argmax(axis=1)
    assert np.ptp(peaks) <= 3, f"fold not phase-coherent: {peaks}"

    # profile SNR on our own archive via the reference's pfd_snr surface
    rc = cli_snr.main(["psr.pfd", "--on-pulse", "0.3", "0.7"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "SNR" in out
    snr_vals = [float(tok) for line in out.splitlines()
                for tok in [line.split()[-1]]
                if "SNR" in line and tok.replace(".", "", 1).replace(
                    "-", "", 1).isdigit()]
    assert snr_vals and max(snr_vals) > 10.0


def test_prepfold_dat_single_subband(tmp_path, monkeypatch):
    from pypulsar_tpu.cli import prepfold as cli_fold
    from pypulsar_tpu.io.datfile import write_dat
    from pypulsar_tpu.io.infodata import InfoData

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(4)
    N, dt, period = 1 << 15, 1e-3, 0.0731
    t = np.arange(N) * dt
    phase = (t / period) % 1.0
    ts = rng.standard_normal(N).astype(np.float32)
    ts += 0.8 * np.exp(-0.5 * ((phase - 0.25) / 0.03) ** 2).astype(np.float32)
    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = dt
    inf.N = N
    inf.telescope = "Fake"
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 1
    inf.chan_width = 100.0
    inf.object = "DATFOLD"
    write_dat("one", ts, inf)
    rc = cli_fold.main(["one.dat", "-p", str(period), "-n", "64",
                        "--npart", "16", "-o", "one.pfd"])
    assert rc == 0
    pfd = PfdFile("one.pfd")
    assert pfd.profs.shape == (16, 1, 64)
    prof = pfd.sumprof
    assert (prof.max() - np.median(prof)) > 5.0 * prof.std() * 0.2
    peak_phase = prof.argmax() / 64.0
    assert abs(peak_phase - 0.25) < 0.08


def test_prepfold_par_ephemeris_fold(tmp_path, monkeypatch):
    """--par folds through native polyco generation: a pulsar with a real
    spin-down (P changing over the observation) stays phase-coherent
    under the ephemeris fold but smears under the constant-period fold."""
    from pypulsar_tpu.cli import prepfold as cli_fold
    from pypulsar_tpu.core import psrmath
    from pypulsar_tpu.io.datfile import write_dat
    from pypulsar_tpu.io.infodata import InfoData

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(8)
    N, dt = 1 << 16, 1e-3
    epoch = 55000.0
    f0, f1 = 19.37, -6e-3  # strong spin-down: ~13 rotations of drift over T
    t = np.arange(N) * dt
    phase = f0 * t + 0.5 * f1 * t * t
    ts = rng.standard_normal(N).astype(np.float32)
    ts += 1.0 * np.exp(
        -0.5 * (((phase % 1.0) - 0.5) / 0.03) ** 2).astype(np.float32)
    inf = InfoData()
    inf.epoch = epoch
    inf.dt = dt
    inf.N = N
    inf.telescope = "Fake"
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 1
    inf.chan_width = 100.0
    inf.object = "PARFOLD"
    inf.bary = 1  # synthetic series is barycentred; 'Fake' has no site id
    write_dat("pf", ts, inf)
    with open("pf.par", "w") as f:
        f.write(f"PSR J0000+0000\nF0 {f0}\nF1 {f1}\nPEPOCH {epoch}\nDM 12.5\n")

    rc = cli_fold.main(["pf.dat", "--par", "pf.par", "-n", "64",
                        "--npart", "16", "-o", "par.pfd"])
    assert rc == 0
    rc = cli_fold.main(["pf.dat", "-p", str(1.0 / f0), "-n", "64",
                        "--npart", "16", "-o", "const.pfd"])
    assert rc == 0

    from pypulsar_tpu.io.prestopfd import PfdFile

    def contrast(fn):
        prof = PfdFile(fn).sumprof
        return (prof.max() - np.median(prof)) / max(prof.std(), 1e-9)

    c_par, c_const = contrast("par.pfd"), contrast("const.pfd")
    assert c_par > 1.5 * c_const, (c_par, c_const)
    assert PfdFile("par.pfd").bestdm == 12.5  # DM defaulted from the par
    # header pdot reflects the apparent spin-down the fold used
    pd = PfdFile("par.pfd").curr_p2
    assert abs(pd - (-f1 / f0 ** 2)) < 0.1 * abs(f1 / f0 ** 2)
    # per-partition peaks aligned under the ephemeris fold
    tvp = PfdFile("par.pfd").time_vs_phase()
    peaks = tvp.argmax(axis=1)
    spread = np.ptp(((peaks - peaks[0] + 32) % 64))
    assert spread <= 8, f"ephemeris fold not coherent: {peaks}"
