"""Tests for the dedispersion planner, masked detrend, harmonic ratios,
progress meter, and colour codes."""

import io
import sys

import numpy as np
import pytest

from pypulsar_tpu.plan import (
    ALLOW_DMSTEPS,
    Observation,
    DDplan,
    guess_DMstep,
)
from pypulsar_tpu.core.psrmath import dm_smear
from pypulsar_tpu.utils import show_progress
from pypulsar_tpu.utils.approx_harm import approx_harm, output_harm
from pypulsar_tpu.utils.detrend import detrend, fit_poly, old_detrend
from pypulsar_tpu.utils import colour


class TestDDplan:
    def setup_method(self):
        # PALFA-like observation: 64 us, 1400 MHz, 300 MHz BW, 1024 chans
        self.obs = Observation(64e-6, 1400.0, 300.0, 1024)

    def test_guess_dmstep(self):
        # dt*0.0001205*fctr^3/BW
        assert np.allclose(
            guess_DMstep(64e-6, 150.0, 1400.0),
            64e-6 * 0.0001205 * 1400.0**3 / 150.0,
        )

    def test_allow_factors_pow2(self):
        assert self.obs.allow_factors == [1, 2, 4, 8, 16, 32, 64]

    def test_allow_factors_divisors(self):
        obs = Observation(64e-6, 1400.0, 300.0, 1024, numsamp=60)
        # divisors of 60 up to 64
        assert obs.allow_factors == [1, 2, 3, 4, 5, 6, 10, 12, 15, 20, 30, 60]

    def test_plan_covers_range(self):
        plan = self.obs.gen_ddplan(0.0, 500.0)
        assert plan.DDsteps[0].loDM == 0.0
        assert plan.DDsteps[-1].hiDM >= 500.0
        # steps tile the range contiguously
        for a, b in zip(plan.DDsteps[:-1], plan.DDsteps[1:]):
            assert np.allclose(a.hiDM, b.loDM)
        # monotonically non-decreasing dDM and downsamp
        dDMs = [s.dDM for s in plan.DDsteps]
        downs = [s.downsamp for s in plan.DDsteps]
        assert dDMs == sorted(dDMs)
        assert downs == sorted(downs)
        for s in plan.DDsteps:
            assert s.dDM in ALLOW_DMSTEPS

    def test_trial_lists(self):
        plan = self.obs.gen_ddplan(0.0, 100.0)
        dms = plan.all_dms()
        assert dms[0] == 0.0
        assert np.all(np.diff(dms) > 0)
        assert len(dms) == sum(s.numDMs for s in plan.DDsteps)

    def test_work_fracts(self):
        plan = self.obs.gen_ddplan(0.0, 500.0)
        assert np.allclose(plan.work_fracts.sum(), 1.0)
        # workfract proportional to numDMs/downsamp
        wfs = np.array([s.numDMs / s.downsamp for s in plan.DDsteps])
        assert np.allclose(plan.work_fracts, wfs / wfs.sum())

    def test_smearing_bounded(self):
        # total smearing should stay within a small factor of the optimal
        plan = self.obs.gen_ddplan(0.0, 500.0)
        for step in plan.DDsteps:
            chan = dm_smear(step.DMs, self.obs.chanwidth, self.obs.fctr)
            floor = np.sqrt(chan**2 + self.obs.dt**2)
            assert np.all(step.tot_smear < 3.5 * np.maximum(floor, plan.resolution))

    def test_subband_plan(self):
        plan = self.obs.gen_ddplan(0.0, 300.0, numsub=64)
        for step in plan.DDsteps:
            assert step.numprepsub > 0
            assert step.DMs_per_prepsub * step.numprepsub == step.numDMs
            # subband smearing stays below other contributions
            assert step.sub_smearing <= 0.8 * min(
                step.BW_smearing, self.obs.dt * step.downsamp
            ) + 1e-12

    def test_str_format(self):
        plan = self.obs.gen_ddplan(0.0, 100.0)
        s = str(plan)
        assert "Low DM" in s and "WorkFract" in s

    def test_resolution_request(self):
        fine = self.obs.gen_ddplan(0.0, 100.0)
        coarse = self.obs.gen_ddplan(0.0, 100.0, resolution=2.0)  # 2 ms
        assert coarse.DDsteps[0].downsamp > fine.DDsteps[0].downsamp
        assert len(coarse.all_dms()) < len(fine.all_dms())


class TestDetrend:
    def test_removes_linear_trend(self):
        x = np.arange(100, dtype=float)
        y = 3.0 + 0.5 * x
        out = detrend(y)
        assert np.allclose(out, 0.0, atol=1e-9)

    def test_masked_glitch_ignored(self):
        x = np.arange(200, dtype=float)
        y = 1.0 + 0.1 * x
        y[50:60] += 100.0  # glitch
        ym = np.ma.masked_array(y, mask=np.zeros(200, dtype=bool))
        ym.mask[50:60] = True
        out = detrend(ym)
        # unmasked region is detrended to ~0 despite the masked glitch
        assert np.allclose(out.compressed(), 0.0, atol=1e-9)
        # masked region keeps its mask
        assert out.mask[55]

    def test_numpieces(self):
        # piecewise-linear signal removed by 2-piece linear detrend
        y = np.concatenate([np.linspace(0, 10, 50), np.linspace(20, 0, 50)])
        out = detrend(y, numpieces=2)
        assert np.allclose(out, 0.0, atol=1e-9)
        assert not np.allclose(detrend(y), 0.0, atol=1e-3)  # 1 piece can't

    def test_breakpoints(self):
        y = np.concatenate([np.full(50, 5.0), np.full(50, -3.0)])
        out = detrend(y, order=0, bp=[50])
        assert np.allclose(out, 0.0, atol=1e-12)

    def test_old_detrend_mask(self):
        y = np.ones(50)
        y[10] = 1000.0
        mask = np.zeros(50, dtype=bool)
        mask[10] = True
        out = old_detrend(y, mask=mask)
        assert np.allclose(np.delete(out, 10), 0.0, atol=1e-9)

    def test_fit_poly_coeffs(self):
        x = np.arange(30, dtype=float)
        y = 2.0 - 1.5 * x + 0.25 * x**2
        coeffs, poly = fit_poly(y, x, order=2)
        assert np.allclose(coeffs, [2.0, -1.5, 0.25], atol=1e-8)
        assert np.allclose(poly, y, atol=1e-7)

    def test_all_masked_raises(self):
        y = np.ma.masked_array(np.ones(10), mask=np.ones(10, dtype=bool))
        with pytest.raises(ValueError):
            fit_poly(y, np.ma.asarray(np.arange(10)))


class TestApproxHarm:
    def test_exact_harmonics(self):
        assert approx_harm(2.0, 1.0) == (2, 1)
        assert approx_harm(1.0, 3.0) == (1, 3)
        assert approx_harm(3.0, 2.0) == (3, 2)

    def test_near_harmonic(self):
        m, n = approx_harm(2.003, 1.0)
        assert (m, n) == (2, 1)

    def test_output_format(self):
        assert output_harm(2.0, 1.0) == "2/1"
        out = output_harm(2.003, 1.0)
        assert out.startswith("2/1 + ")

    def test_irrational(self):
        # pi/1 is approximated by 22/7 (within tol, k<=9), with a residue term
        out = output_harm(np.pi, 1.0)
        assert out.startswith("22/7 ")
        # a ratio needing m>9 AND n>9 (here exactly 10/11) falls back to
        # printing the plain float
        out = output_harm(10.0, 11.0)
        assert "/" not in out
        assert float(out) == pytest.approx(10.0 / 11.0, abs=1e-6)


class TestShowProgress:
    def test_yields_all(self, capsys):
        items = list(range(10))
        out = list(show_progress(items))
        assert out == items
        captured = capsys.readouterr()
        assert "100 %" in captured.out
        assert "Done" in captured.out

    def test_width_bar(self, capsys):
        list(show_progress(range(4), width=10))
        captured = capsys.readouterr()
        assert "[" in captured.out and "]" in captured.out


class TestColour:
    def test_cstring_wraps(self):
        s = colour.cstring("hello", fg="red", bold=True)
        assert s.startswith("\033[1;31;49m")
        assert s.endswith(colour.DEFAULT_CODE)
        assert "hello" in s

    def test_preset(self):
        s = colour.cstring("oops", preset="error")
        assert s.startswith("\033[1;31m")

    def test_cset_current(self):
        colour.cset(fg="green")
        try:
            assert colour.cstring("x").startswith("\033[0;32;49m")
        finally:
            colour.creset()
        assert colour.cstring("x").startswith(colour.DEFAULT_CODE)

    def test_bad_colour_raises(self):
        with pytest.raises(ValueError):
            colour.cstring("x", fg="chartreuse")


class TestDetrendBlocks:
    def test_matches_old_detrend_per_block(self):
        from pypulsar_tpu.utils.detrend import detrend_blocks

        rng = np.random.RandomState(0)
        B, L = 6, 400
        x = np.sort(rng.uniform(1.0, 3.0, size=(B, L)), axis=1)
        y = (0.5 + 1.5 * x - 0.3 * x**2
             + 0.05 * rng.randn(B, L))
        omit = rng.rand(B, L) < 0.2
        omit[2] = False  # one fully-kept block
        got = detrend_blocks(y, x, omit, order=2)
        for b in range(B):
            ref = old_detrend(y[b], xdata=x[b], mask=omit[b], order=2)
            np.testing.assert_allclose(got[b], ref, atol=2e-3)

    def test_fully_omitted_block_passes_through(self):
        from pypulsar_tpu.utils.detrend import detrend_blocks

        y = np.ones((2, 16))
        x = np.tile(np.arange(16.0), (2, 1))
        omit = np.zeros((2, 16), dtype=bool)
        omit[1] = True  # nothing to fit: y returned unchanged
        out = detrend_blocks(y, x, omit, order=1)
        np.testing.assert_allclose(out[0], 0.0, atol=1e-5)
        np.testing.assert_allclose(out[1], 1.0)

    def test_nonfinite_masked_cells_do_not_poison_the_fit(self):
        """log10 of a zeroed power bin is -inf; once masked it must be
        EXCLUDED from the fit (0 * -inf = NaN would otherwise poison the
        whole block), while the output still carries the original cell."""
        from pypulsar_tpu.utils.detrend import detrend_blocks

        rng = np.random.RandomState(1)
        L = 200
        x = np.linspace(1.0, 2.0, L)[None]
        y = (3.0 + 2.0 * x + 0.01 * rng.randn(1, L))
        y[0, 50] = -np.inf  # masked non-finite cell
        omit = np.zeros((1, L), dtype=bool)
        omit[0, 50] = True
        out = detrend_blocks(y, x, omit, order=1)
        assert np.isfinite(np.delete(out[0], 50)).all()
        assert np.abs(np.delete(out[0], 50)).max() < 0.1
        assert out[0, 50] == -np.inf  # original value minus finite fit
