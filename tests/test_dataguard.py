"""Data-integrity layer tests (round 13): salvaging readers, the
validity scrub through the device chain, finite-output gates, and the
corruption/fuzz tooling.

The contract under test, end to end: garbage input bytes mean
"flagged, salvaged, and reported" — never "crash, hang, or silently
wrong candidates". Every reader, fed arbitrary corrupted bytes, parses
(possibly salvaging a prefix) or raises a located ``DataFormatError``;
a NaN born mid-chunk is zero-filled ON DEVICE and counted in ``data.*``
telemetry; and no non-finite value can reach a .cands/.cand/.txtcand
row. The checked-in corpus in ``tests/fixtures/corrupt/`` pins the
reader half (regenerate with ``make_corpus.py`` — every fixture comes
from the ONE shared corruption code path, never hand-hexed bytes)."""

import glob
import io as _io
import json
import os
import warnings

import numpy as np
import pytest

from pypulsar_tpu.io import sigproc
from pypulsar_tpu.io.errors import DataFormatError, read_exact
from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import dataguard, faultinject

from tests.test_accel_pipeline import SWEEP_ARGS, _pulsar_fil

CORPUS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "corrupt")


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.reset()
    yield
    faultinject.reset()


# ---------------------------------------------------------------------------
# read_exact + header parsing: located errors, never bare struct.error
# ---------------------------------------------------------------------------


def test_read_exact_short_read_is_located():
    f = _io.BytesIO(b"\x01\x02\x03")
    f.read(1)
    with pytest.raises(DataFormatError) as ei:
        read_exact(f, 8, "/data/x.fil", "value of 'tsamp'")
    assert ei.value.path == "/data/x.fil"
    assert ei.value.offset == 1
    assert "wanted 8 bytes, got 2" in str(ei.value)


def test_dataformaterror_is_valueerror():
    """Existing broad ``except ValueError`` reader handlers keep
    classifying the new taxonomy."""
    assert issubclass(DataFormatError, ValueError)


def test_read_header_empty_file_located():
    with pytest.raises(DataFormatError) as ei:
        sigproc.read_header(_io.BytesIO(b""), path="empty.fil")
    assert "empty.fil" in str(ei.value)


def test_read_header_truncated_mid_keyword():
    """A header cut mid-field names the file and the byte offset."""
    buf = sigproc.addto_hdr("HEADER_START", None)[:8]
    with pytest.raises(DataFormatError) as ei:
        sigproc.read_header(_io.BytesIO(buf), path="cut.fil")
    assert ei.value.offset is not None


def test_read_header_runaway_stream_terminates():
    """A stream that keeps yielding decodable keywords without
    HEADER_END must terminate with a clean error, not walk megabytes
    of payload as 'header'."""
    buf = sigproc.addto_hdr("HEADER_START", None)
    buf += sigproc.addto_hdr("nifs", 1) * (sigproc.MAX_HEADER_KEYS + 8)
    with pytest.raises(DataFormatError, match="runaway header"):
        sigproc.read_header(_io.BytesIO(buf), path="runaway.fil")


@pytest.mark.parametrize("patch, field", [
    (dict(nbits=7), "nbits"),
    (dict(nbits=0), "nbits"),
    (dict(nchans=0), "nchans"),
    (dict(nchans=1 << 30), "nchans"),
    (dict(tsamp=float("nan")), "tsamp"),
    (dict(tsamp=-1e-3), "tsamp"),
    (dict(fch1=float("inf")), "fch1"),
    (dict(nifs=0), "nifs"),
])
def test_validate_header_rejects_insane_fields(patch, field):
    hdr = dict(nchans=16, tsamp=1e-3, fch1=1500.0, foff=-1.0, nbits=32,
               nifs=1)
    hdr.update(patch)
    with pytest.raises(DataFormatError, match=field):
        sigproc.validate_header(hdr, "x.fil")


def test_validate_header_accepts_sane():
    sigproc.validate_header(dict(nchans=16, tsamp=1e-3, fch1=1500.0,
                                 foff=-1.0, nbits=8, nifs=1), "x.fil")


# ---------------------------------------------------------------------------
# the checked-in corrupted-fixture corpus, against every reader
# ---------------------------------------------------------------------------


def _corpus_files():
    fns = [fn for fn in sorted(glob.glob(os.path.join(CORPUS, "*")))
           if not fn.endswith((".py", ".md", ".inf"))]
    assert len(fns) >= 12, f"corpus missing — regenerate: {fns}"
    return fns


def _open_and_read(fn):
    """Open fixture ``fn`` with its format's reader and actually READ
    from it; returns the salvage report (None = whole)."""
    if fn.endswith(".fil"):
        from pypulsar_tpu.io.filterbank import FilterbankFile

        fb = FilterbankFile(fn)
        try:
            n = min(int(fb.number_of_samples), 8)
            if n > 0:
                fb.get_samples(0, n)
            return fb.salvage
        finally:
            fb.close()
    if fn.endswith(".fits"):
        from pypulsar_tpu.io.psrfits import PsrfitsFile

        pf = PsrfitsFile(fn)
        try:
            n = min(int(pf.nspec), 4)
            if n > 0:
                pf.get_spectra(0, n)
            return None
        finally:
            pf.close()
    from pypulsar_tpu.io.datfile import Datfile

    d = Datfile(fn)
    try:
        d.read_all()
        return d.salvage
    finally:
        d.close()


@pytest.mark.parametrize(
    "fn", _corpus_files(),
    ids=[os.path.basename(f) for f in _corpus_files()])
def test_corrupted_fixture_corpus(fn):
    """Every corpus file produces the outcome its name prefix declares:
    ``err_`` a located DataFormatError, ``salv_`` a successful open
    with a salvage report, ``ok_`` a clean parse — NEVER an unhandled
    raw exception (struct.error, IndexError, UnicodeDecodeError...)."""
    want = os.path.basename(fn).split("_")[0]
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            salvage = _open_and_read(fn)
    except DataFormatError as e:
        assert want == "err", f"{fn}: unexpected DataFormatError {e}"
        assert os.path.basename(fn) in str(e), (
            f"error not located: {e}")
        return
    if want == "salv":
        assert salvage is not None, f"{fn}: expected a salvage report"
        assert salvage["missing_samples"] > 0 \
            or salvage["partial_tail_bytes"] > 0
    else:
        assert want == "ok", f"{fn}: expected DataFormatError, parsed"


# ---------------------------------------------------------------------------
# truncated-tail salvage: the valid prefix reads back exactly
# ---------------------------------------------------------------------------


def test_filterbank_salvage_reads_valid_prefix(tmp_path):
    """Truncating a .fil mid-spectrum: the reader opens, reports the
    missing span, and the surviving whole samples read back
    bit-identical to the pristine file's prefix."""
    from pypulsar_tpu.io.filterbank import FilterbankFile

    fil = _pulsar_fil(tmp_path, T=2048)
    with FilterbankFile(fil) as fb:
        whole = fb.get_samples(0, 2048)
        hsize = fb.header_size
        bps = fb.bytes_per_spectrum
    cut = str(tmp_path / "cut.fil")
    with open(fil, "rb") as f:
        img = f.read()
    keep = 1200
    with open(cut, "wb") as f:
        f.write(img[: hsize + keep * bps + 3])  # +3: mid-spectrum
    with pytest.warns(UserWarning, match="salvaged"):
        fb = FilterbankFile(cut)
    try:
        assert fb.number_of_samples == keep
        assert fb.salvage == {
            "read_samples": keep, "expected_samples": 2048,
            "missing_samples": 2048 - keep, "partial_tail_bytes": 3}
        np.testing.assert_array_equal(fb.get_samples(0, keep),
                                      whole[:keep])
    finally:
        fb.close()


def test_datfile_salvage_clamps_inf_N(tmp_path):
    from pypulsar_tpu.io.datfile import Datfile, write_dat
    from pypulsar_tpu.io.infodata import InfoData

    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = 1e-3
    inf.DM = 0.0
    series = np.arange(501, dtype=np.float32)  # odd size on purpose
    base = str(tmp_path / "t")
    write_dat(base, series, inf)
    os.truncate(base + ".dat", 300 * 4 + 2)  # mid-sample cut
    with pytest.warns(UserWarning, match="salvaged"):
        d = Datfile(base + ".dat")
    try:
        assert d.infdata.N == 300
        assert d.salvage["missing_samples"] == 201
        assert d.salvage["partial_tail_bytes"] == 2
        np.testing.assert_array_equal(d.read_all(), series[:300])
    finally:
        d.close()


def test_write_filterbank_stamps_nsamples(tmp_path):
    """The writer records the sample count so readers can cross-check
    the file size (what turns truncation into a REPORTED salvage)."""
    from pypulsar_tpu.io.filterbank import FilterbankFile, \
        write_filterbank

    fn = str(tmp_path / "n.fil")
    write_filterbank(fn, dict(nchans=4, tsamp=1e-3, fch1=1500.0,
                              foff=-1.0, nbits=32),
                     np.zeros((37, 4), np.float32))
    with FilterbankFile(fn) as fb:
        assert fb.header["nsamples"] == 37
        assert fb.salvage is None


# ---------------------------------------------------------------------------
# deterministic corruption + the structure-aware reader fuzz
# ---------------------------------------------------------------------------


def test_corrupt_file_deterministic(tmp_path):
    """Same (kind, seed) -> byte-identical corruption; different seeds
    differ. The determinism bench/tests leans on to replay a fault."""
    imgs = {}
    for tag, seed in (("a", 5), ("b", 5), ("c", 6)):
        sub = tmp_path / tag
        sub.mkdir()
        fn = _pulsar_fil(sub, T=1024)  # same basename: seed decides
        dataguard.corrupt_file(fn, "bitflip", seed=seed)
        with open(fn, "rb") as f:
            imgs[tag] = f.read()
    assert imgs["a"] == imgs["b"]
    assert imgs["a"] != imgs["c"]


def test_corrupt_file_kinds_and_bad_kind(tmp_path):
    fil = _pulsar_fil(tmp_path, T=1024)
    with open(fil, "rb") as f:
        pristine = f.read()
    for kind in dataguard.CORRUPT_KINDS:
        fn = str(tmp_path / f"{kind}.fil")
        with open(fn, "wb") as f:
            f.write(pristine)
        desc = dataguard.corrupt_file(fn, kind, seed=3)
        assert desc["kind"] == kind
        with open(fn, "rb") as f:
            assert f.read() != pristine, f"{kind} was a no-op"
    with pytest.raises(ValueError, match="unknown corruption kind"):
        dataguard.corrupt_file(fil, "gamma_ray")


def test_fuzz_mutate_deterministic():
    base = bytes(range(256)) * 8
    a = dataguard.fuzz_mutate(base, dataguard._rng(1, "t"))
    b = dataguard.fuzz_mutate(base, dataguard._rng(1, "t"))
    c = dataguard.fuzz_mutate(base, dataguard._rng(2, "t"))
    assert a == b
    assert a != c or len(a) != len(c)


@pytest.mark.parametrize("fmt", ["filterbank", "psrfits", "dat"])
def test_reader_fuzz_quick(fmt, tmp_path):
    """Tier-1 fuzz slice: 60 seeded mutations per format, zero contract
    violations (the 500-per-format acceptance run is the slow twin
    below + the committed CORRUPT_r01.json receipt)."""
    counts, failures = dataguard.run_reader_fuzz(
        fmt, 60, 11, str(tmp_path / fmt))
    assert not failures, f"contract violations: {failures[:5]}"
    assert sum(counts.values()) == 60


@pytest.mark.slow
@pytest.mark.parametrize("fmt", ["filterbank", "psrfits", "dat"])
def test_reader_fuzz_full(fmt, tmp_path):
    """The acceptance-scale fuzz (N=500 per format), opted into by
    ``make test-corruption``."""
    counts, failures = dataguard.run_reader_fuzz(
        fmt, 500, 1, str(tmp_path / fmt))
    assert not failures, f"contract violations: {failures[:5]}"
    assert sum(counts.values()) == 500


# ---------------------------------------------------------------------------
# the stream scrub: non-finite cells zero-filled + counted, on device
# ---------------------------------------------------------------------------


def _nan_spectra(C=4, T=512, n_bad=37):
    from pypulsar_tpu.core.spectra import Spectra

    rng = np.random.default_rng(3)
    data = rng.standard_normal((C, T)).astype(np.float32)
    flat = data.reshape(-1)
    flat[rng.choice(flat.size, size=n_bad, replace=False)] = np.nan
    flat[0] = np.inf
    return Spectra(1500.0 - np.arange(float(C)), 1e-3, data)


def test_guarded_source_scrubs_and_accounts():
    from pypulsar_tpu.parallel.staged import _SpectraSource

    sp = _nan_spectra()
    src = dataguard.guard_source(_SpectraSource(sp))
    assert isinstance(src, dataguard.GuardedSource)
    with telemetry.session() as tlm:
        blocks = [np.asarray(b) for _, b in
                  src.chan_major_blocks(256, 0)]
        for b in blocks:
            assert np.isfinite(b).all()
        totals = tlm.counter_totals()
    assert src.stats.nonfinite_cells == 38  # 37 NaN + 1 inf
    assert totals["data.nonfinite_cells"] == 38
    assert tlm.event_counts.get("data.nonfinite_scrubbed", 0) >= 1
    assert src.stats.fraction_bad() == pytest.approx(38 / (4 * 512))


def test_guard_disabled_by_env(monkeypatch):
    from pypulsar_tpu.parallel.staged import _SpectraSource

    monkeypatch.setenv(dataguard.ENV_GUARD, "0")
    src = dataguard.guard_source(_SpectraSource(_nan_spectra()))
    assert not isinstance(src, dataguard.GuardedSource)


def test_guard_skips_integer_sources(tmp_path):
    """uint filterbanks cannot hold a NaN: the hot 8-bit path stays
    unwrapped (and untouched) unless a data fault needs a landing."""
    from pypulsar_tpu.io.filterbank import FilterbankFile, \
        write_filterbank
    from pypulsar_tpu.parallel.staged import _ReaderSource

    fn = str(tmp_path / "u8.fil")
    write_filterbank(fn, dict(nchans=4, tsamp=1e-3, fch1=1500.0,
                              foff=-1.0, nbits=8),
                     np.zeros((64, 4), np.uint8))
    with FilterbankFile(fn) as fb:
        src = _ReaderSource(fb, 0, None)
        assert not isinstance(dataguard.guard_source(src),
                              dataguard.GuardedSource)
        faultinject.configure("nanburst:data.block:1")
        assert isinstance(dataguard.guard_source(src),
                          dataguard.GuardedSource)


def test_sweep_through_nan_input_stays_finite(tmp_path):
    """End-to-end through the DEVICE chain: a .fil with a NaN burst in
    its payload sweeps to finite SNRs (the scrub zero-fills before
    dedispersion), with the masked cells reported in telemetry."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_flat

    fil = _pulsar_fil(tmp_path, T=4096)
    dataguard.corrupt_file(fil, "nanburst", seed=9)
    with telemetry.session() as tlm:
        res = sweep_flat(filterbank.FilterbankFile(fil),
                         np.arange(8) * 10.0, nsub=8, group_size=4,
                         chunk_payload=2048).steps[0].result
        totals = tlm.counter_totals()
    assert np.isfinite(np.asarray(res.snr)).all()
    assert totals["data.nonfinite_cells"] > 0
    assert totals["data.cells"] > 0


# ---------------------------------------------------------------------------
# data-fault injection at read time (faultinject DATA kinds)
# ---------------------------------------------------------------------------


def test_trip_data_fires_once_deterministically():
    a = np.zeros(400, np.float32)
    faultinject.configure("nanburst:data.block:2")
    out1 = faultinject.trip_data("data.block", a)
    assert np.isfinite(out1).all()  # hit 1: untouched
    out2 = faultinject.trip_data("data.block", a)
    assert np.isnan(out2).sum() > 0
    out3 = faultinject.trip_data("data.block", a)
    assert np.isfinite(out3).all()  # disarmed after firing
    # replaying the same (kind, point, hit) corrupts identical bytes
    faultinject.configure("nanburst:data.block:2")
    faultinject.trip_data("data.block", a)
    replay = faultinject.trip_data("data.block", a)
    np.testing.assert_array_equal(
        np.isnan(out2), np.isnan(replay))


def test_corrupt_array_kinds():
    rng = dataguard._rng(4, "t")
    base = np.ones((8, 64), np.float32)
    nan = faultinject.corrupt_array(base, "nanburst", rng)
    assert np.isnan(nan).sum() > 0 and np.isinf(nan).sum() == 1
    drop = faultinject.corrupt_array(base, "dropblock", rng)
    assert (drop == 0).sum() > 0
    dc = faultinject.corrupt_array(base, "dcjump", rng)
    assert dc.max() > 1e3
    u8 = faultinject.corrupt_array(np.ones(256, np.uint8), "dcjump",
                                   rng)
    assert u8.dtype == np.uint8 and u8.max() > 1
    trunc = faultinject.corrupt_array(base, "truncate", rng)
    assert (trunc.reshape(-1)[-10:] == 0).all()


def test_nanburst_gate_acceptance(tmp_path):
    """THE acceptance gate test: inject a NaN burst mid-chunk into a
    clean sweep, and assert (a) the published .cands table is 100%
    finite, (b) the masked fraction is reported in telemetry, (c) the
    injection is recorded — garbage degraded the run, visibly, and
    nothing non-finite reached a row."""
    from pypulsar_tpu.cli import sweep as cli_sweep

    fil = _pulsar_fil(tmp_path, T=8192)
    olddir = os.getcwd()
    os.chdir(tmp_path)
    try:
        with telemetry.session() as tlm:
            assert cli_sweep.main(
                [fil, "-o", "gate", *SWEEP_ARGS, "--chunk", "2048",
                 "--fault-inject", "nanburst:data.block:2"]) == 0
            totals = tlm.counter_totals()
            events = dict(tlm.event_counts)
        rows = np.atleast_2d(np.loadtxt("gate.cands"))
        if rows.size:
            assert np.isfinite(rows).all()
        assert totals["data.nonfinite_cells"] > 0, (
            "masked fraction unreported")
        assert totals["data.cells"] > 0
        assert events.get("resilience.fault_injected", 0) == 1
    finally:
        os.chdir(olddir)


# ---------------------------------------------------------------------------
# finite-output gates
# ---------------------------------------------------------------------------


def test_finite_rows_gate_counts_drops(capsys):
    rows = [{"dm": 1.0, "snr": 9.0, "time_sec": 0.5},
            {"dm": 2.0, "snr": float("nan"), "time_sec": 0.5},
            {"dm": 3.0, "snr": 8.0, "time_sec": float("inf")}]
    with telemetry.session() as tlm:
        good = dataguard.finite_rows(rows, ("dm", "snr", "time_sec"))
        totals = tlm.counter_totals()
    assert good == rows[:1]
    assert totals["data.nonfinite_cands_dropped"] == 2
    assert "dropped 2 non-finite" in capsys.readouterr().out


def test_finite_cands_gate(capsys):
    from pypulsar_tpu.fourier.accelsearch import AccelCandidate

    good = AccelCandidate(r=100.0, z=0.0, power=40.0, sigma=9.0,
                          numharm=2)
    nan_sig = AccelCandidate(r=100.0, z=0.0, power=40.0,
                             sigma=float("nan"), numharm=2)
    r_zero = AccelCandidate(r=0.0, z=0.0, power=40.0, sigma=9.0,
                            numharm=2)
    with telemetry.session() as tlm:
        out = dataguard.finite_cands([good, nan_sig, r_zero], T=100.0)
        totals = tlm.counter_totals()
    assert out == [good]
    assert totals["data.nonfinite_cands_dropped"] == 2


def test_write_candfiles_gates_nonfinite(tmp_path):
    """No non-finite value reaches a .cand/.txtcand pair — the gate
    sits in the shared writer every accel path funnels through."""
    from pypulsar_tpu.fourier.accelsearch import AccelCandidate
    from pypulsar_tpu.io.prestocand import read_rzwcands
    from pypulsar_tpu.parallel.accelpipe import write_candfiles

    cands = [AccelCandidate(r=100.0, z=0.0, power=40.0, sigma=9.0,
                            numharm=2),
             AccelCandidate(r=200.0, z=float("nan"), power=40.0,
                            sigma=8.0, numharm=2)]
    candfn = str(tmp_path / "g_ACCEL_20.cand")
    txtfn = str(tmp_path / "g_ACCEL_20.txtcand")
    write_candfiles(candfn, txtfn, cands, T=100.0)
    assert len(read_rzwcands(candfn)) == 1
    body = open(txtfn).read()
    assert "nan" not in body.lower() and "inf" not in body.lower()


# ---------------------------------------------------------------------------
# ingest validation + survey degrade-vs-quarantine policy
# ---------------------------------------------------------------------------


def test_validate_input_reports(tmp_path):
    fil = _pulsar_fil(tmp_path, T=1024)
    rep = dataguard.validate_input(fil)
    assert rep["format"] == "filterbank"
    assert rep["bad_frac"] == 0.0 and rep["salvage"] is None
    # truncated: recognized, salvaged, bad_frac = missing fraction
    dataguard.corrupt_file(fil, "truncate", seed=1)
    rep = dataguard.validate_input(fil)
    assert 0.3 < rep["bad_frac"] < 0.5
    assert rep["salvage"]["missing_samples"] > 0
    # garbage header after a positive sniff: a DATA error
    dataguard.corrupt_file(fil, "header", seed=1)
    with pytest.raises(DataFormatError):
        dataguard.validate_input(fil)
    # unrecognized or missing: None (the stage itself will complain)
    other = tmp_path / "notes.txt"
    other.write_text("hello")
    assert dataguard.validate_input(str(other)) is None
    assert dataguard.validate_input(str(tmp_path / "gone.fil")) is None


def test_max_bad_frac_env(monkeypatch):
    assert dataguard.max_bad_frac_default() == 0.5
    monkeypatch.setenv(dataguard.ENV_MAX_BAD_FRAC, "0.25")
    assert dataguard.max_bad_frac_default() == 0.25
    monkeypatch.setenv(dataguard.ENV_MAX_BAD_FRAC, "bogus")
    assert dataguard.max_bad_frac_default() == 0.5


def test_survey_data_quarantine_vs_degrade(tmp_path):
    """The fleet policy end to end: a garbage-header input is DATA-
    quarantined at ingest (zero stages burned, reason 'data' distinct
    from runtime quarantine), a salvageable truncated input below the
    --max-bad-frac bar completes DEGRADED with its salvage story in
    the manifest, and --status renders both verdicts."""
    from pypulsar_tpu.survey.dag import SurveyConfig, build_dag
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import (Observation, format_status,
                                           status_rows)

    from tests.test_survey import CFG_KW, OBS

    fil_bad = _pulsar_fil(tmp_path, name="bad.fil", **OBS)
    fil_cut = _pulsar_fil(tmp_path, name="cut.fil", **OBS)
    dataguard.corrupt_file(fil_bad, "header", seed=2)
    dataguard.corrupt_file(fil_cut, "truncate", seed=2)
    out = tmp_path / "out"
    os.makedirs(out)
    obs = [Observation("bad", fil_bad, str(out / "bad")),
           Observation("cut", fil_cut, str(out / "cut"))]
    cfg = SurveyConfig(**CFG_KW)
    result = FleetScheduler(obs, cfg, max_host_workers=2).run()
    assert set(result.quarantined) == {"bad"}
    q = result.quarantined["bad"]
    assert q["reason"] == "data" and q["stage"] == "ingest"
    # the degraded obs ran its WHOLE chain on the salvaged prefix
    assert len(result.ran) == len(build_dag(cfg))
    rows = status_rows([o.manifest for o in obs])
    by = {r["obs"]: r for r in rows}
    dq = by["cut"]["data_quality"]
    assert dq["salvage"]["missing_samples"] > 0
    assert 0.3 < dq["bad_frac"] < 0.5
    assert by["bad"]["quarantine"]["reason"] == "data"
    rendered = format_status(rows)
    assert "DATA-QUARANTINED" in rendered
    assert "salvaged" in rendered


def test_survey_max_bad_frac_zero_quarantines_salvage(tmp_path):
    """Tightening --max-bad-frac below the salvaged fraction flips the
    SAME input from degrade to data-quarantine — without burning a
    single stage (ingest happens before any lease is taken)."""
    from pypulsar_tpu.survey.dag import SurveyConfig
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    from tests.test_survey import CFG_KW, OBS

    fil = _pulsar_fil(tmp_path, **OBS)
    dataguard.corrupt_file(fil, "truncate", seed=2)
    obs = [Observation("a", fil, str(tmp_path / "a"))]
    result = FleetScheduler(obs, SurveyConfig(**CFG_KW),
                            max_bad_frac=0.1).run()
    assert set(result.quarantined) == {"a"}
    assert result.quarantined["a"]["reason"] == "data"
    assert len(result.ran) == 0


# ---------------------------------------------------------------------------
# satellites: py2 integer-division regressions + --corrupt tooling
# ---------------------------------------------------------------------------


def test_ra_dec_string_fields_stay_in_range():
    """The py2-era ``int(v / 10000)`` field splits truncated through a
    float quotient; the floor-division port must keep every field in
    range at the odd boundary values that used to wobble."""
    vals = [0.0, 1.5, 95959.9999, 123456.789, 235959.9999,
            85959.99999999999, -123456.789]
    for v in vals:
        for fn, lim in ((sigproc.ra_to_hms_string, 24),
                        (sigproc.dec_to_dms_string, 90)):
            s = fn(v)
            neg = s.startswith("-")
            hh, mm, ss = s.lstrip("-").split(":")
            assert 0 <= int(mm) < 60, f"{fn.__name__}({v}) = {s}"
            assert 0.0 <= float(ss) < 100.0
            rebuilt = (int(hh) * 10000 + int(mm) * 100 + float(ss))
            assert rebuilt == pytest.approx(abs(v), abs=1e-3)
            assert neg == (v < 0)


def test_psrfits_data_size_exact_int(tmp_path):
    """PsrfitsData.data_size is an exact integer byte count even at odd
    sample counts (the py2 float ``/ 8.0`` leaked fractional floats
    into count fields)."""
    from pypulsar_tpu.io.datafile import PsrfitsData
    from pypulsar_tpu.io.psrfits import write_psrfits

    fn = str(tmp_path / "odd.fits")
    rng = np.random.default_rng(5)
    write_psrfits(fn, rng.integers(0, 40, (8, 48)).astype(np.float32),
                  1500.0 - np.arange(8.0), 1e-3, nsamp_per_subint=16,
                  nbits=8)
    d = PsrfitsData([fn])
    assert isinstance(d.data_size, int)
    assert d.data_size == d.num_samples * 8 * d.num_channels_per_record \
        // 8


def test_filterbank_odd_sizes_exact(tmp_path):
    """Sample counts stay exact at odd sizes and sub-byte widths."""
    from pypulsar_tpu.io.filterbank import FilterbankFile, \
        write_filterbank

    fn = str(tmp_path / "odd.fil")
    write_filterbank(fn, dict(nchans=6, tsamp=1e-3, fch1=1500.0,
                              foff=-1.0, nbits=32),
                     np.zeros((101, 6), np.float32))
    with FilterbankFile(fn) as fb:
        assert fb.number_of_samples == 101
        assert isinstance(fb.number_of_samples, int)


def test_make_synthetic_fil_corrupt_flag(tmp_path):
    """--corrupt KIND[:SEED] corrupts through the ONE shared code path;
    float-only kinds are rejected for the uint payload."""
    from pypulsar_tpu.io.filterbank import FilterbankFile

    from tests.test_survey import _load_make_synthetic_fil

    mod = _load_make_synthetic_fil()
    common = ["--nchan", "8", "--duration", "0.5", "--tsamp", "1e-3",
              "--period-samples", "128", "--width", "2"]
    fn = str(tmp_path / "cut.fil")
    mod.main(["--out", fn, *common, "--corrupt", "truncate:3"])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with FilterbankFile(fn) as fb:
            assert fb.salvage is not None
            assert fb.salvage["missing_samples"] > 0
    with pytest.raises(SystemExit, match="f32 payload"):
        mod.main(["--out", str(tmp_path / "x.fil"), *common,
                  "--corrupt", "nanburst"])


def test_pfd_snr_gates_nonfinite_row(monkeypatch):
    """A pathological archive (non-finite SNR from a corrupted stats
    block) lands as an ERROR row in the JSON summary, never as a NaN."""
    import argparse

    from pypulsar_tpu.cli import pfd_snr as mod
    from pypulsar_tpu.fold import profile_snr

    class _FakePfd:
        candnm = "FAKE"
        bestdm = 10.0
        curr_p1 = 0.1

    monkeypatch.setattr(mod, "effective_sefd", lambda args, pfd: None)
    monkeypatch.setattr(profile_snr, "pfd_snr",
                        lambda pfd, **kw: {"snr": float("nan"),
                                           "weq": 1.0, "smean": None})
    args = argparse.Namespace(interactive=False, on_pulse=None,
                              model_file=None, gauss_file=None,
                              json="x.json")
    rows = []
    with telemetry.session() as tlm:
        mod._append_archive_row(args, _FakePfd(), "fake.pfd", rows)
        totals = tlm.counter_totals()
    assert rows == [{"pfd": "fake.pfd", "name": "FAKE",
                     "best_dm": 10.0, "period": 0.1, "snr": None,
                     "weq_bins": None, "smean_mjy": None,
                     "ra": None, "dec": None,
                     "error": "non-finite SNR"}]
    assert totals["data.nonfinite_cands_dropped"] == 1
    assert json.dumps(rows)  # the summary stays serializable


def test_pfd_corrupt_string_length_is_located(tmp_path):
    """A corrupt negative/huge header string length in a .pfd must
    raise a located DataFormatError instead of slurping the file."""
    import struct as _struct

    from pypulsar_tpu.io.prestopfd import PfdFile

    for bad_len in (-5, 1 << 30):
        fn = tmp_path / f"bad_{bad_len & 0xffffffff}.pfd"
        fn.write_bytes(_struct.pack("<12i", *([4] * 12))
                       + _struct.pack("<i", bad_len) + b"x" * 8)
        with pytest.raises(DataFormatError) as ei:
            PfdFile(str(fn))
        assert "implausible" in str(ei.value) and str(fn) in str(ei.value)


def test_pfd_and_mask_corrupt_counts_are_located(tmp_path):
    """Corrupt negative/huge array counts in .pfd/.mask headers must
    raise located DataFormatErrors — np.fromfile would otherwise slurp
    the file (negative) or silently short-read and misalign (huge)."""
    import struct as _struct

    from pypulsar_tpu.io.prestopfd import PfdFile
    from pypulsar_tpu.io.rfimask import RfifindMask

    # .pfd: numdms = -1 with an otherwise readable fixed header
    fn = tmp_path / "negdms.pfd"
    fn.write_bytes(_struct.pack("<12i", -1, *([1] * 11)) + b"\x00" * 240)
    with pytest.raises(DataFormatError) as ei:
        PfdFile(str(fn))
    assert "implausible dms count" in str(ei.value)

    # .mask: zap-channel count corrupted negative
    mf = tmp_path / "neg.mask"
    mf.write_bytes(b"\x00" * 48 + _struct.pack("<3i", 4, 2, 10)
                   + _struct.pack("<i", -7))
    with pytest.raises(DataFormatError) as ei:
        RfifindMask(str(mf))
    assert "implausible zap channels count" in str(ei.value)
