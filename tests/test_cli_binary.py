"""Tests for pfd_snr, gridding, fitkepler, and demodulate CLIs."""

import os

import matplotlib
import numpy as np
import pytest

matplotlib.use("Agg", force=True)

from pypulsar_tpu.core.psrmath import SECPERDAY
from pypulsar_tpu.io.datfile import Datfile, write_dat
from pypulsar_tpu.io.infodata import InfoData
from pypulsar_tpu.io.parfile import write_par
from pypulsar_tpu.io.prestopfd import make_pfd


def _gauss_profs(npart=8, nsub=4, proflen=64, amp=50.0, phase=0.3,
                 width=0.03, noise=1.0, seed=0):
    rng = np.random.RandomState(seed)
    phases = np.arange(proflen) / proflen
    shape = amp * np.exp(-0.5 * ((phases - phase) / width) ** 2)
    profs = rng.randn(npart, nsub, proflen) * noise + shape / nsub
    return profs


def _make_pfd_file(tmp_path, name="cand.pfd", amp=50.0, rastr=None,
                   decstr=None):
    profs = _gauss_profs(amp=amp)
    pfd = make_pfd(profs, dt=1e-3, lofreq=1400.0, chan_wid=25.0,
                   fold_p1=0.064, bestdm=0.0, candnm="TEST")
    if rastr:
        pfd.rastr = rastr
    if decstr:
        pfd.decstr = decstr
    fn = str(tmp_path / name)
    pfd.write(fn)
    return fn


def test_pfd_snr_cli(tmp_path, capsys):
    from pypulsar_tpu.cli import pfd_snr

    fn = _make_pfd_file(tmp_path)
    rc = pfd_snr.main([fn, "--on-pulse", "0.2", "0.4", "--sefd", "3.0"])
    assert rc == 0
    out = capsys.readouterr().out
    snr_line = [ln for ln in out.splitlines() if ln.startswith("SNR:")][-1]
    snr = float(snr_line.split()[1])
    assert snr > 10.0
    assert "Mean flux density" in out


def test_pfd_snr_rejects_conflicting_flags(tmp_path):
    from pypulsar_tpu.cli import pfd_snr

    fn = _make_pfd_file(tmp_path)
    assert pfd_snr.main([fn, "--sefd", "3", "--gain", "10"]) == 1
    assert pfd_snr.main([fn, "--gain", "10"]) == 1


def test_pfd_snr_model_file(tmp_path, capsys):
    from pypulsar_tpu.cli import pfd_snr

    fn = _make_pfd_file(tmp_path)
    mfn = str(tmp_path / "comps.m")
    with open(mfn, "w") as f:
        f.write("# phase concentration amplitude\n")
        f.write("0.3 300.0 1.0\n")
    rc = pfd_snr.main([fn, "-m", mfn])
    assert rc == 0
    out = capsys.readouterr().out
    snr = float([ln for ln in out.splitlines()
                 if ln.startswith("SNR:")][-1].split()[1])
    assert snr > 10.0


def test_gridding_recovers_position(tmp_path, capsys):
    from pypulsar_tpu.cli import gridding
    from pypulsar_tpu.astro.estimate_snr import airy_pattern

    # pulsar at RA 12:00:02, Dec 30:00:30; 5 pointings around 12:00:00
    # +30:00:00 with SNRs set by the Airy beam
    fwhm = 3.35
    true_ra_am = (12 + 0 / 60 + 2.0 / 3600) * 15 * 60
    true_dec_am = (30 + 0 / 60 + 30.0 / 3600) * 60
    true_snr = 40.0
    pfdfns = []
    offsets = [(0, 0), (1.0, 0), (-1.0, 0), (0, 1.0), (0, -1.0)]
    from pypulsar_tpu.cli.gridding import angsep_arcmin
    for ii, (dra, ddec) in enumerate(offsets):
        ra_am = (12 * 15 * 60) + dra  # pointing RA in arcmin
        dec_am = (30 * 60) + ddec
        sep = angsep_arcmin(true_ra_am, true_dec_am, ra_am, dec_am)
        snr = true_snr * float(np.atleast_1d(airy_pattern(fwhm, sep))[0])
        # profile amplitude tuned so measured SNR ~ target snr
        h, rem = divmod(ra_am / 60 / 15, 1)
        m, rem = divmod(rem * 60, 1)
        s = rem * 60
        rastr = "%02d:%02d:%07.4f" % (h, m, s)
        dh, drem = divmod(dec_am / 60, 1)
        dm_, drem = divmod(drem * 60, 1)
        ds = drem * 60
        decstr = "%02d:%02d:%07.4f" % (dh, dm_, ds)
        fn = _make_pfd_file(tmp_path, "point%d.pfd" % ii,
                            amp=snr * 1.17, rastr=rastr, decstr=decstr)
        pfdfns.append(fn)
    rc = gridding.main(pfdfns + ["--fwhm", str(fwhm), "--no-plot"])
    assert rc == 0
    out = capsys.readouterr().out
    res_line = [ln for ln in out.splitlines() if "RA:" in ln and
                "results" not in ln][-1]
    # crude: fitted RA/Dec within ~1 arcmin of truth
    parts = res_line.split()
    fit_ra = float(parts[parts.index("RA:") + 1])
    fit_dec = float(parts[parts.index("Dec:") + 1])
    assert abs(fit_ra - true_ra_am) < 2.0
    assert abs(fit_dec - true_dec_am) < 2.0


def test_fitkepler_recovers_orbit(tmp_path, capsys):
    from pypulsar_tpu.cli import fitkepler
    from pypulsar_tpu.cli.fitkepler import kepler_period

    # circular orbit: asini=2 lt-s, Porb=0.5 d, Ppsr=5 ms
    true = (2.0, 0.5, 0.005, 55000.1, 0.0, 0.0)
    rng = np.random.RandomState(1)
    mjds = 55000.0 + np.linspace(0, 1.0, 40)
    ps = kepler_period(mjds, *true)
    perr = 2e-9
    ps = ps + rng.randn(ps.size) * perr
    fn = str(tmp_path / "periods.txt")
    np.savetxt(fn, np.column_stack([mjds, ps * 1000,
                                    np.full(ps.size, perr * 1000)]))
    rc = fitkepler.main([fn, "--init", "1.5", "0.45", "0.005", "55000.05",
                         "0.001", "0.0", "--no-plot"])
    assert rc == 0
    out = capsys.readouterr().out
    asini = float([ln for ln in out.splitlines()
                   if "Asini" in ln][0].split(":")[1])
    porb = float([ln for ln in out.splitlines()
                  if "Porb" in ln][0].split(":")[1])
    assert asini == pytest.approx(2.0, rel=0.01)
    assert porb == pytest.approx(0.5, rel=0.001)
    assert "Min companion mass" in out


def test_eccentric_anomaly_solves_kepler():
    from pypulsar_tpu.cli.fitkepler import eccentric_anomaly

    for ecc in (0.0, 0.3, 0.9):
        ma = np.linspace(0.01, 2 * np.pi - 0.01, 50)
        E = eccentric_anomaly(ecc, ma)
        # Kepler's equation: M = E - e sin E (mod 2pi)
        back = np.mod(E - ecc * np.sin(E), 2 * np.pi)
        np.testing.assert_allclose(back, np.mod(ma, 2 * np.pi), atol=1e-9)


def test_binary_polycos_match_exact_phase(tmp_path):
    """Native Keplerian polycos reproduce the exact BT-orbit rotation
    count to < 1e-5 rotations across several orbits."""
    from pypulsar_tpu.fold.polycos import (_bt_roemer_delay,
                                           create_polycos_from_binary)

    parfn = str(tmp_path / "bin.par")
    write_par(parfn, dict(PSR="J0001+0001", F0=200.0, F1=-1e-14,
                          PEPOCH=55000.0, DM=5.0, BINARY="BT", A1=5.0,
                          PB=0.2, T0=55000.05, OM=45.0, E=0.1))
    pcos = create_polycos_from_binary(parfn, 55000.0, 55001.0)
    rng = np.random.RandomState(0)
    for mjd in 55000.0 + rng.rand(25):
        mjdi, mjdf = int(mjd), mjd - int(mjd)
        got = pcos.get_rotation(mjdi, mjdf)
        delay = float(_bt_roemer_delay(np.array([mjd]), 0.2, 5.0, 0.1,
                                       45.0, 55000.05)[0])
        tau = (mjd - 55000.0) * SECPERDAY - delay
        exact = 200.0 * tau + 0.5 * (-1e-14) * tau ** 2
        assert abs(got - exact) < 1e-5, (mjd, got, exact)
    # apparent frequency is modulated around F0 by ~ 2 pi a1 / Pb_s * F0
    freqs = [pcos.get_freq(55000, f) for f in np.linspace(0.1, 0.9, 20)]
    vmax = 2 * np.pi * 5.0 / (0.2 * SECPERDAY)
    assert max(freqs) > 200.0 * (1 + 0.3 * vmax)
    assert min(freqs) < 200.0 * (1 - 0.3 * vmax)


def test_binary_polycos_ell1(tmp_path):
    """ELL1 ephemerides (TASC/EPS1/EPS2) produce the same polycos as the
    equivalent BT parameterization."""
    from pypulsar_tpu.fold.polycos import create_polycos_from_binary

    ecc, om_deg, pb, tasc = 0.01, 30.0, 0.3, 55000.02
    om = np.deg2rad(om_deg)
    t0 = tasc + om / (2 * np.pi) * pb
    bt_fn = str(tmp_path / "bt.par")
    ell1_fn = str(tmp_path / "ell1.par")
    common = dict(PSR="J2", F0=150.0, F1=0.0, PEPOCH=55000.0, DM=1.0,
                  A1=3.0, PB=pb)
    write_par(bt_fn, dict(common, BINARY="BT", T0=t0, OM=om_deg, E=ecc))
    write_par(ell1_fn, dict(common, BINARY="ELL1", TASC=tasc,
                            EPS1=ecc * np.sin(om), EPS2=ecc * np.cos(om)))
    p_bt = create_polycos_from_binary(bt_fn, 55000.0, 55000.5)
    p_ell = create_polycos_from_binary(ell1_fn, 55000.0, 55000.5)
    for f in np.linspace(0.05, 0.45, 9):
        r1 = p_bt.get_rotation(55000, f)
        r2 = p_ell.get_rotation(55000, f)
        assert abs(r1 - r2) < 1e-4, (f, r1, r2)


def test_binary_polycos_rejects_unknown_model(tmp_path):
    from pypulsar_tpu.fold.polycos import (PolycoError,
                                           create_polycos_from_binary)

    parfn = str(tmp_path / "weird.par")
    write_par(parfn, dict(PSR="J3", F0=100.0, PEPOCH=55000.0, DM=1.0,
                          BINARY="DDK", A1=3.0, PB=0.3))
    with pytest.raises(PolycoError):
        create_polycos_from_binary(parfn, 55000.0, 55000.5)


def test_demodulate(tmp_path, monkeypatch, capsys):
    from pypulsar_tpu.cli import demodulate

    monkeypatch.chdir(tmp_path)
    # Build a .dat whose samples encode their own index, with a binary
    # pulsar parfile; demodulation should add/drop samples
    N, dt = 200000, 1e-3
    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = dt
    inf.N = N
    inf.telescope = "Arecibo"
    inf.bary = 1
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 1
    inf.chan_width = 100.0
    inf.DM = 0.0
    inf.RA = "12:00:00.0000"
    inf.DEC = "30:00:00.0000"
    inf.object = "FAKE"
    data = np.arange(N, dtype=np.float32)
    basefn = str(tmp_path / "binpsr")
    write_dat(basefn, data, inf)
    parfn = str(tmp_path / "bin.par")
    # strong orbit: asini=10 lt-s, Pb=0.05 d -> drift of many samples
    write_par(parfn, dict(PSR="J0000+0000", F0=100.0, F1=0.0,
                          PEPOCH=55000.0, DM=0.0, RAJ="12:00:00",
                          DECJ="30:00:00", BINARY="BT", A1=10.0,
                          PB=0.05, T0=55000.0, OM=0.0, E=0.0))
    rc = demodulate.main([basefn + ".dat", "-f", parfn])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert os.path.exists(basefn + "_demod.dat")
    newinf = InfoData(basefn + "_demod.inf")
    demod = np.fromfile(basefn + "_demod.dat", dtype=np.float32)
    assert newinf.N == demod.size
    assert demod.size % 2 == 0
    nrem = int(out.split("removed:")[1].split()[0])
    nadd = int(out.split("added:")[1].split()[0])
    assert nrem + nadd > 0
    assert demod.size == N + nadd - nrem - ((N + nadd - nrem) % 2)
