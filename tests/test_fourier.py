"""Fourier-layer tests: JAX-vs-NumPy-twin parity (deredden, errors, harmonic
sums, interpolation, spectrogram) and end-to-end .fft pipeline checks."""

import numpy as np
import pytest

from pypulsar_tpu.fourier import (
    PrestoFFT,
    kernels,
    numpy_ref,
    power_law,
    write_fft,
    get_smear_response,
    smearing_function,
)
from pypulsar_tpu.io.infodata import InfoData


def make_series(n=1 << 15, f0=37.0, dt=1e-3, amp=1.0, seed=0, redamp=0.0):
    rng = np.random.RandomState(seed)
    t = np.arange(n) * dt
    x = rng.standard_normal(n) + amp * np.sin(2 * np.pi * f0 * t)
    if redamp:
        # integrated noise = steep red spectrum
        x = x + redamp * np.cumsum(rng.standard_normal(n)) / np.sqrt(n)
    return x.astype(np.float32)


def make_fft(n=1 << 15, **kw):
    x = make_series(n, **kw)
    return np.fft.rfft(x).astype(np.complex64)


def make_inf(tmp_path, n, dt=1e-3, DM=0.0):
    inf = InfoData()
    inf.basenm = "synth"
    inf.telescope = "GBT"
    inf.N = n
    inf.dt = dt
    inf.DM = DM
    inf.lofreq = 1400.0
    inf.BW = 300.0
    inf.numchan = 1024
    inf.chan_width = 300.0 / 1024
    inf.epoch = 55000.0
    return inf


class TestInterpolate:
    def test_exact_at_integer_bins(self):
        fft = make_fft(1 << 12)
        r = np.arange(100, 200, dtype=float)
        out = np.asarray(kernels.fourier_interpolate(fft, r, m=32))
        np.testing.assert_allclose(out, fft[100:200], rtol=1e-5, atol=1e-3)

    def test_matches_numpy_twin(self):
        fft = make_fft(1 << 12)
        r = np.linspace(10.25, 1000.75, 64)
        jax_out = np.asarray(kernels.fourier_interpolate(fft, r, m=16))
        np_out = numpy_ref.fourier_interpolate(fft.astype(np.complex128), r, m=16)
        np.testing.assert_allclose(jax_out, np_out, rtol=1e-4, atol=1e-2)

    def test_half_bin_signal_recovery(self):
        # a tone exactly between bins: interpolation at the true (fractional)
        # bin recovers more power than either neighboring integer bin
        n = 1 << 12
        dt = 1e-3
        freqs = np.fft.rfftfreq(n, dt)
        df = freqs[1] - freqs[0]
        f0 = freqs[500] + 0.5 * df
        t = np.arange(n) * dt
        fft = np.fft.rfft(np.sin(2 * np.pi * f0 * t))
        interp = np.asarray(
            kernels.fourier_interpolate(fft.astype(np.complex64),
                                        np.array([500.5]), m=32)
        )
        assert np.abs(interp[0]) ** 2 > np.abs(fft[500]) ** 2
        assert np.abs(interp[0]) ** 2 > np.abs(fft[501]) ** 2

    def test_odd_m_raises(self):
        with pytest.raises(ValueError):
            kernels.fourier_interpolate(make_fft(256), np.array([1.0]), m=3)


class TestHarmonicSum:
    def test_matches_twin(self):
        powers = np.abs(make_fft(1 << 13)) ** 2
        for nharm in (2, 4, 8):
            jax_out = np.asarray(kernels.harmonic_sum(powers.astype(np.float32), nharm))
            np_out = numpy_ref.harmonic_sum(powers, nharm)
            np.testing.assert_allclose(jax_out, np_out, rtol=1e-5)

    def test_boosts_harmonic_rich_signal(self):
        # narrow pulse train has many strong harmonics; harmonic summing must
        # raise its significance vs the noise floor
        n = 1 << 14
        dt = 1e-3
        rng = np.random.RandomState(2)
        x = rng.standard_normal(n)
        period_bins = 128  # divides n: fundamental lands on an exact bin
        x[::period_bins] += 8.0  # sharp pulses: power in many harmonics
        powers = np.abs(np.fft.rfft(x)) ** 2
        fund_bin = n // period_bins  # fundamental
        hs = np.asarray(kernels.harmonic_sum(powers.astype(np.float32), 8))

        # robust (MAD-based) significance: harmonic summing also boosts
        # sub-harmonic alias bins, so a plain std would overestimate noise
        def z(arr, bin_):
            med = np.median(arr)
            mad = np.median(np.abs(arr - med)) * 1.4826
            return (arr[bin_] - med) / mad

        assert z(hs, fund_bin) > z(powers, fund_bin)

    def test_incoherent_and_coherent_run(self):
        fft = make_fft(1 << 10)
        powers = np.abs(fft) ** 2
        inc = np.asarray(kernels.incoherent_harmonic_sum(fft, powers.astype(np.float32), 4))
        coh = np.asarray(kernels.coherent_harmonic_sum(fft, 4))
        assert inc.shape == powers.shape
        assert coh.shape == powers.shape
        assert np.all(np.isfinite(inc))
        assert np.all(np.isfinite(coh))


class TestDeredden:
    @pytest.mark.parametrize("n", [5000, 1 << 15])
    def test_matches_sequential_reference(self, n):
        fft = make_fft(n, redamp=5.0)
        jax_out = np.asarray(kernels.deredden(fft))
        np_out = numpy_ref.deredden(fft.astype(np.complex128))
        np.testing.assert_allclose(jax_out, np_out, rtol=1e-4, atol=1e-4)

    def test_flattens_red_noise(self):
        n = 1 << 15
        fft = make_fft(n, amp=0.0, redamp=20.0, seed=3)
        dered = np.asarray(kernels.deredden(fft))
        p = np.abs(dered) ** 2
        lo = np.median(p[10:1000])
        hi = np.median(p[n // 4 :])
        praw = np.abs(fft) ** 2
        lo_raw = np.median(praw[10:1000])
        hi_raw = np.median(praw[n // 4 :])
        assert lo_raw / hi_raw > 5  # red input
        assert lo / hi < 2  # whitened output

    def test_errors_match_sequential(self):
        powers = (np.abs(make_fft(20000, redamp=3.0)) ** 2).astype(np.float64)
        jax_out = np.asarray(kernels.estimate_power_errors(powers))
        np_out = numpy_ref.estimate_power_errors(powers)
        np.testing.assert_allclose(jax_out, np_out, rtol=1e-4, atol=1e-6)


class TestSpectrogram:
    def test_matches_twin(self):
        x = make_series(1 << 12)
        jax_out = np.asarray(kernels.spectrogram(x, 512))
        np_out = numpy_ref.spectrogram(x.astype(np.float64), 512)
        np.testing.assert_allclose(jax_out, np_out, rtol=1e-3, atol=1e-2)

    def test_tone_localized(self):
        x = make_series(1 << 14, f0=100.0, dt=1e-3, amp=5.0)
        spec = np.asarray(kernels.spectrogram(x, 1024))
        freqs = np.fft.rfftfreq(1024, 1e-3)
        peak_bins = spec[:, 1:].argmax(axis=1) + 1
        assert np.all(np.abs(freqs[peak_bins] - 100.0) < 2.0)


class TestPrestoFFTFile:
    def test_read_write_roundtrip(self, tmp_path):
        n = 1 << 12
        fft = make_fft(n)
        inf = make_inf(tmp_path, n)
        fftfn = str(tmp_path / "synth.fft")
        write_fft(fftfn, fft, inf)
        pfft = PrestoFFT(fftfn)
        np.testing.assert_allclose(pfft.fft, fft)
        assert len(pfft.freqs) == len(pfft.fft)
        assert pfft.freqs[0] == 0.0
        np.testing.assert_allclose(pfft.powers, np.abs(fft) ** 2, rtol=1e-5)
        pfft.close()

    def test_maxfreq_truncation(self, tmp_path):
        n = 1 << 12
        fft = make_fft(n)
        inf = make_inf(tmp_path, n)
        fftfn = str(tmp_path / "synth.fft")
        write_fft(fftfn, fft, inf)
        pfft = PrestoFFT(fftfn, maxfreq=100.0)
        assert np.all(pfft.freqs < 100.0)
        assert len(pfft.fft) == len(pfft.freqs)
        pfft.close()

    def test_white_level_and_fit(self, tmp_path):
        n = 1 << 15
        dt = 1e-4  # Nyquist 5000 Hz so >1000 Hz white band exists
        x = make_series(n, dt=dt, amp=0.0, redamp=30.0, seed=5)
        fft = np.fft.rfft(x).astype(np.complex64)
        inf = make_inf(tmp_path, n, dt=dt)
        fftfn = str(tmp_path / "synth.fft")
        write_fft(fftfn, fft, inf)
        pfft = PrestoFFT(fftfn)
        white = pfft.estimate_white_power_level(1000)
        assert white > 0
        fit = pfft.fit_powers(freqlim=50.0)
        assert fit["index"] < -0.5  # steep red noise detected
        assert fit["amp"] > 0
        pfft.close()

    def test_missing_file_raises(self, tmp_path):
        with pytest.raises(ValueError):
            PrestoFFT(str(tmp_path / "nope.fft"))


class TestSmearResponse:
    def test_zero_ddm_is_unity(self):
        resp = get_smear_response(0.0)
        assert resp(1.0) == 1

    def test_response_lowpass(self):
        # wrong-DM smearing suppresses high fluctuation frequencies
        obs = dict(chan_width=0.3, numchan=1024, lofreq=1200.0, N=1 << 14, dt=1e-3)
        resp = get_smear_response(1.0, **obs)
        assert resp(0.5) > resp(100.0)

    def test_smearing_kernel_support(self):
        flo, fhi, ddm = 1200.0, 1500.0, 1.0
        smear = smearing_function(flo, fhi, ddm)
        tmax = 4.15e3 * ddm * (flo**-2 - fhi**-2)
        times = np.linspace(-tmax, 2 * tmax, 1000)
        w = smear(times.copy())
        assert np.all(w[(times < 0) | (times > tmax)] == 0)
        assert np.any(w[(times > 0) & (times < tmax)] > 0)


def test_power_law():
    f = np.array([1.0, 10.0])
    np.testing.assert_allclose(power_law(f, 2.0, -1.0, 3.0), [5.0, 3.2])
