"""Streaming survey daemon tests (round 23): multi-tenant admission,
quota-aware shedding, graceful degradation. The overload contract under
test: accepted work is sacred (journal-manifested, survives restart),
unaccepted work sheds lowest-priority/thinnest-quota first past the
queue bound with a trace-reconstructible reason, a starved low-quota
tenant cannot stall a high-priority one, and the guard's hysteresis
keeps a threshold-hovering gauge from flapping admission."""

import io
import json
import os
import socket
import threading
import time

import pytest

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.survey.daemon import (
    SurveyDaemon,
    TenantSpec,
    journal_path,
    parse_tenant_spec,
    read_tenant_status,
)
from pypulsar_tpu.survey.dag import SurveyConfig
from pypulsar_tpu.survey.scheduler import FleetScheduler
from pypulsar_tpu.survey.state import Observation, format_status

from tests.test_survey import _stub, _stub_stages


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.reset()
    yield
    faultinject.reset()


def _raw(path, n=64):
    with open(path, "wb") as f:
        f.write(b"\x5a" * n)
    return str(path)


def _daemon(tmp_path, **kw):
    kw.setdefault("stages", _stub_stages())
    kw.setdefault("quiesce_s", 0.1)
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("idle_exit_s", 0.8)
    kw.setdefault("min_free_mb", 0)
    return SurveyDaemon(str(tmp_path / "out"), SurveyConfig(), **kw)


def _run_to_drain(d, timeout=30):
    t = threading.Thread(target=d.run, daemon=True)
    t.start()
    t.join(timeout=timeout)
    if t.is_alive():  # salvage the wedge so pytest itself can exit
        d.request_drain()
        t.join(timeout=10)
    assert not t.is_alive(), "daemon did not drain"
    return d


# ---------------------------------------------------------------------------
# ResourceGuard hysteresis (satellite: no admission flapping)

def test_guard_hysteresis_counts_transitions(tmp_path, monkeypatch):
    """A pending gauge oscillating AT the threshold produces ONE
    pause/resume episode with the resume margin, not one per
    oscillation — the regression the hysteresis knob exists for."""
    from pypulsar_tpu.resilience import health

    def transitions(margin):
        g = health.ResourceGuard(str(tmp_path), min_free_bytes=0,
                                 max_pending=4, resume_margin=margin)
        flips, prev = 0, None
        with telemetry.session():
            for i in range(20):
                # hover: 5 (over the bound), 4 (at it), 5, 4, ...
                telemetry.gauge("accel.pending_depth",
                                5 if i % 2 == 0 else 4)
                paused = g.admit() is not None
                if prev is not None and paused != prev:
                    flips += 1
                prev = paused
        return flips

    # margin-free guard faithfully amplifies every oscillation
    assert transitions(0.0) >= 10
    # hysteretic guard latches: one pause, no resume until real slack
    # (resume bound 4/1.25 = 3.2; the gauge never gets there)
    assert transitions(0.25) <= 1


def test_guard_hysteresis_resumes_past_margin(tmp_path):
    from pypulsar_tpu.resilience import health

    g = health.ResourceGuard(str(tmp_path), min_free_bytes=0,
                             max_pending=4, resume_margin=0.25)
    with telemetry.session():
        telemetry.gauge("x.pending_depth", 5)
        reason = g.admit()
        assert reason is not None and "backpressure" in reason
        # back AT the bound is not enough while paused ...
        telemetry.gauge("x.pending_depth", 4)
        reason = g.admit()
        assert reason is not None and "resume margin" in reason
        # ... genuine slack past the margin is
        telemetry.gauge("x.pending_depth", 3)
        assert g.admit() is None
        # and the re-pause threshold is back to the base bound
        telemetry.gauge("x.pending_depth", 5)
        assert g.admit() is not None


# ---------------------------------------------------------------------------
# tenant grammar + token buckets

def test_parse_tenant_spec_grammar():
    t = parse_tenant_spec("vlbi:3:1.5:4")
    assert (t.name, t.priority, t.rate, t.burst) == ("vlbi", 3, 1.5, 4.0)
    t = parse_tenant_spec("archive")
    assert t.name == "archive" and t.priority == 0
    t = parse_tenant_spec("fast::2")  # skipped field keeps its default
    assert t.priority == 0 and t.rate == 2.0
    with pytest.raises(ValueError):
        parse_tenant_spec(":1")
    with pytest.raises(ValueError):
        parse_tenant_spec("a:b")
    with pytest.raises(ValueError):
        parse_tenant_spec("a:1:2:3:4")


def test_token_bucket_refills_at_rate():
    t = TenantSpec("x", rate=1000.0, burst=2.0)
    assert t.try_take() and t.try_take()
    assert not t.try_take()  # burst exhausted
    time.sleep(0.01)         # 1000/s refills ~10 tokens -> capped at 2
    assert t.try_take()
    unmetered = TenantSpec("y", rate=0.0, burst=1.0)
    assert all(unmetered.try_take() for _ in range(50))


# ---------------------------------------------------------------------------
# the daemon lifecycle: watch lane, socket lane, books, drain

def test_daemon_watch_and_socket_lanes(tmp_path):
    watch = tmp_path / "in"
    watch.mkdir()
    _raw(watch / "w0.raw")
    d = _daemon(tmp_path, watch=[(str(watch), "teamA")], port=0,
                tenants=[TenantSpec("teamA", priority=1)])
    t = threading.Thread(target=d.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while d.stats()["accepted"] < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        # socket lane: synchronous verdict
        p = _raw(tmp_path / "sock0.raw")
        with socket.create_connection(("127.0.0.1", d.port),
                                      timeout=5) as s:
            s.sendall(f"teamB {p}\n".encode())
            verdict = s.makefile().readline().split()
        assert verdict[0] in ("accepted", "pending"), verdict
        # malformed line gets an error verdict, not a dead handler
        with socket.create_connection(("127.0.0.1", d.port),
                                      timeout=5) as s:
            s.sendall(b"just-one-field\n")
            assert s.makefile().readline().startswith("error")
    finally:
        t.join(timeout=30)
    assert not t.is_alive()
    st = d.stats()
    assert st["submitted"] == 2 and st["accepted"] == 2
    assert st["completed"] == 2 and st["shed"] == 0
    assert d.result is not None and d.result.ok
    # artifacts from the stub chain exist for both lanes
    for stem in ("w0", "sock0"):
        assert os.path.exists(str(tmp_path / "out" / f"{stem}.host1.out"))
    # the tenants.json mirror reflects the drained books
    snap = read_tenant_status(str(tmp_path / "out"))
    assert snap["tenants"]["teamA"]["completed"] == 1
    assert snap["tenants"]["teamB"]["completed"] == 1
    assert snap["draining"] is True


def test_daemon_dedupes_resubmitted_paths(tmp_path):
    p = _raw(tmp_path / "a.raw")
    d = _daemon(tmp_path, initial=[("t", p), ("t", p)])
    _run_to_drain(d)
    st = d.stats()
    assert st["submitted"] == 1 and st["completed"] == 1


# ---------------------------------------------------------------------------
# overload shedding: priority- and quota-ordered, never accepted work

def test_shed_lowest_priority_thinnest_quota_first(tmp_path, monkeypatch):
    """Past the queue bound the daemon sheds the lowest-priority
    pending arrival (thinnest token bucket within a priority) and the
    decision trail reconstructs from the trace events alone."""
    trace = str(tmp_path / "trace.jsonl")
    d = _daemon(tmp_path, queue_bound=2,
                tenants=[TenantSpec("gold", priority=5, rate=0.0),
                         TenantSpec("lead", priority=0, rate=0.0)])
    # hold admission shut so arrivals pile up pending: the node-level
    # guard refusing is exactly the sustained-overload regime
    monkeypatch.setattr(d._guard, "admit", lambda: "backpressure: test")
    with telemetry.session(trace):
        for i in range(2):
            v, _ = d._arrive("gold", _raw(tmp_path / f"g{i}.raw"),
                             lane="test")
            assert v == "pending"
        # the bound is full of gold; lead arrivals shed THEMSELVES
        v, why = d._arrive("lead", _raw(tmp_path / "l0.raw"), lane="test")
        assert v == "shed" and "lowest priority 0" in why
        # another gold arrival sheds the remaining lead? none left —
        # gold itself is now the only tenant, newest sheds first
        v, _ = d._arrive("gold", _raw(tmp_path / "g2.raw"), lane="test")
        assert v == "shed"
    st = d.stats()
    assert st["submitted"] == 4 and st["shed"] == 2
    assert st["accepted"] == 0  # nothing admitted through a shut guard
    # shed trail from the trace alone: tenant/reason/queue_depth attrs
    evs = []
    with open(trace) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "event" and rec["name"] == "daemon.shed":
                evs.append(rec["attrs"])
    assert len(evs) == 2
    assert {e["tenant"] for e in evs} == {"lead", "gold"}
    assert all(e["queue_depth"] == 3 and "queue full" in e["reason"]
               for e in evs)
    # and the journal carries the same verdicts for the restart replay
    recs = [json.loads(ln)
            for ln in open(journal_path(str(tmp_path / "out")))]
    assert sum(1 for r in recs if r["type"] == "shed") == 2


def test_starved_low_quota_tenant_does_not_stall_high_priority(tmp_path):
    """A pending over-quota arrival ahead of the queue must not block
    admission for tenants that still have tokens."""
    files = [("greedy", _raw(tmp_path / "g0.raw")),
             ("greedy", _raw(tmp_path / "g1.raw")),  # over quota: waits
             ("steady", _raw(tmp_path / "s0.raw")),
             ("steady", _raw(tmp_path / "s1.raw"))]
    d = _daemon(tmp_path, idle_exit_s=0.0, initial=files,
                tenants=[TenantSpec("greedy", priority=5, rate=1e-6,
                                    burst=1.0),
                         TenantSpec("steady", priority=0, rate=0.0)])
    t = threading.Thread(target=d.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 15
        while d.stats()["completed"] < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        # g1 waits on a near-never refill: the drain sheds it loudly
        d.request_drain()
        t.join(timeout=30)
    assert not t.is_alive()
    st = d.stats()
    # steady's work completed despite greedy's exhausted bucket parked
    # at the head of the (higher-priority) queue; greedy's second
    # arrival drains as unaccepted shed at shutdown, never silently
    assert st["completed"] >= 3, st
    assert st["shed"] == st["submitted"] - st["accepted"]
    b = d.tenant_snapshot()["tenants"]
    assert b["steady"]["completed"] == 2
    assert b["greedy"]["completed"] == 1
    assert b["greedy"]["shed"] == 1


# ---------------------------------------------------------------------------
# injected faults at the ingest edges (satellite: chaos arming points)

def test_arrival_fault_degrades_to_rescan(tmp_path):
    """An injected fault at daemon.arrival means the arrival was never
    seen: the watch lane re-sees the file next scan and the books count
    it exactly once."""
    watch = tmp_path / "in"
    watch.mkdir()
    _raw(watch / "w0.raw")
    faultinject.configure("io:daemon.arrival:1")
    d = _daemon(tmp_path, watch=[(str(watch), "t")])
    _run_to_drain(d)
    assert faultinject.fired_counts().get("io", 0) == 1
    st = d.stats()
    assert st["submitted"] == 1 and st["completed"] == 1


def test_admit_fault_repends_and_retries(tmp_path):
    """An injected fault at daemon.admit re-pends the arrival (counted
    once) and the next tick admits it."""
    faultinject.configure("io:daemon.admit:1")
    d = _daemon(tmp_path, initial=[("t", _raw(tmp_path / "a.raw"))])
    _run_to_drain(d)
    assert faultinject.fired_counts().get("io", 0) == 1
    st = d.stats()
    assert st["submitted"] == 1 and st["accepted"] == 1
    assert st["completed"] == 1


# ---------------------------------------------------------------------------
# accepted work is sacred: vanish handling + restart replay

def test_vanished_input_after_admit_data_quarantines(tmp_path):
    """An accepted observation whose source file disappears between
    admission and stage start is LOUDLY data-quarantined — not a crash,
    not a retry loop (satellite regression)."""
    gate = threading.Event()
    held = threading.Event()

    def slow_run(obs, cfg):
        held.set()
        assert gate.wait(10)
        with open(f"{obs.outbase}.dev1.out", "w") as f:
            f.write("ok\n")
        return 0

    from pypulsar_tpu.survey.dag import StageSpec

    stages = _stub_stages()
    stages[0] = StageSpec("dev1", "stub", True, (),
                          lambda o, c: [],
                          lambda o, c: [f"{o.outbase}.dev1.out"],
                          run=slow_run)
    outdir = str(tmp_path / "out")
    os.makedirs(outdir)
    sched = FleetScheduler([], SurveyConfig(), stages=stages,
                           service=True, devices=1, retries=2)
    t = threading.Thread(target=sched.run, daemon=True)
    t.start()
    try:
        assert sched.wait_ready(10)
        a = _raw(tmp_path / "a.raw")
        b = _raw(tmp_path / "b.raw")
        sched.submit(Observation("a", a, os.path.join(outdir, "a")))
        assert held.wait(10)  # a's device stage holds the one lease
        sched.submit(Observation("b", b, os.path.join(outdir, "b")))
        os.remove(b)          # vanishes between admit and stage start
        gate.set()
        sched.request_drain()
    finally:
        gate.set()
        t.join(timeout=30)
    assert not t.is_alive()
    # run() returned in the daemon thread; the manifests carry the
    # verdicts: b must be DATA-quarantined with a loud vanish reason
    import glob

    from pypulsar_tpu.survey.state import MANIFEST_SUFFIX, status_rows
    rows = {r["obs"]: r for r in status_rows(
        sorted(glob.glob(os.path.join(outdir, "*" + MANIFEST_SUFFIX))))}
    qb = rows["b"]["quarantine"]
    assert qb is not None and qb.get("reason") == "data"
    assert "vanished" in qb["error"]
    assert rows["b"].get("retries", {}) == {}  # no retry loop
    # the healthy observation completed normally
    assert rows["a"]["quarantine"] is None
    assert len(rows["a"]["done"]) == 2


def test_restart_replays_journal_without_rerunning_terminal(tmp_path):
    """A second daemon over the same outdir folds journaled terminal
    verdicts straight into the books and resubmits only open accepts."""
    p0 = _raw(tmp_path / "a.raw")
    p1 = _raw(tmp_path / "b.raw")
    d1 = _daemon(tmp_path, initial=[("t", p0), ("t", p1)])
    _run_to_drain(d1)
    assert d1.stats()["completed"] == 2
    # restart: nothing to resubmit, books carry the history
    d2 = _daemon(tmp_path, idle_exit_s=0.4)
    assert d2.recover() == 0
    assert d2.stats()["completed"] == 2
    assert d2.stats()["accepted"] == 2
    # a journal with an OPEN accept (no terminal record) resubmits with
    # resume=True: the already-journaled stages are skipped, not re-run
    p2 = _raw(tmp_path / "c.raw")
    with open(journal_path(str(tmp_path / "out")), "a") as f:
        f.write(json.dumps(
            {"type": "accept", "tenant": "t", "obs": "c", "infile": p2,
             "outbase": str(tmp_path / "out" / "c"),
             "t_unix": time.time()}) + "\n")
        # a torn tail must be tolerated, not crash the replay
        f.write('{"type": "accept", "tenant": "t", "obs"')
    d3 = _daemon(tmp_path, idle_exit_s=0.8)
    _run_to_drain(d3)
    st = d3.stats()
    assert st["completed"] == 3 and st["accepted_open"] == 0
    assert d3.result is not None and d3.result.ok
    # zero re-runs of a+b's validated stages: only c's two stages ran
    assert len(d3.result.ran) == 2, d3.result.ran


# ---------------------------------------------------------------------------
# status surfaces (satellite: tenants block + tlmsum roll-up)

def test_format_status_renders_tenants_block():
    snap = {"queue_depth": 1, "queue_bound": 8, "accepted_open": 2,
            "draining": False,
            "tenants": {"vlbi": {"priority": 3, "rate": 1.5, "burst": 4,
                                 "tokens": 2.5, "submitted": 7,
                                 "accepted": 5, "shed": 1,
                                 "quarantined": 1, "completed": 3},
                        "archive": {"priority": 0, "rate": 0,
                                    "burst": 8, "tokens": 8.0,
                                    "submitted": 2, "accepted": 2,
                                    "shed": 0, "quarantined": 0,
                                    "completed": 2}}}
    text = format_status([], tenants=snap)
    assert "# tenants (accept queue 1/8, 2 accepted in flight):" in text
    assert "vlbi" in text and "prio 3" in text
    assert "7 submitted / 5 accepted / 1 shed" in text
    assert "unmetered" in text          # archive has rate 0
    snap["draining"] = True
    assert "DRAINING" in format_status([], tenants=snap)
    # absent block (no daemon ever ran): no tenants section at all
    assert "tenants" not in format_status([], tenants=None)


def test_tlmsum_per_tenant_rollup_renders():
    from pypulsar_tpu.obs.summarize import (
        TraceSummary,
        combine_summaries,
        render,
    )

    s = TraceSummary()
    s.feed({"type": "event", "name": "daemon.arrival", "t": 0.0,
            "attrs": {"tenant": "vlbi", "path": "x.fil"}})
    s.feed({"type": "event", "name": "daemon.accept", "t": 0.1,
            "attrs": {"tenant": "vlbi", "obs": "x"}})
    s.feed({"type": "event", "name": "daemon.terminal", "t": 0.2,
            "attrs": {"tenant": "vlbi", "obs": "x", "state": "done"}})
    s.feed({"type": "event", "name": "daemon.shed", "t": 0.3,
            "attrs": {"tenant": "archive", "reason": "queue full",
                      "queue_depth": 9}})
    s.feed({"type": "event", "name": "daemon.terminal", "t": 0.4,
            "attrs": {"tenant": "archive", "obs": "y",
                      "state": "quarantined"}})
    s.finish()
    assert s.tenant_stats["vlbi"] == {"arrivals": 1, "accepted": 1,
                                      "completed": 1}
    assert s.tenant_stats["archive"] == {"shed": 1, "quarantined": 1}
    combined = combine_summaries([s, s])
    assert combined.tenant_stats["vlbi"]["accepted"] == 2
    buf = io.StringIO()
    render(combined, buf)
    out = buf.getvalue()
    assert "# per-tenant (daemon admission):" in out
    assert "vlbi" in out and "accepted     2" in out


def test_statusd_snapshot_carries_tenants(tmp_path):
    from pypulsar_tpu.obs.statusd import fleet_snapshot

    d = _daemon(tmp_path, initial=[("t", _raw(tmp_path / "a.raw"))])
    _run_to_drain(d)
    snap = fleet_snapshot(str(tmp_path / "out"))
    assert snap["tenants"] is not None
    assert snap["tenants"]["tenants"]["t"]["completed"] == 1
    # and the --status renderer consumes it end to end
    text = format_status(snap["rows"], tenants=snap["tenants"])
    assert "# tenants" in text


# the acceptance-scale soak twin (the committed record is SOAK_r01.json;
# marked slow per the chaos-harness convention so tier-1 stays bounded)

@pytest.mark.slow
def test_daemon_soak_harness():
    """bench.py --daemon-soak --quick in-process: the full overload
    storm (bulk flood + chaos spray + ingest quarantine), the SIGKILL'd
    and restarted --daemon subprocess, the SIGTERM drain, and byte
    parity vs the batch reference — every gate asserted by the harness
    itself."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    args = bench.parse_args(["--daemon-soak", "--quick", "--child"])
    record = bench.run_daemon_soak(args)
    assert record["value"] == 1.0
    assert record["soak_kill9_reruns"] == 0
    assert record["soak_sigterm_rc"] == 0
    assert record["soak_books"]["submitted"] == (
        record["soak_books"]["accepted"] + record["soak_books"]["shed"])
