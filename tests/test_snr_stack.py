"""Tests: HEALPix interp, skytemp, radiometer SNR, .pfd round trip,
profile SNR (parity targets: healpy.get_interp_val, reference
utils/{skytemp,estimate_snr}.py, external prepfold.pfd, bin/pfd_snr.py)."""

import numpy as np
import pytest

from pypulsar_tpu.astro import estimate_snr, healpix, skytemp
from pypulsar_tpu.core import psrmath
from pypulsar_tpu.fold import profile_snr
from pypulsar_tpu.io.prestopfd import PfdFile, fft_rotate, make_pfd


class TestHealpix:
    def test_npix_nside(self):
        assert healpix.npix(8) == 768
        assert healpix.nside_from_npix(768) == 8
        with pytest.raises(ValueError):
            healpix.nside_from_npix(1000)

    def test_pix2ang_ang2pix_roundtrip(self):
        nside = 16
        pix = np.arange(healpix.npix(nside))
        theta, phi = healpix.pix2ang(nside, pix)
        back = healpix.ang2pix(nside, theta, phi)
        np.testing.assert_array_equal(back, pix)

    def test_ring_structure(self):
        # ring z values must be strictly decreasing over all rings
        nside = 8
        i = np.arange(1, 4 * nside)
        _, _, z, _ = healpix._ring_info(nside, i)
        assert (np.diff(z) < 0).all()
        # pixel counts sum to npix
        _, rp, _, _ = healpix._ring_info(nside, i)
        assert rp.sum() == healpix.npix(nside)

    def test_interp_smooth_function(self):
        # interpolation of a smooth function sampled on pixel centers
        # should reproduce the function well away from the poles
        nside = 64
        pix = np.arange(healpix.npix(nside))
        theta, phi = healpix.pix2ang(nside, pix)
        f = lambda th, ph: np.cos(th) + 0.3 * np.sin(th) * np.cos(ph)
        m = f(theta, phi)
        rng = np.random.RandomState(0)
        th_test = rng.uniform(0.3, np.pi - 0.3, 500)
        ph_test = rng.uniform(0, 2 * np.pi, 500)
        got = healpix.get_interp_val(m, th_test, ph_test)
        np.testing.assert_allclose(got, f(th_test, ph_test), atol=2e-3)

    def test_interp_at_pixel_centers_exact(self):
        nside = 16
        pix = np.arange(healpix.npix(nside))
        theta, phi = healpix.pix2ang(nside, pix)
        m = np.arange(healpix.npix(nside), dtype=float)
        # at exact centers of the equatorial belt the interp is dominated
        # by the pixel itself
        sel = (theta > 1.0) & (theta < np.pi - 1.0)
        got = healpix.get_interp_val(m, theta[sel], phi[sel])
        # neighbors are close in value only for smooth maps; use a smooth map
        m2 = np.cos(theta)
        got2 = healpix.get_interp_val(m2, theta[sel], phi[sel])
        np.testing.assert_allclose(got2, np.cos(theta[sel]), atol=5e-3)
        assert np.isfinite(got).all()


class TestSkytemp:
    def test_get_skytemp_from_synthetic_map(self, tmp_path):
        nside = 32
        pix = np.arange(healpix.npix(nside))
        theta, phi = healpix.pix2ang(nside, pix)
        # temperature pattern: hot galactic plane (theta ~ pi/2)
        m = 10.0 + 40.0 * np.exp(-((theta - np.pi / 2) / 0.2) ** 2)
        fn = str(tmp_path / "haslam.fits")
        skytemp.write_healpix_map(fn, m)
        t_plane = skytemp.get_skytemp(0.0, 0.0, freq=408.0, mapfn=fn)
        t_pole = skytemp.get_skytemp(0.0, 85.0, freq=408.0, mapfn=fn)
        assert t_plane == pytest.approx(50.0, rel=0.05)
        assert t_pole == pytest.approx(10.0, rel=0.05)

    def test_freq_scaling_honors_index(self, tmp_path):
        nside = 8
        m = np.full(healpix.npix(nside), 20.0)
        fn = str(tmp_path / "flat.fits")
        skytemp.write_healpix_map(fn, m)
        t408 = skytemp.get_skytemp(10.0, 10.0, freq=408.0, mapfn=fn)
        t1400 = skytemp.get_skytemp(10.0, 10.0, freq=1400.0, mapfn=fn)
        assert t1400 / t408 == pytest.approx((1400.0 / 408.0) ** -2.7)
        # unlike the reference (SURVEY.md §2.6), index is honored
        t_flat = skytemp.get_skytemp(10.0, 10.0, freq=1400.0, index=0.0,
                                     mapfn=fn)
        assert t_flat == pytest.approx(t408)


class TestEstimateSnr:
    def test_airy_pattern(self):
        assert estimate_snr.airy_pattern(10.0, 0.0) == pytest.approx(1.0)
        assert estimate_snr.airy_pattern(10.0, 5.0) == pytest.approx(0.5, abs=0.01)
        assert estimate_snr.airy_pattern(10.0, 20.0) < 0.05

    def test_change_freq(self):
        S, e = estimate_snr.change_freq(10.0, 1.0, 400.0, 1400.0, -1.8)
        k = (1400.0 / 400.0) ** -1.8
        assert S == pytest.approx(10.0 * k)
        assert e == pytest.approx(1.0 * k)

    def test_radiometer_scalings(self):
        est = estimate_snr.SnrEstimator(freq=1400.0, bw=100.0, numpol=2,
                                        gain=10.0, systemp=30.0, fwhm=3.5)
        snr1, err1 = est.estimate_snr(za=5, az=0, Smean=1.0, Sfreq=1400.0,
                                      time=600.0, angsep=0.0, period=0.5)
        snr2, _ = est.estimate_snr(za=5, az=0, Smean=2.0, Sfreq=1400.0,
                                   time=600.0, angsep=0.0, period=0.5)
        assert snr2 == pytest.approx(2 * snr1)  # linear in flux
        snr4t, _ = est.estimate_snr(za=5, az=0, Smean=1.0, Sfreq=1400.0,
                                    time=2400.0, angsep=0.0, period=0.5)
        assert snr4t == pytest.approx(2 * snr1)  # sqrt(t)
        assert np.isnan(err1).all()  # no flux error given
        # off-axis reduces SNR
        snr_off, _ = est.estimate_snr(za=5, az=0, Smean=1.0, Sfreq=1400.0,
                                      time=600.0, angsep=2.0, period=0.5)
        assert snr_off < snr1

    def test_gain_curve_callable(self):
        gain = lambda za=0, az=0: 11.0 - 0.1 * za
        est = estimate_snr.SnrEstimator(1400.0, 100.0, 2, gain, 25.0, 3.5)
        s_low, _ = est.estimate_snr(0, 0, 1.0, 1400.0, 600.0, 0.0, 0.5)
        s_high, _ = est.estimate_snr(15, 0, 1.0, 1400.0, 600.0, 0.0, 0.5)
        assert s_low > s_high


class TestPfd:
    def _fake(self, proflen=64, npart=8, nsub=4, pulse_phase=0.3):
        rng = np.random.RandomState(0)
        template = psrmath.gaussian_profile(proflen, pulse_phase, 0.06)
        profs = (1000.0 + 50.0 * template[None, None, :]
                 + rng.randn(npart, nsub, proflen) * 1.0)
        return make_pfd(profs, dt=1e-3, lofreq=1400.0, chan_wid=25.0,
                        numchan=4, fold_p1=0.5, bestdm=0.0)

    def test_roundtrip(self, tmp_path):
        p = self._fake()
        fn = str(tmp_path / "fake.pfd")
        p.write(fn)
        q = PfdFile(fn)
        assert q.proflen == p.proflen and q.npart == p.npart
        assert q.candnm == p.candnm
        assert q.curr_p1 == p.curr_p1
        np.testing.assert_allclose(q.profs, p.profs)
        np.testing.assert_allclose(q.stats, p.stats)
        assert q.rastr == "00:00:00.00"

    def test_fft_rotate(self):
        x = np.zeros(32)
        x[4] = 1.0
        y = fft_rotate(x, 3.0)
        assert np.argmax(y) == 7
        # fractional rotation conserves total flux
        z = fft_rotate(x, 2.5)
        assert z.sum() == pytest.approx(x.sum())

    def test_dedisperse_aligns_subbands(self):
        proflen, npart, nsub = 64, 4, 8
        dm, p1 = 50.0, 0.5
        lofreq, chan_wid, numchan = 1300.0, 1.0, 64
        chan_per_sub = numchan // nsub
        subfreqs = lofreq + (np.arange(nsub) * chan_per_sub
                             + 0.5 * (chan_per_sub - 1)) * chan_wid
        delays = psrmath.delay_from_DM(dm, subfreqs)
        delays -= delays[-1]
        template = psrmath.gaussian_profile(proflen, 0.5, 0.05)
        profs = np.zeros((npart, nsub, proflen))
        for j in range(nsub):
            shift = delays[j] / p1 * proflen
            profs[:, j, :] = fft_rotate(template, shift)[None, :] * 10 + 100
        p = make_pfd(profs, dt=1e-3, lofreq=lofreq, chan_wid=chan_wid,
                     numchan=numchan, fold_p1=p1, bestdm=dm)
        smeared_peak = p.sumprof.max()
        p.dedisperse()
        assert p.currdm == dm
        aligned_peak = p.sumprof.max()
        assert aligned_peak > smeared_peak
        # after dedispersion all subbands peak at the template phase
        prof = p.sumprof - p.sumprof.min()
        assert abs(int(np.argmax(prof)) - 32) <= 1

    def test_adjust_period_aligns_parts(self):
        proflen, npart, nsub = 64, 16, 1
        p1 = 0.5
        p_wrong = p1 * (1 + 2e-4)  # folded at slightly wrong period
        T_part = 10.0
        template = psrmath.gaussian_profile(proflen, 0.5, 0.05)
        profs = np.zeros((npart, nsub, proflen))
        for i in range(npart):
            t = i * T_part
            dphi = (1.0 / p1 - 1.0 / p_wrong) * t
            profs[i, 0, :] = fft_rotate(template, dphi * proflen) * 10 + 100
        p = make_pfd(profs, dt=1e-3, lofreq=1400.0, chan_wid=1.0,
                     numchan=1, fold_p1=p_wrong)
        p.T = npart * T_part  # override synthesized T for the test
        drift_peak = p.sumprof.max()
        p.adjust_period(p=p1)
        assert p.sumprof.max() > drift_peak
        assert p.curr_p1 == p1

    def test_dof_corr_limits(self):
        p = self._fake()
        # many samples per bin -> correction ~1; <1 sample per bin -> ~dt_per_bin
        p.dt_per_bin = 100.0
        assert p.DOF_corr() == pytest.approx(1.0, rel=0.01)
        p.dt_per_bin = 0.01
        assert p.DOF_corr() == pytest.approx(0.01, rel=0.01)


class TestProfileSnr:
    def test_calc_snr_known_signal(self):
        proflen = 128
        rng = np.random.RandomState(1)
        std_true = 2.0
        template = np.zeros(proflen)
        template[60:68] = 50.0
        prof = template + rng.randn(proflen) * std_true + 10.0
        onpulse = profile_snr.onpulse_from_regions(proflen, [(58, 70)])
        snr, weq, area, offmean = profile_snr.calc_snr(prof, onpulse, std_true)
        # analytic: area ~ 400, weq ~ 8, snr ~ 400/2/sqrt(8) ~ 70
        assert snr == pytest.approx(400.0 / 2.0 / np.sqrt(8.0), rel=0.15)
        assert offmean == pytest.approx(10.0, abs=0.5)

    def test_onpulse_auto(self):
        prof = np.ones(64)
        prof[30:34] = 30.0
        mask = profile_snr.onpulse_auto(prof)
        assert mask[30:34].all()
        assert mask.sum() == 4

    def test_pfd_snr_end_to_end(self):
        proflen, npart, nsub = 64, 8, 4
        rng = np.random.RandomState(2)
        template = psrmath.gaussian_profile(proflen, 0.5, 0.08)
        template /= template.max()
        profs = (1000.0 + rng.randn(npart, nsub, proflen) * 5.0
                 + 30.0 * template[None, None, :])
        p = make_pfd(profs, dt=1e-3, lofreq=1400.0, chan_wid=25.0,
                     numchan=4, fold_p1=0.5)
        out = profile_snr.pfd_snr(p, regions=[(24, 40)], dedisperse=False)
        assert out["snr"] > 5
        assert out["smean"] is None
        out2 = profile_snr.pfd_snr(p, regions=[(24, 40)], dedisperse=False,
                                   sefd=3.0)
        assert out2["smean"] is not None and out2["smean"] > 0

    def test_gaussfitfile(self, tmp_path):
        fn = str(tmp_path / "g.gaussians")
        with open(fn, "w") as f:
            f.write("const = 1.0 +/- 0\n")
            f.write("phas1 = 0.25 +/- 0\nampl1 = 5.0 +/- 0\nfwhm1 = 0.05 +/- 0\n")
            f.write("phas2 = 0.60 +/- 0\nampl2 = 2.0 +/- 0\nfwhm2 = 0.10 +/- 0\n")
        comps, const = profile_snr.read_gaussfitfile(fn, 128)
        assert comps.shape == (2, 128)
        assert np.argmax(comps[0]) == 32
        assert np.argmax(comps[1]) == pytest.approx(77, abs=1)

    def test_model_alignment(self):
        proflen = 64
        model = psrmath.gaussian_profile(proflen, 0.2, 0.06)
        prof = np.roll(model, 10) * 3 + 1
        rot = profile_snr.get_rotation(prof, model)
        # transform() rotates LEFT (PRESTO rotate convention): a profile
        # np.roll'ed right by 10 needs a left rotation of n-10
        assert rot == pytest.approx(54.0 / 64.0, abs=1.0 / 64)
        mask = profile_snr.onpulse_from_model(prof, model)
        assert mask[np.argmax(prof)]
