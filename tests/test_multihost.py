"""Multi-host survey fleet tests (round 18): the coordination plane's
safety contracts (monotonic fencing tokens, stale-token write rejection,
double-adoption resolving to one winner), the scheduler's claim/adopt
loop (hosts split a fleet without duplicating work, orphans are adopted
and resume byte-exactly, a netstalled host cedes to its adopter), and
the M-process CLI integration (a host SIGKILL'd mid-stage loses its
observation to a survivor and a final resume re-runs nothing).

In-process tests drive several FleetScheduler instances — each with its
own FleetPlane handle — over one shared directory with stub stage DAGs:
the coordination machinery is identical to the M-process case (the
plane is plain files), only the failure *injection* differs. The real
SIGKILL/process-death paths run as subprocess integration tests behind
a cached spawn-capability probe (the same pattern as
test_distributed._require_cpu_collectives, which gates on jax
COLLECTIVES — deliberately not reused here: the plane needs no
collectives, and this container's jaxlib fails that probe while
spawning plain subprocesses just fine)."""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.resilience.health import HostHealth
from pypulsar_tpu.survey.dag import StageSpec, SurveyConfig
from pypulsar_tpu.survey.fleet import (
    FleetPlane,
    StaleLeaseError,
    read_plane_status,
)
from pypulsar_tpu.survey.scheduler import FleetScheduler
from pypulsar_tpu.survey.state import (
    ObsManifest,
    Observation,
    format_status,
    status_rows,
)

_SPAWN_PROBE: list = []  # cached (ok, detail), once per session


def _require_spawn():
    """Capability gate for the subprocess integration tests: can this
    container spawn a python child that imports the package? (Spawn-less
    sandboxes skip cleanly instead of failing red.)"""
    if not _SPAWN_PROBE:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = (repo + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        env["JAX_PLATFORMS"] = "cpu"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import pypulsar_tpu; print('OK')"],
                env=env, capture_output=True, text=True, timeout=120)
            _SPAWN_PROBE.append(
                (proc.returncode == 0 and "OK" in proc.stdout,
                 proc.stderr.strip().splitlines()[-1][-200:]
                 if proc.stderr.strip() else ""))
        except (OSError, subprocess.TimeoutExpired) as e:
            _SPAWN_PROBE.append((False, f"{type(e).__name__}: {e}"))
    ok, detail = _SPAWN_PROBE[0]
    if not ok:
        pytest.skip("environment capability: cannot spawn python "
                    f"subprocesses ({detail})")


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.reset()
    yield
    faultinject.reset()


def _plane(td, host, lease_s=1.0, settle_s=0.02, heartbeat_s=None):
    return FleetPlane(str(td), host_id=host, lease_s=lease_s,
                      settle_s=settle_s, heartbeat_s=heartbeat_s)


def _mk_stage(name, deps=(), slow_s=0.0, device=None):
    def run(o, c, _n=name, _s=slow_s):
        if _s:
            time.sleep(_s)
        with open(f"{o.outbase}.{_n}.out", "w") as f:
            f.write(_n + o.name)
        return 0

    return StageSpec(name, "stub", device if device is not None
                     else name.startswith("dev"), tuple(deps),
                     lambda o, c: [],
                     lambda o, c, n=name: [f"{o.outbase}.{n}.out"],
                     run=run)


def _mk_obs(td, n):
    obs = []
    for i in range(n):
        raw = os.path.join(str(td), f"o{i}.raw")
        with open(raw, "wb") as f:
            f.write(b"x" * 64)
        obs.append(Observation(f"o{i}", raw, os.path.join(str(td),
                                                          f"o{i}")))
    return obs


# ---------------------------------------------------------------------------
# plane primitives: tokens, fencing, adoption, double-adoption
# ---------------------------------------------------------------------------


def test_fencing_tokens_strictly_monotonic_across_hosts(tmp_path):
    """Every allocation — from any host, interleaved however — yields a
    strictly larger integer: the property the whole fencing design
    rests on (an adopter ALWAYS outranks the host it adopted from)."""
    pa, pb = _plane(tmp_path, "hA"), _plane(tmp_path, "hB")
    got = []
    lock = threading.Lock()

    def grab(p, k):
        for _ in range(k):
            t = p.next_token()
            with lock:
                got.append(t)

    ts = [threading.Thread(target=grab, args=(p, 10))
          for p in (pa, pb, pa, pb)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(got) == 40
    assert len(set(got)) == 40, "token collision across racing hosts"


def test_stale_fencing_token_write_rejected(tmp_path):
    """The acceptance bullet verbatim: after adoption, the dead host's
    manifest append is a no-op — ObsManifest.mark_done raises
    StaleLeaseError BEFORE touching the journal file."""
    pa = _plane(tmp_path, "hA", settle_s=0.0)
    pb = _plane(tmp_path, "hB", settle_s=0.0)
    pa.register()
    pb.register()
    t_a = pa.claim("o0")
    assert t_a is not None
    # hA goes silent (stop renewing WITHOUT marking left: a death, not
    # an exit), hB adopts past the lease bound
    pa._stop.set()
    pa._renew.join()
    time.sleep(1.2)
    t_b = pb.claim("o0")
    assert t_b is not None and t_b > t_a
    out = str(tmp_path / "art.out")
    with open(out, "w") as f:
        f.write("bytes")
    m = ObsManifest(str(tmp_path / "o0.survey.jsonl"), "fp",
                    token=t_a, fence=lambda: pa.fence("o0", t_a))
    size_before = os.path.getsize(m.path) if os.path.exists(m.path) else 0
    with pytest.raises(StaleLeaseError):
        m.mark_done("s1", [out])
    size_after = os.path.getsize(m.path) if os.path.exists(m.path) else 0
    assert size_after == size_before, "stale write touched the manifest"
    m.close()
    # the adopter's fenced write goes through and carries ITS token
    m2 = ObsManifest(str(tmp_path / "o0.survey.jsonl"), "fp",
                     token=t_b, fence=lambda: pb.fence("o0", t_b))
    m2.mark_done("s1", [out])
    assert m2.done_stages() == {"s1"}
    m2.close()
    recs = [json.loads(ln) for ln in
            open(str(tmp_path / "o0.survey.jsonl")) if ln.strip()]
    assert [r.get("token") for r in recs if r.get("type") == "done"] \
        == [t_b]
    pb.close()


def test_double_adoption_race_resolves_to_one_winner(tmp_path):
    """Two survivors adopt the same orphan concurrently: os.replace
    leaves exactly one claim, the settle re-read kicks the loser out,
    and — for the residual race — at most one of the two tokens can
    ever pass a fence afterwards."""
    dead = _plane(tmp_path, "dead", settle_s=0.0)
    dead.register()
    assert dead.claim("o0") is not None
    dead._stop.set()
    dead._renew.join()
    time.sleep(1.2)  # past the 1 s lease: o0 is an orphan

    tokens = {}
    barrier = threading.Barrier(2)

    def adopt(host):
        p = _plane(tmp_path, host, settle_s=0.1)
        p.register()
        barrier.wait()
        tokens[host] = (p, p.claim("o0"))

    ts = [threading.Thread(target=adopt, args=(h,)) for h in ("hA", "hB")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    survivors = []
    for host, (p, tok) in tokens.items():
        if tok is None:
            continue
        try:
            p.fence("o0", tok)
            survivors.append(host)
        except StaleLeaseError:
            pass
    assert len(survivors) == 1, (
        f"double adoption must resolve to ONE winner, got {survivors} "
        f"(tokens {dict((h, t) for h, (_, t) in tokens.items())})")
    for p, _ in tokens.values():
        p.close()


def test_left_host_running_claim_is_adoptable_immediately(tmp_path):
    """A clean exit (lease marked LEFT) with an observation still
    running is an orphan right away — no lease-timeout wait."""
    pa = _plane(tmp_path, "hA", lease_s=60.0, settle_s=0.0)
    pa.register()
    assert pa.claim("o0") is not None
    pa.close()  # LEFT, claim still "running"
    pb = _plane(tmp_path, "hB", lease_s=60.0, settle_s=0.0)
    pb.register()
    assert pb.claim("o0") is not None
    pb.close()


def test_netstall_fault_kind_registered_and_bounded(tmp_path, monkeypatch):
    """The new kind parses in both grammars, stalls (bounded by
    PYPULSAR_TPU_HANG_S), and counts as fired."""
    monkeypatch.setenv("PYPULSAR_TPU_HANG_S", "0.2")
    assert "netstall" in faultinject.KINDS
    assert "netstall" in faultinject.CHAOS_KINDS
    faultinject.parse_chaos_spec("1:0.5:netstall+kill")
    faultinject.configure("netstall:fleet.heartbeat:1")
    t0 = time.monotonic()
    faultinject.trip("fleet.heartbeat")  # stalls ~0.2 s, then returns
    assert 0.15 <= time.monotonic() - t0 < 2.0
    assert faultinject.fired_counts().get("netstall") == 1


# ---------------------------------------------------------------------------
# scheduler claim/adopt loop (in-process hosts, stub DAGs)
# ---------------------------------------------------------------------------


def _run_hosts(tmp_path, obs, stages, hosts, lease_s=1.0, stagger=0.0):
    """Run one FleetScheduler per host id concurrently over the shared
    dir; returns {host: FleetResult} (exceptions re-raised)."""
    results = {}
    errors = {}

    def go(host):
        plane = _plane(tmp_path, host, lease_s=lease_s)
        try:
            results[host] = FleetScheduler(
                obs, SurveyConfig(), stages=stages, plane=plane).run()
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors[host] = e

    ts = []
    for host in hosts:
        t = threading.Thread(target=go, args=(host,))
        t.start()
        ts.append(t)
        if stagger:
            time.sleep(stagger)
    for t in ts:
        t.join(timeout=60)
    return results, errors


def test_hosts_split_fleet_every_stage_exactly_once(tmp_path):
    """Two hosts over four observations: every stage of every obs runs
    exactly once fleet-wide, both hosts exit ok, and each host saw the
    other's observations complete remotely."""
    stages = [_mk_stage("dev1", slow_s=0.05), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 4)
    results, errors = _run_hosts(tmp_path, obs, stages, ("hA", "hB"))
    assert not errors, errors
    assert all(r.ok for r in results.values())
    ran = [x for r in results.values() for x in r.ran]
    assert len(ran) == len(set(ran)) == 8, ran
    for i in range(4):
        for s in ("dev1", "host1"):
            assert os.path.exists(str(tmp_path / f"o{i}.{s}.out"))
    assert all(r.remote_done for r in results.values())


def test_surplus_hosts_join_claim_pool_and_adopt(tmp_path):
    """The shard_files idle-host fix at fleet level: THREE hosts over
    TWO observations — the surplus host gets no initial work yet exits
    cleanly as a pool member, and when a loaded host dies its orphan is
    adopted (by whichever idle host wins the race) instead of dying
    with it."""
    stages = [_mk_stage("dev1", slow_s=0.3), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 2)
    # host hA dies at its first stage-done boundary (InjectedKill
    # unwinds its fleet like a signal); hB and the initially idle hC
    # between them must finish everything
    faultinject.configure("kill:survey.stage_done.dev1:1")
    results, errors = _run_hosts(tmp_path, obs, stages,
                                 ("hA", "hB", "hC"), stagger=0.05)
    faultinject.reset()
    assert set(errors) == {"hA"} \
        and isinstance(errors["hA"], faultinject.InjectedKill)
    assert results["hB"].ok and results["hC"].ok
    ran = [x for h in ("hB", "hC") for x in results[h].ran]
    assert len(ran) == len(set(ran)), f"duplicated stage runs: {ran}"
    for i in range(2):
        for s in ("dev1", "host1"):
            assert os.path.exists(str(tmp_path / f"o{i}.{s}.out"))
    adopted = results["hB"].adopted + results["hC"].adopted
    assert adopted, "the dead host's observation was never adopted"
    # a final validated single-host resume re-runs nothing
    final = FleetScheduler(obs, SurveyConfig(), stages=stages,
                           resume=True).run()
    assert final.ran == [] and len(final.skipped) == 4


def test_netstalled_host_cedes_to_adopter_single_winner(tmp_path,
                                                        monkeypatch):
    """The split-brain scenario end to end: hA's heartbeat renewer is
    parked by a netstall while its (slow) stage still runs; hB adopts
    past the lease bound; hA's next manifest append is rejected by the
    fencing token and the observation is CEDED — one winner, no retry,
    no quarantine, and the winner's artifacts validate."""
    monkeypatch.setenv("PYPULSAR_TPU_HANG_S", "4")
    stages = [_mk_stage("dev1", slow_s=2.5), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 1)
    faultinject.configure("netstall:fleet.heartbeat:2")
    results = {}

    def go(host, plane):
        results[host] = FleetScheduler(
            obs, SurveyConfig(), stages=stages, plane=plane).run()

    pa = _plane(tmp_path, "hA", lease_s=0.8, heartbeat_s=0.2)
    ta = threading.Thread(target=go, args=("hA", pa))
    ta.start()
    time.sleep(1.6)  # hA mid-stage, heartbeat silent past the lease
    pb = _plane(tmp_path, "hB", lease_s=0.8, heartbeat_s=0.2)
    tb = threading.Thread(target=go, args=("hB", pb))
    tb.start()
    ta.join(timeout=60)
    tb.join(timeout=60)
    assert results["hA"].ok and results["hB"].ok
    assert results["hA"].ceded == ["o0"]
    assert results["hA"].ran == []  # its done never landed
    assert results["hB"].adopted == ["o0"]
    assert ("o0", "dev1") in results["hB"].ran
    final = FleetScheduler(obs, SurveyConfig(), stages=stages,
                           resume=True).run()
    assert final.ran == [] and len(final.skipped) == 2


def test_adopted_obs_resumes_from_manifest_not_from_scratch(tmp_path):
    """Adoption IS resume: stages the dead host's manifest recorded
    (and whose artifacts validate) are skipped by the adopter."""
    stages = [_mk_stage("dev1"), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 1)
    # hA completes dev1 then dies at host1's start boundary
    faultinject.configure("kill:survey.stage_start.host1:1")
    pa = _plane(tmp_path, "hA")
    with pytest.raises(faultinject.InjectedKill):
        FleetScheduler(obs, SurveyConfig(), stages=stages,
                       plane=pa).run()
    faultinject.reset()
    pb = _plane(tmp_path, "hB")
    r = FleetScheduler(obs, SurveyConfig(), stages=stages,
                       plane=pb).run()
    assert r.ok and r.adopted == ["o0"]
    assert ("o0", "dev1") in r.skipped, "validated stage re-ran"
    assert r.ran == [("o0", "host1")]


def test_torn_manifest_tail_survives_adoption(tmp_path):
    """A host SIGKILL'd mid-manifest-append leaves a torn trailing
    line; the adopter's shared-mode journal must keep every whole
    record (the newline framing glues the torn tail onto a blank) and
    redo only the unrecorded stage."""
    stages = [_mk_stage("dev1"), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 1)
    pa = _plane(tmp_path, "hA", settle_s=0.0)
    pa.register()
    t_a = pa.claim("o0")
    m = ObsManifest(obs[0].manifest, "fp-torn", token=t_a,
                    fence=lambda: pa.fence("o0", t_a))
    art = str(tmp_path / "o0.dev1.out")
    with open(art, "w") as f:
        f.write("dev1o0")
    m.mark_done("dev1", [art])
    m.close()
    # the kill: a torn half-record at the tail, no trailing newline
    with open(obs[0].manifest, "a") as f:
        f.write('{"type": "done", "unit": "stage:host1", "outp')
    pa._stop.set()
    pa._renew.join()
    time.sleep(1.2)
    pb = _plane(tmp_path, "hB", settle_s=0.0)
    pb.register()
    t_b = pb.claim("o0")
    m2 = ObsManifest(obs[0].manifest, "fp-torn", token=t_b,
                     fence=lambda: pb.fence("o0", t_b))
    assert m2.done_stages() == {"dev1"}, "whole record lost to the tear"
    art2 = str(tmp_path / "o0.host1.out")
    with open(art2, "w") as f:
        f.write("host1o0")
    m2.mark_done("host1", [art2])  # appends cleanly past the tear
    assert m2.done_stages() == {"dev1", "host1"}
    m2.close()
    # a fresh read (the resume path) agrees
    m3 = ObsManifest(obs[0].manifest, "fp-torn")
    assert m3.done_stages() == {"dev1", "host1"}
    m3.close()
    pb.close()


def test_reconfigured_plane_rerun_reopens_terminal_claims(tmp_path):
    """A terminal claim left by a PREVIOUS configuration's fleet must
    not short-circuit a reconfigured rerun: the claim is re-opened when
    the manifest fingerprint no longer matches, and the observation is
    re-run — the plane-mode form of the restart-on-fingerprint-mismatch
    contract. A SAME-config rerun still runs nothing."""
    stages = [_mk_stage("dev1"), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 1)
    r1 = FleetScheduler(obs, SurveyConfig(numdms=8), stages=stages,
                        plane=_plane(tmp_path, "hA")).run()
    assert r1.ok and len(r1.ran) == 2
    # same config: the done claim + matching manifest short-circuit
    r2 = FleetScheduler(obs, SurveyConfig(numdms=8), stages=stages,
                        plane=_plane(tmp_path, "hB")).run()
    assert r2.ok and r2.ran == [] and r2.remote_done == ["o0"]
    # changed config: terminal claim re-opened, everything re-runs
    r3 = FleetScheduler(obs, SurveyConfig(numdms=16), stages=stages,
                        plane=_plane(tmp_path, "hC")).run()
    assert r3.ok and len(r3.ran) == 2 and r3.remote_done == []


def test_plane_resume_revalidates_done_claims(tmp_path):
    """An explicit --resume in plane mode re-validates a done claim's
    artifacts: a corrupted artifact re-opens the claim and redoes
    exactly the non-validating stage (the single-host resume
    contract, kept across hosts)."""
    stages = [_mk_stage("dev1"), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 1)
    cfg = SurveyConfig()
    assert FleetScheduler(obs, cfg, stages=stages,
                          plane=_plane(tmp_path, "hA")).run().ok
    with open(str(tmp_path / "o0.host1.out"), "w") as f:
        f.write("corrupted past the recorded sha256")
    # without --resume the done claim is trusted (cheap path)
    r = FleetScheduler(obs, cfg, stages=stages,
                       plane=_plane(tmp_path, "hB")).run()
    assert r.ran == []
    # with --resume the validation failure re-opens and redoes it
    r = FleetScheduler(obs, cfg, stages=stages, resume=True,
                       plane=_plane(tmp_path, "hC")).run()
    assert r.ok and ("o0", "host1") in r.ran
    assert ("o0", "dev1") in r.skipped  # the intact stage still skips


def test_claim_write_cannot_regress_a_higher_token(tmp_path):
    """The claim file's token may only go up: a slower claimant whose
    allocated token is LOWER than what the file now holds loses at the
    pre-write re-read instead of regressing the winner's claim."""
    dead = _plane(tmp_path, "dead", settle_s=0.0)
    dead.register()
    assert dead.claim("o0") is not None
    dead._stop.set()
    dead._renew.join()
    time.sleep(1.2)
    pa = _plane(tmp_path, "hA", settle_s=0.0)
    pa.register()
    pb = _plane(tmp_path, "hB", settle_s=0.0)
    pb.register()
    t_low = pa.next_token()   # hA allocates FIRST (lower token)...
    t_b = pb.claim("o0")      # ...but hB claims first with a higher one
    assert t_b is not None and t_b > t_low
    # simulate hA's delayed write exactly: it read the orphan before
    # hB's claim landed (hosts() says the holder is gone) and its
    # allocator already returned t_low — the pre-write re-read must
    # refuse to regress the file below t_b
    pa.hosts = lambda: {}
    pa.next_token = lambda: t_low
    assert pa.claim("o0") is None
    assert pb.read_claim("o0").get("token") == t_b
    pb.fence("o0", t_b)  # the winner's fence still passes
    pa.close()
    pb.close()


def test_host_health_strikes_bar_claims(tmp_path):
    """HostHealth: adoption/cede strikes accumulate per host id and bar
    it from new claims past the limit."""
    hh = HostHealth(limit=2)
    assert not hh.strike("flappy", kind="adopted")
    assert not hh.is_quarantined("flappy")
    assert hh.strike("flappy", kind="ceded")  # newly quarantined
    assert hh.is_quarantined("flappy")
    snap = hh.snapshot()
    assert snap["flappy"]["strikes"] == 2
    assert snap["flappy"]["quarantined"] is True


# ---------------------------------------------------------------------------
# status + tlmsum views
# ---------------------------------------------------------------------------


def test_status_renders_host_liveness_and_owner_column(tmp_path):
    """--status with a plane: per-obs owner column, adoption
    annotation, and the LIVE/DEAD/LEFT host block."""
    stages = [_mk_stage("dev1"), _mk_stage("host1", ("dev1",))]
    obs = _mk_obs(tmp_path, 2)
    pa = _plane(tmp_path, "hA")
    assert FleetScheduler(obs, SurveyConfig(), stages=stages,
                          plane=pa).run().ok
    plane_view = read_plane_status(str(tmp_path))
    assert plane_view is not None
    assert plane_view["hosts"]["hA"]["left"] is True
    text = format_status(status_rows([o.manifest for o in obs]),
                         plane=plane_view)
    assert "host" in text.splitlines()[0]
    assert "hA" in text and "LEFT" in text
    assert "complete" in text
    # an adopted claim annotates its row
    plane_view["claims"]["o0"]["adopted_from"] = "ghost"
    text = format_status(status_rows([o.manifest for o in obs]),
                         plane=plane_view)
    assert "adopted from ghost" in text


def test_tlmsum_per_host_rollup_renders(tmp_path, capsys):
    """Host-stamped stage spans and adoption/cede events land in the
    per-host section of the summary (and combine across traces)."""
    from pypulsar_tpu.obs.summarize import (
        combine_summaries,
        render,
        summarize,
    )

    recs_a = [
        {"type": "meta", "tool": "survey"},
        {"type": "span", "name": "survey.stage.sweep", "t": 0.0,
         "dur": 2.0, "attrs": {"obs": "o0", "host": "hA"}},
        {"type": "event", "name": "survey.obs_ceded", "t": 2.0,
         "attrs": {"host": "hA", "obs": "o1"}},
        {"type": "end", "wall": 3.0},
    ]
    recs_b = [
        {"type": "meta", "tool": "survey"},
        {"type": "span", "name": "survey.stage.fold", "t": 0.0,
         "dur": 1.0, "attrs": {"obs": "o1", "host": "hB"}},
        {"type": "event", "name": "survey.obs_adopted", "t": 1.0,
         "attrs": {"host": "hB", "obs": "o1", "adopted_from": "hA"}},
        {"type": "counters", "counters": {"survey.adoptions": 1}},
        {"type": "end", "wall": 3.0},
    ]
    # the per-OBS trace echoes the same stage span and a hostless
    # adoption event for forensics: summarizing it alongside the fleet
    # traces must not double-count busy seconds, obs_lost, or the
    # health-line adoption total
    recs_obs_echo = [
        {"type": "meta", "tool": "survey-obs", "obs": "o1"},
        {"type": "span", "name": "survey.stage.fold", "t": 0.0,
         "dur": 1.0, "attrs": {"host": "hB", "outputs": 1}},
        {"type": "event", "name": "survey.obs_adopted", "t": 0.5,
         "attrs": {"adopted_from": "hA", "token": 7}},
        {"type": "end", "wall": 1.5},
    ]
    sa, sb = summarize(recs_a), summarize(recs_b)
    so = summarize(recs_obs_echo)
    assert sa.host_busy == {"hA": [2.0, 1]}
    assert so.host_busy == {} and so.host_events == {}
    combined = combine_summaries([sa, sb, so])
    assert set(combined.host_busy) == {"hA", "hB"}
    assert combined.host_busy["hB"] == [1.0, 1]  # echo not double-booked
    assert combined.host_events["hB"]["obs_adopted"] == 1
    assert combined.host_events["hA"]["obs_lost"] == 1
    assert combined.host_events["hA"]["obs_ceded"] == 1
    render(combined, sys.stdout)
    out = capsys.readouterr().out
    assert "# per-host:" in out
    assert "hA" in out and "obs_ceded=1" in out and "obs_lost=1" in out
    assert "obs adoptions=1" in out  # the fleet-health line (counter)


# ---------------------------------------------------------------------------
# M-process CLI integration (spawn-gated)
# ---------------------------------------------------------------------------

_CLI_FLAGS = ["--lodm", "0", "--dmstep", "10", "--numdms", "4",
              "-s", "8", "--group-size", "2", "--threshold", "8",
              "--mask-time", "1.0", "--accel-zmax", "20",
              "--accel-numharm", "2", "--accel-sigma", "3",
              "--accel-batch", "4", "--sift-sigma", "5",
              "--sift-min-hits", "2", "--fold-nbins", "32",
              "--fold-npart", "8"]


def _cli_cfg():
    return SurveyConfig(
        mask=True, mask_time=1.0, lodm=0.0, dmstep=10.0, numdms=4,
        nsub=8, group_size=2, threshold=8.0, accel_zmax=20.0,
        accel_numharm=2, accel_sigma=3.0, accel_batch=4, sift_sigma=5.0,
        sift_min_hits=2, fold_nbins=32, fold_npart=8)


def _spawn_cli_host(rank, count, fils, outdir, tlmdir, lease_s,
                    extra_env=None):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = (repo + os.pathsep
                         + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYPULSAR_TPU_HOST_LEASE_S"] = str(lease_s)
    env["PYPULSAR_TPU_NUM_PROCESSES"] = str(count)
    env["PYPULSAR_TPU_PROCESS_ID"] = str(rank)
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "pypulsar_tpu.cli", "survey", *fils,
         "-o", outdir, *_CLI_FLAGS, "--host-id", f"host{rank}",
         "--telemetry-dir", tlmdir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


@pytest.fixture(scope="module")
def cli_fils(tmp_path_factory):
    from tests.test_accel_pipeline import _pulsar_fil

    root = tmp_path_factory.mktemp("mh_cli")
    return [_pulsar_fil(root, name=f"mh{i}.fil", seed=9 + i, C=16,
                        T=4096) for i in range(2)]


def test_sigkill_host_mid_stage_adoption_cli(cli_fils, tmp_path):
    """THE integration contract: a 2-process CLI fleet, host0 parked
    mid-sweep by an armed hang and SIGKILL'd (lease goes silent — no
    cleanup of any kind); host1 adopts the orphan, the fleet completes,
    and a final in-process resume re-runs zero stages."""
    _require_spawn()
    outdir = str(tmp_path / "out")
    tlmdir = str(tmp_path / "tlm")
    lease_s = 2.0
    victim = _spawn_cli_host(0, 2, cli_fils, outdir, tlmdir, lease_s,
                             extra_env={
                                 "PYPULSAR_TPU_FAULTS":
                                     "hang:sweep.chunk_dispatch:1",
                                 "PYPULSAR_TPU_HANG_S": "600"})
    survivor = _spawn_cli_host(1, 2, cli_fils, outdir, tlmdir, lease_s)
    vtrace = os.path.join(tlmdir, "fleet.host0.jsonl")
    deadline = time.monotonic() + 240
    parked = False
    while time.monotonic() < deadline and victim.poll() is None:
        try:
            parked = "resilience.fault_injected" in open(vtrace).read()
        except OSError:
            parked = False
        if parked:
            break
        time.sleep(0.25)
    assert parked, "victim never reached the armed mid-sweep hang"
    os.kill(victim.pid, signal.SIGKILL)
    assert victim.wait(timeout=60) == -signal.SIGKILL
    out, _ = survivor.communicate(timeout=600)
    assert survivor.returncode == 0, out[-3000:]
    assert "ADOPTED" in out
    # every observation's chain completed (sifted list + SNR summary)
    for i in range(2):
        assert os.path.exists(os.path.join(outdir, f"mh{i}.accelcands"))
        assert os.path.exists(os.path.join(outdir, f"mh{i}_snr.json"))
    adoptions = []
    for p in glob.glob(os.path.join(tlmdir, "*.jsonl")):
        for line in open(p):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "event" \
                    and rec.get("name") == "survey.obs_adopted":
                adoptions.append(rec.get("attrs", {}))
    assert any(a.get("adopted_from") == "host0" for a in adoptions)
    # final no-fault resume (plain single-host) validates everything
    obs = [Observation(f"mh{i}", cli_fils[i],
                       os.path.join(outdir, f"mh{i}")) for i in range(2)]
    final = FleetScheduler(obs, _cli_cfg(), resume=True).run()
    assert final.ok and final.ran == [], final.ran
    # --status over the shared dir shows the DEAD host and the owners
    from pypulsar_tpu.cli import survey as cli_survey

    assert cli_survey.main(["--status", "-o", outdir]) == 0


@pytest.mark.slow
def test_sigkill_every_stage_boundary_cli(cli_fils, tmp_path):
    """SIGKILL-equivalent (exit:137, no cleanup) at EVERY stage-done
    boundary of a 2-process fleet: the survivor adopts and completes
    each time, and the resumed artifacts validate (final resume runs
    nothing). Slow-marked: five full subprocess fleets."""
    _require_spawn()
    for ki, stage in enumerate(("mask", "sweep", "sift", "fold", "snr")):
        outdir = str(tmp_path / f"out{ki}")
        tlmdir = str(tmp_path / f"tlm{ki}")
        victim = _spawn_cli_host(
            0, 2, cli_fils, outdir, tlmdir, 2.0,
            extra_env={"PYPULSAR_TPU_FAULTS":
                       f"exit:survey.stage_done.{stage}:1"})
        survivor = _spawn_cli_host(1, 2, cli_fils, outdir, tlmdir, 2.0)
        vcode = victim.wait(timeout=600)
        victim.stdout.close()
        out, _ = survivor.communicate(timeout=600)
        assert vcode == 137, f"{stage}: victim exit {vcode}"
        assert survivor.returncode == 0, f"{stage}: {out[-3000:]}"
        obs = [Observation(f"mh{i}", cli_fils[i],
                           os.path.join(outdir, f"mh{i}"))
               for i in range(2)]
        final = FleetScheduler(obs, _cli_cfg(), resume=True).run()
        assert final.ok and final.ran == [], (stage, final.ran)
