"""psrlint: fixture pair (true positive + near-miss true negative) per
rule, the suppression/select/ignore machinery, and the repo-wide smoke
gate (`psrlint --json` exits 0 on HEAD — the same invariant `make lint`
enforces).

Fixtures are written into a tmp project tree so per-rule path scopes
(PL002 outside mesh.py, PL006 inside io/, PL009 in the resilience
modules) are exercised exactly as the real gate sees them.
"""

import json
import os

import pytest

from pypulsar_tpu.analysis import all_rules, run_psrlint
from pypulsar_tpu.analysis.engine import run as engine_run

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(tmp_path, files, readme=None, **kw):
    """Write {relpath: source} under tmp_path and lint the tree."""
    for rel, src in files.items():
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(src)
    if readme is not None:
        (tmp_path / "README.md").write_text(readme)
    paths = sorted({rel.split("/")[0] if "/" in rel else rel
                    for rel in files})
    return engine_run(all_rules(), paths, str(tmp_path),
                      readme_path=str(tmp_path / "README.md")
                      if readme is not None else None, **kw)


def codes(report):
    return [f.rule for f in report.findings]


# ---------------------------------------------------------------------------
# PL001 py2 truediv in index/size context

def test_pl001_true_positive(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/a.py":
                          "def f(a, n):\n"
                          "    x = a[n / 2]\n"
                          "    for i in range(n / 4):\n"
                          "        x += i\n"
                          "    return x\n"}, select="PL001")
    assert codes(rep) == ["PL001", "PL001"]
    assert {f.line for f in rep.findings} == {2, 3}


def test_pl001_near_miss(tmp_path):
    # floor division, an explicit int() coercion, and a float-context
    # division must all stay silent
    rep = lint(tmp_path, {"pypulsar_tpu/a.py":
                          "def f(a, n):\n"
                          "    x = a[n // 2] + a[int(n / 2)]\n"
                          "    mean = x / n\n"
                          "    return x[: n // 4], mean\n"}, select="PL001")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL002 bare jax.devices()

def test_pl002_true_positive(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/work.py":
                          "import jax\n"
                          "def chips():\n"
                          "    return jax.devices()\n"}, select="PL002")
    assert codes(rep) == ["PL002"]


def test_pl002_near_miss(tmp_path):
    # the registry's own module is exempt; call sites that resolve
    # through the lease helper are the sanctioned shape; tests are out
    # of scope (capability asserts)
    rep = lint(tmp_path, {
        "pypulsar_tpu/parallel/mesh.py":
            "import jax\n"
            "def lease_devices():\n"
            "    return jax.devices()\n",
        "pypulsar_tpu/work.py":
            "from pypulsar_tpu.parallel.mesh import lease_devices\n"
            "def chips():\n"
            "    return lease_devices()\n",
        "tests/test_caps.py":
            "import jax\n"
            "def test_n():\n"
            "    assert len(jax.devices()) == 8\n",
    }, select="PL002")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL003 non-atomic artifact write

def test_pl003_true_positive(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/writer.py":
                          "def save(outname, rows):\n"
                          "    with open(outname + '.cands', 'w') as f:\n"
                          "        f.write(str(rows))\n"}, select="PL003")
    assert codes(rep) == ["PL003"]


def test_pl003_near_miss(tmp_path):
    # tmp+os.replace idiom, a read-mode open, and a non-artifact path
    # all stay silent
    rep = lint(tmp_path, {"pypulsar_tpu/writer.py":
                          "import os\n"
                          "def save(outname, rows):\n"
                          "    with open(outname + '.cands.tmp', 'w') as f:\n"
                          "        f.write(str(rows))\n"
                          "    os.replace(outname + '.cands.tmp',\n"
                          "               outname + '.cands')\n"
                          "def load(outname):\n"
                          "    with open(outname + '.cands') as f:\n"
                          "        return f.read()\n"
                          "def note(logdir):\n"
                          "    open(logdir + '/notes.txt', 'w').close()\n"},
               select="PL003")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL004 knob registry drift

_README = ("# x\n\n## Runtime knobs\n\n"
           "| env var | default | what |\n|---|---|---|\n"
           "| `PYPULSAR_TPU_DOCUMENTED` | 1 | a knob |\n"
           "\n## Next section\n")


def test_pl004_code_without_table_row(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "import os\n"
                          "A = os.environ.get('PYPULSAR_TPU_DOCUMENTED')\n"
                          "B = os.environ.get('PYPULSAR_TPU_SECRET')\n"},
               readme=_README, select="PL004")
    assert codes(rep) == ["PL004"]
    assert "PYPULSAR_TPU_SECRET" in rep.findings[0].message
    assert rep.findings[0].path == "pypulsar_tpu/mod.py"


def test_pl004_stale_table_row_and_helper_reads(tmp_path):
    # the env_float helper and ENV_* constant-binding idioms both count
    # as in-code registration; a row nothing reads is the finding
    readme = _README.replace(
        "\n## Next section\n",
        "| `PYPULSAR_TPU_VIA_HELPER` | 2 | helper knob |\n"
        "| `PYPULSAR_TPU_VIA_CONST` | 3 | const knob |\n"
        "| `PYPULSAR_TPU_GONE` | 4 | removed knob |\n"
        "\n## Next section\n")
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "import os\n"
                          "from pypulsar_tpu.resilience.health import env_float\n"
                          "A = os.environ.get('PYPULSAR_TPU_DOCUMENTED')\n"
                          "B = env_float('PYPULSAR_TPU_VIA_HELPER', 2.0)\n"
                          "ENV_C = 'PYPULSAR_TPU_VIA_CONST'\n"},
               readme=readme, select="PL004")
    assert codes(rep) == ["PL004"]
    assert "PYPULSAR_TPU_GONE" in rep.findings[0].message
    assert rep.findings[0].path == "README.md"


# ---------------------------------------------------------------------------
# PL005 dead fault point

def test_pl005_true_positive(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/prod.py":
            "from pypulsar_tpu.resilience import faultinject\n"
            "def work():\n"
            "    faultinject.trip('real.point')\n",
        "tests/test_faults.py":
            "from pypulsar_tpu.resilience import faultinject\n"
            "def test_ghost():\n"
            "    faultinject.configure('oom:ghost.point:1')\n",
    }, select="PL005")
    assert codes(rep) == ["PL005"]
    assert "ghost.point" in rep.findings[0].message
    assert rep.findings[0].path == "tests/test_faults.py"


def test_pl005_near_miss(tmp_path):
    # covered shapes: an exact production literal, a dynamic-prefix
    # f-string (stage points), and a machinery self-test tripping its
    # own ad-hoc point
    rep = lint(tmp_path, {
        "pypulsar_tpu/prod.py":
            "from pypulsar_tpu.resilience import faultinject\n"
            "def work(stage):\n"
            "    faultinject.trip('real.point')\n"
            "    faultinject.trip(f'survey.stage_start.{stage}')\n",
        "tests/test_faults.py":
            "from pypulsar_tpu.resilience import faultinject\n"
            "def test_real():\n"
            "    faultinject.configure(\n"
            "        'oom:real.point:1, io:survey.stage_start.sweep')\n"
            "def test_selfmade():\n"
            "    faultinject.configure('io:mine:1')\n"
            "    faultinject.trip('mine')\n",
    }, select="PL005")
    assert codes(rep) == []


def test_pl005_tuple_point_registry_defines(tmp_path):
    # round 24: FAULT_POINTS = ("a", "b") tuple/list registries in
    # production count as definitions (the broker publishes its points
    # that way) — but a point absent from the tuple is still dead
    rep = lint(tmp_path, {
        "pypulsar_tpu/prod.py":
            "from pypulsar_tpu.resilience import faultinject\n"
            "FAULT_POINTS = ('broker.submit', 'broker.dispatch')\n"
            "def work():\n"
            "    for p in FAULT_POINTS:\n"
            "        faultinject.trip(p)\n",
        "tests/test_faults.py":
            "from pypulsar_tpu.resilience import faultinject\n"
            "def test_real():\n"
            "    faultinject.configure(\n"
            "        'io:broker.submit:1, kill:broker.dispatch:1')\n"
            "def test_ghost():\n"
            "    faultinject.configure('io:broker.ghost:1')\n",
    }, select="PL005")
    assert codes(rep) == ["PL005"]
    assert "broker.ghost" in rep.findings[0].message


# ---------------------------------------------------------------------------
# PL006 raw header read in io/

def test_pl006_true_positive(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/io/fmt.py":
                          "import struct\n"
                          "def header(f):\n"
                          "    (n,) = struct.unpack('<i', f.read(4))\n"
                          "    return f.read(n).decode('ascii')\n"},
               select="PL006")
    assert codes(rep) == ["PL006", "PL006"]


def test_pl006_near_miss(tmp_path):
    # read_exact-mediated reads are the sanctioned shape, and the rule
    # only patrols io/ (a tool doing raw reads of its own scratch files
    # is out of scope)
    rep = lint(tmp_path, {
        "pypulsar_tpu/io/fmt.py":
            "import struct\n"
            "from pypulsar_tpu.io.errors import read_exact\n"
            "def header(f, path):\n"
            "    (n,) = struct.unpack('<i', read_exact(f, 4, path, 'len'))\n"
            "    return read_exact(f, n, path, 'name').decode('ascii')\n",
        "pypulsar_tpu/utils/scratch.py":
            "import struct\n"
            "def peek(f):\n"
            "    return struct.unpack('<i', f.read(4))\n",
    }, select="PL006")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL007 mutable default

def test_pl007_true_positive(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "def f(x, acc=[], opts={}):\n"
                          "    return x, acc, opts\n"}, select="PL007")
    assert codes(rep) == ["PL007", "PL007"]


def test_pl007_near_miss(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "def f(x, acc=None, opts=(), name=''):\n"
                          "    acc = [] if acc is None else acc\n"
                          "    return x, acc, opts, name\n"}, select="PL007")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL008 span leak

def test_pl008_true_positive(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "from pypulsar_tpu.obs import telemetry\n"
                          "def work():\n"
                          "    telemetry.span('stage')\n"
                          "    return 1\n"}, select="PL008")
    assert codes(rep) == ["PL008"]


def test_pl008_near_miss(tmp_path):
    # with-block, ExitStack.enter_context, and returning the manager to
    # the caller are the sanctioned shapes; an ObsTrace-style record
    # call on another object is a different API
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "import contextlib\n"
                          "from pypulsar_tpu.obs import telemetry\n"
                          "def work(trace):\n"
                          "    with telemetry.span('stage'):\n"
                          "        pass\n"
                          "    with contextlib.ExitStack() as es:\n"
                          "        es.enter_context(telemetry.span('s2'))\n"
                          "    trace.span('done', 0.0, 1.0)\n"
                          "def shim(name):\n"
                          "    return telemetry.span(name)\n"}, select="PL008")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL009 swallowed fault

def test_pl009_true_positive(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/parallel/stage.py":
                          "def run(fn):\n"
                          "    try:\n"
                          "        return fn()\n"
                          "    except Exception:\n"
                          "        return None\n"}, select="PL009")
    assert codes(rep) == ["PL009"]


def test_pl009_hyphenated_word_is_not_a_reason(tmp_path):
    # "# best-effort" has a hyphen but no space-delimited dash marker:
    # it must NOT count as a reasoned comment
    rep = lint(tmp_path, {"pypulsar_tpu/survey/util.py":
                          "def run(fn):\n"
                          "    try:\n"
                          "        return fn()\n"
                          "    except Exception:  # best-effort\n"
                          "        return None\n"}, select="PL009")
    assert codes(rep) == ["PL009"]


def test_pl009_near_miss(tmp_path):
    # a no_degrade gate, a reasoned trailing comment, and propagating
    # the exception as a value are all compliant; modules outside the
    # resilience-adjacent set are out of scope
    rep = lint(tmp_path, {
        "pypulsar_tpu/parallel/stage.py":
            "from pypulsar_tpu.resilience import health\n"
            "def run(fn):\n"
            "    try:\n"
            "        return fn()\n"
            "    except Exception as e:\n"
            "        if health.no_degrade(e):\n"
            "            raise\n"
            "        return None\n"
            "def probe(fn):\n"
            "    try:\n"
            "        return fn()\n"
            "    except Exception:  # noqa: BLE001 - probe is best-effort\n"
            "        return None\n"
            "def ferry(fn):\n"
            "    try:\n"
            "        return fn(), None\n"
            "    except Exception as e:\n"
            "        return None, e\n",
        "pypulsar_tpu/astro/coords.py":
            "def parse(s):\n"
            "    try:\n"
            "        return float(s)\n"
            "    except Exception:\n"
            "        return None\n",
    }, select="PL009")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL011 raw knob read outside the registry

def test_pl011_true_positive(tmp_path):
    # a const-string read, the ENV_* constant-indirection idiom, and a
    # subscript read are all raw-read shapes (round 17: tune/knobs.py
    # is the single read path)
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "import os\n"
                          "ENV_DEPTH = 'PYPULSAR_TPU_DEPTH'\n"
                          "a = os.environ.get('PYPULSAR_TPU_CHUNK')\n"
                          "b = os.environ.get(ENV_DEPTH, '4')\n"
                          "c = os.environ['PYPULSAR_TPU_MODE']\n"},
               select="PL011")
    assert codes(rep) == ["PL011", "PL011", "PL011"]
    assert {f.line for f in rep.findings} == {3, 4, 5}


def test_pl011_near_miss(tmp_path):
    # the registry module itself is exempt; registry accessors are the
    # sanctioned shape; non-knob env vars are out of scope; env WRITES
    # (bench arming subprocess knobs) are not reads; tests may poke env
    # directly (precedence tests need to)
    rep = lint(tmp_path, {
        "pypulsar_tpu/tune/knobs.py":
            "import os\n"
            "def env_raw(name):\n"
            "    return os.environ.get('PYPULSAR_TPU_ANY')\n",
        "pypulsar_tpu/mod.py":
            "import os\n"
            "from pypulsar_tpu.tune import knobs\n"
            "a = knobs.env_int('PYPULSAR_TPU_CHUNK')\n"
            "b = os.environ.get('JAX_PLATFORMS')\n"
            "def arm():\n"
            "    os.environ['PYPULSAR_TPU_FAULTS'] = 'oom:x'\n",
        "tests/test_knobs.py":
            "import os\n"
            "def test_env_wins():\n"
            "    os.environ['PYPULSAR_TPU_CHUNK'] = '5'\n"
            "    assert os.environ.get('PYPULSAR_TPU_CHUNK') == '5'\n",
    }, select="PL011")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL012 lock-order inversion (psrrace static, round 19)

def test_pl012_cross_file_cycle(tmp_path):
    # the AB/BA deadlock split across two files: the acquisition graph
    # is project-wide (class-qualified keys merge), so each half looks
    # innocent alone and the CYCLE is the finding
    rep = lint(tmp_path, {
        "pypulsar_tpu/a.py":
            "def one(sched, health):\n"
            "    with sched._lock:\n"
            "        with health._lock:\n"
            "            pass\n",
        "pypulsar_tpu/b.py":
            "def two(sched, health):\n"
            "    with health._lock:\n"
            "        with sched._lock:\n"
            "            pass\n",
    }, select="PL012")
    # non-self receivers key by their chain verbatim, so conventionally
    # named receivers merge across files and the CYCLE is the finding
    assert codes(rep) == ["PL012"]
    assert "cycle" in rep.findings[0].message


def test_pl012_self_deadlock_and_consistent_order(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "a_lock = threading.Lock()\n"
            "b_lock = threading.Lock()\n"
            "def nested_same():\n"
            "    with a_lock:\n"
            "        with a_lock:\n"
            "            pass\n",
    }, select="PL012")
    assert codes(rep) == ["PL012"]
    assert "non-reentrant" in rep.findings[0].message


def test_pl012_near_miss(tmp_path):
    # a consistent order everywhere, a reentrant rlock re-with, and
    # non-lock context managers are all silent
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "a_lock = threading.Lock()\n"
            "b_lock = threading.Lock()\n"
            "an_rlock = threading.RLock()\n"
            "def one():\n"
            "    with a_lock:\n"
            "        with b_lock:\n"
            "            pass\n"
            "def two():\n"
            "    with a_lock:\n"
            "        with b_lock:\n"
            "            pass\n"
            "def re():\n"
            "    with an_rlock:\n"
            "        with an_rlock:\n"
            "            pass\n"
            "def files(path):\n"
            "    with open(path) as f:\n"
            "        with open(path + '2') as g:\n"
            "            return f, g\n",
    }, select="PL012")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL013 blocking call while holding a lock

def test_pl013_true_positive(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import time, threading, subprocess\n"
            "a_lock = threading.Lock()\n"
            "def slow(t, fut):\n"
            "    with a_lock:\n"
            "        time.sleep(1)\n"
            "        open('x.txt').read()\n"
            "        subprocess.run(['true'])\n"
            "        fut.result()\n"
            "        t.join(timeout=5)\n",
    }, select="PL013")
    assert len(codes(rep)) == 5
    assert all(c == "PL013" for c in codes(rep))


def test_pl013_near_miss(tmp_path):
    # blocking work OUTSIDE the critical section, a cv.wait (releases
    # the lock by contract), str.join, and a closure defined (not run)
    # under the lock are all silent
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import time, threading\n"
            "a_lock = threading.Lock()\n"
            "a_cv = threading.Condition(a_lock)\n"
            "def ok(parts):\n"
            "    with a_lock:\n"
            "        n = len(parts)\n"
            "        name = ','.join(parts)\n"
            "    time.sleep(0.1)\n"
            "    with a_cv:\n"
            "        while n:\n"
            "            a_cv.wait(0.1)\n"
            "            n -= 1\n"
            "    with a_lock:\n"
            "        def later():\n"
            "            time.sleep(1)\n"
            "        return later, name\n",
    }, select="PL013")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL014 bare acquire

def test_pl014_true_positive(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "a_lock = threading.Lock()\n"
            "def leak():\n"
            "    a_lock.acquire()\n"
            "    work = 1\n"
            "    a_lock.release()\n"
            "    return work\n",
    }, select="PL014")
    assert codes(rep) == ["PL014"]


def test_pl014_near_miss(tmp_path):
    # acquire-then-try/finally (both shapes: next-sibling and inside
    # the try), the with statement, and non-lock .acquire() names
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "a_lock = threading.Lock()\n"
            "def sibling():\n"
            "    a_lock.acquire()\n"
            "    try:\n"
            "        return 1\n"
            "    finally:\n"
            "        a_lock.release()\n"
            "def inside():\n"
            "    try:\n"
            "        a_lock.acquire()\n"
            "        return 1\n"
            "    finally:\n"
            "        a_lock.release()\n"
            "def managed():\n"
            "    with a_lock:\n"
            "        return 1\n"
            "def other(backend):\n"
            "    backend.acquire()\n",
    }, select="PL014")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL015 condition wait outside a predicate loop

def test_pl015_true_positive(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "cv = threading.Condition()\n"
            "def bad(ready):\n"
            "    with cv:\n"
            "        if not ready():\n"
            "            cv.wait()\n",
    }, select="PL015")
    assert codes(rep) == ["PL015"]


def test_pl015_near_miss(tmp_path):
    # while-loop waits (incl. while True) and wait_for are the
    # sanctioned shapes; Event/processes named un-cv-ishly are out of
    # scope (an Event.wait has no predicate contract to violate)
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "cv = threading.Condition()\n"
            "stop = threading.Event()\n"
            "def good(ready):\n"
            "    with cv:\n"
            "        while not ready():\n"
            "            cv.wait(0.1)\n"
            "def forever():\n"
            "    with cv:\n"
            "        while True:\n"
            "            cv.wait(0.1)\n"
            "def pred(ready):\n"
            "    with cv:\n"
            "        cv.wait_for(ready)\n"
            "def ev(proc):\n"
            "    stop.wait(1.0)\n"
            "    proc.wait()\n",
    }, select="PL015")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL016 thread daemon-or-join discipline

def test_pl016_true_positive(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "def orphan(fn):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n"
            "    return t\n",
    }, select="PL016")
    assert codes(rep) == ["PL016"]


def test_pl016_near_miss(tmp_path):
    # daemon kwarg, .daemon assignment (Timer idiom), and a join in the
    # creating function are all declared lifetimes; sep.join(parts)
    # must not count as a thread join
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "def daemonized(fn):\n"
            "    t = threading.Thread(target=fn, daemon=True)\n"
            "    t.start()\n"
            "def timered(fn):\n"
            "    t = threading.Timer(0.5, fn)\n"
            "    t.daemon = True\n"
            "    t.start()\n"
            "def joined(fn, parts):\n"
            "    name = ','.join(parts)\n"
            "    t = threading.Thread(target=fn, name=name)\n"
            "    t.start()\n"
            "    t.join(timeout=5)\n",
    }, select="PL016")
    assert codes(rep) == []


def test_pl016_str_join_does_not_count(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/mod.py":
            "import threading\n"
            "def sneaky(fn, parts):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n"
            "    return ','.join(parts)\n",
    }, select="PL016")
    assert codes(rep) == ["PL016"]


# ---------------------------------------------------------------------------
# PL017 telemetry name drift

def test_pl017_consumer_name_nothing_emits(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/prod.py":
            "from pypulsar_tpu.obs import telemetry\n"
            "def f():\n"
            "    telemetry.event('survey.slo_burn', frac=0.9)\n",
        "tests/test_x.py":
            "def test_x(tlm):\n"
            "    assert tlm.event_counts.get('survey.slo_burn')\n"
            "    assert tlm.event_counts.get('survey.slo_burm')\n",
    }, select="PL017")
    assert codes(rep) == ["PL017"]
    assert "survey.slo_burm" in rep.findings[0].message
    assert rep.findings[0].path == "tests/test_x.py"


def test_pl017_event_nobody_consumes(tmp_path):
    rep = lint(tmp_path, {
        "pypulsar_tpu/prod.py":
            "from pypulsar_tpu.obs import telemetry\n"
            "def f():\n"
            "    telemetry.event('survey.orphan_verdict', n=1)\n",
        "tests/test_x.py": "def test_x():\n    pass\n",
    }, select="PL017")
    assert codes(rep) == ["PL017"]
    assert "survey.orphan_verdict" in rep.findings[0].message
    assert rep.findings[0].path == "pypulsar_tpu/prod.py"


def test_pl017_near_misses(tmp_path):
    # matched emit/consume pairs, f-string prefixes, the assigned-name
    # emit shape, fault points, file names, and out-of-family names are
    # all clean in both directions
    rep = lint(tmp_path, {
        "pypulsar_tpu/prod.py":
            "from pypulsar_tpu.obs import telemetry\n"
            "from pypulsar_tpu.resilience import faultinject\n"
            "def f(stage, reason):\n"
            "    telemetry.event('survey.quarantine', stage=stage)\n"
            "    telemetry.counter('survey.stages_run')\n"
            "    with telemetry.span(f'survey.stage.{stage}'):\n"
            "        faultinject.trip(f'survey.stage_start.{stage}')\n"
            "    name = 'survey.deadline_exceeded'\n"
            "    telemetry.event(name, after=1.0)\n"
            "    telemetry.event('mesh.device_strike', dev=0)\n",
        "tests/test_x.py":
            "from pypulsar_tpu.resilience import faultinject\n"
            "def test_x(tlm, tmp_path):\n"
            "    assert tlm.event_counts.get('survey.quarantine')\n"
            "    assert tlm.event_counts.get('survey.deadline_exceeded')\n"
            "    assert tlm.stages.get('survey.stage.sweep')\n"
            "    faultinject.configure('kill:survey.stage_start.sweep:1')\n"
            "    assert faultinject.hits('survey.stage_start.sweep')\n"
            "    assert (tmp_path / 'tune.json').exists()\n",
    }, select="PL017")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# PL018 raw jax.jit outside the compilation plane

def test_pl018_true_positives(tmp_path):
    # decorator (bare and parameterized), direct call, and a partial
    # indirection are all raw-jit escapes from the compilation plane
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "import functools\n"
                          "import jax\n"
                          "@jax.jit\n"
                          "def f(x):\n"
                          "    return x\n"
                          "@jax.jit(static_argnames=('n',))\n"
                          "def g(x, n):\n"
                          "    return x\n"
                          "h = jax.jit(lambda x: x)\n"
                          "mk = functools.partial(jax.jit, donate_argnums=0)\n"},
               select="PL018")
    assert codes(rep) == ["PL018"] * 4
    assert {f.line for f in rep.findings} == {3, 6, 9, 10}


def test_pl018_near_misses(tmp_path):
    # the plane itself, the registered ops/ leaf kernels, tests, other
    # modules' .jit attributes, and prose mentions all stay silent
    rep = lint(tmp_path, {
        "pypulsar_tpu/compile/plane.py":
            "import jax\n"
            "def plane_jit(fn):\n"
            "    return jax.jit(fn)\n",
        "pypulsar_tpu/ops/kernels.py":
            "import jax\n"
            "@jax.jit\n"
            "def leaf(x):\n"
            "    return x\n",
        "pypulsar_tpu/mod.py":
            "from pypulsar_tpu.compile import plane_jit\n"
            "@plane_jit(stage='sweep')\n"
            "def f(x):\n"
            "    return x\n"
            "HELP = 'wraps jax.jit with an AOT registry'\n"
            "def g(nn, self_like):\n"
            "    return nn.jit, self_like.jit\n",
        "tests/test_mod.py":
            "import jax\n"
            "def test_f():\n"
            "    assert jax.jit(lambda x: x)(1) == 1\n",
    }, select="PL018")
    assert codes(rep) == []


# ---------------------------------------------------------------------------
# suppressions / select / ignore / baseline / output

def test_suppression_silences_and_unused_is_flagged(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "def f(acc=[]):  # psrlint: ignore[PL007] -- fixture\n"
                          "    return acc\n"
                          "def g():  # psrlint: ignore[PL007] -- stale\n"
                          "    return 1\n"})
    assert codes(rep) == ["PL010"]
    assert rep.findings[0].line == 3


def test_suppression_comma_list(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "def f(a, n, acc=[]):  # psrlint: ignore[PL007, PL001]\n"
                          "    return a[n / 2], acc\n"})
    # the PL001 is on line 2, not the suppressed line 1 — so that
    # half of the comma list is an unused suppression
    assert sorted(codes(rep)) == ["PL001", "PL010"]


def test_select_and_ignore(tmp_path):
    files = {"pypulsar_tpu/mod.py":
             "import jax\n"
             "def f(a, n, acc=[]):\n"
             "    return a[n / 2], acc, jax.devices()\n"}
    assert sorted(codes(lint(tmp_path, dict(files)))) == [
        "PL001", "PL002", "PL007"]
    assert sorted(codes(lint(tmp_path, dict(files),
                             select="PL001,PL007"))) == ["PL001", "PL007"]
    assert sorted(codes(lint(tmp_path, dict(files),
                             ignore="PL002"))) == ["PL001", "PL007"]


def test_pl004_message_string_is_not_a_registration(tmp_path):
    # a constant that merely MENTIONS a knob inside prose must not
    # register it (the row-less "knob" would be pure noise), and a
    # knob-valued constant outside the ENV_* convention must not mask
    # drift (a stale README row stays reported)
    readme = _README.replace(
        "\n## Next section\n",
        "| `PYPULSAR_TPU_GONE` | 4 | removed knob |\n\n## Next section\n")
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "import os\n"
                          "A = os.environ.get('PYPULSAR_TPU_DOCUMENTED')\n"
                          "HINT = 'PYPULSAR_TPU_FAULTS is unset'\n"
                          "OLD_NAME = 'PYPULSAR_TPU_GONE'\n"},
               readme=readme, select="PL004")
    assert codes(rep) == ["PL004"]
    assert "PYPULSAR_TPU_GONE" in rep.findings[0].message
    assert rep.findings[0].path == "README.md"


def test_cli_unwraps_nested_baseline(tmp_path):
    """The committed tools/lint_baseline.json nests the psrlint debt
    under a 'psrlint' key; the CLI must unwrap it before the engine."""
    from pypulsar_tpu.cli import psrlint as cli

    pkg = tmp_path / "pypulsar_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text("def f(acc=[]):\n    return acc\n")
    basefn = tmp_path / "base.json"
    basefn.write_text(json.dumps({
        "psrlint": {"PL007": [{"path": "pypulsar_tpu/mod.py", "line": 1}]},
        "ruff": []}))
    assert cli.main(["--root", str(tmp_path), "pypulsar_tpu",
                     "--select", "PL007"]) == 1
    assert cli.main(["--root", str(tmp_path), "pypulsar_tpu",
                     "--select", "PL007",
                     "--baseline", str(basefn)]) == 0


def test_baseline_drops_known_findings(tmp_path):
    files = {"pypulsar_tpu/mod.py": "def f(acc=[]):\n    return acc\n"}
    dirty = lint(tmp_path, dict(files), select="PL007")
    assert codes(dirty) == ["PL007"]
    base = {"PL007": [{"path": "pypulsar_tpu/mod.py", "line": 1}]}
    assert codes(lint(tmp_path, dict(files), select="PL007",
                      baseline=base)) == []


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/bad.py": "def f(:\n    pass\n"})
    assert codes(rep) == ["PL100"]
    # tokenize raises IndentationError (not TokenError) on a bad
    # dedent — the gate must still report, not traceback (bad.py from
    # above is still in the tree, so both parse failures show)
    rep = lint(tmp_path, {"pypulsar_tpu/dedent.py":
                          "def f():\n    x = 1\n   y = 2\n"})
    assert codes(rep) == ["PL100", "PL100"]
    assert {f.path for f in rep.findings} == {
        "pypulsar_tpu/bad.py", "pypulsar_tpu/dedent.py"}


def test_cli_missing_path_is_loud(tmp_path):
    """A typo'd path must exit 2, never 'clean: 0 file(s)' + exit 0."""
    from pypulsar_tpu.cli import psrlint as cli

    (tmp_path / "pypulsar_tpu").mkdir()
    assert cli.main(["--root", str(tmp_path), "no_such_file.py"]) == 2
    # an existing dir with no Python files is equally suspicious
    (tmp_path / "empty").mkdir()
    assert cli.main(["--root", str(tmp_path), "empty"]) == 2


def test_report_json_schema(tmp_path):
    rep = lint(tmp_path, {"pypulsar_tpu/mod.py":
                          "def f(acc=[]):\n    return acc\n"}, select="PL007")
    doc = json.loads(rep.to_json())
    assert doc["files"] == 1 and doc["counts"] == {"PL007": 1}
    (finding,) = doc["findings"]
    assert set(finding) == {"rule", "path", "line", "col", "message"}
    assert finding["rule"] == "PL007" and finding["line"] == 1


def test_rule_catalog_complete():
    got = {r.code for r in all_rules()}
    assert got == ({f"PL00{i}" for i in range(1, 10)}
                   | {f"PL01{i}" for i in range(1, 9)})
    assert all(r.summary and r.name for r in all_rules())


# ---------------------------------------------------------------------------
# the repo-wide gate

def test_repo_is_clean_smoke():
    """`psrlint --json` exits 0 on HEAD — the `make lint` invariant.
    Every suppression in the tree must also be in use (PL010 runs)."""
    from pypulsar_tpu.cli import psrlint as cli

    rc = cli.main(["--root", REPO_ROOT, "--json"])
    assert rc == 0


def test_single_file_scan_keeps_project_context():
    """Linting ONE file must not report the unscanned rest of the tree
    as knob drift / dead fault points: the CLI hands cross-file rules
    the whole default scope and clips their findings to the request."""
    from pypulsar_tpu.cli import psrlint as cli

    for target in ("pypulsar_tpu/io/sigproc.py", "tests/test_resilience.py"):
        assert cli.main(["--root", REPO_ROOT, target]) == 0


def test_repo_baseline_is_empty():
    """The checked-in third-party baseline carries zero violations —
    landing debt there needs a conscious diff, not a silent append."""
    with open(os.path.join(REPO_ROOT, "tools", "lint_baseline.json")) as f:
        base = json.load(f)
    assert all(not v for k, v in base.items()
               if not k.startswith("_")), base


def test_cli_registered():
    from pypulsar_tpu.cli.__main__ import TOOLS

    assert "psrlint" in TOOLS
