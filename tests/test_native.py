"""Native C++ codec: build, parity vs NumPy fallback, IO integration."""

import importlib
import os

import numpy as np
import pytest

from pypulsar_tpu import native


requires_native = pytest.mark.skipif(not native.available(),
                                     reason="native codec not built")


@pytest.fixture
def rng():
    return np.random.RandomState(0)


@requires_native
@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_unpack_bits_parity(rng, nbits, monkeypatch):
    raw = rng.randint(0, 256, size=4096).astype(np.uint8)
    got = native.unpack_bits(raw, nbits)
    # NumPy reference: shift out fields lowest-order-first
    per = 8 // nbits
    shifts = np.arange(per, dtype=np.uint8) * nbits
    expect = ((raw[:, None] >> shifts) & ((1 << nbits) - 1)
              ).reshape(-1).astype(np.float32)
    np.testing.assert_array_equal(got, expect)
    assert got.dtype == np.float32


@requires_native
def test_widen_parity(rng):
    for dtype in (np.uint8, np.uint16):
        raw = rng.randint(0, np.iinfo(dtype).max, size=1000).astype(dtype)
        np.testing.assert_array_equal(native.widen(raw),
                                      raw.astype(np.float32))


@requires_native
def test_scale_offset_weight_parity(rng):
    nspec, nchan = 64, 32
    data = rng.rand(nspec, nchan).astype(np.float32)
    scales = rng.rand(nchan).astype(np.float32) + 0.5
    offsets = rng.randn(nchan).astype(np.float32)
    weights = (rng.rand(nchan) > 0.2).astype(np.float32)
    expect = (data * scales + offsets) * weights
    got = native.scale_offset_weight(data.copy(), scales, offsets, weights)
    np.testing.assert_allclose(got, expect, rtol=1e-6)


@requires_native
def test_zero_dm_parity(rng):
    data = rng.rand(128, 16).astype(np.float32) * 100
    expect = data - data.mean(axis=1, keepdims=True)
    got = native.zero_dm(data.copy())
    np.testing.assert_allclose(got, expect, atol=2e-4)


@requires_native
def test_transpose_parity(rng):
    for dtype in (np.uint8, np.uint16, np.float32):
        if np.issubdtype(dtype, np.integer):
            raw = rng.randint(0, 200, size=50 * 7).astype(dtype)
        else:
            raw = rng.rand(50 * 7).astype(dtype)
        got = native.transpose_to_chan_major(raw, 50, 7)
        expect = raw.reshape(50, 7).astype(np.float32).T
        np.testing.assert_array_equal(got, expect)
        assert got.flags["C_CONTIGUOUS"]


@requires_native
def test_boxcar_peak_snr_parity(rng):
    series = rng.randn(4096).astype(np.float32)
    series[1000:1008] += 10.0
    widths = [1, 2, 4, 8, 16]
    got = native.boxcar_peak_snr(series, widths)
    csum = np.concatenate(([0.0], np.cumsum(series, dtype=np.float64)))
    for w, g in zip(widths, got):
        sums = csum[w:] - csum[:-w]
        assert g == pytest.approx(sums.max() / np.sqrt(w), rel=1e-5)
    # the matched width should have the highest SNR
    assert np.argmax(got) == widths.index(8)


def test_fallback_matches_native(rng, monkeypatch):
    """The NumPy fallback path produces identical results."""
    raw = rng.randint(0, 256, size=512).astype(np.uint8)
    data2d = rng.rand(32, 8).astype(np.float32)
    series = rng.randn(256).astype(np.float32)
    ref = {
        "unpack": native.unpack_bits(raw, 4),
        "sow": native.scale_offset_weight(
            data2d.copy(), np.ones(8), np.zeros(8), np.ones(8)),
        "transpose": native.transpose_to_chan_major(raw[:256], 32, 8),
        "boxcar": native.boxcar_peak_snr(series, [1, 4]),
    }
    monkeypatch.setenv("PYPULSAR_TPU_NO_NATIVE", "1")
    fallback = importlib.reload(native)
    try:
        assert not fallback.available()
        np.testing.assert_array_equal(fallback.unpack_bits(raw, 4),
                                      ref["unpack"])
        np.testing.assert_allclose(
            fallback.scale_offset_weight(data2d.copy(), np.ones(8),
                                         np.zeros(8), np.ones(8)),
            ref["sow"], rtol=1e-6)
        np.testing.assert_array_equal(
            fallback.transpose_to_chan_major(raw[:256], 32, 8),
            ref["transpose"])
        np.testing.assert_allclose(fallback.boxcar_peak_snr(series, [1, 4]),
                                   ref["boxcar"], rtol=1e-5)
    finally:
        monkeypatch.delenv("PYPULSAR_TPU_NO_NATIVE")
        importlib.reload(native)


@requires_native
def test_filterbank_native_path(tmp_path, rng):
    """8-bit .fil read through the native transpose matches the python
    path."""
    from pypulsar_tpu.io.filterbank import FilterbankFile, write_filterbank

    C, T = 8, 200
    data = rng.randint(0, 255, size=(T, C)).astype(np.uint8)
    fn = str(tmp_path / "n8.fil")
    write_filterbank(fn, dict(fch1=1500.0, foff=-1.0, nchans=C, tsamp=1e-3,
                              nbits=8, tstart=55000.0), data)
    with FilterbankFile(fn) as fb:
        spec = fb.get_spectra(10, 100)
        direct = fb.get_samples(10, 100)
    np.testing.assert_array_equal(np.asarray(spec.data), direct.T)


@requires_native
def test_psrfits_native_path(tmp_path, rng):
    """4-bit PSRFITS read via the native unpack matches expectations."""
    from pypulsar_tpu.io.psrfits import PsrfitsFile, write_psrfits

    C, T = 8, 128
    data = rng.randint(0, 15, size=(C, T)).astype(np.float32)
    freqs = 1400.0 + np.arange(C)
    fn = str(tmp_path / "n4.fits")
    write_psrfits(fn, data, freqs, tsamp=1e-3, nsamp_per_subint=64,
                  nbits=4)
    with PsrfitsFile(fn) as pf:
        spec = pf.get_spectra(0, T)
    # get_spectra returns high-freq-first; flip to match input order
    np.testing.assert_array_equal(np.asarray(spec.data)[::-1], data)


def test_prefetch_reader_matches_sync_reads(tmp_path):
    """Native background-thread block reader yields byte-identical blocks
    to synchronous reads, for aligned and tail blocks."""
    from pypulsar_tpu import native

    rng = np.random.RandomState(5)
    nspec, nchan = 1111, 16
    data = rng.randn(nspec, nchan).astype(np.float32)
    fn = str(tmp_path / "pf.raw")
    data.tofile(fn)
    bps = nchan * 4
    reader = native.PrefetchReader(fn, 0, bps, nspec, payload=128,
                                   overlap=32, depth=2)
    blocks = [(s, raw.view(np.float32).reshape(-1, nchan).copy())
              for s, raw in reader]
    pos, expect = 0, []
    while pos < nspec:
        n = min(128 + 32, nspec - pos)
        expect.append((pos, data[pos:pos + n]))
        pos += 128
    assert len(blocks) == len(expect)
    for (sa, ba), (sb, bb) in zip(blocks, expect):
        assert sa == sb
        np.testing.assert_array_equal(ba, bb)


def test_filterbank_iter_blocks_prefetch_parity(tmp_path):
    """iter_blocks(prefetch=True) == iter_blocks(prefetch=False)."""
    from pypulsar_tpu.io import filterbank

    rng = np.random.RandomState(6)
    T, C = 2000, 32
    data = rng.randn(T, C).astype(np.float32)
    fn = str(tmp_path / "pf.fil")
    hdr = dict(nchans=C, tsamp=1e-3, fch1=1500.0, foff=-2.0, tstart=55000.0,
               nbits=32, nifs=1, source_name="PF")
    filterbank.write_filterbank(fn, hdr, data)
    fb = filterbank.FilterbankFile(fn)
    a = list(fb.iter_blocks(512, overlap=64, prefetch=True))
    b = list(fb.iter_blocks(512, overlap=64, prefetch=False))
    assert len(a) == len(b)
    for (sa, ba), (sb, bb) in zip(a, b):
        assert sa == sb
        np.testing.assert_array_equal(ba, bb)


def test_filterbank_iter_blocks_windowed_prefetch(tmp_path):
    """A [start, end) window rides the native prefetcher too (the gate
    used to require the whole file, silently dropping to synchronous
    reads for bounded sweeps); positions stay absolute."""
    from pypulsar_tpu.io import filterbank

    rng = np.random.RandomState(9)
    T, C = 3000, 16
    data = rng.randn(T, C).astype(np.float32)
    fn = str(tmp_path / "win.fil")
    hdr = dict(nchans=C, tsamp=1e-3, fch1=1500.0, foff=-2.0, tstart=55000.0,
               nbits=32, nifs=1, source_name="WIN")
    filterbank.write_filterbank(fn, hdr, data)
    fb = filterbank.FilterbankFile(fn)
    for start, end in ((0, 1100), (700, 2500), (512, T)):
        a = list(fb.iter_blocks(512, overlap=64, start=start, end=end,
                                prefetch=True))
        b = list(fb.iter_blocks(512, overlap=64, start=start, end=end,
                                prefetch=False))
        assert len(a) == len(b) and a[0][0] == start
        for (sa, ba), (sb, bb) in zip(a, b):
            assert sa == sb
            np.testing.assert_array_equal(ba, bb)


def test_filterbank_prefetch_8bit(tmp_path):
    """The prefetch path handles packed uint8 files (bytes-per-spectrum
    accounting differs from float32)."""
    from pypulsar_tpu.io import filterbank

    rng = np.random.RandomState(7)
    T, C = 1500, 16
    data = rng.randint(0, 255, size=(T, C)).astype(np.uint8)
    fn = str(tmp_path / "b8.fil")
    hdr = dict(nchans=C, tsamp=1e-3, fch1=1500.0, foff=-2.0, tstart=55000.0,
               nbits=8, nifs=1, source_name="B8")
    filterbank.write_filterbank(fn, hdr, data)
    fb = filterbank.FilterbankFile(fn)
    a = list(fb.iter_blocks(512, overlap=32, prefetch=True))
    b = list(fb.iter_blocks(512, overlap=32, prefetch=False))
    assert len(a) == len(b) and len(a) == 3
    for (sa, ba), (sb, bb) in zip(a, b):
        assert sa == sb
        np.testing.assert_array_equal(ba, bb)
    np.testing.assert_array_equal(a[0][1][:10], data[:10].astype(np.float32))
