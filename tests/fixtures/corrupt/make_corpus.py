#!/usr/bin/env python
"""Regenerate the checked-in corrupted-fixture corpus.

Every fixture is built deterministically from a small VALID file plus
ONE corruption from the shared recipe (``resilience.dataguard.
corrupt_file`` / targeted byte surgery) — never hand-hexed bytes, so
the corpus can always be regenerated and audited:

    python tests/fixtures/corrupt/make_corpus.py

The filename prefix encodes the reader contract tests/test_dataguard.py
asserts for each file:

- ``err_``  — the reader must raise ``DataFormatError`` (located: path
  in the message), never a raw ``struct.error``/``IndexError``/hang;
- ``salv_`` — the reader must OPEN the file, expose a non-None
  ``salvage`` report, and read the whole valid prefix;
- ``ok_``   — the reader must parse cleanly (the damage is payload-
  level and the dataguard scrub downstream owns it).

Extensions map to readers: ``.fil`` -> FilterbankFile, ``.fits`` ->
PsrfitsFile, ``.dat`` (+ ``.inf`` sidecar) -> Datfile.
"""

import os
import struct
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, "..", "..", ".."))

from pypulsar_tpu.io import sigproc  # noqa: E402
from pypulsar_tpu.io.datfile import write_dat  # noqa: E402
from pypulsar_tpu.io.filterbank import write_filterbank  # noqa: E402
from pypulsar_tpu.io.infodata import InfoData  # noqa: E402
from pypulsar_tpu.io.psrfits import write_psrfits  # noqa: E402
from pypulsar_tpu.resilience.dataguard import corrupt_file  # noqa: E402

C, T = 8, 64  # tiny: the whole corpus stays a few KB


def _base_fil(fn):
    rng = np.random.default_rng(11)
    data = rng.standard_normal((T, C)).astype(np.float32)
    write_filterbank(fn, dict(nchans=C, tsamp=1e-3, fch1=1500.0,
                              foff=-1.0, nbits=32,
                              source_name="CORPUS"), data)
    return fn


def _patched_fil(fn, **patch):
    """A .fil whose header carries GARBAGE field values: pack_header
    writes what validate_header must reject (a writer round-trip cannot
    produce these, so the corpus patches the packed bytes directly)."""
    _base_fil(fn)
    with open(fn, "rb") as f:
        hdr, order, hsize = sigproc.read_header(f, path=fn)
        payload = f.read()
    hdr.update(patch)
    with open(fn, "wb") as f:
        f.write(sigproc.pack_header(hdr, order))
        f.write(payload)


def main():
    # --- filterbank ---
    f = _base_fil(os.path.join(HERE, "err_truncated_header.fil"))
    os.truncate(f, 30)  # mid-keyword: read_exact must locate the cut
    f = _base_fil(os.path.join(HERE, "salv_truncated_payload.fil"))
    corrupt_file(f, "truncate", seed=1)
    _patched_fil(os.path.join(HERE, "err_garbage_nbits.fil"), nbits=7)
    _patched_fil(os.path.join(HERE, "err_garbage_nchans.fil"),
                 nchans=1 << 30)
    f = _base_fil(os.path.join(HERE, "err_garbage_keywords.fil"))
    corrupt_file(f, "header", seed=2)
    open(os.path.join(HERE, "err_zero_length.fil"), "wb").close()
    with open(os.path.join(HERE, "err_not_sigproc.fil"), "wb") as fh:
        fh.write(b"\x2a\x00\x00\x00NOT_A_HEADER" * 4)
    f = _base_fil(os.path.join(HERE, "ok_nanburst_payload.fil"))
    corrupt_file(f, "nanburst", seed=3)

    # --- psrfits ---
    rng = np.random.default_rng(13)
    fits_data = rng.integers(0, 40, size=(C, T)).astype(np.float32)
    freqs = 1500.0 - np.arange(float(C))
    base = os.path.join(HERE, "err_truncated_payload.fits")
    write_psrfits(base, fits_data, freqs, 1e-3, nsamp_per_subint=16,
                  nbits=8)
    os.truncate(base, os.path.getsize(base) * 2 // 3)
    base = os.path.join(HERE, "err_garbage_subint.fits")
    write_psrfits(base, fits_data, freqs, 1e-3, nsamp_per_subint=16,
                  nbits=8)
    # overwrite the SUBINT NSBLK card's value with an insane one:
    # _validate_subint must reject the geometry with a located error
    with open(base, "r+b") as fh:
        img = fh.read()
        at = img.index(b"NSBLK")
        fh.seek(at)
        fh.write(f"{'NSBLK':<8s}= {-5:>20d}".encode("ascii"))
    open(os.path.join(HERE, "err_zero_length.fits"), "wb").close()

    # --- .dat/.inf ---
    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = 1e-3
    inf.DM = 10.0
    series = np.random.default_rng(17).standard_normal(T).astype(
        np.float32)
    b = os.path.join(HERE, "salv_truncated")
    write_dat(b, series, inf)
    os.truncate(b + ".dat", T * 4 * 2 // 3 + 2)  # mid-sample cut
    b = os.path.join(HERE, "err_garbage_inf")
    write_dat(b, series, inf)
    with open(b + ".inf", "wb") as fh:
        fh.write(b"\x00\xff" * 200)
    # a zero-length .dat under a sidecar claiming T samples SALVAGES
    # (reads the empty valid prefix, reports all T missing)
    b = os.path.join(HERE, "salv_zero_length")
    write_dat(b, series, inf)
    open(b + ".dat", "wb").close()

    names = sorted(n for n in os.listdir(HERE)
                   if not n.endswith((".py", ".md")))
    total = sum(os.path.getsize(os.path.join(HERE, n)) for n in names)
    print(f"corpus: {len(names)} files, {total} bytes")
    for n in names:
        print(f"  {n}")


if __name__ == "__main__":
    main()
