"""Pipelined sweep->accel handoff tests (round 6): the streamed path's
candidate tables must be bit-identical to the .dat round trip, the
--write-dats tee must write the identical bytes, kill/resume through
--accel-skip-existing must reproduce the uninterrupted tables, and the
shared prefetch core must move only WHEN work happens, never values or
order."""

import glob
import os

import numpy as np
import pytest

from pypulsar_tpu.io import filterbank
from pypulsar_tpu.ops import numpy_ref


def _pulsar_fil(tmp_path, name="psr.fil", C=32, T=16384, dt=5e-4,
                dm=40.0, period=0.1024, amp=10.0, seed=5):
    """A .fil with an injected dispersed pulse train (P=102.4 ms at
    DM 40) — strong enough that the accel search recovers it at the
    fundamental through every prep path."""
    rng = np.random.RandomState(seed)
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(T, C).astype(np.float32) * 2.0 + 30.0
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for t0 in np.arange(0.01, T * dt, period):
        s = int(t0 / dt)
        for c in range(C):
            idx = s + bins[c]
            if idx < T:
                data[idx, c] += amp
    fn = str(tmp_path / name)
    hdr = dict(nchans=C, tsamp=dt, fch1=float(freqs[0]),
               foff=float(freqs[1] - freqs[0]), tstart=55000.0, nbits=32,
               nifs=1, source_name="PSR")
    filterbank.write_filterbank(fn, hdr, data)
    return fn


SWEEP_ARGS = ["--lodm", "0", "--dmstep", "10", "--numdms", "8",
              "-s", "8", "--group-size", "4", "--threshold", "8"]
ACCEL_ARGS = ["-z", "20", "-n", "2", "-s", "3"]
HANDOFF_ARGS = ["--accel-search", "--accel-zmax", "20",
                "--accel-numharm", "2", "--accel-sigma", "3",
                "--accel-batch", "4"]


def _run_dat_roundtrip(fil, outbase, monkeypatch, extra_accel=()):
    """Reference chain: sweep --write-dats (streamed writer) ->
    accelsearch --batch over the .dats."""
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.cli import sweep as cli_sweep

    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    assert cli_sweep.main([fil, "-o", outbase, *SWEEP_ARGS,
                           "--write-dats"]) == 0
    dats = sorted(glob.glob(f"{outbase}_DM*.dat"))
    assert len(dats) == 8
    assert cli_accel.main([*dats, "--batch", "4", *ACCEL_ARGS,
                           *extra_accel]) == 0
    return sorted(glob.glob(f"{outbase}_DM*_ACCEL_20.cand"))


@pytest.mark.parametrize("device_prep", [True, False])
def test_stream_handoff_bit_identical_to_dat_roundtrip(tmp_path,
                                                       monkeypatch,
                                                       device_prep):
    """The acceptance contract of the round-6 tentpole: the streamed
    sweep->accel path produces candidate tables BIT-IDENTICAL to the
    .dat write + re-read chain, for both prep paths, and recovers the
    injected pulsar."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    prep_flags = ([] if device_prep else ["--no-device-prep"])
    a_cands = _run_dat_roundtrip(fil, "a", monkeypatch,
                                 extra_accel=prep_flags)
    assert a_cands

    handoff_prep = ([] if device_prep else ["--no-accel-device-prep"])
    assert cli_sweep.main([fil, "-o", "b", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", *handoff_prep]) == 0
    for fa in a_cands:
        fb = "b" + os.path.basename(fa)[1:]
        assert os.path.exists(fb), fb
        assert open(fa, "rb").read() == open(fb, "rb").read(), fa
        ta, tb = fa[:-5] + ".txtcand", fb[:-5] + ".txtcand"
        assert open(ta).read() == open(tb).read(), ta

    # the injected pulsar (f0 = 1/0.1024 Hz) is in the DM-40 table — a
    # delta-like pulse train puts its power across MANY harmonics, so
    # accept any harmonic k*f0 (k integer) among the top candidates
    from pypulsar_tpu.io.prestocand import read_rzwcands

    T = 16384 * 5e-4
    cands = read_rzwcands("b_DM40.00_ACCEL_20.cand")
    f0 = 1.0 / 0.1024

    def is_harmonic(c):
        k = (c.r / T) / f0
        return k > 0.5 and abs(k - round(k)) < 0.02

    assert any(is_harmonic(c) and c.sig > 10 for c in cands[:10]), \
        "injected pulsar not recovered"


def test_stream_handoff_write_dats_tee_identical(tmp_path, monkeypatch):
    """--accel-search --write-dats tees the IDENTICAL .dat bytes the
    streamed writer would have produced (the tee is the same chunk
    stream, not a second implementation)."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "w", *SWEEP_ARGS,
                           "--write-dats"]) == 0
    assert cli_sweep.main([fil, "-o", "t", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", "--write-dats"]) == 0
    dats = sorted(glob.glob("w_DM*.dat"))
    assert len(dats) == 8
    for fw in dats:
        ft = "t" + os.path.basename(fw)[1:]
        assert open(fw, "rb").read() == open(ft, "rb").read(), fw
        iw, it = fw[:-4] + ".inf", ft[:-4] + ".inf"
        # .inf sidecars agree apart from the basename line
        lw = [l for l in open(iw) if "Data file name" not in l]
        lt = [l for l in open(it) if "Data file name" not in l]
        assert lw == lt


def test_stream_handoff_kill_resume_bit_identical(tmp_path, monkeypatch):
    """A run killed mid-search (BaseException after the first batch — the
    serial fallback must NOT swallow it) resumes with
    --accel-skip-existing: finished trials are skipped, the rest are
    searched, and every final table is bit-identical to an uninterrupted
    run's."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.fourier import accelsearch as accel_mod

    # uninterrupted reference
    assert cli_sweep.main([fil, "-o", "r", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    ref = {os.path.basename(f)[1:]: open(f, "rb").read()
           for f in sorted(glob.glob("r_DM*_ACCEL_20.cand"))}
    assert len(ref) == 8

    real_batch = accel_mod.accel_search_batch
    calls = {"n": 0}

    def dying_batch(*a, **kw):
        calls["n"] += 1
        if calls["n"] > 1:
            raise KeyboardInterrupt("simulated SIGINT mid-run")
        return real_batch(*a, **kw)

    monkeypatch.setattr(accel_mod, "accel_search_batch", dying_batch)
    with pytest.raises(KeyboardInterrupt):
        cli_sweep.main([fil, "-o", "k", *SWEEP_ARGS, *HANDOFF_ARGS,
                        "--accel-only"])
    monkeypatch.setattr(accel_mod, "accel_search_batch", real_batch)
    done = sorted(glob.glob("k_DM*_ACCEL_20.cand"))
    assert 0 < len(done) < 8  # the kill landed mid-run

    # resume: finished trials skipped, the rest searched
    assert cli_sweep.main([fil, "-o", "k", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", "--accel-skip-existing"]) == 0
    got = {os.path.basename(f)[1:]: open(f, "rb").read()
           for f in sorted(glob.glob("k_DM*_ACCEL_20.cand"))}
    assert got == ref


def test_stream_handoff_ram_budget_slices(tmp_path, monkeypatch):
    """A series buffer over PYPULSAR_TPU_ACCEL_STREAM_RAM streams in DM
    slices (extra raw-file passes) with unchanged candidate tables —
    including a budget whose raw slice size (6) is NOT a multiple of the
    stage-1 group size (4): slices must align to group boundaries or the
    regrouped trials dedisperse at different group-mean DMs (review
    repro: 4/8 tables diverged before the alignment fix)."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "f", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    fulls = sorted(glob.glob("f_DM*_ACCEL_20.cand"))
    assert len(fulls) == 8
    # budgets for a raw slice of 4 (aligned) and 6 (MISALIGNED vs the
    # --group-size 4 in SWEEP_ARGS; must round down to 4)
    for tag, trials_per_slice in (("s", 2), ("m", 6)):
        monkeypatch.setenv("PYPULSAR_TPU_ACCEL_STREAM_RAM",
                           str(4 * 16384 * trials_per_slice))
        assert cli_sweep.main([fil, "-o", tag, *SWEEP_ARGS,
                               *HANDOFF_ARGS, "--accel-only"]) == 0
        for ff in fulls:
            fs = tag + os.path.basename(ff)[1:]
            assert open(ff, "rb").read() == open(fs, "rb").read(), \
                (tag, ff)


def test_cli_accelsearch_prefetch_matches_inline(tmp_path, monkeypatch):
    """--prefetch 0 (inline prep) and the default background prefetch
    produce identical candidate files — the pipeline moves WHEN prep
    happens, never what the search sees."""
    monkeypatch.chdir(tmp_path)
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from tests.test_accelsearch import _write_fake_dat

    rng = np.random.RandomState(21)
    N, dt = 1 << 14, 5e-4
    bases = []
    for ii in range(5):
        ts = rng.standard_normal(N).astype(np.float32)
        ts += 0.25 * np.cos(2 * np.pi * (33.0 + 6.0 * ii)
                            * np.arange(N) * dt).astype(np.float32)
        bases.append(_write_fake_dat(str(tmp_path / f"pp{ii}"), ts, dt))
    dats = [b + ".dat" for b in bases]
    argv = dats + ["--batch", "2", "-z", "10", "-n", "2", "-s", "3"]
    assert cli_accel.main(argv + ["--prefetch", "0"]) == 0
    inline = {b: open(b + "_ACCEL_10.cand", "rb").read() for b in bases}
    for b in bases:
        os.remove(b + "_ACCEL_10.cand")
    assert cli_accel.main(argv) == 0  # default --prefetch 4
    for b in bases:
        assert open(b + "_ACCEL_10.cand", "rb").read() == inline[b], b


def test_cli_accelsearch_device_prep_default_on(tmp_path, monkeypatch):
    """--batch >= 2 engages device prep by DEFAULT (round 6 flip under
    the matched-candidate contract); --no-device-prep opts out; --batch 1
    stays on the serial host path."""
    monkeypatch.chdir(tmp_path)
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.fourier import kernels as _k
    from tests.test_accelsearch import _write_fake_dat

    rng = np.random.RandomState(22)
    N, dt = 1 << 13, 5e-4
    bases = []
    for ii in range(2):
        ts = rng.standard_normal(N).astype(np.float32)
        bases.append(_write_fake_dat(str(tmp_path / f"dd{ii}"), ts, dt))
    dats = [b + ".dat" for b in bases]

    calls = []
    real_prep = _k.prep_spectra_batch

    def spy(series, *a, **kw):
        calls.append(np.asarray(series).shape[0])
        return real_prep(series, *a, **kw)

    monkeypatch.setattr(_k, "prep_spectra_batch", spy)
    assert cli_accel.main(dats + ["--batch", "2", "-z", "8", "-n", "1",
                                  "-s", "4"]) == 0
    assert calls == [2], calls  # default-on for the grouped path
    calls.clear()
    for b in bases:
        os.remove(b + "_ACCEL_8.cand")
    assert cli_accel.main(dats + ["--batch", "2", "-z", "8", "-n", "1",
                                  "-s", "4", "--no-device-prep"]) == 0
    assert calls == [], calls
    for b in bases:
        os.remove(b + "_ACCEL_8.cand")
    assert cli_accel.main(dats + ["-z", "8", "-n", "1", "-s", "4"]) == 0
    assert calls == [], calls  # serial path never device-preps


def test_cli_sweep_accel_flag_validation(tmp_path, monkeypatch):
    """--accel-search composes only with the flat single-file mode."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path, name="v.fil", T=4096)
    from pypulsar_tpu.cli import sweep as cli_sweep

    with pytest.raises(SystemExit):
        cli_sweep.main([fil, "--ddplan", "--hidm", "100",
                        "--accel-search"])
    with pytest.raises(SystemExit):
        cli_sweep.main([fil, "--numdms", "4", "--accel-only"])
    with pytest.raises(SystemExit):
        cli_sweep.main([fil, fil, "--numdms", "4", "--accel-search"])


def test_prefetch_values_order_and_errors():
    """parallel.prefetch: values and order are identical to inline
    iteration; transform runs on the worker; producer errors re-raise at
    the consumer; an abandoned consumer stops the worker."""
    import threading
    import time

    from pypulsar_tpu.parallel.prefetch import prefetch

    seen_threads = set()

    def xf(x):
        seen_threads.add(threading.current_thread().name)
        return x * 2

    out = list(prefetch(iter(range(20)), depth=3, name="t", transform=xf))
    assert out == [2 * i for i in range(20)]
    assert seen_threads == {"pypulsar-t"}

    def bad():
        yield 1
        raise OSError("producer died")

    it = prefetch(bad(), depth=2, name="t2")
    assert next(it) == 1
    with pytest.raises(OSError, match="producer died"):
        list(it)

    produced = []

    def many():
        for i in range(1000):
            produced.append(i)
            yield i

    it = prefetch(many(), depth=2, name="t3")
    next(it)
    it.close()
    deadline = time.time() + 5.0
    while time.time() < deadline and any(
            t.name == "pypulsar-t3" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert len(produced) < 20


def test_prefetch_pending_depth_gauge(tmp_path):
    """Under an active telemetry session the prefetch queue fill lands on
    the {name}.pending_depth gauge — the acceptance evidence that the
    pipeline actually ran ahead."""
    import time

    from pypulsar_tpu.obs import telemetry
    from pypulsar_tpu.parallel.prefetch import prefetch

    with telemetry.session() as tlm:
        src = prefetch(iter(range(8)), depth=2, name="gtest")
        first = next(src)
        time.sleep(0.2)  # let the worker fill the queue behind us
        rest = list(src)
        assert [first] + rest == list(range(8))
        gauges = tlm.gauge_values()
    assert "gtest.pending_depth" in gauges
    assert gauges["gtest.pending_depth"]["max"] >= 1


def test_stream_handoff_prefetch_zero_inline_identical(tmp_path,
                                                       monkeypatch):
    """--accel-prefetch 0 runs prep inline (no worker thread) with
    identical candidate tables."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "p", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    assert cli_sweep.main([fil, "-o", "q", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", "--accel-prefetch", "0"]) == 0
    fulls = sorted(glob.glob("p_DM*_ACCEL_20.cand"))
    assert len(fulls) == 8
    for fp in fulls:
        fq = "q" + os.path.basename(fp)[1:]
        assert open(fp, "rb").read() == open(fq, "rb").read(), fp


def test_stream_handoff_prep_failure_falls_back_serial(tmp_path,
                                                       monkeypatch):
    """A device-prep dispatch failing ON THE PREFETCH WORKER degrades
    that batch to the per-spectrum serial host-prep fallback instead of
    aborting the run (the error travels as a value through the queue)."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.fourier import kernels as _k

    # reference: the host-prep handoff (what the fallback computes)
    assert cli_sweep.main([fil, "-o", "h", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", "--no-accel-device-prep"]) == 0
    ref = {os.path.basename(f)[1:]: open(f, "rb").read()
           for f in sorted(glob.glob("h_DM*_ACCEL_20.cand"))}
    assert len(ref) == 8

    def boom(series, *a, **kw):
        raise RuntimeError("synthetic device-prep failure")

    monkeypatch.setattr(_k, "prep_spectra_batch", boom)
    assert cli_sweep.main([fil, "-o", "x", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    got = {os.path.basename(f)[1:]: open(f, "rb").read()
           for f in sorted(glob.glob("x_DM*_ACCEL_20.cand"))}
    assert got == ref


def test_stream_handoff_auto_group_size_parity(tmp_path, monkeypatch):
    """With --group-size left at its auto default (0), the handoff
    resolves the SAME group size as the .dat chain (stage-1 groups
    dedisperse at the group mean DM, so a different group is a different
    series) — tables stay bit-identical without the explicit flag."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.cli import sweep as cli_sweep

    args = ["--lodm", "0", "--dmstep", "10", "--numdms", "8", "-s", "8",
            "--threshold", "8"]
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    assert cli_sweep.main([fil, "-o", "g", *args, "--write-dats"]) == 0
    dats = sorted(glob.glob("g_DM*.dat"))
    assert cli_accel.main([*dats, "--batch", "4", *ACCEL_ARGS]) == 0
    assert cli_sweep.main([fil, "-o", "n", *args, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    fulls = sorted(glob.glob("g_DM*_ACCEL_20.cand"))
    assert len(fulls) == 8
    for fg in fulls:
        fn = "n" + os.path.basename(fg)[1:]
        assert open(fg, "rb").read() == open(fn, "rb").read(), fg


# ---------------------------------------------------------------------------
# multi-chip: DM-sharded sweep->accel handoff (round 11)
# ---------------------------------------------------------------------------

_MESH_PROBE: list = []  # cached (ok, detail) — the same capability-probe
#                         pattern as test_distributed's CPU-collectives gate


def require_virtual_mesh(k):
    """Skip cleanly where fewer than k devices exist or the backend
    cannot execute an in-process shard_map (environment capability, not
    a code bug); cached per session. tests/conftest.py forces the
    8-virtual-device CPU recipe, so these normally run."""
    import jax

    if len(jax.devices()) < k:
        pytest.skip(f"environment capability: {len(jax.devices())} "
                    f"devices < {k} (needs "
                    f"--xla_force_host_platform_device_count)")
    if not _MESH_PROBE:
        try:
            import jax.numpy as jnp
            from jax.sharding import PartitionSpec as P

            from pypulsar_tpu.parallel import make_mesh
            from pypulsar_tpu.parallel.sweep import shard_map_compat

            mesh = make_mesh([2], ("dm",), devices=jax.devices()[:2])
            fn = shard_map_compat(lambda x: x * 2, mesh=mesh,
                                  in_specs=(P("dm"),), out_specs=P("dm"))
            np.testing.assert_array_equal(
                np.asarray(fn(jnp.arange(4.0))), np.arange(4.0) * 2)
            _MESH_PROBE.append((True, ""))
        except Exception as e:  # noqa: BLE001 - capability, not a bug
            _MESH_PROBE.append((False, f"{type(e).__name__}: {e}"))
    ok, detail = _MESH_PROBE[0]
    if not ok:
        pytest.skip("environment capability: in-process shard_map "
                    "collectives unavailable: " + detail)


@pytest.mark.parametrize("numdms,mesh_k", [(8, 4), (6, 4)])
def test_stream_handoff_sharded_byte_identical(tmp_path, monkeypatch,
                                               numdms, mesh_k):
    """The multi-chip acceptance contract: `sweep --mesh k
    --accel-search` (DM-sharded dedispersion + batch-sharded prep +
    shard_map'd search, all over the same k devices) writes
    .cand/.txtcand/.dat artifacts BYTE-identical to the 1-device run —
    including the 6-trials-on-4-chips case, where both the trial groups
    and the dispatch batches pad to device multiples."""
    require_virtual_mesh(mesh_k)
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    args = ["--lodm", "0", "--dmstep", "10", "--numdms", str(numdms),
            "-s", "8", "--group-size", "4", "--threshold", "8",
            *HANDOFF_ARGS, "--accel-only", "--write-dats"]
    assert cli_sweep.main([fil, "-o", "s1", *args]) == 0
    assert cli_sweep.main([fil, "-o", "sk", *args,
                           "--mesh", str(mesh_k)]) == 0
    compared = 0
    for fa in sorted(glob.glob("s1_DM*")):
        if fa.endswith(".inf"):
            continue  # .inf embeds the basename; parity-checked elsewhere
        fb = "sk" + os.path.basename(fa)[2:]
        assert os.path.exists(fb), fb
        assert open(fa, "rb").read() == open(fb, "rb").read(), fa
        compared += 1
    assert compared == 3 * numdms  # .dat + .cand + .txtcand per trial


def test_sharded_handoff_stamps_device_telemetry(tmp_path, monkeypatch):
    """The sharded pipeline stamps device ids on its spans/counters so
    tlmsum's per-device section can show per-chip utilization."""
    require_virtual_mesh(2)
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.obs.summarize import load_records, summarize

    assert cli_sweep.main([fil, "-o", "t", "--lodm", "0", "--dmstep",
                           "10", "--numdms", "8", "-s", "8",
                           "--group-size", "4", "--threshold", "8",
                           *HANDOFF_ARGS, "--accel-only", "--mesh", "2",
                           "--telemetry", "t.jsonl"]) == 0
    s = summarize(load_records("t.jsonl"))
    assert sorted(s.device_busy) and len(s.device_busy) == 2
    for _d, (busy, nsp) in s.device_busy.items():
        assert busy > 0 and nsp > 0
    assert s.counters.get("device0.dedisperse.chunks", 0) >= 1
    assert s.counters.get("device1.accel.stream_batches", 0) >= 1


# ---------------------------------------------------------------------------
# spectral fusion: the fused sweep->accel handoff (round 15)
# ---------------------------------------------------------------------------


SPECTRAL_ARGS = [*HANDOFF_ARGS, "--accel-only", "--spectral"]


def _cand_bytes(prefix):
    return {os.path.basename(f)[len(prefix):]: open(f, "rb").read()
            for f in sorted(glob.glob(f"{prefix}_DM*_ACCEL_20.*cand"))}


@pytest.mark.parametrize("T,extra", [
    (16384, []),                      # single chunk, power-of-two
    (15000, ["--chunk", "4096"]),     # non-pow2 out_len + partial tail
])
def test_spectral_handoff_bit_identical_to_streamed(tmp_path, monkeypatch,
                                                    T, extra):
    """The round-15 parity gate: `--spectral` (stitched regime, the
    default) writes candidate tables BIT-identical to the streamed
    device-prep handoff — including a non-power-of-two series length
    and a trailing partial chunk, the geometries where the decimated
    shortcut is structurally impossible and the stitch must carry the
    exact overlap-save windows."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path, T=T)
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "s", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", *extra]) == 0
    assert cli_sweep.main([fil, "-o", "f", *SWEEP_ARGS, *SPECTRAL_ARGS,
                           *extra]) == 0
    ref, got = _cand_bytes("s"), _cand_bytes("f")
    assert len(ref) == 16  # .cand + .txtcand per trial
    assert got == ref


def test_spectral_handoff_fourier_engine_identical(tmp_path, monkeypatch):
    """Same gate under the TPU-default fourier engine (the stitch
    consumes the SAME chunk kernel the streamed path pulls to host, so
    engine choice cannot open a gap)."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep

    eng = ["--engine", "fourier"]
    assert cli_sweep.main([fil, "-o", "s", *SWEEP_ARGS, *HANDOFF_ARGS,
                           "--accel-only", *eng]) == 0
    assert cli_sweep.main([fil, "-o", "f", *SWEEP_ARGS, *SPECTRAL_ARGS,
                           *eng]) == 0
    assert _cand_bytes("f") == _cand_bytes("s")


def test_spectral_slice_budget_and_stitch_counters(tmp_path, monkeypatch):
    """A PYPULSAR_TPU_SPECFUSE_HBM budget below the whole trial set
    fuses in group-aligned DM slices (one extra raw pass each) with
    unchanged candidate tables, and the specfuse telemetry counters
    record the stitched chunks and the series bytes kept on device."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.obs.summarize import load_records, summarize
    from pypulsar_tpu.parallel.specfuse import spectral_trial_bytes

    assert cli_sweep.main([fil, "-o", "w", *SWEEP_ARGS,
                           *SPECTRAL_ARGS]) == 0
    # budget for exactly 4 trials/slice (aligned to --group-size 4)
    monkeypatch.setenv("PYPULSAR_TPU_SPECFUSE_HBM",
                       str(4 * spectral_trial_bytes(16384)))
    assert cli_sweep.main([fil, "-o", "v", *SWEEP_ARGS, *SPECTRAL_ARGS,
                           "--telemetry", "v.jsonl"]) == 0
    assert _cand_bytes("v") == _cand_bytes("w")
    s = summarize(load_records("v.jsonl"))
    assert s.counters.get("specfuse.chunks_stitched", 0) >= 2  # 2 slices
    # 8 trials x 16384 samples x 8 B (D2H pull + H2D re-ship elided)
    assert s.counters.get("specfuse.bytes_on_device") == 8 * 8 * 16384


def test_spectral_kill_resume_at_stitch_boundary(tmp_path, monkeypatch):
    """A kill AT THE NEW STAGE BOUNDARY (the specfuse.after_stitch
    fault point, second DM slice) resumes with --accel-skip-existing:
    the first slice's finished .cands are skipped, the rest are fused
    and searched, and every final table is bit-identical to an
    uninterrupted run."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.resilience import faultinject
    from pypulsar_tpu.resilience.faultinject import InjectedKill

    assert cli_sweep.main([fil, "-o", "r", *SWEEP_ARGS,
                           *SPECTRAL_ARGS]) == 0
    ref = _cand_bytes("r")
    assert len(ref) == 16

    from pypulsar_tpu.parallel.specfuse import spectral_trial_bytes

    monkeypatch.setenv("PYPULSAR_TPU_SPECFUSE_HBM",
                       str(4 * spectral_trial_bytes(16384)))
    try:
        with pytest.raises(InjectedKill):
            cli_sweep.main([fil, "-o", "k", *SWEEP_ARGS, *SPECTRAL_ARGS,
                            "--fault-inject",
                            "kill:specfuse.after_stitch:2"])
    finally:
        faultinject.reset()
    done = _cand_bytes("k")
    assert 0 < len(done) < 16  # first slice landed, second did not
    assert cli_sweep.main([fil, "-o", "k", *SWEEP_ARGS, *SPECTRAL_ARGS,
                           "--accel-skip-existing"]) == 0
    assert _cand_bytes("k") == ref


@pytest.mark.parametrize("numdms,mesh_k", [(8, 4), (6, 4)])
def test_spectral_handoff_sharded_byte_identical(tmp_path, monkeypatch,
                                                 numdms, mesh_k):
    """`--spectral --mesh k`: the stitch buffer, the fused prep planes
    and the search all stay P('dm')-sharded over the k devices, and the
    candidate tables are BYTE-identical to the 1-device streamed run —
    including the 6-trials-on-4-chips case where trial groups pad to
    the device multiple."""
    require_virtual_mesh(mesh_k)
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.obs.summarize import load_records, summarize

    args = ["--lodm", "0", "--dmstep", "10", "--numdms", str(numdms),
            "-s", "8", "--group-size", "4", "--threshold", "8"]
    assert cli_sweep.main([fil, "-o", "s1", *args, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    assert cli_sweep.main([fil, "-o", "sk", *args, *SPECTRAL_ARGS,
                           "--mesh", str(mesh_k),
                           "--telemetry", "sk.jsonl"]) == 0
    ref, got = _cand_bytes("s1"), _cand_bytes("sk")
    assert len(ref) == 2 * numdms
    assert got == ref
    # per-device stamps land on the specfuse counters (PR 6 contract)
    s = summarize(load_records("sk.jsonl"))
    assert s.counters.get("device0.specfuse.chunks_stitched", 0) >= 1
    assert s.counters.get(f"device{mesh_k - 1}.specfuse.chunks_stitched",
                          0) >= 1


def test_spectral_decimate_matches_circular_reference():
    """The opt-in decimated regime's kernel contract: the per-trial
    decimated spectrum is EXACTLY (to f32 rounding) the T-point rfft of
    the two-stage CIRCULARLY dedispersed, mean-subtracted series — the
    Fourier-domain-dedispersion convention, which differs from the
    zero-padded linear engines only in the final max-shift samples
    (why decimate is opt-in rather than the parity default)."""
    import jax.numpy as jnp

    from pypulsar_tpu.ops.fourier_dedisperse import (
        fourier_chunk_len,
        sweep_chunk_spectra,
    )
    from pypulsar_tpu.parallel.sweep import make_sweep_plan

    rng = np.random.RandomState(0)
    C, T, dt = 16, 4096, 5e-4
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32) * 2.0 + 30.0
    dms = np.array([0.0, 10.0, 20.0, 30.0])
    plan = make_sweep_plan(dms, freqs, dt, nsub=8, group_size=2,
                           widths=(1,))
    need = T + plan.min_overlap
    n_fft = fourier_chunk_len(need)
    block = jnp.pad(jnp.asarray(data), ((0, 0), (0, need - T)))
    re_f, im_f = sweep_chunk_spectra(
        block, jnp.asarray(plan.stage1_bins),
        jnp.asarray(plan.stage2_bins), plan.nsub, n_fft, n_fft // T,
        T // 2 + 1, T)

    d64 = data.astype(np.float64)
    d64 = d64 - d64.mean(axis=1, keepdims=True)
    per = C // plan.nsub
    for gi in range(plan.stage1_bins.shape[0]):
        sub = np.zeros((plan.nsub, T))
        for c in range(C):
            sub[c // per] += np.roll(d64[c],
                                     -int(plan.stage1_bins[gi, c]))
        for ti in range(plan.group_size):
            d = gi * plan.group_size + ti
            if d >= len(dms):
                break
            ts = np.zeros(T)
            for sb in range(plan.nsub):
                ts += np.roll(sub[sb],
                              -int(plan.stage2_bins[gi, ti, sb]))
            ref = np.fft.rfft(ts)
            got = (np.asarray(re_f[d]).astype(np.float64)
                   + 1j * np.asarray(im_f[d]))
            err = np.abs(ref - got)
            err[0] = 0.0  # DC conventions differ; deredden overwrites it
            rms = np.sqrt((np.abs(ref) ** 2).mean())
            assert err.max() / rms < 2e-5, (d, err.max() / rms)


def test_spectral_decimate_optin_elides_fft_pairs(tmp_path, monkeypatch):
    """PYPULSAR_TPU_SPECFUSE_MODE=decimate on an eligible geometry
    (single fourier chunk, power-of-two T): the telemetry counters
    prove ZERO per-trial transforms (one irfft+rfft pair elided per
    trial), and the injected pulsar is still recovered at its DM."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.io.prestocand import read_rzwcands
    from pypulsar_tpu.obs.summarize import load_records, summarize

    monkeypatch.setenv("PYPULSAR_TPU_SPECFUSE_MODE", "decimate")
    assert cli_sweep.main([fil, "-o", "d", *SWEEP_ARGS, *SPECTRAL_ARGS,
                           "--engine", "fourier",
                           "--telemetry", "d.jsonl"]) == 0
    s = summarize(load_records("d.jsonl"))
    assert s.counters.get("specfuse.fft_pairs_elided") == 8
    assert not s.counters.get("specfuse.chunks_stitched")
    T = 16384 * 5e-4
    f0 = 1.0 / 0.1024
    cands = read_rzwcands("d_DM40.00_ACCEL_20.cand")

    def is_harmonic(c):
        k = (c.r / T) / f0
        return k > 0.5 and abs(k - round(k)) < 0.02

    assert any(is_harmonic(c) and c.sig > 10 for c in cands[:10])


# ---------------------------------------------------------------------------
# tree engine through the handoff chain (round 16): the shared-work
# engine must feed every stage unchanged — same within-engine byte
# contracts the fourier engine carries
# ---------------------------------------------------------------------------


TREE_SWEEP_ARGS = [*SWEEP_ARGS, "--engine", "tree"]


def test_tree_handoff_bit_identical_to_dat_roundtrip(tmp_path,
                                                     monkeypatch):
    """The round-6 chain contract under engine='tree': the streamed
    sweep->accel handoff's candidate tables are BIT-identical to the
    .dat write + re-read chain (same tree chunk kernel feeds both), and
    the injected pulsar is recovered."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "a", *TREE_SWEEP_ARGS,
                           "--write-dats"]) == 0
    dats = sorted(glob.glob("a_DM*.dat"))
    assert len(dats) == 8
    assert cli_accel.main([*dats, "--batch", "4", *ACCEL_ARGS]) == 0
    a_cands = sorted(glob.glob("a_DM*_ACCEL_20.cand"))
    assert a_cands

    assert cli_sweep.main([fil, "-o", "b", *TREE_SWEEP_ARGS,
                           *HANDOFF_ARGS, "--accel-only"]) == 0
    for fa in a_cands:
        fb = "b" + os.path.basename(fa)[1:]
        assert open(fa, "rb").read() == open(fb, "rb").read(), fa
        ta, tb = fa[:-5] + ".txtcand", fb[:-5] + ".txtcand"
        assert open(ta).read() == open(tb).read(), ta

    from pypulsar_tpu.io.prestocand import read_rzwcands

    T = 16384 * 5e-4
    cands = read_rzwcands("b_DM40.00_ACCEL_20.cand")
    f0 = 1.0 / 0.1024

    def is_harmonic(c):
        k = (c.r / T) / f0
        return k > 0.5 and abs(k - round(k)) < 0.02

    assert any(is_harmonic(c) and c.sig > 10 for c in cands[:10]), \
        "injected pulsar not recovered under engine=tree"


@pytest.mark.parametrize("T,extra", [
    (16384, []),                      # single chunk, power-of-two
    (15000, ["--chunk", "4096"]),     # non-pow2 out_len + partial tail
])
def test_tree_spectral_bit_identical_to_streamed(tmp_path, monkeypatch,
                                                 T, extra):
    """'tree feeds specfuse unchanged': `--engine tree --spectral`
    candidate tables are BYTE-identical to the tree-engine streamed
    handoff at every tested geometry — the same within-engine chain
    invariance the fourier engine's round-15 gate pinned. (Cross-ENGINE
    tables differ by f32 summation order for every engine pair — the
    measured 0/16 finding recorded in BENCHNOTES round 16 — so the byte
    contract is per engine, as it always was.)"""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path, T=T)
    from pypulsar_tpu.cli import sweep as cli_sweep

    assert cli_sweep.main([fil, "-o", "s", *TREE_SWEEP_ARGS,
                           *HANDOFF_ARGS, "--accel-only", *extra]) == 0
    assert cli_sweep.main([fil, "-o", "f", *TREE_SWEEP_ARGS,
                           *SPECTRAL_ARGS, *extra]) == 0
    ref, got = _cand_bytes("s"), _cand_bytes("f")
    assert len(ref) == 16
    assert got == ref


@pytest.mark.parametrize("numdms,mesh_k", [(8, 4), (6, 4)])
def test_tree_spectral_sharded_byte_identical(tmp_path, monkeypatch,
                                              numdms, mesh_k):
    """`--engine tree --spectral --mesh k`: per-device tree tables,
    P('dm')-sharded stitch and search — candidate tables BYTE-identical
    to the 1-device tree streamed run, incl. the 6-trials-on-4-chips
    padding case; the tree counters land with per-device stamps (the
    PR 6 lease contract)."""
    require_virtual_mesh(mesh_k)
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.obs.summarize import load_records, summarize

    args = ["--lodm", "0", "--dmstep", "10", "--numdms", str(numdms),
            "-s", "8", "--group-size", "4", "--threshold", "8",
            "--engine", "tree"]
    assert cli_sweep.main([fil, "-o", "s1", *args, *HANDOFF_ARGS,
                           "--accel-only"]) == 0
    assert cli_sweep.main([fil, "-o", "sk", *args, *SPECTRAL_ARGS,
                           "--mesh", str(mesh_k),
                           "--telemetry", "sk.jsonl"]) == 0
    ref, got = _cand_bytes("s1"), _cand_bytes("sk")
    assert len(ref) == 2 * numdms
    assert got == ref
    s = summarize(load_records("sk.jsonl"))
    assert s.counters.get("tree.adds_total", 0) > 0
    assert s.counters.get("device0.tree.adds_total", 0) > 0
    assert s.counters.get(f"device{mesh_k - 1}.tree.adds_total", 0) > 0
    assert s.gauges.get("tree.merge_levels", {}).get("max", 0) == 5


def test_tree_spectral_kill_resume_at_stitch_boundary(tmp_path,
                                                      monkeypatch):
    """Kill at the specfuse.after_stitch boundary under engine='tree',
    resume with --accel-skip-existing: final tables bit-identical to an
    uninterrupted tree run (the existing harness, new engine)."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.parallel.specfuse import spectral_trial_bytes
    from pypulsar_tpu.resilience import faultinject
    from pypulsar_tpu.resilience.faultinject import InjectedKill

    assert cli_sweep.main([fil, "-o", "r", *TREE_SWEEP_ARGS,
                           *SPECTRAL_ARGS]) == 0
    ref = _cand_bytes("r")
    assert len(ref) == 16

    monkeypatch.setenv("PYPULSAR_TPU_SPECFUSE_HBM",
                       str(4 * spectral_trial_bytes(16384)))
    try:
        with pytest.raises(InjectedKill):
            cli_sweep.main([fil, "-o", "k", *TREE_SWEEP_ARGS,
                            *SPECTRAL_ARGS, "--fault-inject",
                            "kill:specfuse.after_stitch:2"])
    finally:
        faultinject.reset()
    done = _cand_bytes("k")
    assert 0 < len(done) < 16
    assert cli_sweep.main([fil, "-o", "k", *TREE_SWEEP_ARGS,
                           *SPECTRAL_ARGS,
                           "--accel-skip-existing"]) == 0
    assert _cand_bytes("k") == ref


def test_spectral_survey_dag_argv_composition():
    """The spectral survey DAG: the sweep stage swaps the .dat tee for
    --spectral, and the fold stage streams the RAW file with the
    sweep's series geometry AND its rfifind mask — a maskless fold
    would reintroduce the RFI the search excluded (review catch)."""
    from pypulsar_tpu.survey.dag import (
        SurveyConfig,
        _fold_argv,
        _mask_file,
        _sweep_argv,
    )
    from pypulsar_tpu.survey.state import Observation

    obs = Observation("b0", "/d/b0.fil", "/o/b0")
    cfg = SurveyConfig(accel_spectral=True, mask=True)
    sw = _sweep_argv(obs, cfg)
    assert "--spectral" in sw and "--write-dats" not in sw
    fa = _fold_argv(obs, cfg)
    assert fa[0] == obs.infile and "--datbase" not in fa
    assert fa[fa.index("--mask") + 1] == _mask_file(obs)
    assert "--mask" not in _fold_argv(
        obs, SurveyConfig(accel_spectral=True, mask=False))
    no_fuse = _fold_argv(obs, SurveyConfig(accel_spectral=False))
    assert "--datbase" in no_fuse and "--mask" not in no_fuse


def test_foldbatch_mask_is_stream_only(tmp_path, monkeypatch):
    """foldbatch --mask is rejected loudly for .dat/--datbase sources
    (those series were masked when written; silently ignoring the flag
    would fold a different stream than requested)."""
    monkeypatch.chdir(tmp_path)
    from pypulsar_tpu.cli import foldbatch as cli_fold

    open("c.txt", "w").write("0.1 40.0\n")
    with pytest.raises(SystemExit):
        cli_fold.main(["--cands", "c.txt", "--datbase", "x",
                       "--mask", "m.mask"])
    with pytest.raises(SystemExit):
        cli_fold.main(["x.dat", "--cands", "c.txt", "--mask", "m.mask"])


def test_spectral_flag_validation(tmp_path, monkeypatch):
    """--spectral composes only with --accel-search and excludes the
    flags that contradict fusion (--write-dats, --no-accel-device-prep)."""
    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path, name="sv.fil", T=4096)
    from pypulsar_tpu.cli import sweep as cli_sweep

    with pytest.raises(SystemExit):
        cli_sweep.main([fil, "--numdms", "4", "--spectral"])
    with pytest.raises(SystemExit):
        cli_sweep.main([fil, "--numdms", "4", *HANDOFF_ARGS,
                        "--spectral", "--write-dats"])
    with pytest.raises(SystemExit):
        cli_sweep.main([fil, "--numdms", "4", *HANDOFF_ARGS,
                        "--spectral", "--no-accel-device-prep"])


def test_lease_devices_resolver_contract():
    """parallel.mesh.lease_devices: inside a device_lease only the
    leased chips are addressable (and over-asking raises); outside, the
    local device list is the pool."""
    require_virtual_mesh(3)
    import jax

    from pypulsar_tpu.parallel import mesh as mesh_mod

    local = jax.local_devices()
    assert mesh_mod.lease_devices(2) == local[:2]
    with mesh_mod.device_lease(local[2:3]):
        assert mesh_mod.lease_devices() == [local[2]]
        assert mesh_mod.lease_devices(1) == [local[2]]
        with pytest.raises(ValueError, match="lease"):
            mesh_mod.lease_devices(2)
        # nesting shadows then restores
        with mesh_mod.device_lease(local[:2]):
            assert mesh_mod.lease_devices(2) == local[:2]
        assert mesh_mod.lease_devices() == [local[2]]
    assert mesh_mod.lease_devices() == local
