"""Multi-host path tests (SURVEY.md §2.4 rows 4-5, VERDICT r2 item 6).

Single-process behavior is tested in-process; the real ``jax.distributed``
2-process path runs as a subprocess integration test on the CPU backend
(two ranks join a localhost coordinator, sweep disjoint file shares, and
all-gather the merged candidate table)."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from pypulsar_tpu.ops import numpy_ref
from pypulsar_tpu.parallel import distributed

_MP_PROBE: list = []  # cached (ok, detail) of the capability probe

_PROBE_SCRIPT = textwrap.dedent("""
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(os.environ["PROBE_COORD"], 2,
                               int(os.environ["PROBE_RANK"]))
    from jax.experimental import multihost_utils
    out = multihost_utils.process_allgather(np.arange(4.0))
    assert np.asarray(out).size == 8
    print("PROBE OK")
""")


def _probe_cpu_collectives():
    """(ok, detail): can this jaxlib run REAL 2-process CPU collectives?
    Some jaxlib builds raise 'Multiprocess computations aren't
    implemented on the CPU backend' from process_allgather — an
    environment capability, not a code bug, so the two-process
    integration tests skip with that reason instead of failing red."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env["PROBE_COORD"] = f"127.0.0.1:{port}"
        env["PROBE_RANK"] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _PROBE_SCRIPT], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    try:
        outs = [p.communicate(timeout=120) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return False, "2-process collective probe timed out"
    for p, (_out, err) in zip(procs, outs):
        if p.returncode != 0:
            tail = err.strip().splitlines()
            return False, (tail[-1][-200:] if tail else "no stderr")
    return True, ""


def _require_cpu_collectives():
    """Runtime capability gate for the two-process integration tests
    (probe runs once per session, only when such a test executes)."""
    if not _MP_PROBE:
        _MP_PROBE.append(_probe_cpu_collectives())
    ok, detail = _MP_PROBE[0]
    if not ok:
        pytest.skip("environment capability: jaxlib CPU backend cannot "
                    f"run 2-process collectives ({detail})")


def test_shard_files_round_robin():
    files = [f"f{i}" for i in range(7)]
    assert distributed.shard_files(files, index=0, count=3) == ["f0", "f3", "f6"]
    assert distributed.shard_files(files, index=2, count=3) == ["f2", "f5"]
    all_shards = [distributed.shard_files(files, index=i, count=3)
                  for i in range(3)]
    assert sorted(sum(all_shards, [])) == sorted(files)


def test_shard_files_surplus_hosts_empty_not_aliased():
    """The round-18 idle-host contract: with more processes than files
    the surplus ranks get clean EMPTY slices (they join the survey
    claim pool as adopters — tests/test_multihost.py pins that side),
    the partition still covers every file exactly once, and an
    out-of-grid rank is a loud error rather than a silent alias of
    another host's share."""
    files = [f"f{i}" for i in range(3)]
    shards = [distributed.shard_files(files, index=i, count=8)
              for i in range(8)]
    assert [s for s in shards[3:] if s] == []  # surplus ranks idle
    assert sorted(sum(shards, [])) == sorted(files)  # no file dropped
    assert all(len(s) <= 1 for s in shards)  # and none double-assigned
    with pytest.raises(ValueError):
        distributed.shard_files(files, index=8, count=8)
    with pytest.raises(ValueError):
        distributed.shard_files(files, index=-1, count=8)
    with pytest.raises(ValueError):
        distributed.shard_files(files, index=0, count=0)


def test_local_rank_env_first(monkeypatch):
    """local_rank/local_count read the launcher env grid without
    touching jax — the path the survey --hosts children derive their
    host ids from."""
    monkeypatch.setenv(distributed.ENV_NPROC, "4")
    monkeypatch.setenv(distributed.ENV_PID, "2")
    assert distributed.local_count() == 4
    assert distributed.local_rank() == 2
    monkeypatch.setenv(distributed.ENV_NPROC, "1")
    assert distributed.local_count() == 1
    assert distributed.local_rank() == 0


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv(distributed.ENV_COORD, raising=False)
    assert distributed.initialize() is False


def test_allgather_candidates_single_process():
    recs = np.array([[0.0, 60.0, 12.0, 2.0, 100.0],
                     [1.0, 30.0, 8.0, 4.0, 50.0]])
    out = distributed.allgather_candidates(recs, pad_to=4)
    np.testing.assert_array_equal(out, recs)


def _write_fil(path, dm, t0, seed, C=32, T=8192, dt=1e-3):
    from pypulsar_tpu.io import filterbank

    freqs = 1500.0 - 2.0 * np.arange(C)
    rng = np.random.RandomState(seed)
    data = rng.randn(T, C).astype(np.float32)
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for c in range(C):
        idx = t0 + bins[c]
        if idx < T:
            data[idx, c] += 10.0
    hdr = dict(nchans=C, tsamp=dt, fch1=1500.0, foff=-2.0, tstart=55000.0,
               nbits=32, nifs=1, source_name="DTEST")
    filterbank.write_filterbank(path, hdr, data)


def test_multi_host_sweep_single_process(tmp_path):
    """The multi-host API degenerates correctly to one process."""
    f0 = str(tmp_path / "a.fil")
    f1 = str(tmp_path / "b.fil")
    _write_fil(f0, dm=40.0, t0=2000, seed=0)
    _write_fil(f1, dm=90.0, t0=5000, seed=1)
    dms = np.linspace(0.0, 120.0, 16)
    merged = distributed.multi_host_sweep([f0, f1], dms, nsub=8,
                                          group_size=4, topk_per_file=4)
    assert set(merged[:, 0].astype(int)) == {0, 1}
    best_a = merged[merged[:, 0] == 0][0]
    best_b = merged[merged[:, 0] == 1][0]
    assert abs(best_a[1] - 40.0) <= 16.0
    assert abs(best_b[1] - 90.0) <= 16.0


def test_time_shard_merge_matches_whole_sweep(tmp_path):
    """Two in-process time-shard windows merge to the sequential sweep:
    mb/ab (every peak value and its global sample) bit-identical, SNR
    equal to f64 re-association (the seam contract of the windowed
    _ReaderSource + merge_accum_parts)."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_flat
    from pypulsar_tpu.parallel.sweep import finalize_sweep, merge_accum_parts

    fn = str(tmp_path / "ts.fil")
    _write_fil(fn, dm=60.0, t0=6000, seed=3, T=8192)
    dms = np.linspace(0.0, 100.0, 12)
    whole = sweep_flat(filterbank.FilterbankFile(fn), dms, nsub=8,
                       group_size=4, chunk_payload=2048).steps[0].result

    plan = None
    parts = []
    for rank in (0, 1):
        plan, acc = distributed.time_shard_local_accum(
            fn, dms, rank, 2, nsub=8, group_size=4, chunk_payload=2048)
        parts.append(acc)
    assert parts[0].n + parts[1].n == 8192
    merged = merge_accum_parts(parts)
    res = finalize_sweep(plan, merged.n, merged.s, merged.ss, merged.mb,
                         merged.ab, merged.baseline_sum)
    np.testing.assert_array_equal(res.peak_sample, whole.peak_sample)
    np.testing.assert_allclose(res.snr, whole.snr, rtol=1e-9, atol=1e-9)
    # the recovered injection survives sharding
    best = res.best(1)[0]
    assert abs(best["dm"] - 60.0) <= 10.0 and best["snr"] > 8.0


def test_time_shard_masked_matches_flat(tmp_path):
    """rfimask fill composes with time windows: the masked time-sharded
    merge equals the masked sequential sweep (mask fill is per-block and
    window blocks are the same blocks)."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.io.rfimask import RfifindMask, write_mask
    from pypulsar_tpu.parallel.staged import sweep_flat
    from pypulsar_tpu.parallel.sweep import finalize_sweep, merge_accum_parts

    fn = str(tmp_path / "tsm.fil")
    _write_fil(fn, dm=60.0, t0=6000, seed=5, T=8192)
    # DIFFERENT channels per interval: a window-relative (instead of
    # file-absolute) interval lookup on rank 1 would fill the wrong
    # channels and fail the parity below
    maskfn = str(tmp_path / "tsm.mask")
    nint = 4
    write_mask(maskfn, nchan=32, nint=nint, ptsperint=8192 // nint,
               zap_chans=np.array([], np.int64),
               zap_ints=np.array([], np.int64),
               zap_chans_per_int=[np.array([3]), np.array([5, 11]),
                                  np.array([7]), np.array([9, 20])])
    mask = RfifindMask(maskfn)

    dms = np.linspace(0.0, 100.0, 12)
    whole = sweep_flat(filterbank.FilterbankFile(fn), dms, nsub=8,
                       group_size=4, chunk_payload=2048,
                       rfimask=mask).steps[0].result
    plan = None
    parts = []
    for rank in (0, 1):
        plan, acc = distributed.time_shard_local_accum(
            fn, dms, rank, 2, nsub=8, group_size=4, chunk_payload=2048,
            rfimask=mask)
        parts.append(acc)
    merged = merge_accum_parts(parts)
    res = finalize_sweep(plan, merged.n, merged.s, merged.ss, merged.mb,
                         merged.ab, merged.baseline_sum)
    np.testing.assert_array_equal(res.peak_sample, whole.peak_sample)
    np.testing.assert_allclose(res.snr, whole.snr, rtol=1e-9, atol=1e-9)


def test_time_shard_downsampled_matches_flat(tmp_path):
    """--downsamp composes with time windows: windows align to whole raw
    bins, so the downsampled shard merge equals the downsampled
    sequential sweep."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_flat
    from pypulsar_tpu.parallel.sweep import finalize_sweep, merge_accum_parts

    fn = str(tmp_path / "tsd.fil")
    _write_fil(fn, dm=60.0, t0=6000, seed=6, T=8192)
    dms = np.linspace(0.0, 100.0, 12)
    whole = sweep_flat(filterbank.FilterbankFile(fn), dms, downsamp=2,
                       nsub=8, group_size=4,
                       chunk_payload=1024).steps[0].result
    plan = None
    parts = []
    for rank in (0, 1):
        plan, acc = distributed.time_shard_local_accum(
            fn, dms, rank, 2, nsub=8, group_size=4, chunk_payload=1024,
            downsamp=2)
        parts.append(acc)
    assert parts[0].n + parts[1].n == 4096  # downsampled sample count
    merged = merge_accum_parts(parts)
    res = finalize_sweep(plan, merged.n, merged.s, merged.ss, merged.mb,
                         merged.ab, merged.baseline_sum)
    np.testing.assert_array_equal(res.peak_sample, whole.peak_sample)
    np.testing.assert_allclose(res.snr, whole.snr, rtol=1e-9, atol=1e-9)


def test_time_shard_single_count_matches_flat(tmp_path):
    """count=1 time_sharded_sweep is exactly sweep_flat (the degenerate
    window is the whole file and no collective runs)."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_flat

    fn = str(tmp_path / "ts1.fil")
    _write_fil(fn, dm=45.0, t0=3000, seed=4, T=4096)
    dms = np.linspace(0.0, 100.0, 8)
    whole = sweep_flat(filterbank.FilterbankFile(fn), dms, nsub=8,
                       group_size=4, chunk_payload=2048).steps[0].result
    res = distributed.time_sharded_sweep(fn, dms, nsub=8, group_size=4,
                                         chunk_payload=2048, rank=0, count=1)
    np.testing.assert_array_equal(res.snr, whole.snr)
    np.testing.assert_array_equal(res.peak_sample, whole.peak_sample)


_RANK_SCRIPT = textwrap.dedent("""
    import os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    from pypulsar_tpu.parallel import distributed

    ok = distributed.initialize()
    assert ok, "distributed.initialize() did not engage"
    assert jax.process_count() == 2
    files = [{f0!r}, {f1!r}]
    dms = np.linspace(0.0, 120.0, 16)
    merged = distributed.multi_host_sweep(files, dms, nsub=8, group_size=4,
                                          topk_per_file=4)
    np.save(os.path.join({out!r}, "merged_rank%d.npy" % jax.process_index()),
            merged)
    print("RANK", jax.process_index(), "OK", len(merged))
""")


_TS_RANK_SCRIPT = textwrap.dedent("""
    import os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    from pypulsar_tpu.parallel import distributed

    ok = distributed.initialize()
    assert ok, "distributed.initialize() did not engage"
    dms = np.linspace(0.0, 100.0, 12)
    res = distributed.time_sharded_sweep({fn!r}, dms, nsub=8, group_size=4,
                                         chunk_payload=2048)
    rank = jax.process_index()
    np.save(os.path.join({out!r}, "ts_snr_rank%d.npy" % rank), res.snr)
    np.save(os.path.join({out!r}, "ts_peak_rank%d.npy" % rank),
            res.peak_sample)
    print("RANK", rank, "OK")
""")


def test_time_sharded_sweep_two_process(tmp_path):
    """Real jax.distributed: 2 CPU ranks each stream HALF of one file's
    time axis (windowed prefetch + seam overlap), all-gather ~KB
    accumulators, and finalize identical SweepResults — the road past a
    per-host wire ceiling (BENCHNOTES r4)."""
    _require_cpu_collectives()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fn = str(tmp_path / "big.fil")
    _write_fil(fn, dm=60.0, t0=6000, seed=3, T=8192)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = _TS_RANK_SCRIPT.format(repo=repo, fn=fn, out=str(tmp_path))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env[distributed.ENV_COORD] = f"127.0.0.1:{port}"
        env[distributed.ENV_NPROC] = "2"
        env[distributed.ENV_PID] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out}\n{err[-2000:]}"

    s0 = np.load(tmp_path / "ts_snr_rank0.npy")
    s1 = np.load(tmp_path / "ts_snr_rank1.npy")
    np.testing.assert_array_equal(s0, s1)  # identical result everywhere
    np.testing.assert_array_equal(np.load(tmp_path / "ts_peak_rank0.npy"),
                                  np.load(tmp_path / "ts_peak_rank1.npy"))
    # and it equals the sequential single-process sweep
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_flat

    dms = np.linspace(0.0, 100.0, 12)
    whole = sweep_flat(filterbank.FilterbankFile(fn), dms, nsub=8,
                       group_size=4, chunk_payload=2048).steps[0].result
    # ranks ran single-device CPU; this process compiles under the 8-way
    # virtual mesh conftest — different XLA reduction layouts move the
    # f32 chunk moments by ulps, so the cross-config check uses the
    # engine's documented f32 tolerance (ranks themselves match exactly)
    np.testing.assert_allclose(s0, whole.snr, rtol=1e-5, atol=1e-4)


def test_time_shard_events_match_flat(tmp_path):
    """--all-events composes with time sharding: window-local per-chunk
    peak records concatenate in rank order to exactly the sequential
    sweep's chunk sequence, so the multi-event list is identical."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_flat
    from pypulsar_tpu.parallel.sweep import finalize_sweep, merge_accum_parts

    fn = str(tmp_path / "tse.fil")
    # one pulse per window: t0=2000 lands in rank 0's half, and a second
    # injection at t=6.1 s in rank 1's half proves cross-window events
    from pypulsar_tpu.io.filterbank import FilterbankFile
    from pypulsar_tpu.io import filterbank as _fb_mod

    _write_fil(fn, dm=60.0, t0=2000, seed=7, T=8192)
    fb0 = FilterbankFile(fn)
    data = fb0.get_samples(0, 8192)
    freqs = 1500.0 - 2.0 * np.arange(32)
    bins = numpy_ref.bin_delays(60.0, freqs, 1e-3)
    for c in range(32):
        idx = 6100 + bins[c]
        if idx < 8192:
            data[idx, c] += 10.0
    hdr = dict(nchans=32, tsamp=1e-3, fch1=1500.0, foff=-2.0,
               tstart=55000.0, nbits=32, nifs=1, source_name="DTEST")
    _fb_mod.write_filterbank(fn, hdr, data)

    dms = np.linspace(0.0, 100.0, 12)
    whole_res = sweep_flat(FilterbankFile(fn), dms, nsub=8, group_size=4,
                           chunk_payload=2048,
                           keep_chunk_peaks=True).steps[0].result
    plan = None
    parts = []
    for rank in (0, 1):
        plan, acc = distributed.time_shard_local_accum(
            fn, dms, rank, 2, nsub=8, group_size=4, chunk_payload=2048,
            keep_chunk_peaks=True)
        parts.append(acc)
    assert len(parts[0].chunk_mb) + len(parts[1].chunk_mb) == 4
    merged = merge_accum_parts(parts)
    res = finalize_sweep(plan, merged.n, merged.s, merged.ss, merged.mb,
                         merged.ab, merged.baseline_sum,
                         chunk_mb=list(merged.chunk_mb),
                         chunk_ab=list(merged.chunk_ab))
    ev_whole = whole_res.events(6.0)
    ev_shard = res.events(6.0)
    assert len(ev_whole) == len(ev_shard) and ev_whole
    for a, b in zip(ev_whole, ev_shard):
        assert a == b
    # events from BOTH windows made it through the merge
    samples = [e["sample"] for e in ev_shard]
    assert min(samples) < 4096 <= max(samples)


def test_cli_time_shard_single_process(tmp_path, monkeypatch, capsys):
    """`sweep --time-shard` with no coordinator degenerates to the plain
    flat sweep and writes the same .cands."""
    from pypulsar_tpu.cli.sweep import main

    monkeypatch.chdir(tmp_path)
    _write_fil(str(tmp_path / "one.fil"), dm=60.0, t0=6000, seed=3, T=8192)
    rc = main(["one.fil", "--numdms", "12", "--dmstep", "9.0", "-s", "8",
               "--threshold", "7", "--chunk", "2048"])
    assert rc == 0
    plain = (tmp_path / "one.cands").read_text()
    os.remove(tmp_path / "one.cands")
    rc = main(["one.fil", "--numdms", "12", "--dmstep", "9.0", "-s", "8",
               "--threshold", "7", "--chunk", "2048", "--time-shard"])
    assert rc == 0
    assert (tmp_path / "one.cands").read_text() == plain

    # --all-events parity through the CLI (chunk peaks ride AccumParts)
    rc = main(["one.fil", "--numdms", "12", "--dmstep", "9.0", "-s", "8",
               "--threshold", "7", "--chunk", "2048", "--all-events",
               "-o", "ev_plain"])
    assert rc == 0
    rc = main(["one.fil", "--numdms", "12", "--dmstep", "9.0", "-s", "8",
               "--threshold", "7", "--chunk", "2048", "--all-events",
               "--time-shard", "-o", "ev_shard"])
    assert rc == 0
    assert ((tmp_path / "ev_shard.events").read_text()
            == (tmp_path / "ev_plain.events").read_text())
    assert ((tmp_path / "ev_shard.pulses").read_text()
            == (tmp_path / "ev_plain.pulses").read_text())


_TS_CLI_RANK_SCRIPT = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    os.chdir({out!r})
    rank = os.environ["PYPULSAR_TPU_PROCESS_ID"]
    from pypulsar_tpu.cli.sweep import main
    rc = main([{fn!r}, "--time-shard", "--numdms", "12", "--dmstep", "9.0",
               "-s", "8", "--threshold", "7", "--chunk", "2048",
               "--all-events"])
    assert rc == 0
    print("RANK", rank, "OK")
""")


def test_cli_time_shard_two_process(tmp_path):
    """`sweep --time-shard` under 2 real jax.distributed CPU ranks: each
    rank streams half the file, rank 0 writes the .cands, and it matches
    a plain single-process sweep of the whole file."""
    _require_cpu_collectives()
    from pypulsar_tpu.cli.sweep import main

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fn = str(tmp_path / "one.fil")
    _write_fil(fn, dm=60.0, t0=6000, seed=3, T=8192)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = _TS_CLI_RANK_SCRIPT.format(repo=repo, fn=fn, out=str(tmp_path))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env[distributed.ENV_COORD] = f"127.0.0.1:{port}"
        env[distributed.ENV_NPROC] = "2"
        env[distributed.ENV_PID] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out}\n{err[-2000:]}"
    sharded = (tmp_path / "one.cands").read_text()
    rows = [ln.split() for ln in sharded.splitlines()
            if ln.strip() and not ln.startswith("#")]
    assert rows, "no candidates written"
    # the injected DM=60 pulsar is the strongest candidate
    best = max(rows, key=lambda r: float(r[1]))
    assert abs(float(best[0]) - 60.0) <= 10.0
    assert float(best[1]) > 8.0
    # --all-events rode the cross-rank peak gather: event rows from BOTH
    # halves of the file made it into rank 0's artifact, and the plain
    # single-process run reproduces them byte-for-byte
    events = (tmp_path / "one.events").read_text()
    ev_rows = [ln.split() for ln in events.splitlines()
               if ln.strip() and not ln.startswith("#")]
    assert ev_rows  # the injected pulse (t=6.0 s, rank 1's window)
    assert any(abs(float(r[2]) - 6.0) < 0.1 for r in ev_rows)
    from pypulsar_tpu.cli.sweep import main as sweep_main
    import os as _os
    _cwd = _os.getcwd()
    _os.chdir(tmp_path)
    try:
        assert sweep_main([fn, "--numdms", "12", "--dmstep", "9.0",
                           "-s", "8", "--threshold", "7", "--chunk",
                           "2048", "--all-events", "-o", "seq"]) == 0
    finally:
        _os.chdir(_cwd)
    assert (tmp_path / "seq.events").read_text() == events


_CLI_RANK_SCRIPT = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    os.chdir({out!r})
    # rank from the env, NOT jax.process_index(): touching the backend
    # before the CLI's own distributed.initialize() would break init
    rank = os.environ["PYPULSAR_TPU_PROCESS_ID"]
    from pypulsar_tpu.cli.sweep import main
    rc = main([{f0!r}, {f1!r}, "--ddplan", "--hidm", "100", "-s", "8",
               "--group-size", "4", "--threshold", "6",
               "-o", "rank" + rank])
    assert rc == 0
    print("RANK", rank, "OK")
""")


def test_cli_sweep_ddplan_two_process(tmp_path):
    """The user-facing path (VERDICT r3 item 5): two jax.distributed CPU
    ranks run ``cli sweep --ddplan`` over two files; each rank writes the
    .cands artifact for its own file share and both write identical
    merged tables."""
    _require_cpu_collectives()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    f0 = str(tmp_path / "a.fil")
    f1 = str(tmp_path / "b.fil")
    _write_fil(f0, dm=40.0, t0=2000, seed=0)
    _write_fil(f1, dm=90.0, t0=5000, seed=1)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = _CLI_RANK_SCRIPT.format(repo=repo, f0=f0, f1=f1,
                                     out=str(tmp_path))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env[distributed.ENV_COORD] = f"127.0.0.1:{port}"
        env[distributed.ENV_NPROC] = "2"
        env[distributed.ENV_PID] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out}\n{err[-2000:]}"

    # per-file artifacts written by the owning rank (round-robin share)
    assert (tmp_path / "a.cands").exists()
    assert (tmp_path / "b.cands").exists()
    # each rank wrote a merged table; contents must be identical
    m0 = (tmp_path / "rank0_merged.cands").read_text()
    m1 = (tmp_path / "rank1_merged.cands").read_text()
    assert m0 == m1 and len(m0.splitlines()) > 2
    # both files' candidates are in the merged table
    assert "a.fil" in m0 and "b.fil" in m0


def test_multi_host_sweep_two_process(tmp_path):
    """Real jax.distributed: 2 CPU ranks, disjoint file shares, merged
    candidate tables identical on both ranks and covering both files."""
    _require_cpu_collectives()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    f0 = str(tmp_path / "a.fil")
    f1 = str(tmp_path / "b.fil")
    _write_fil(f0, dm=40.0, t0=2000, seed=0)
    _write_fil(f1, dm=90.0, t0=5000, seed=1)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = _RANK_SCRIPT.format(repo=repo, f0=f0, f1=f1, out=str(tmp_path))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # no virtual device mesh in the ranks
        env[distributed.ENV_COORD] = f"127.0.0.1:{port}"
        env[distributed.ENV_NPROC] = "2"
        env[distributed.ENV_PID] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out}\n{err[-2000:]}"

    m0 = np.load(tmp_path / "merged_rank0.npy")
    m1 = np.load(tmp_path / "merged_rank1.npy")
    np.testing.assert_array_equal(m0, m1)  # same merged table everywhere
    assert set(m0[:, 0].astype(int)) == {0, 1}  # both hosts' files present


def _write_fil8(path, dm, t0, seed, C=32, T=8192, dt=1e-3):
    """8-bit variant for the host-downsample wire-path tests."""
    from pypulsar_tpu.io import filterbank

    freqs = 1500.0 - 2.0 * np.arange(C)
    rng = np.random.RandomState(seed)
    data = rng.randint(0, 160, size=(T, C)).astype(np.uint8)
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for c in range(C):
        for k in range(4):
            idx = t0 + k + bins[c]
            if idx < T:
                data[idx, c] += 60
    hdr = dict(nchans=C, tsamp=dt, fch1=1500.0, foff=-2.0, tstart=55000.0,
               nbits=8, nifs=1, source_name="DTEST8")
    filterbank.write_filterbank(path, hdr, np.minimum(data, 255))


def test_host_downsample_matches_device_path(tmp_path, monkeypatch):
    """VERDICT r4 item 3: host-side downsample-before-wire (exact integer
    bin sums shipped as uint16) is bit-identical to the device
    downsample path, while shipping 2/factor B per raw sample."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import (_host_downsample_wins,
                                              _ReaderSource, sweep_flat)

    fn = str(tmp_path / "hds.fil")
    _write_fil8(fn, dm=60.0, t0=6000, seed=9)
    dms = np.linspace(0.0, 100.0, 12)
    src = _ReaderSource(filterbank.FilterbankFile(fn))
    assert _host_downsample_wins(src, 4)       # 2/4 < 1 B/sample
    assert not _host_downsample_wins(src, 2)   # 2/2 = 1 B/sample: no win
    monkeypatch.setenv("PYPULSAR_TPU_HOST_DOWNSAMP", "0")
    dev = sweep_flat(filterbank.FilterbankFile(fn), dms, downsamp=4,
                     nsub=8, group_size=4,
                     chunk_payload=1024).steps[0].result
    monkeypatch.setenv("PYPULSAR_TPU_HOST_DOWNSAMP", "1")
    host = sweep_flat(filterbank.FilterbankFile(fn), dms, downsamp=4,
                      nsub=8, group_size=4,
                      chunk_payload=1024).steps[0].result
    np.testing.assert_array_equal(host.snr, dev.snr)
    np.testing.assert_array_equal(host.peak_sample, dev.peak_sample)
    np.testing.assert_array_equal(host.mean, dev.mean)


def test_time_sharded_ddplan_single_count_matches_staged(tmp_path):
    """count=1 time_sharded_ddplan equals the sequential staged sweep."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_ddplan
    from pypulsar_tpu.plan.ddplan import Observation

    fn = str(tmp_path / "tsp.fil")
    _write_fil(fn, dm=60.0, t0=6000, seed=4)
    fil = filterbank.FilterbankFile(fn)
    obs = Observation(dt=1e-3, fctr=1469.0, BW=64.0, numchan=32)
    plan = obs.gen_ddplan(0.0, 120.0)
    seq = sweep_ddplan(fil, plan, nsub=8, group_size=4, chunk_payload=1024)
    ts = distributed.time_sharded_ddplan(
        filterbank.FilterbankFile(fn), plan, nsub=8, group_size=4,
        chunk_payload=1024, rank=0, count=1)
    assert len(ts.steps) == len(seq.steps)
    assert [s.downsamp for s in ts.steps] == [s.downsamp for s in seq.steps]
    for a, b in zip(ts.steps, seq.steps):
        np.testing.assert_allclose(a.result.snr, b.result.snr,
                                   rtol=1e-6, atol=1e-5)
        np.testing.assert_array_equal(a.result.peak_sample,
                                      b.result.peak_sample)
    best = ts.best(1)[0]
    assert abs(best["dm"] - 60.0) <= 6.0 and best["snr"] > 8.0


def test_time_sharded_ddplan_inprocess_merge_matches(tmp_path):
    """Two in-process windows per DDstep merge to the sequential staged
    result (the collective-free half of time_sharded_ddplan)."""
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.parallel.staged import sweep_ddplan
    from pypulsar_tpu.parallel.sweep import finalize_sweep, merge_accum_parts
    from pypulsar_tpu.plan.ddplan import Observation

    fn = str(tmp_path / "tsp2.fil")
    _write_fil8(fn, dm=60.0, t0=6000, seed=5)
    fil = filterbank.FilterbankFile(fn)
    obs = Observation(dt=1e-3, fctr=1469.0, BW=64.0, numchan=32)
    plan = obs.gen_ddplan(0.0, 1000.0)
    assert any(s.downsamp > 1 for s in plan.DDsteps)  # staged for real
    seq = sweep_ddplan(fil, plan, nsub=8, group_size=4, chunk_payload=1024)
    for i, st in enumerate(plan.DDsteps):
        parts = []
        sp = None
        for rank in (0, 1):
            sp, acc = distributed.time_shard_local_accum(
                fn, np.asarray(st.DMs), rank, 2, nsub=8, group_size=4,
                chunk_payload=1024, downsamp=int(st.downsamp))
            parts.append(acc)
        merged = merge_accum_parts(parts)
        res = finalize_sweep(sp, merged.n, merged.s, merged.ss, merged.mb,
                             merged.ab, merged.baseline_sum)
        np.testing.assert_array_equal(res.peak_sample,
                                      seq.steps[i].result.peak_sample)
        np.testing.assert_allclose(res.snr, seq.steps[i].result.snr,
                                   rtol=1e-9, atol=1e-9)


_TS_DDPLAN_CLI_RANK_SCRIPT = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    os.chdir({out!r})
    rank = os.environ["PYPULSAR_TPU_PROCESS_ID"]
    from pypulsar_tpu.cli.sweep import main
    rc = main([{fn!r}, "--time-shard", "--ddplan", "--hidm", "1000",
               "-s", "8", "--group-size", "4", "--threshold", "7",
               "--chunk", "1024"])
    assert rc == 0
    print("RANK", rank, "OK")
""")


def test_cli_time_shard_ddplan_two_process(tmp_path):
    """`sweep --time-shard --ddplan` (VERDICT r4 item 3) under 2 real
    jax.distributed CPU ranks: every DDstep's time axis splits across
    ranks, rank 0 writes the .cands, and the artifact equals the
    sequential single-process --ddplan run bit-for-bit."""
    _require_cpu_collectives()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fn = str(tmp_path / "tsdd.fil")
    _write_fil8(fn, dm=60.0, t0=6000, seed=3)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = _TS_DDPLAN_CLI_RANK_SCRIPT.format(repo=repo, fn=fn,
                                               out=str(tmp_path))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env[distributed.ENV_COORD] = f"127.0.0.1:{port}"
        env[distributed.ENV_NPROC] = "2"
        env[distributed.ENV_PID] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out}\n{err[-2000:]}"
    sharded = (tmp_path / "tsdd.cands").read_text()
    rows = [ln.split() for ln in sharded.splitlines()
            if ln.strip() and not ln.startswith("#")]
    assert rows, "no candidates written"
    best = max(rows, key=lambda r: float(r[1]))
    assert abs(float(best[0]) - 60.0) <= 17.0
    assert float(best[1]) > 8.0
    # sequential single-process --ddplan reproduces the artifact
    from pypulsar_tpu.cli.sweep import main as sweep_main
    _cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        assert sweep_main([fn, "--ddplan", "--hidm", "1000", "-s", "8",
                           "--group-size", "4", "--threshold", "7",
                           "--chunk", "1024", "-o", "seqdd"]) == 0
    finally:
        os.chdir(_cwd)
    assert (tmp_path / "seqdd.cands").read_text() == sharded


_TS_DATS_CLI_RANK_SCRIPT = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    os.chdir({out!r})
    rank = os.environ["PYPULSAR_TPU_PROCESS_ID"]
    from pypulsar_tpu.cli.sweep import main
    rc = main([{fn!r}, "--time-shard", "--numdms", "3", "--dmstep", "30.0",
               "-s", "8", "--group-size", "4", "--threshold", "7",
               "--chunk", "1024", "--write-dats"])
    assert rc == 0
    print("RANK", rank, "OK")
""")


def test_cli_time_shard_write_dats_two_process(tmp_path):
    """`sweep --time-shard --write-dats` (VERDICT r4 item 3): each rank
    writes its window's .dat segments, rank 0 concatenates — the result
    is bit-identical to the single-process streamed writer, with .inf
    sidecars carrying the full length."""
    _require_cpu_collectives()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fn = str(tmp_path / "tswd.fil")
    _write_fil8(fn, dm=60.0, t0=6000, seed=7)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = _TS_DATS_CLI_RANK_SCRIPT.format(repo=repo, fn=fn,
                                             out=str(tmp_path))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env[distributed.ENV_COORD] = f"127.0.0.1:{port}"
        env[distributed.ENV_NPROC] = "2"
        env[distributed.ENV_PID] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out}\n{err[-2000:]}"
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.io.infodata import InfoData
    from pypulsar_tpu.parallel.staged import write_dats_streamed

    dms = [0.0, 30.0, 60.0]
    ref_out = str(tmp_path / "refdats")
    write_dats_streamed(ref_out, filterbank.FilterbankFile(fn), dms,
                        nsub=8, group_size=4, chunk_payload=1024)
    for dm in dms:
        got = np.fromfile(tmp_path / f"tswd_DM{dm:.2f}.dat", np.float32)
        ref = np.fromfile(f"{ref_out}_DM{dm:.2f}.dat", np.float32)
        np.testing.assert_array_equal(got, ref)
        assert not (tmp_path / f"tswd_DM{dm:.2f}.w0.dat").exists()
        inf = InfoData(str(tmp_path / f"tswd_DM{dm:.2f}.inf"))
        assert int(inf.N) == 8192


def test_reroot_source_windowed_and_masked(tmp_path):
    """_reroot_source (seek-resume) preserves a window's end bound and
    the mask wrapper, and the re-rooted stream yields the same blocks
    the original stream yields past the cursor."""
    from pypulsar_tpu.parallel.staged import _ReaderSource, _reroot_source
    from pypulsar_tpu.io import filterbank

    fn = str(tmp_path / "rr.fil")
    _write_fil8(fn, dm=60.0, t0=6000, seed=2)
    src = _ReaderSource(filterbank.FilterbankFile(fn), 0, 6144)
    seeked = _reroot_source(src, 2048)
    assert (seeked.start, seeked.end) == (2048, 6144)
    orig = [(p, np.asarray(b)) for p, b in
            src.chan_major_blocks(2048, 64)]
    re = [(p, np.asarray(b)) for p, b in
          seeked.chan_major_blocks(2048, 64)]
    assert [p for p, _ in re] == [p for p, _ in orig if p >= 2048]
    for (p1, b1), (p2, b2) in zip(re, [o for o in orig if o[0] >= 2048]):
        np.testing.assert_array_equal(b1, b2)
