"""Batched fold pipeline tests (round 8): the batched kernels against
their per-candidate golden twins (bit-identical f64 accumulation; device
within the SNR contract), device (p, pdot) refinement against a NumPy
refold-based grid on a toy pulsar, `foldbatch` archives byte-identical to
the serial per-candidate `prepfold` loop, kill/resume through the
journal, OOM halving on the candidate axis, DM-group slicing, and the
telemetry counters visible in tlmsum — mirroring test_accel_pipeline
structure for the fold stage."""

import glob
import json
import os

import numpy as np
import pytest

from pypulsar_tpu.core import psrmath
from pypulsar_tpu.resilience import faultinject


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.reset()
    yield
    faultinject.reset()


def _toy_dat(tmp_path, dm, N=1 << 14, dt=1e-3, period=0.0517, pdot=0.0,
             amp=3.0, seed=None):
    """A .dat/.inf pair with an injected pulse train at (period, pdot)."""
    from pypulsar_tpu.io.datfile import write_dat
    from pypulsar_tpu.io.infodata import InfoData

    rng = np.random.RandomState(int(dm) if seed is None else seed)
    t = np.arange(N) * dt
    f0, f1, _ = psrmath.p_to_f(period, pdot, 0.0)
    phase = t * (f0 + t * (f1 / 2.0))
    ts = rng.standard_normal(N).astype(np.float32)
    ts += amp * np.exp(-0.5 * ((phase % 1.0 - 0.4) / 0.03) ** 2
                       ).astype(np.float32)
    inf = InfoData()
    inf.epoch, inf.dt, inf.N = 55000.0, dt, N
    inf.telescope, inf.object = "Fake", "FOLDPIPE"
    inf.lofreq, inf.BW, inf.numchan, inf.chan_width = 1400.0, 100.0, 1, 100.0
    inf.DM = dm
    base = str(tmp_path / f"toy_DM{dm:.2f}")
    write_dat(base, ts, inf)
    return base + ".dat", ts


def _cands_file(tmp_path, rows, name="cands.txt"):
    fn = str(tmp_path / name)
    with open(fn, "w") as f:
        f.write("# period_s dm [pdot]\n")
        for row in rows:
            f.write(" ".join(repr(x) for x in row) + "\n")
    return fn


# ---------------------------------------------------------------------------
# kernel parity
# ---------------------------------------------------------------------------

def test_fold_parts_batch_golden_twins():
    """Batched fold vs per-candidate twins: the f64 NumPy batch twin is
    BIT-identical to folding each candidate alone with fold_numpy, and
    the device batch matches it within f32 accumulation (counts exact);
    the device batch is also bitwise equal to the serial per-candidate
    device path (fold_parts at C=1) — the archive-parity foundation."""
    from pypulsar_tpu.fold.engine import (
        fold_numpy,
        fold_parts,
        fold_parts_batch,
        fold_parts_batch_numpy,
        phase_to_bins,
    )

    rng = np.random.RandomState(0)
    T, nbins, npart, dt = 1 << 13, 32, 8, 1e-3
    series = rng.standard_normal(T).astype(np.float32)
    periods = [0.0517, 0.0731, 0.0213, 0.1024, 0.0099]
    bin_idx = np.stack([phase_to_bins(np.arange(T) * dt / p, nbins)
                        for p in periods])
    profs, counts = fold_parts_batch(series, bin_idx, nbins, npart)
    profs, counts = np.asarray(profs), np.asarray(counts)
    pN, cN = fold_parts_batch_numpy(series, bin_idx, nbins, npart)

    part_len = T // npart
    for k in range(len(periods)):
        for i in range(npart):
            sl = slice(i * part_len, (i + 1) * part_len)
            p1, c1 = fold_numpy(series[sl].astype(np.float64),
                                bin_idx[k, sl], nbins)
            np.testing.assert_array_equal(pN[k, i], p1)  # bit-identical
            np.testing.assert_array_equal(cN[k, i], c1.astype(np.int64))
    np.testing.assert_array_equal(counts, cN)
    np.testing.assert_allclose(profs, pN, rtol=1e-5, atol=1e-3)

    for k in range(len(periods)):
        pk, _ = fold_parts(series[None, :], bin_idx[k], nbins, npart)
        np.testing.assert_array_equal(np.asarray(pk)[:, 0, :], profs[k])


def test_refine_device_vs_numpy_refold_grid():
    """Device (p, pdot) refinement vs a NumPy REFOLD-based grid on a toy
    pulsar: folding the data at every trial (p, pd) and scoring chi2
    must crown the same grid winner the rotation kernel finds without a
    single refold, and the winner must sit within one grid step of the
    injected truth."""
    from pypulsar_tpu.fold.engine import (
        drift_offsets,
        drift_to_p_pd,
        fold_numpy,
        fold_parts_batch,
        phase_to_bins,
        refine_chi2,
        refine_drift_grid,
    )

    rng = np.random.RandomState(3)
    T, nbins, npart, dt = 1 << 15, 64, 16, 1e-3
    P = 0.0517
    T_sec = T * dt
    dp_true, pd_true = 4e-5, 0.0
    t = np.arange(T) * dt
    f0, f1, _ = psrmath.p_to_f(P + dp_true, pd_true, 0.0)
    phase_true = t * (f0 + t * (f1 / 2.0))
    sig = 5.0 * np.exp(-0.5 * ((phase_true % 1.0 - 0.5) / 0.04) ** 2
                       ).astype(np.float32)
    sig += 0.1 * rng.standard_normal(T).astype(np.float32)

    bin_idx = phase_to_bins(t / P, nbins)[None, :]
    part_profs, _ = fold_parts_batch(sig, bin_idx, nbins, npart)
    dl, dq = refine_drift_grid(21, 5, 2.0)
    chi2 = np.asarray(refine_chi2(part_profs, drift_offsets(dl, dq, npart)))

    # refold-based reference: fold the DATA at each trial's (p, pd)
    chi2_refold = np.empty(len(dl))
    for j in range(len(dl)):
        pj, pdj = drift_to_p_pd(dl[j], dq[j], P, 0.0, T_sec)
        fj0, fj1, _ = psrmath.p_to_f(pj, pdj, 0.0)
        bi = phase_to_bins(t * (fj0 + t * (fj1 / 2.0)), nbins)
        prof, _ = fold_numpy(sig.astype(np.float64), bi, nbins)
        chi2_refold[j] = ((prof - prof.mean()) ** 2).sum()

    jdev, jref = int(chi2[0].argmax()), int(chi2_refold.argmax())
    assert jdev == jref, (jdev, jref)
    best_p, best_pd = drift_to_p_pd(dl[jdev], dq[jdev], P, 0.0, T_sec)
    dp_spacing = (4.0 / 20) * P * P / T_sec
    pd_spacing = (4.0 / 4) * 2 * P * P / (T_sec * T_sec)
    assert abs(best_p - (P + dp_true)) <= dp_spacing
    assert abs(best_pd - pd_true) <= pd_spacing


# ---------------------------------------------------------------------------
# foldbatch vs serial prepfold (the acceptance contract)
# ---------------------------------------------------------------------------

def test_foldbatch_archives_match_serial_prepfold(tmp_path, monkeypatch):
    """N=32 toy candidates through `foldbatch` produce archives whose
    profile and stats arrays are BYTE-identical to per-candidate serial
    `prepfold` runs on the same series, and whose derived SNRs agree
    within the <=2e-6 contract (they are equal: same bytes in, same
    float pipeline)."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch
    from pypulsar_tpu.cli import prepfold as cli_prepfold
    from pypulsar_tpu.fold import profile_snr
    from pypulsar_tpu.io.prestopfd import PfdFile

    monkeypatch.chdir(tmp_path)
    rows = []
    for d, dm in enumerate((10.0, 20.0, 30.0, 40.0)):
        _toy_dat(tmp_path, dm, period=0.0517 * (1 + 0.13 * d))
        rows += [(0.0517 * (1 + 0.13 * d) * (1 + 0.021 * j), dm)
                 for j in range(8)]
    cands = _cands_file(tmp_path, rows)
    assert cli_foldbatch.main(["--cands", cands, "--datbase", "toy",
                               "-o", "bb", "-n", "32", "--npart", "8"]) == 0
    summary = json.load(open("bb_foldbatch.json"))
    assert summary["n_folded"] == 32

    snr_max_diff = 0.0
    n_scored = 0
    for i, ((p, dm), res) in enumerate(zip(rows, summary["results"])):
        out = f"serial_{i:04d}.pfd"
        assert cli_prepfold.main([f"toy_DM{dm:.2f}.dat", "-p", repr(p),
                                  "--dm", str(dm), "-n", "32",
                                  "--npart", "8", "-o", out]) == 0
        a, b = PfdFile(out), PfdFile(res["pfd"])
        np.testing.assert_array_equal(a.profs, b.profs)
        np.testing.assert_array_equal(a.stats, b.stats)
        try:
            sa = profile_snr.pfd_snr(a)["snr"]
            sb = profile_snr.pfd_snr(b)["snr"]
            snr_max_diff = max(snr_max_diff, abs(sa - sb))
            n_scored += 1
        except profile_snr.OnPulseError:
            pass
    assert n_scored > 0
    assert snr_max_diff <= 2e-6


def test_foldbatch_refinement_recovers_injected_drift(tmp_path,
                                                      monkeypatch):
    """A candidate folded slightly off the injected period gets its
    refined (p, pdot) pulled toward the truth in the foldbatch summary."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch

    monkeypatch.chdir(tmp_path)
    P, dp = 0.0517, 5e-5
    _toy_dat(tmp_path, 15.0, N=1 << 15, period=P + dp, amp=6.0)
    cands = _cands_file(tmp_path, [(P, 15.0)])
    assert cli_foldbatch.main(["--cands", cands, "--datbase", "toy",
                               "-o", "rf", "-n", "64", "--npart", "16",
                               "--ntrial-p", "33", "--ntrial-pd", "5"]) == 0
    res = json.load(open("rf_foldbatch.json"))["results"][0]
    # refined period is closer to the truth than the fold period was
    assert abs(res["best_period"] - (P + dp)) < abs(P - (P + dp))
    assert res["chi2_best"] >= res["chi2_nominal"]


# ---------------------------------------------------------------------------
# resilience: kill/resume, OOM halving, prep failure
# ---------------------------------------------------------------------------

def _fold_args(cands, out, journal=None):
    argv = ["--cands", cands, "--datbase", "toy", "-o", out, "-n", "32",
            "--npart", "8", "--ntrial-p", "9", "--ntrial-pd", "3"]
    if journal:
        argv += ["--journal", journal]
    return argv


def test_foldbatch_kill_resume_journal_identical(tmp_path, monkeypatch):
    """A run killed mid-batch (after some archives + journal records)
    resumes from the journal: finished candidates are skipped, the rest
    fold, and every final archive is byte-identical to an uninterrupted
    run's — the journal-identical acceptance proof."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch

    monkeypatch.chdir(tmp_path)
    rows = []
    for d, dm in enumerate((10.0, 20.0)):
        _toy_dat(tmp_path, dm)
        rows += [(0.0517 * (1 + 0.021 * j), dm) for j in range(4)]
    cands = _cands_file(tmp_path, rows)

    assert cli_foldbatch.main(_fold_args(cands, "ref")) == 0
    ref = {os.path.basename(f)[len("ref_"):]: open(f, "rb").read()
           for f in sorted(glob.glob("ref_cand*.pfd"))}
    assert len(ref) == 8

    # kill after the 3rd journal record: mid-run, past whole+partial work
    with pytest.raises(faultinject.InjectedKill):
        cli_foldbatch.main(_fold_args(cands, "kk", journal="kk.jsonl")
                           + ["--fault-inject", "kill:fold.after_journal:3"])
    done = sorted(glob.glob("kk_cand*.pfd"))
    assert 0 < len(done) < 8

    # stale tmp debris on a candidate the kill left UNfolded (a kill mid
    # pfd.write leaves exactly this): the resume must clean it up
    unfolded = sorted(set(ref) - {os.path.basename(f)[len("kk_"):]
                                  for f in glob.glob("kk_cand*.pfd")})[0]
    with open("kk_" + unfolded + ".tmp", "wb") as f:
        f.write(b"stale writer debris")
    assert cli_foldbatch.main(_fold_args(cands, "kk",
                                         journal="kk.jsonl")) == 0
    got = {os.path.basename(f)[len("kk_"):]: open(f, "rb").read()
           for f in sorted(glob.glob("kk_cand*.pfd"))}
    assert got == ref
    assert not glob.glob("kk_cand*.pfd.tmp")
    # the journal recorded every unit exactly once across both runs
    units = [json.loads(ln)["unit"] for ln in open("kk.jsonl")
             if json.loads(ln).get("type") == "done"]
    assert len(units) == len(set(units)) == 8

    # the resumed summary backfills refined (p, pdot) for candidates the
    # FIRST (killed) run folded: they ride the journal's fold_result
    # notes, so the overwritten summary JSON still carries them all
    summary = json.load(open("kk_foldbatch.json"))
    assert len(summary["results"]) == 8
    assert all("best_period" in r for r in summary["results"])
    ref_summary = {r["name"]: r for r in
                   json.load(open("ref_foldbatch.json"))["results"]}
    for r in summary["results"]:
        assert r["best_period"] == ref_summary[r["name"]]["best_period"]


def test_missing_dat_fails_group_not_run(tmp_path, monkeypatch):
    """A missing/unreadable per-DM .dat fails only ITS candidates: the
    remaining groups still fold, the summary is written, and the CLI
    exits 1 to flag the partial failure."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch

    monkeypatch.chdir(tmp_path)
    _toy_dat(tmp_path, 10.0)  # DM 20 .dat deliberately absent
    rows = [(0.0517, 10.0), (0.0731, 10.0), (0.0517, 20.0)]
    cands = _cands_file(tmp_path, rows)
    assert cli_foldbatch.main(_fold_args(cands, "md")) == 1
    assert len(glob.glob("md_cand*_DM10.00_*.pfd")) == 2
    assert not glob.glob("md_cand*_DM20.00_*.pfd")
    summary = json.load(open("md_foldbatch.json"))
    assert summary["n_folded"] == 2 and summary["n_failed"] == 1
    # the summary enumerates the failure, not just counts it
    failed = [r for r in summary["results"] if r.get("failed")]
    assert len(failed) == 1 and "DM20.00" in failed[0]["name"]
    assert len(summary["results"]) == 3


def test_journal_fingerprint_covers_dat_source(tmp_path, monkeypatch):
    """A journaled run re-pointed at a DIFFERENT .dat set must restart,
    not skip units folded from the other data (the dats source identity
    is part of the run fingerprint, like the stream tag)."""
    import shutil

    from pypulsar_tpu.cli import foldbatch as cli_foldbatch

    monkeypatch.chdir(tmp_path)
    _toy_dat(tmp_path, 10.0)
    shutil.copy("toy_DM10.00.dat", "other_DM10.00.dat")
    shutil.copy("toy_DM10.00.inf", "other_DM10.00.inf")
    cands = _cands_file(tmp_path, [(0.0517, 10.0)])
    assert cli_foldbatch.main(_fold_args(cands, "fp",
                                         journal="fp.jsonl")) == 0
    argv = ["--cands", cands, "--datbase", "other", "-o", "fp", "-n",
            "32", "--npart", "8", "--ntrial-p", "9", "--ntrial-pd", "3",
            "--journal", "fp.jsonl"]
    assert cli_foldbatch.main(argv) == 0
    # the other-base run REFOLDED (fingerprint mismatch restarts the
    # journal) instead of trusting the toy-base archive
    s = json.load(open("fp_foldbatch.json"))
    assert s["n_folded"] == 1 and s["n_skipped"] == 0


def test_foldbatch_skip_existing_validates(tmp_path, monkeypatch):
    """--skip-existing trusts only archives that PARSE complete: debris
    (a truncated .pfd from a kill) is refolded, finished ones skip."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch

    monkeypatch.chdir(tmp_path)
    _toy_dat(tmp_path, 10.0)
    rows = [(0.0517 * (1 + 0.021 * j), 10.0) for j in range(3)]
    cands = _cands_file(tmp_path, rows)
    assert cli_foldbatch.main(_fold_args(cands, "sk")) == 0
    pfds = sorted(glob.glob("sk_cand*.pfd"))
    assert len(pfds) == 3
    blob = open(pfds[0], "rb").read()
    with open(pfds[0], "wb") as f:
        f.write(blob[: len(blob) // 2])  # truncation debris
    assert cli_foldbatch.main(_fold_args(cands, "sk")
                              + ["--skip-existing"]) == 0
    assert open(pfds[0], "rb").read() == blob  # refolded, bit-identical


def test_foldbatch_oom_halves_candidate_axis(tmp_path, monkeypatch):
    """An injected device OOM on the batched fold dispatch halves the
    CANDIDATE axis and recovers bit-identically (per-candidate folds are
    independent), with the backoff visible on the telemetry counters."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch
    from pypulsar_tpu.obs import telemetry

    monkeypatch.chdir(tmp_path)
    _toy_dat(tmp_path, 10.0)
    rows = [(0.0517 * (1 + 0.013 * j), 10.0) for j in range(6)]
    cands = _cands_file(tmp_path, rows)
    assert cli_foldbatch.main(_fold_args(cands, "aa")) == 0
    ref = {os.path.basename(f)[3:]: open(f, "rb").read()
           for f in sorted(glob.glob("aa_cand*.pfd"))}

    with telemetry.session() as tlm:
        assert cli_foldbatch.main(
            _fold_args(cands, "bb")
            + ["--fault-inject", "oom:fold.batch_dispatch"]) == 0
        totals = tlm.counter_totals()
    assert totals.get("resilience.oom_backoffs", 0) >= 1
    got = {os.path.basename(f)[3:]: open(f, "rb").read()
           for f in sorted(glob.glob("bb_cand*.pfd"))}
    assert got == ref


def test_foldbatch_device_failure_falls_back_numpy(tmp_path, monkeypatch):
    """A non-OOM device failure degrades the group to the NumPy twin
    fold (profiles within f32 tolerance of the device result) instead of
    failing the run."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch
    from pypulsar_tpu.fold import engine as fold_engine
    from pypulsar_tpu.io.prestopfd import PfdFile

    monkeypatch.chdir(tmp_path)
    _toy_dat(tmp_path, 10.0)
    rows = [(0.0517, 10.0), (0.0731, 10.0)]
    cands = _cands_file(tmp_path, rows)
    assert cli_foldbatch.main(_fold_args(cands, "dd")) == 0

    def boom(*a, **kw):
        raise RuntimeError("synthetic device fold failure")

    monkeypatch.setattr(fold_engine, "_fold_parts_batch_jit", boom)
    assert cli_foldbatch.main(_fold_args(cands, "nn")) == 0
    for fd in sorted(glob.glob("dd_cand*.pfd")):
        fn = "nn" + os.path.basename(fd)[2:]
        a, b = PfdFile(fd), PfdFile(fn)
        np.testing.assert_allclose(a.profs, b.profs, rtol=1e-5, atol=1e-3)


# ---------------------------------------------------------------------------
# DM-group slicing + sources
# ---------------------------------------------------------------------------

def test_dm_group_slicing_and_batch_cap(tmp_path, monkeypatch):
    """Candidates across DMs group by DM; a batch cap splits one DM's
    list into sub-batches sharing the series; every archive lands with
    its own (dm, period) regardless of the slicing."""
    from pypulsar_tpu.io.prestopfd import PfdFile
    from pypulsar_tpu.parallel.foldpipe import FoldCandidate, fold_pipeline

    monkeypatch.chdir(tmp_path)
    for dm in (10.0, 20.0, 30.0):
        _toy_dat(tmp_path, dm)
    cands = [FoldCandidate(0.0517 * (1 + 0.017 * j), dm)
             for dm in (10.0, 30.0, 20.0) for j in range(5)]
    s = fold_pipeline(cands, "gg", source="dats",
                      dat_for_dm=lambda dm: f"toy_DM{dm:.2f}.dat",
                      nbins=32, npart=8, batch=2, ntrial_p=5, ntrial_pd=1)
    assert s["n_folded"] == 15
    by_name = {r["name"]: r for r in s["results"]}
    assert len(by_name) == 15
    for i, c in enumerate(cands):
        name = f"cand{i:04d}_DM{c.dm:.2f}_{c.period * 1e3:.4f}ms"
        p = PfdFile(f"gg_{name}.pfd")
        assert p.bestdm == c.dm
        assert abs(p.curr_p1 - c.period) < 1e-12
        assert p.profs.shape == (8, 1, 32)

    # second run with skip_existing: everything validated, nothing redone
    s2 = fold_pipeline(cands, "gg", source="dats",
                       dat_for_dm=lambda dm: f"toy_DM{dm:.2f}.dat",
                       nbins=32, npart=8, batch=2, ntrial_p=5,
                       ntrial_pd=1, skip_existing=True)
    assert s2["n_skipped"] == 15 and s2["n_folded"] == 0


def test_foldbatch_stream_source_recovers_pulsar(tmp_path, monkeypatch):
    """The streamed source (raw .fil, no .dat round trip) folds the
    sifted DM's candidates off the sweep chunk kernel's series and
    recovers the injected pulsar's phase-coherent profile."""
    from tests.test_accel_pipeline import _pulsar_fil

    from pypulsar_tpu.cli import foldbatch as cli_foldbatch
    from pypulsar_tpu.io.prestopfd import PfdFile

    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)  # P=102.4 ms at DM 40
    cands = _cands_file(tmp_path, [(0.1024, 40.0), (0.1024, 20.0)])
    assert cli_foldbatch.main([fil, "--cands", cands, "-o", "st",
                               "-n", "64", "--npart", "8", "-s", "8",
                               "--group-size", "4"]) == 0
    pfds = sorted(glob.glob("st_cand*.pfd"))
    assert len(pfds) == 2

    def contrast(fn):
        prof = PfdFile(fn).sumprof
        return (prof.max() - np.median(prof)) / max(prof.std(), 1e-9)

    # at the true DM the fold is sharp; 20 DM units off, smeared
    c40 = contrast([f for f in pfds if "_DM40.00_" in f][0])
    c20 = contrast([f for f in pfds if "_DM20.00_" in f][0])
    assert c40 > c20
    # the archive records the FULL integrated band (pfd_snr's radiometer
    # bw = chan_wid * numchan), not one raw channel's width
    p = PfdFile(pfds[0])
    assert p.numchan == 32
    assert p.chan_wid * p.numchan == pytest.approx(4.0 * 32, rel=0.05)


def test_stream_ram_budget_slices_identical(tmp_path, monkeypatch):
    """A fold series buffer over PYPULSAR_TPU_FOLD_STREAM_RAM streams in
    group-aligned DM slices with byte-identical archives."""
    from tests.test_accel_pipeline import _pulsar_fil

    from pypulsar_tpu.cli import foldbatch as cli_foldbatch

    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    rows = [(0.1024, dm) for dm in (0.0, 10.0, 20.0, 30.0, 40.0, 50.0)]
    cands = _cands_file(tmp_path, rows)
    argv = [fil, "--cands", cands, "-n", "32", "--npart", "8", "-s", "8",
            "--group-size", "2"]
    assert cli_foldbatch.main(argv + ["-o", "full"]) == 0
    fulls = sorted(glob.glob("full_cand*.pfd"))
    assert len(fulls) == 6
    # budget for ~3 trials, NOT a multiple of --group-size 2 after the
    # floor divide: must round to group boundaries
    monkeypatch.setenv("PYPULSAR_TPU_FOLD_STREAM_RAM",
                       str(4 * 16384 * 3))
    assert cli_foldbatch.main(argv + ["-o", "sl"]) == 0
    for ff in fulls:
        fs = "sl" + os.path.basename(ff)[4:]
        assert open(ff, "rb").read() == open(fs, "rb").read(), ff


def test_stream_kill_resume_byte_identical(tmp_path, monkeypatch):
    """A STREAMED run killed mid-fold resumes from the journal with
    byte-identical archives: the resumed pass re-plans grouping and
    slice boundaries over the FULL candidate DM grid (not just the
    remaining DMs), so the surviving trials dedisperse from the same
    group-mean series as the uninterrupted run."""
    from tests.test_accel_pipeline import _pulsar_fil

    from pypulsar_tpu.cli import foldbatch as cli_foldbatch

    monkeypatch.chdir(tmp_path)
    fil = _pulsar_fil(tmp_path)
    rows = [(0.1024 * (1 + 0.1 * j), dm)
            for dm in (0.0, 10.0, 20.0, 30.0, 40.0, 50.0)
            for j in range(2)]
    cands = _cands_file(tmp_path, rows)
    argv = [fil, "--cands", cands, "-n", "32", "--npart", "8", "-s", "8",
            "--group-size", "2"]
    assert cli_foldbatch.main(argv + ["-o", "un"]) == 0
    ref = {os.path.basename(f)[len("un_"):]: open(f, "rb").read()
           for f in sorted(glob.glob("un_cand*.pfd"))}
    assert len(ref) == 12

    with pytest.raises(faultinject.InjectedKill):
        cli_foldbatch.main(argv + ["-o", "ks", "--journal", "ks.jsonl",
                                   "--fault-inject",
                                   "kill:fold.after_journal:5"])
    assert 0 < len(glob.glob("ks_cand*.pfd")) < 12
    assert cli_foldbatch.main(argv + ["-o", "ks", "--journal",
                                      "ks.jsonl"]) == 0
    got = {os.path.basename(f)[len("ks_"):]: open(f, "rb").read()
           for f in sorted(glob.glob("ks_cand*.pfd"))}
    assert got == ref


def test_prefetch_zero_inline_identical(tmp_path, monkeypatch):
    """--prefetch 0 (inline prep, no worker thread) produces identical
    archives — the pipeline moves WHEN prep happens, never the values."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch

    monkeypatch.chdir(tmp_path)
    for dm in (10.0, 20.0):
        _toy_dat(tmp_path, dm)
    rows = [(0.0517 * (1 + 0.021 * j), dm) for dm in (10.0, 20.0)
            for j in range(3)]
    cands = _cands_file(tmp_path, rows)
    assert cli_foldbatch.main(_fold_args(cands, "pf")) == 0
    assert cli_foldbatch.main(_fold_args(cands, "pz")
                              + ["--prefetch", "0"]) == 0
    fulls = sorted(glob.glob("pf_cand*.pfd"))
    assert len(fulls) == 6
    for fp in fulls:
        fz = "pz" + os.path.basename(fp)[2:]
        assert open(fp, "rb").read() == open(fz, "rb").read(), fp


# ---------------------------------------------------------------------------
# CLI surface: sift --fold, prepfold --cands, pfd_snr batch
# ---------------------------------------------------------------------------

def test_sift_fold_closes_chain(tmp_path, monkeypatch):
    """raw -> sweep --write-dats -> accelsearch -> sift --fold -> .pfd:
    the whole chain in-tree, ending in archives for every sifted
    candidate."""
    from tests.test_accel_pipeline import (
        ACCEL_ARGS,
        SWEEP_ARGS,
        _pulsar_fil,
    )

    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.cli import sift as cli_sift
    from pypulsar_tpu.cli import sweep as cli_sweep

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    fil = _pulsar_fil(tmp_path)
    assert cli_sweep.main([fil, "-o", "ch", *SWEEP_ARGS,
                           "--write-dats"]) == 0
    dats = sorted(glob.glob("ch_DM*.dat"))
    assert cli_accel.main([*dats, "--batch", "4", *ACCEL_ARGS]) == 0
    cands = sorted(glob.glob("ch_DM*_ACCEL_20.cand"))
    sift_argv = [*cands, "-o", "ch.accelcands", "--fold",
                 "--fold-nbins", "32", "--fold-npart", "8",
                 "--min-sigma", "8", "--journal", "ch.jsonl"]
    assert cli_sift.main(sift_argv) == 0
    from pypulsar_tpu.io.accelcands import parse_candlist

    sifted = parse_candlist("ch.accelcands")
    pfds = sorted(glob.glob("ch_cand*.pfd"))
    assert len(pfds) == len(sifted) > 0
    blobs = {p: open(p, "rb").read() for p in pfds}

    # a rerun whose sift unit validates in the journal must STILL fold:
    # archives lost after the sift completed (e.g. a kill during --fold)
    # reappear BYTE-identical (both passes fold the written .accelcands)
    # while surviving complete archives are skipped, not rewritten
    for p in pfds[: len(pfds) // 2 + 1]:
        os.remove(p)
    assert cli_sift.main(sift_argv) == 0
    assert sorted(glob.glob("ch_cand*.pfd")) == pfds
    for p in pfds:
        assert open(p, "rb").read() == blobs[p], p

    # --fold without -o is an error, not a silently unnamed fold
    with pytest.raises(SystemExit):
        cli_sift.main([*cands, "--fold"])


def test_sift_fold_missing_dats_errors(tmp_path, monkeypatch):
    """sift --fold without the .dat series fails loudly with guidance,
    not silently or with a traceback."""
    from tests.test_accel_pipeline import (
        ACCEL_ARGS,
        SWEEP_ARGS,
        _pulsar_fil,
    )

    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.cli import sift as cli_sift
    from pypulsar_tpu.cli import sweep as cli_sweep

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    fil = _pulsar_fil(tmp_path)
    assert cli_sweep.main([fil, "-o", "md", *SWEEP_ARGS,
                           "--write-dats"]) == 0
    dats = sorted(glob.glob("md_DM*.dat"))
    assert cli_accel.main([*dats, "--batch", "4", *ACCEL_ARGS]) == 0
    for d in dats:
        os.remove(d)
    cands = sorted(glob.glob("md_DM*_ACCEL_20.cand"))
    rc = cli_sift.main([*cands, "-o", "md.accelcands", "--fold",
                        "--min-sigma", "8"])
    assert rc == 1
    assert not glob.glob("md_cand*.pfd")


def test_sift_fold_dm_text_roundtrip(tmp_path, monkeypatch):
    """The .dat join key survives DMs that do not round-trip through the
    .accelcands %.2f text (~1 in 5 grid DMs): a candidate parsed back as
    147.33 must still find toy_DM147.33.dat whose .inf stores
    147.32999999999998."""
    import argparse

    from pypulsar_tpu.cli.sift import _fold_sifted
    from pypulsar_tpu.io.accelcands import Candidate, write_candlist

    monkeypatch.chdir(tmp_path)
    dm_exact = 0.03 * 4911  # 147.32999999999998 != float("147.33")
    assert float(f"{dm_exact:.2f}") != dm_exact
    _toy_dat(tmp_path, dm_exact)  # writes toy_DM147.33.dat
    cand = Candidate(accelfile="toy_DM147.33_ACCEL_20.cand", candnum=1,
                     dm=f"{dm_exact:.2f}", snr=10.0, sigma=8.0,
                     numharm=1, ipow=50.0, cpow=50.0, period=0.0517,
                     r=100.0, z=0.0)
    write_candlist([cand], "rt.accelcands")
    files = [("toy_DM147.33_ACCEL_20.cand", dm_exact, 16.384, [])]
    args = argparse.Namespace(outfile="rt.accelcands", fold_nbins=32,
                              fold_npart=8, fold_outbase=None)
    assert _fold_sifted(args, files) == 0
    assert glob.glob("rt_cand*.pfd")


def test_prepfold_cands_delegates_to_foldbatch(tmp_path, monkeypatch):
    """prepfold --cands FILE folds the whole list through the shared
    pipeline, rejecting the single-candidate flags."""
    from pypulsar_tpu.cli import prepfold as cli_prepfold

    monkeypatch.chdir(tmp_path)
    datfn, _ = _toy_dat(tmp_path, 10.0)
    cands = _cands_file(tmp_path, [(0.0517, 10.0), (0.0731, 10.0)])
    assert cli_prepfold.main([datfn, "--cands", cands, "-n", "32",
                              "--npart", "8", "-o", "pc"]) == 0
    assert len(glob.glob("pc_cand*.pfd")) == 2
    with pytest.raises(SystemExit):
        cli_prepfold.main([datfn, "--cands", cands, "-p", "0.05"])
    # single-candidate overrides are rejected, not silently dropped
    with pytest.raises(SystemExit):
        cli_prepfold.main([datfn, "--cands", cands, "--dm", "80"])
    with pytest.raises(SystemExit):
        cli_prepfold.main([datfn, "--cands", cands, "--pd", "1e-12"])
    with pytest.raises(SystemExit):
        cli_prepfold.main([datfn, "--cands", cands, "--nsub", "128"])


def test_pfd_snr_batch_glob_json(tmp_path, monkeypatch):
    """pfd_snr takes a glob + --json and emits one machine-readable
    summary row per archive (name, best DM, SNR, mean flux)."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch
    from pypulsar_tpu.cli import pfd_snr as cli_snr

    monkeypatch.chdir(tmp_path)
    _toy_dat(tmp_path, 10.0, amp=6.0)
    cands = _cands_file(tmp_path, [(0.0517, 10.0), (0.0731, 10.0)])
    assert cli_foldbatch.main(_fold_args(cands, "sj")) == 0
    # clean batch: rc 0
    assert cli_snr.main(["sj_cand*.pfd", "--sefd", "10.0",
                         "--json", "clean.json"]) == 0
    assert len(json.load(open("clean.json"))) == 2
    with open("sj_cand9999_corrupt.pfd", "wb") as f:
        f.write(b"\x01\x02debris")  # truncation debris caught by the glob
    # unreadable inputs: summary still written, but rc 1 for pipelines
    # gating on the exit code
    assert cli_snr.main(["sj_cand*.pfd", "typo_*.pfd", "--sefd", "10.0",
                         "--json", "snr.json"]) == 1
    rows = json.load(open("snr.json"))
    # corrupt archive AND the zero-match glob each get an error row —
    # neither silently vanishes from the survey summary
    assert len(rows) == 4
    assert any(r["pfd"] == "typo_*.pfd" and r.get("error")
               for r in rows)
    for row in rows:
        assert {"pfd", "name", "best_dm", "period", "snr"} <= set(row)
    assert sum(1 for r in rows if r.get("error", "").startswith(
        "unreadable")) == 2
    scored = [r for r in rows if r["snr"] is not None]
    assert scored and scored[0]["snr"] > 5.0
    assert scored[0]["smean_mjy"] is not None

    # a mid-analysis failure on ONE archive (not just a parse failure)
    # is contained to an error row too
    from pypulsar_tpu.fold import profile_snr as _ps

    real = _ps.pfd_snr
    hits = {"n": 0}

    def flaky(pfd, **kw):
        hits["n"] += 1
        if hits["n"] == 2:
            raise RuntimeError("synthetic analysis failure")
        return real(pfd, **kw)

    monkeypatch.setattr(_ps, "pfd_snr", flaky)
    os.remove("sj_cand9999_corrupt.pfd")
    assert cli_snr.main(["sj_cand*.pfd", "--sefd", "10.0",
                         "--json", "fl.json"]) == 1
    fl = json.load(open("fl.json"))
    assert len(fl) == 2
    assert sum(1 for r in fl if str(r.get("error", "")).startswith(
        "failed")) == 1
    assert sum(1 for r in fl if r["snr"] is not None) == 1


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_foldbatch_telemetry_counters_in_tlmsum(tmp_path, monkeypatch,
                                                capsys):
    """--telemetry records fold.cands_folded and the fold.pending_depth
    prefetch gauge, and tlmsum renders them."""
    from pypulsar_tpu.cli import foldbatch as cli_foldbatch
    from pypulsar_tpu.cli.tlmsum import main as tlmsum_main

    monkeypatch.chdir(tmp_path)
    for dm in (10.0, 20.0):
        _toy_dat(tmp_path, dm)
    rows = [(0.0517 * (1 + 0.021 * j), dm) for dm in (10.0, 20.0)
            for j in range(3)]
    cands = _cands_file(tmp_path, rows)
    assert cli_foldbatch.main(_fold_args(cands, "tl")
                              + ["--telemetry", "tl.jsonl"]) == 0
    recs = [json.loads(ln) for ln in open("tl.jsonl")]
    counters = {}
    gauges = set()
    for r in recs:
        if r.get("type") == "counters":
            counters.update(r.get("counters", {}))
            gauges.update(r.get("gauges", {}))
    assert counters.get("fold.cands_folded") == 6
    assert "fold.pending_depth" in gauges
    capsys.readouterr()
    assert tlmsum_main(["tl.jsonl"]) == 0
    out = capsys.readouterr().out
    assert "fold.cands_folded" in out
    assert "fold_parts_batch" in out
    assert "fold.pending_depth" in out


# ---------------------------------------------------------------------------
# satellite: pulse ceil-div fix
# ---------------------------------------------------------------------------

def test_pulse_interp_and_downsamp_exact_multiple():
    """fold/pulse.py:179 regression: at an exact multiple the ceil-div
    is the exact factor — the interpolation is the identity and the
    result is the pure block-mean of the original profile (the py2
    ``int(N/num)+1`` resampled through a 25%-larger grid instead)."""
    import warnings

    from pypulsar_tpu.fold.pulse import Pulse

    prof = np.arange(8, dtype=float)
    p = Pulse(1, 55000.0, 0.0, 8e-3, prof, "x.dat", 1e-3, 10.0, "Fake",
              1400.0, 1.0, 100.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        p.interp_and_downsamp(4)
    assert p.N == 4
    # interpolate(8) is the identity; downsample(2) sums adjacent bins
    np.testing.assert_allclose(p.profile, [1.0, 5.0, 9.0, 13.0])
    assert p.dt == pytest.approx(2e-3)

    # non-multiple case unchanged: ceil(10/4) == int(10/4)+1 == 3
    p2 = Pulse(2, 55000.0, 0.0, 1e-2, np.arange(10, dtype=float), "x.dat",
               1e-3, 10.0, "Fake", 1400.0, 1.0, 100.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        p2.interp_and_downsamp(4)
    assert p2.N == 4
