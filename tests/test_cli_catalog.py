"""Tests for pyppdot (catalog + P-Pdot), pyplotres, and residuals IO."""

import os

import matplotlib
import numpy as np
import pytest

matplotlib.use("Agg", force=True)

from pypulsar_tpu.io.residuals import read_residuals, write_residuals


def test_parse_bundled_catalog(capsys):
    from pypulsar_tpu.cli.pyppdot import DEFAULT_CATALOG, parse_pulsar_file

    pulsars = parse_pulsar_file(DEFAULT_CATALOG)
    # full catalog: 1830 reference rows (minus '*'-period entries and
    # commented duplicates) + magnetar/RRAT includes
    assert len(pulsars) > 1700
    names = {p.name for p in pulsars}
    # INCLUDE pulls in magnetars and RRATs
    assert "B0531+21" in names          # Crab
    assert "J1809-1943" in names        # magnetar include
    assert "J1819-1458" in names        # RRAT include
    crab = next(p for p in pulsars if p.name == "B0531+21")
    assert crab.snr and not crab.binary
    rrat = next(p for p in pulsars if p.name == "J1819-1458")
    assert rrat.rrat
    mag = next(p for p in pulsars if p.name == "J1808-2024")
    assert mag.magnetar
    hulse = next(p for p in pulsars if p.name == "B1913+16")
    assert hulse.binary
    ter5 = next(p for p in pulsars if p.name == "J1748-2446ad")
    assert ter5.pdot == 0.0 and ter5.binary  # catalog lists no Pdot for Ter5ad
    uplims = [p for p in pulsars if p.pdot_uplim]
    assert uplims, "catalog should contain '<' Pdot upper limits"


def test_derived_parameters_crab():
    from pypulsar_tpu.cli.pyppdot import params_from_ppdot

    b, age, edot = params_from_ppdot(0.0334, 4.21e-13)
    # Crab: B ~ 3.8e12 G, tau_c ~ 1250 yr, Edot ~ 4.5e38 erg/s
    assert b == pytest.approx(3.8e12, rel=0.1)
    assert age == pytest.approx(1.26e3, rel=0.1)
    assert edot == pytest.approx(4.5e38, rel=0.15)
    assert params_from_ppdot(None, 1e-15) == (None, None, None)


def test_line_families_are_inverses():
    from pypulsar_tpu.cli import pyppdot

    p = 0.1
    for pdot_f, p_f, val in [
            (pyppdot.pdot_from_edot, pyppdot.p_from_edot, 1e33),
            (pyppdot.pdot_from_bfield, pyppdot.p_from_bfield, 1e12),
            (pyppdot.pdot_from_age, pyppdot.p_from_age, 1e6)]:
        pdot = float(pdot_f(p, val))
        assert float(p_f(pdot, val)) == pytest.approx(p, rel=1e-9)


def test_pyppdot_cli(tmp_path, capsys):
    from pypulsar_tpu.cli import pyppdot

    out = str(tmp_path / "ppdot.png")
    rc = pyppdot.main(["--def-lines", "--binaries", "--rrats",
                       "--magnetars", "--snrs", "-o", out])
    assert rc == 0 and os.path.getsize(out) > 1000


def test_pyppdot_info(capsys):
    from pypulsar_tpu.cli import pyppdot

    assert pyppdot.main(["--info", "B0531+21"]) == 0
    out = capsys.readouterr().out
    assert "PSR B0531+21" in out and "B-field" in out
    assert pyppdot.main(["--info", "NOSUCH"]) == 1


def test_residuals_roundtrip(tmp_path):
    fn = str(tmp_path / "resid2.tmp")
    n = 25
    rng = np.random.RandomState(0)
    mjds = 55000.0 + np.sort(rng.rand(n) * 100)
    phs = rng.randn(n) * 1e-3
    freq_hz = 10.0
    write_residuals(fn, bary_TOA=mjds, postfit_phs=phs,
                    postfit_sec=phs / freq_hz,
                    orbit_phs=np.linspace(0, 1, n),
                    uncertainty=np.full(n, 5e-6),
                    prefit_sec=phs / freq_hz + 1e-4)
    r = read_residuals(fn)
    assert r.numTOAs == n
    np.testing.assert_allclose(r.bary_TOA, mjds)
    np.testing.assert_allclose(r.postfit_phs, phs)
    np.testing.assert_allclose(r.uncertainty, 5e-6)
    # derived prefit phase: prefit_sec * (postfit_phs/postfit_sec)
    np.testing.assert_allclose(r.prefit_phs,
                               (phs / freq_hz + 1e-4) * freq_hz)


def test_pyplotres_cli(tmp_path):
    from pypulsar_tpu.cli import pyplotres

    fn = str(tmp_path / "resid2.tmp")
    n = 30
    rng = np.random.RandomState(1)
    write_residuals(fn, bary_TOA=55000 + np.arange(n, dtype=float),
                    postfit_phs=rng.randn(n) * 1e-3,
                    postfit_sec=rng.randn(n) * 1e-4,
                    prefit_sec=rng.randn(n) * 1e-3)
    out = str(tmp_path / "res.png")
    rc = pyplotres.main(["--resid-file", fn, "--both", "-y", "usec",
                         "-x", "mjd", "-o", out])
    assert rc == 0 and os.path.getsize(out) > 1000
    assert pyplotres.main(["--resid-file",
                           str(tmp_path / "missing.tmp")]) == 1
