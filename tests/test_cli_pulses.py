"""Tests for the single-pulse / TOA CLI tools (dissect, pulses_to_toa,
sum_profs, pulse_energy_distribution)."""

import glob
import os

import matplotlib
import numpy as np
import pytest

matplotlib.use("Agg", force=True)

from pypulsar_tpu.core.psrmath import SECPERDAY
from pypulsar_tpu.io.datfile import write_dat
from pypulsar_tpu.io.infodata import InfoData


PERIOD = 0.25   # s
DT = 1e-3       # s


def _make_pulsar_dat(tmp_path, N=8000, snr=30.0, seed=0):
    """A .dat with a strong pulse at phase 0.3 of a 0.25 s period."""
    rng = np.random.RandomState(seed)
    data = rng.randn(N).astype(np.float32)
    t = np.arange(N) * DT
    phase = (t / PERIOD) % 1.0
    data[np.abs(phase - 0.3) < 0.02] += snr
    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = DT
    inf.N = N
    inf.telescope = "Arecibo"
    inf.bary = 1  # synthetic data: no topocentric corrections needed
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 256
    inf.chan_width = 100.0 / 256
    inf.DM = 10.0
    inf.object = "FAKE"
    basefn = str(tmp_path / "pulsar")
    write_dat(basefn, data, inf)
    return basefn + ".dat"


@pytest.fixture
def pulsar_dat(tmp_path):
    return _make_pulsar_dat(tmp_path)


def _write_parfile(tmp_path):
    from pypulsar_tpu.io.parfile import write_par

    parfn = str(tmp_path / "fake.par")
    write_par(parfn, dict(PSR="J0000+0000", F0=1.0 / PERIOD, F1=0.0,
                          PEPOCH=55000.0, DM=10.0))
    return parfn


def _write_template(tmp_path, nbins=64):
    phases = np.arange(nbins) / nbins
    template = np.exp(-0.5 * ((phases - 0.3) / 0.02) ** 2)
    fn = str(tmp_path / "template.txt")
    np.savetxt(fn, np.column_stack([np.arange(nbins), template]))
    return fn


def test_dissect_constant_period(pulsar_dat, tmp_path, monkeypatch, capsys):
    from pypulsar_tpu.cli import dissect

    monkeypatch.chdir(tmp_path)
    rc = dissect.main([pulsar_dat, "-p", str(PERIOD), "-r", "0.2:0.4",
                       "-t", "5", "--no-joydiv-plot", "--no-pulse-plots"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Autopsy report:" in out
    # 8000 samples / 250 per period = 32 pulses, all with injected signal
    assert "Total number of pulses searched: 32" in out
    profs = glob.glob(str(tmp_path / "pulsar.prof*"))
    assert len(profs) > 25  # nearly every rotation has the strong pulse


def test_dissect_requires_period_source(pulsar_dat):
    from pypulsar_tpu.cli import dissect

    assert dissect.main([pulsar_dat]) == 1
    assert dissect.main([pulsar_dat, "-p", "0.25", "--use-parfile",
                         "x.par"]) == 1


def test_dissect_parfile_toas(pulsar_dat, tmp_path, monkeypatch, capsys):
    from pypulsar_tpu.cli import dissect

    monkeypatch.chdir(tmp_path)
    parfn = _write_parfile(tmp_path)
    template = _write_template(tmp_path)
    rc = dissect.main([pulsar_dat, "--use-parfile", parfn, "-t", "5",
                       "-r", "0.2:0.4",
                       "--toas", "--template", template, "--min-pulses", "4",
                       "--no-joydiv-plot", "--no-pulse-plots",
                       "--no-text-files"])
    assert rc == 0
    out = capsys.readouterr().out
    toa_lines = [ln for ln in out.splitlines()
                 if ln.strip().startswith("FAKE") or "55000" in ln]
    # princeton TOA lines carry the observing freq and MJD ~55000
    assert any("55000" in ln for ln in toa_lines)
    assert "Number of TOAs:" in out
    ntoas = int(out.split("Number of TOAs:")[1].split()[0])
    assert ntoas >= 4


def test_dissect_joydiv_plot(pulsar_dat, tmp_path, monkeypatch):
    from pypulsar_tpu.cli import dissect

    monkeypatch.chdir(tmp_path)
    rc = dissect.main([pulsar_dat, "-p", str(PERIOD), "-r", "0.2:0.4",
                       "-t", "5", "--no-pulse-plots", "--no-text-files"])
    assert rc == 0
    assert os.path.exists(str(tmp_path / "pulsar.joydiv.ps"))


def test_toa_accuracy_constant_period(pulsar_dat, tmp_path, monkeypatch,
                                      capsys):
    """TOA MJDs should land near the injected pulse peaks (phase 0.3)."""
    from pypulsar_tpu.cli import dissect

    monkeypatch.chdir(tmp_path)
    parfn = _write_parfile(tmp_path)
    template = _write_template(tmp_path)
    rc = dissect.main([pulsar_dat, "--use-parfile", parfn, "-t", "5",
                       "-r", "0.2:0.4",
                       "--toas", "--template", template, "--min-pulses", "1",
                       "--no-joydiv-plot", "--no-pulse-plots",
                       "--no-text-files"])
    assert rc == 0
    out = capsys.readouterr().out
    mjds = []
    for ln in out.splitlines():
        for p in ln.split():
            # princeton TOA MJDs carry >= 10 decimal digits; the report
            # table's "%5.4f" MJD column does not
            if p.startswith("55000.") and len(p.split(".")[1]) >= 10:
                mjds.append(float(p))
    assert len(mjds) >= 4
    # each TOA should land at the injected pulse phase (0.3) mod period
    secs = (np.array(mjds) - 55000.0) * SECPERDAY
    phases = (secs / PERIOD) % 1.0
    assert np.ptp(phases) < 0.05
    assert abs(np.median(phases) - 0.3) < 0.05


def test_sum_profs_and_energy_distribution(pulsar_dat, tmp_path,
                                           monkeypatch, capsys):
    from pypulsar_tpu.cli import dissect, pulse_energy_distribution, sum_profs

    monkeypatch.chdir(tmp_path)
    rc = dissect.main([pulsar_dat, "-p", str(PERIOD), "-r", "0.2:0.4",
                       "-t", "5", "--no-joydiv-plot", "--no-pulse-plots"])
    assert rc == 0
    profs = sorted(glob.glob(str(tmp_path / "pulsar.prof*")))
    profs = [p for p in profs if not p.endswith(".ps")]
    assert len(profs) >= 4

    rc = sum_profs.main(profs[:4] + ["--scale", "-o",
                                     str(tmp_path / "summed")])
    assert rc == 0
    summed_fns = glob.glob(str(tmp_path / "summed.summedprof"))
    assert len(summed_fns) == 1
    from pypulsar_tpu.fold.pulse import read_pulse_from_file
    summed = read_pulse_from_file(summed_fns[0])
    assert summed.N > 0

    out = str(tmp_path / "energies.png")
    rc = pulse_energy_distribution.main(profs + ["-s", out, "-a"])
    assert rc == 0 and os.path.getsize(out) > 1000


def test_pulses_to_toa(pulsar_dat, tmp_path, monkeypatch, capsys):
    from pypulsar_tpu.cli import dissect, pulses_to_toa

    monkeypatch.chdir(tmp_path)
    rc = dissect.main([pulsar_dat, "-p", str(PERIOD), "-r", "0.2:0.4",
                       "-t", "5", "--no-joydiv-plot", "--no-pulse-plots"])
    assert rc == 0
    capsys.readouterr()
    profs = sorted(glob.glob(str(tmp_path / "pulsar.prof*")))
    profs = [p for p in profs if not p.endswith(".ps")][:6]
    template = _write_template(tmp_path, nbins=50)
    rc = pulses_to_toa.main(profs + ["--template", template,
                                     "--min-pulses", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert any("55000." in ln for ln in out.splitlines())
