"""Tests for progress, receivers, ne2001 fallback, freq_at_epoch,
parfile_diff, and the tempo2 wrapper gating."""

import io

import numpy as np
import pytest

from pypulsar_tpu.io.parfile import write_par
from pypulsar_tpu.utils import (
    bhat_pulse_broadening,
    freq_at_epoch,
    get_pulse_broadening,
    receivers,
    show_progress,
)


def test_show_progress_yields_all_and_reports():
    buf = io.StringIO()
    out = list(show_progress(range(10), width=20, file=buf))
    assert out == list(range(10))
    text = buf.getvalue()
    assert "100 %" in text and text.endswith("Done\n")
    assert "[====================]" in text


def test_show_progress_generator_with_tot():
    buf = io.StringIO()
    gen = (x * x for x in range(5))
    assert list(show_progress(gen, tot=5, file=buf)) == [0, 1, 4, 9, 16]


def test_alfa_receiver_curves():
    # spot values from the NAIC beam-0 fits: gain ~ 11 K/Jy at low ZA,
    # dropping past za=14; tsys rises toward the ZA limit
    g = receivers.alfa.gain(np.array([5.0, 10.0, 19.0]))
    assert g[0] > g[2]          # gain falls off at high ZA
    assert 8.0 < g[1] < 12.0
    t = receivers.alfa.tsys(np.array([5.0, 19.0]))
    assert t[1] > t[0]
    s = receivers.alfa.sefd(10.0)
    assert np.ndim(s) == 0 and 1.0 < float(s) < 6.0
    # clipping: below start_za the value equals the start_za value
    assert receivers.alfa.gain(0.0) == pytest.approx(
        float(receivers.alfa.gain(5.0)))


def test_lwide_receiver_curves():
    assert receivers.lwide.gain(0.0) == pytest.approx(10.14891)
    # cubic falloff beyond 14 deg
    assert receivers.lwide.gain(18.0) < receivers.lwide.gain(10.0)
    assert receivers.lwide.tsys(12.0) == 30.0


def test_bhat_broadening_scalings():
    # higher DM -> more scattering; higher freq -> less
    assert bhat_pulse_broadening(300.0) > bhat_pulse_broadening(30.0)
    t1 = bhat_pulse_broadening(100.0, freq=1.0)
    t2 = bhat_pulse_broadening(100.0, freq=2.0)
    assert t1 / t2 == pytest.approx(2.0 ** 3.86, rel=1e-6)
    # fallback path of get_pulse_broadening (no NE2001 installed)
    assert get_pulse_broadening(30.0, 5.0, 100.0) == pytest.approx(
        bhat_pulse_broadening(100.0))


def test_freq_at_epoch(tmp_path):
    parfn = str(tmp_path / "test.par")
    write_par(parfn, dict(PSR="J0000+0000", F0=10.0, F1=-1e-14,
                          PEPOCH=55000.0, F0_ERR=1e-8, F1_ERR=1e-16))
    f, ferr = freq_at_epoch(parfn, 55100.0)
    dt = 100.0 * 86400.0
    assert f == pytest.approx(10.0 - 1e-14 * dt)
    assert ferr == pytest.approx(np.sqrt(1e-16 + dt ** 2 * 1e-32))


def test_parfile_diff_same_par_is_zero(tmp_path):
    from pypulsar_tpu.utils.parfile_diff import rotation_diffs

    parfn = str(tmp_path / "a.par")
    write_par(parfn, dict(PSR="J0001+0001", F0=2.0, F1=0.0, PEPOCH=55000.0,
                          DM=10.0))
    mjds, diffs = rotation_diffs(parfn, [parfn], mjd_start=55000.0,
                                 mjd_end=55002.0, num=12)
    # identical ephemeris: zero rotation offset (up to the fractional-turn
    # snap residual which is exactly 0 here since both use the same polycos)
    np.testing.assert_allclose(diffs, 0.0, atol=1e-6)
    assert mjds.shape == (12,)


def test_parfile_diff_offset_f0(tmp_path):
    from pypulsar_tpu.utils.parfile_diff import rotation_diffs

    ref = str(tmp_path / "ref.par")
    cmp_ = str(tmp_path / "cmp.par")
    write_par(ref, dict(PSR="J1", F0=2.0, F1=0.0, PEPOCH=55000.0, DM=10.0))
    # df = 1e-6 Hz -> after 1 day, offset ~ 0.0864 rotations
    write_par(cmp_, dict(PSR="J1", F0=2.0 + 1e-6, F1=0.0, PEPOCH=55000.0,
                         DM=10.0))
    mjds, diffs = rotation_diffs(ref, [cmp_], mjd_start=55000.0,
                                 mjd_end=55001.0, num=5)
    expect = (mjds - 55000.0) * 86400.0 * 1e-6
    np.testing.assert_allclose(diffs[:, 0], expect, atol=2e-3)


def test_tempo2_gated():
    from pypulsar_tpu.utils import tempo2

    if not tempo2.have_tempo2():
        with pytest.raises(FileNotFoundError):
            tempo2.get_resids("x.par", "x.tim")
