"""Tests for the per-stage profiling subsystem (new surface; reference has
none — SURVEY.md §5 tracing row)."""

import io
import time

from pypulsar_tpu.utils import profiling


def test_inactive_is_noop():
    assert not profiling.is_active()
    with profiling.stage("x"):
        pass
    profiling.record("x", 1.0)  # must not raise or leak state
    assert not profiling.is_active()


def test_stage_report_collects_and_prints():
    buf = io.StringIO()
    with profiling.stage_report(file=buf) as rep:
        assert profiling.is_active()
        with profiling.stage("alpha"):
            time.sleep(0.01)
        with profiling.stage("alpha"):
            pass
        with profiling.stage("beta"):
            pass
        totals = rep.totals()
    assert not profiling.is_active()
    assert totals["alpha"] >= 0.01
    assert set(totals) == {"alpha", "beta"}
    out = buf.getvalue()
    assert "stage breakdown" in out
    assert "alpha" in out and "(2 calls)" in out


def test_nested_report_uses_outer_collector():
    buf = io.StringIO()
    with profiling.stage_report(file=buf) as outer:
        with profiling.stage("before"):
            pass
        with profiling.stage_report(file=buf):
            with profiling.stage("inner"):
                pass
        assert set(outer.totals()) == {"before", "inner"}
    # only the outermost context prints
    assert buf.getvalue().count("stage breakdown") == 1


def test_sweep_emits_stages():
    import numpy as np

    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.parallel import sweep_spectra

    rng = np.random.RandomState(0)
    freqs = 1500.0 - 2.0 * np.arange(32)
    spec = Spectra(freqs, 1e-3, rng.randn(32, 2048).astype(np.float32))
    buf = io.StringIO()
    with profiling.stage_report(file=buf) as rep:
        sweep_spectra(spec, np.linspace(0, 50, 8), nsub=8, group_size=4)
    assert "dispatch_sweep_chunk" in rep.totals()
    assert "device_wait+accumulate" in rep.totals()
