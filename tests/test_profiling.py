"""Tests for the per-stage profiling subsystem (new surface; reference has
none — SURVEY.md §5 tracing row)."""

import io
import time

from pypulsar_tpu.utils import profiling


def test_inactive_is_noop():
    assert not profiling.is_active()
    with profiling.stage("x"):
        pass
    profiling.record("x", 1.0)  # must not raise or leak state
    assert not profiling.is_active()


def test_stage_report_collects_and_prints():
    buf = io.StringIO()
    with profiling.stage_report(file=buf) as rep:
        assert profiling.is_active()
        with profiling.stage("alpha"):
            time.sleep(0.01)
        with profiling.stage("alpha"):
            pass
        with profiling.stage("beta"):
            pass
        totals = rep.totals()
    assert not profiling.is_active()
    assert totals["alpha"] >= 0.01
    assert set(totals) == {"alpha", "beta"}
    out = buf.getvalue()
    assert "stage breakdown" in out
    assert "alpha" in out and "(2 calls)" in out


def test_nested_report_uses_outer_collector():
    buf = io.StringIO()
    with profiling.stage_report(file=buf) as outer:
        with profiling.stage("before"):
            pass
        with profiling.stage_report(file=buf):
            with profiling.stage("inner"):
                pass
        assert set(outer.totals()) == {"before", "inner"}
    # only the outermost context prints
    assert buf.getvalue().count("stage breakdown") == 1


def test_stage_report_inside_telemetry_session_scopes_to_block(tmp_path):
    """The shim contract: a stage_report inside an obs telemetry session
    piggybacks on the session (no second collector), scopes its totals to
    its own block, still prints, and leaves the session running."""
    import json

    from pypulsar_tpu.obs import telemetry

    path = str(tmp_path / "t.jsonl")
    buf = io.StringIO()
    with telemetry.session(path) as tlm:
        with profiling.stage("before_report"):
            pass
        with profiling.stage_report(file=buf) as rep:
            with profiling.stage("inside_report"):
                pass
        assert telemetry.is_active()  # report exit must not close it
        totals = rep.totals()
        assert set(totals) == {"inside_report"}  # scoped to the block
        # the session saw BOTH stages
        assert set(tlm.stages) == {"before_report", "inside_report"}
    assert buf.getvalue().count("stage breakdown") == 1
    # profiling.stage call sites landed in the JSONL trace as spans
    names = [json.loads(l)["name"] for l in open(path)
             if '"span"' in l]
    assert "before_report" in names and "inside_report" in names


def test_record_feeds_active_session():
    from pypulsar_tpu.obs import telemetry

    with telemetry.session() as tlm:
        profiling.record("manual", 0.25)
        assert abs(tlm.stages["manual"][0] - 0.25) < 1e-9
        assert tlm.stages["manual"][1] == 1


def test_sweep_emits_stages():
    import numpy as np

    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.parallel import sweep_spectra

    rng = np.random.RandomState(0)
    freqs = 1500.0 - 2.0 * np.arange(32)
    spec = Spectra(freqs, 1e-3, rng.randn(32, 2048).astype(np.float32))
    buf = io.StringIO()
    with profiling.stage_report(file=buf) as rep:
        sweep_spectra(spec, np.linspace(0, 50, 8), nsub=8, group_size=4)
    assert "dispatch_sweep_chunk" in rep.totals()
    assert "device_wait+accumulate" in rep.totals()
