"""Candidate data plane tests (round 25): the store's safety contracts
(fenced appends rejected BEFORE touching the file, kill -9 mid-append +
re-publish yielding exactly-once records, torn tails tolerated,
pre/post-compaction query identity), multi-host racing publishes, the
cross-observation candsift (harmonic clustering, known-source veto),
the shared matcher, the ``cands`` CLI, the statusd ``/candidates``
endpoint, and the scheduler's terminal-edge ingest — extending the
``tests/test_multihost.py`` pattern (in-process FleetPlane handles over
one shared directory; the plane is plain files, so the coordination
machinery is identical to the M-process case)."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from pypulsar_tpu.candstore import (CandStore, cross_sift, load_catalog,
                                    match_known, normalize_obs,
                                    store_dir)
from pypulsar_tpu.candstore.match import (CatalogError, format_ratio,
                                          harmonic_ratio)
from pypulsar_tpu.resilience import faultinject
from pypulsar_tpu.survey.dag import StageSpec, SurveyConfig
from pypulsar_tpu.survey.fleet import FleetPlane, StaleLeaseError
from pypulsar_tpu.survey.scheduler import FleetScheduler
from pypulsar_tpu.survey.state import Observation


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.reset()
    yield
    faultinject.reset()


def _plane(td, host, lease_s=1.0, settle_s=0.02, heartbeat_s=None):
    return FleetPlane(str(td), host_id=host, lease_s=lease_s,
                      settle_s=settle_s, heartbeat_s=heartbeat_s)


def _rec(p_s, dm, snr, epoch=55000.0, tenant="default", **extra):
    rec = {"p_s": p_s, "dm": dm, "snr": snr, "epoch_mjd": epoch,
           "tenant": tenant}
    rec.update(extra)
    return rec


# ---------------------------------------------------------------------------
# the shared (P, DM) matcher
# ---------------------------------------------------------------------------


def test_harmonic_ratio_fundamental_harmonic_subharmonic():
    assert harmonic_ratio(0.1024, 0.1024, 1e-3) == (1, 1)
    assert harmonic_ratio(0.0512, 0.1024, 1e-3) == (1, 2)  # harmonic
    assert harmonic_ratio(0.2048, 0.1024, 1e-3) == (2, 1)  # subharm
    assert harmonic_ratio(0.1024 * 2 / 3, 0.1024, 1e-3) == (2, 3)
    assert harmonic_ratio(0.0777, 0.1024, 1e-4) is None
    assert format_ratio((1, 1)) == "fundamental"
    assert format_ratio((1, 2)) == "1/2 harmonic"


def test_catalog_text_and_json_roundtrip(tmp_path):
    txt = tmp_path / "cat.txt"
    txt.write_text("# comment\nB0531+21 0.0333924 56.77\n"
                   "J0437-47 0.00575745 2.64 0.0005 0.3\n")
    cat = load_catalog(str(txt))
    assert [s.name for s in cat] == ["B0531+21", "J0437-47"]
    assert cat[1].tol_p == 0.0005 and cat[1].tol_dm == 0.3
    js = tmp_path / "cat.json"
    js.write_text(json.dumps([{"name": "X", "p_s": 0.1, "dm": 10.0}]))
    assert load_catalog(str(js))[0].p_s == 0.1
    bad = tmp_path / "bad.txt"
    bad.write_text("onlytwo 0.1\n")
    with pytest.raises(CatalogError):
        load_catalog(str(bad))


def test_match_known_harmonic_aware_with_dm_gate(tmp_path):
    cat = load_catalog(str(_write_cat(tmp_path)))
    hit = match_known(0.0333924 / 2, 56.8, cat)  # detected at 2nd harm
    assert hit is not None and hit[0].name == "B0531+21"
    assert hit[1] == (1, 2)
    assert match_known(0.0333924, 99.0, cat) is None  # DM gate


def _write_cat(tmp_path):
    cat = tmp_path / "known.txt"
    cat.write_text("B0531+21 0.0333924 56.77\n")
    return cat


# ---------------------------------------------------------------------------
# store: publish / query / books
# ---------------------------------------------------------------------------


def test_publish_query_roundtrip_with_filters(tmp_path):
    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1024, 40.0, 12.0, epoch=55000.0),
                      _rec(0.5, 10.0, 6.0, epoch=55000.0)], "fp0")
    st.publish("o1", [_rec(0.1024, 40.05, 9.0, epoch=55010.0,
                           tenant="lofar")], "fp1")
    assert len(st.query()) == 3
    near = st.query(near=(0.1024, 40.0), tol_p=1e-3, tol_dm=0.5)
    assert [r["obs"] for r in near] == ["o0", "o1"]  # SNR-ranked
    assert [r["obs"] for r in st.query(tenant="lofar")] == ["o1"]
    assert [r["obs"] for r in
            st.query(epoch_range=(55005.0, 55015.0))] == ["o1"]
    assert len(st.query(top=1)) == 1
    assert st.query(top=1)[0]["snr"] == 12.0


def test_duplicate_publish_same_fingerprint_is_noop(tmp_path):
    st = CandStore(str(tmp_path))
    assert st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA") == 1
    assert st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA") == 0
    assert len(st.query()) == 1
    assert st.published() == {"o0": "fpA"}


def test_changed_fingerprint_supersedes_old_records(tmp_path):
    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA")
    st.publish("o0", [_rec(0.2, 21.0, 7.0)], "fpB")
    recs = st.query()
    assert len(recs) == 1 and recs[0]["p_s"] == 0.2
    st.compact()
    recs2 = st.query()
    assert len(recs2) == 1 and recs2[0]["p_s"] == 0.2


def test_torn_tail_tolerated(tmp_path):
    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA")
    seg = st._segments()[0]
    with open(seg, "a") as f:
        f.write('\n{"type": "note", "event": "cand", "uid": "torn')
    assert len(st.query()) == 1  # fragment skipped, not fatal
    st.publish("o1", [_rec(0.3, 30.0, 6.0)], "fpB")
    assert len(st.query()) == 2  # appends after the tear still land
    assert st.compact()
    assert len(st.query()) == 2


def test_kill_mid_append_then_republish_exactly_once(tmp_path):
    """The acceptance contract: a kill -9 mid-append leaves orphan
    records in the segment log (no books entry); the resume re-publish
    appends a full fresh copy and the query surface dedups by uid to
    exactly-once records."""
    st = CandStore(str(tmp_path))
    recs = [_rec(0.1 + 0.01 * i, 20.0 + i, 5.0 + i) for i in range(4)]
    faultinject.configure("kill:candstore.append:3")
    with pytest.raises(faultinject.InjectedKill):
        st.publish("o0", recs, "fpA")
    faultinject.reset()
    assert st.published() == {}  # books never saw the torn publish
    assert st.publish("o0", recs, "fpA") == 4  # resume re-publishes
    got = st.query()
    assert len(got) == 4  # exactly-once, not 6
    # the raw log really does hold duplicates — dedup did the work
    raw = sum(1 for seg in st._segments()
              for line in open(seg) if '"event": "cand"' in line)
    assert raw == 6
    st.compact()
    assert len(st.query()) == 4


def test_kill_during_compaction_loses_nothing(tmp_path):
    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA")
    faultinject.configure("kill:candstore.compact:1")
    with pytest.raises(faultinject.InjectedKill):
        st.compact()
    faultinject.reset()
    assert len(st.query()) == 1  # segments untouched
    assert st.compact()
    assert len(st.query()) == 1


# ---------------------------------------------------------------------------
# store: compaction + snapshot index
# ---------------------------------------------------------------------------


def test_query_identical_pre_and_post_compaction(tmp_path):
    st = CandStore(str(tmp_path))
    for i in range(5):
        st.publish(f"o{i}", [_rec(0.05 + 0.03 * j, 5.0 * j + i, 4.0 + j,
                                  epoch=55000.0 + i)
                             for j in range(6)], f"fp{i}")
    queries = [dict(), dict(near=(0.08, 5.0)), dict(top=7),
               dict(epoch_range=(55001.0, 55003.0)),
               dict(near=(0.11, 10.0), tol_dm=3.0)]
    pre = [st.query(**q) for q in queries]
    assert st.compact()
    post = [st.query(**q) for q in queries]
    assert pre == post
    assert st._segments() == []  # consumed segments unlinked
    snap = st._read_snapshot()
    dms = [r["dm"] for r in snap["records"]]
    assert dms == sorted(dms)  # (DM, P)-sorted
    assert snap["index"], "snapshot must carry the B-range index"
    starts = [b["start"] for b in snap["index"]]
    assert starts == sorted(starts)


def test_auto_compaction_threshold(tmp_path, monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_CANDSTORE_COMPACT_RECORDS", "3")
    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA")
    assert st._segments()  # below threshold: log retained
    st.publish("o1", [_rec(0.2, 21.0, 6.0),
                      _rec(0.3, 22.0, 7.0)], "fpB")
    assert st._segments() == []  # threshold crossed: auto-compacted
    assert st.status()["compactions"] == 1
    assert len(st.query()) == 3


def test_segment_rotation_bound(tmp_path, monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_CANDSTORE_SEGMENT_BYTES", "200")
    st = CandStore(str(tmp_path))
    for i in range(4):
        st.publish(f"o{i}", [_rec(0.1 + i, 20.0, 5.0)], f"fp{i}")
    assert len(st._segments()) > 1  # tiny bound: the log rolled
    assert len(st.query()) == 4


# ---------------------------------------------------------------------------
# multi-host fencing
# ---------------------------------------------------------------------------


def test_stale_token_writer_rejected_before_touching_store(tmp_path):
    """A dead host's late publish must be a no-op: the fence fires
    before the store directory even exists."""
    pa = _plane(tmp_path, "hA", settle_s=0.0)
    ta = pa.claim("o0")
    assert ta is not None
    # hA never registered a lease, so hB adopts o0 immediately with a
    # strictly higher token — hA is now the dead host waking up
    pb = _plane(tmp_path, "hB", settle_s=0.0)
    tb = pb.claim("o0")
    assert tb is not None and tb > ta
    st = CandStore(str(tmp_path),
                   fence=lambda: pa.fence("o0", ta))
    with pytest.raises(StaleLeaseError):
        st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA", token=ta)
    assert not os.path.exists(store_dir(str(tmp_path)))
    # the adopter's publish (current token) lands fine
    st2 = CandStore(str(tmp_path),
                    fence=lambda: pb.fence("o0", tb))
    assert st2.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA",
                       token=tb) == 1
    assert len(st2.query()) == 1


def test_two_racing_hosts_publish_to_one_store(tmp_path):
    """Two hosts publishing different observations concurrently into
    one store: every record lands exactly once, no torn lines."""
    pa = _plane(tmp_path, "hA", settle_s=0.0)
    pb = _plane(tmp_path, "hB", settle_s=0.0)
    errors = []

    def go(plane, host, lo):
        try:
            for i in range(lo, lo + 4):
                obs = f"o{i}"
                tok = plane.claim(obs)
                assert tok is not None, (host, obs)
                st = CandStore(str(tmp_path),
                               fence=lambda o=obs, t=tok:
                               plane.fence(o, t))
                st.publish(obs, [_rec(0.05 * (i + 1), 10.0 + i,
                                      5.0 + i)], f"fp{i}", token=tok)
                plane.mark_terminal(obs, tok, "done")
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append((host, e))

    ts = [threading.Thread(target=go, args=(pa, "hA", 0)),
          threading.Thread(target=go, args=(pb, "hB", 4))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errors, errors
    st = CandStore(str(tmp_path))
    got = st.query()
    assert sorted(r["obs"] for r in got) == [f"o{i}" for i in range(8)]
    assert st.compact()
    assert sorted(r["obs"] for r in st.query()) \
        == [f"o{i}" for i in range(8)]


# ---------------------------------------------------------------------------
# compaction vs concurrent publishers (the retire-then-read discipline)
# ---------------------------------------------------------------------------


def test_retired_segments_stay_readable_and_next_compact_adopts(tmp_path):
    """A compactor killed between retiring a segment and replacing the
    snapshot leaves ``*.retired-*`` files: queries must still see
    their records, and the next compaction folds and unlinks them."""
    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA")
    seg = st._segments()[0]
    os.rename(seg, seg + ".retired-dead-1")
    assert st._segments() == []
    assert len(st.query()) == 1  # retired file still read
    assert st.compact()
    assert len(st.query()) == 1
    assert st._retired_segments() == []  # adopted + unlinked


def test_publisher_republishes_when_segment_retired_midflight(tmp_path):
    """The writer half of the handshake: a racing compactor renames
    the publisher's segment away between appends; the publish must
    notice (inode check) and re-append into a fresh segment BEFORE
    booking — books must never assert records that live only in a
    file a compactor may unlink."""
    outdir = str(tmp_path)
    calls = {"n": 0, "renamed": False}

    def fence():
        calls["n"] += 1
        # after the first record lands, play the concurrent
        # compactor: retire the active segment out from under us
        if calls["n"] == 3 and not calls["renamed"]:
            segs = CandStore(outdir)._segments()
            if segs:
                os.rename(segs[0], segs[0] + ".retired-race-1")
                calls["renamed"] = True

    st = CandStore(outdir, fence=fence)
    recs = [_rec(0.1 + 0.01 * i, 20.0 + i, 5.0 + i) for i in range(3)]
    assert st.publish("o0", recs, "fpA") == 3
    ro = CandStore(outdir)
    assert ro.published() == {"o0": "fpA"}
    assert len(ro.query()) == 3  # exactly-once despite the race
    assert ro._segments(), "records must live in a LINKED segment"
    # the fresh segment alone holds a full copy: unlinking the retired
    # file (what the racing compactor goes on to do) loses nothing
    for seg in ro._retired_segments():
        os.remove(seg)
    assert len(CandStore(outdir).query()) == 3


def test_compact_lock_steal_exactly_once_and_owned_release(tmp_path):
    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA")
    lock = st._lock_path
    with open(lock, "w") as f:
        f.write("dead-compactor")
    old = time.time() - 3600
    os.utime(lock, (old, old))
    tok = st._take_compact_lock()
    assert tok is not None  # stale lock stolen
    # a second contender sees the winner's FRESH lock and backs off
    # (two racing os.remove stealers could both "win" — the bug class)
    assert st._take_compact_lock() is None
    # a thief that decided we were dead replaced the lock: release
    # must not delete the thief's lock out from under it
    with open(lock, "w") as f:
        f.write("thief")
    st._release_compact_lock(tok)
    assert os.path.exists(lock)


def test_compact_aborts_when_lock_stolen_midrun(tmp_path):
    """A compaction that overruns the staleness age and loses its lock
    must NOT replace the snapshot or unlink anything — its stale view
    could erase records the thief already folded in."""
    outdir = str(tmp_path)
    CandStore(outdir).publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA")
    probe = CandStore(outdir)
    calls = {"n": 0}

    def fence():
        calls["n"] += 1
        if calls["n"] >= 2:  # after the lock is held: play the thief
            with open(probe._lock_path, "w") as f:
                f.write("thief")

    assert CandStore(outdir, fence=fence).compact() is False
    assert not os.path.exists(probe.snapshot_path)  # replace aborted
    assert len(CandStore(outdir).query()) == 1  # retired rows readable


def test_published_cache_sees_other_writers(tmp_path):
    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1, 20.0, 5.0)], "fpA")
    assert st.published() == {"o0": "fpA"}
    # another handle (another host) books o1: the cached parse must be
    # invalidated by the ledger's stat signature, not trusted stale
    CandStore(str(tmp_path)).publish("o1", [_rec(0.2, 21.0, 6.0)],
                                     "fpB")
    assert st.published() == {"o0": "fpA", "o1": "fpB"}


# ---------------------------------------------------------------------------
# cross-observation candsift
# ---------------------------------------------------------------------------


def test_cross_sift_clusters_epochs_and_harmonics(tmp_path):
    """The same pulsar at three epochs — once at its 2nd harmonic —
    collapses to ONE multi-epoch cluster; per-epoch noise stays in
    singletons below it."""
    recs = [
        _rec(0.1024, 40.0, 12.0, epoch=55000.0, uid="a", obs="o0"),
        _rec(0.10241, 40.1, 10.0, epoch=55010.0, uid="b", obs="o1"),
        _rec(0.0512, 39.9, 8.0, epoch=55020.0, uid="c", obs="o2"),
        _rec(0.777, 12.0, 6.0, epoch=55000.0, uid="d", obs="o0"),
        _rec(0.333, 77.0, 5.5, epoch=55010.0, uid="e", obs="o1"),
    ]
    clusters = cross_sift(recs, tol_p=1e-3, tol_dm=0.5)
    assert len(clusters) == 3
    top = clusters[0]
    assert top["n_epochs"] == 3 and top["n_hits"] == 3
    assert top["p_s"] == 0.1024  # strongest record seeds the cluster
    assert "1/2 harmonic" in top["harmonics"]
    assert sorted(top["obs"]) == ["o0", "o1", "o2"]
    assert all(c["n_epochs"] == 1 for c in clusters[1:])


def test_cross_sift_known_source_veto(tmp_path):
    cat = load_catalog(str(_write_cat(tmp_path)))
    recs = [_rec(0.0333924, 56.8, 20.0, uid="crab"),
            _rec(0.4, 12.0, 6.0, uid="new")]
    clusters = cross_sift(recs, tol_p=1e-3, tol_dm=0.5, known=cat)
    by_known = {c["known_source"]: c for c in clusters}
    assert "B0531+21" in by_known
    assert by_known["B0531+21"]["known_ratio"] == "fundamental"
    assert by_known[None]["p_s"] == 0.4


# ---------------------------------------------------------------------------
# query surfaces: cands CLI + statusd /candidates
# ---------------------------------------------------------------------------


def test_cands_cli_json_and_sift(tmp_path, capsys):
    from pypulsar_tpu.cli import cands as cands_cli

    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1024, 40.0, 12.0, epoch=55000.0)], "fp0")
    st.publish("o1", [_rec(0.1024, 40.0, 9.0, epoch=55010.0)], "fp1")
    assert cands_cli.main([str(tmp_path), "--near", "0.1024", "40.0",
                           "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert [r["obs"] for r in rows] == ["o0", "o1"]
    assert cands_cli.main([str(tmp_path), "--sift", "--json"]) == 0
    clusters = json.loads(capsys.readouterr().out)
    assert len(clusters) == 1 and clusters[0]["n_epochs"] == 2
    # --compact forces compaction and answers identically
    assert cands_cli.main([str(tmp_path), "--compact", "--json"]) == 0
    assert len(json.loads(capsys.readouterr().out)) == 2
    assert CandStore(str(tmp_path))._segments() == []


def test_statusd_candidates_endpoint(tmp_path):
    from pypulsar_tpu.obs.statusd import StatusServer

    st = CandStore(str(tmp_path))
    st.publish("o0", [_rec(0.1024, 40.0, 12.0, tenant="lofar"),
                      _rec(0.7, 10.0, 5.0)], "fp0")
    with StatusServer(str(tmp_path), port=0) as srv:
        doc = json.loads(urllib.request.urlopen(
            srv.url + "/candidates", timeout=10).read())
        assert doc["n"] == 2
        assert doc["store"]["publishes"] == 1
        doc2 = json.loads(urllib.request.urlopen(
            srv.url + "/candidates?p=0.1024&dm=40.0&tenant=lofar",
            timeout=10).read())
        assert doc2["n"] == 1
        assert doc2["records"][0]["snr"] == 12.0
        # malformed query params are the CLIENT's fault: 400 naming
        # the parameter, not a generic 500 "snapshot failed"
        for bad in ("?top=abc", "?p=x&dm=40.0", "?epoch_lo=5&epoch_hi=z"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/candidates" + bad,
                                       timeout=10)
            assert ei.value.code == 400


# ---------------------------------------------------------------------------
# ingest: normalize + the scheduler's terminal edge
# ---------------------------------------------------------------------------


def _snr_stage():
    """Stub DAG stage that writes a pfd_snr-shaped summary, so the
    terminal-edge ingest has something real to normalize."""
    def run(o, c):
        rows = [{"pfd": f"{o.outbase}.pfd", "name": o.name,
                 "best_dm": 40.0, "period": 0.1024, "snr": 11.0,
                 "weq_bins": 4.0, "smean_mjy": None,
                 "ra": "05:34:21.0", "dec": "22:00:57.0"}]
        with open(f"{o.outbase}_snr.json", "w") as f:
            json.dump(rows, f)
        return 0

    return StageSpec("snr", "stub", False, (), lambda o, c: [],
                     lambda o, c: [f"{o.outbase}_snr.json"], run=run)


def _mk_obs(td, n):
    obs = []
    for i in range(n):
        raw = os.path.join(str(td), f"o{i}.raw")
        with open(raw, "wb") as f:
            f.write(b"x" * 64)
        obs.append(Observation(f"o{i}", raw,
                               os.path.join(str(td), f"o{i}")))
    return obs


def test_fingerprint_tracks_tenant_not_trace_id(tmp_path):
    """Metadata that rides on the records but is not in the artifact
    files (tenant, header position/epoch) must move the fingerprint —
    a tenant remap over unchanged artifacts has to supersede the old
    rows, not dup-skip and leave /candidates?tenant= wrong forever.
    trace_id differs every run and must NOT move it."""
    outbase = str(tmp_path / "o0")
    rows = [{"pfd": "x.pfd", "best_dm": 40.0, "period": 0.1024,
             "snr": 11.0}]
    with open(outbase + "_snr.json", "w") as f:
        json.dump(rows, f)
    raw = str(tmp_path / "o0.raw")
    _, fp_a = normalize_obs("o0", outbase, raw)
    _, fp_b = normalize_obs("o0", outbase, raw, tenant="lofar")
    assert fp_a != fp_b
    _, fp_c = normalize_obs("o0", outbase, raw)
    assert fp_c == fp_a  # deterministic
    _, fp_d = normalize_obs("o0", outbase, raw, trace_id="t-123")
    assert fp_d == fp_a  # resume keeps its exactly-once no-op


def test_normalize_obs_prefers_row_radec(tmp_path):
    outbase = str(tmp_path / "o0")
    rows = [{"pfd": "x.pfd", "best_dm": 40.0, "period": 0.1024,
             "snr": 11.0, "ra": "05:34:21.0", "dec": "22:00:57.0"}]
    with open(outbase + "_snr.json", "w") as f:
        json.dump(rows, f)
    recs, fp = normalize_obs("o0", outbase, str(tmp_path / "o0.raw"))
    assert len(recs) == 1
    assert recs[0]["ra"] == "05:34:21.0"
    assert recs[0]["dm"] == 40.0 and recs[0]["p_s"] == 0.1024
    # fingerprint tracks artifact content
    with open(outbase + "_snr.json", "a") as f:
        f.write(" ")
    _, fp2 = normalize_obs("o0", outbase, str(tmp_path / "o0.raw"))
    assert fp2 != fp


def test_scheduler_terminal_edge_publishes(tmp_path):
    obs = _mk_obs(tmp_path, 2)
    res = FleetScheduler(obs, SurveyConfig(),
                         stages=[_snr_stage()]).run()
    assert res.ok
    st = CandStore(str(tmp_path))
    got = st.query()
    assert sorted(r["obs"] for r in got) == ["o0", "o1"]
    assert got[0]["ra"] == "05:34:21.0"
    assert st.published().keys() == {"o0", "o1"}
    # a --resume over the same artifacts is an exactly-once no-op
    res2 = FleetScheduler(obs, SurveyConfig(),
                          stages=[_snr_stage()]).run()
    assert res2.ok
    assert len(CandStore(str(tmp_path)).query()) == 2


def test_scheduler_store_disabled_leaves_no_trace(tmp_path, monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_CANDSTORE", "0")
    obs = _mk_obs(tmp_path, 1)
    res = FleetScheduler(obs, SurveyConfig(),
                         stages=[_snr_stage()]).run()
    assert res.ok
    assert not os.path.exists(store_dir(str(tmp_path)))


# ---------------------------------------------------------------------------
# sift --known-sources (the within-obs half of the shared matcher)
# ---------------------------------------------------------------------------


def test_sift_cli_known_sources_veto(tmp_path):
    from pypulsar_tpu.cli import sift as sift_cli
    from pypulsar_tpu.io.accelcands import parse_candlist
    from pypulsar_tpu.io.infodata import InfoData
    from pypulsar_tpu.io.prestocand import write_rzwcands

    N, dt = 32768, 1e-3
    T = N * dt
    base = str(tmp_path / "x_DM56.77")
    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = dt
    inf.N = N
    inf.DM = 56.77
    inf.telescope = "Fake"
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 1
    inf.chan_width = 100.0
    inf.object = "FAKE"
    inf.to_file(base + ".inf")
    # one candidate at the Crab period, one at an unknown 0.25 s
    write_rzwcands(base + "_ACCEL_50.cand",
                   [dict(r=T / 0.0333924, rerr=0.1, z=0.0, zerr=0.1,
                         sig=12.0, pow=50.0),
                    dict(r=T / 0.25, rerr=0.1, z=0.0, zerr=0.1,
                         sig=9.0, pow=30.0)])
    out = str(tmp_path / "sifted.accelcands")
    rc = sift_cli.main([base + "_ACCEL_50.cand", "-o", out,
                        "--min-hits", "1",
                        "--known-sources", str(_write_cat(tmp_path))])
    assert rc == 0
    kept = parse_candlist(out)
    assert len(kept) == 1
    assert abs(kept[0].period - 0.25) < 1e-3  # Crab vetoed, new kept
