"""The complete survey chain through the real CLIs, one synthetic
observation end to end:

    .fil (injected pulsar + RFI channel)
      -> rfifind        (native mask generation)
      -> sweep --mask --write-dats   (DM sweep + dedispersed series)
      -> accelsearch    (periodicity search of the best .dat)
      -> sift           (reference-format .accelcands)
      -> prepfold       (fold at the recovered P, DM -> .pfd)
      -> pfd_snr        (final profile SNR)

Each stage's output is asserted against the injected parameters before
the next stage consumes it — the cross-stage contract no per-tool test
exercises."""

import os

import numpy as np
import pytest

from pypulsar_tpu.io.filterbank import write_filterbank
from pypulsar_tpu.ops import numpy_ref

C, DT = 64, 1e-3
T = 1 << 16  # 65.5 s
P_TRUE = 0.05  # 20 Hz
DM_TRUE = 60.0
RFI_ROW = 9  # high-frequency-first data row; mask channel = C-1-9 = 54


@pytest.fixture(scope="module")
def obs_dir(tmp_path_factory):
    """Synthesize the observation once for all stages."""
    d = tmp_path_factory.mktemp("pipeline")
    rng = np.random.RandomState(42)
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    delays = numpy_ref.bin_delays(DM_TRUE, freqs, DT)
    t = np.arange(T) * DT
    # faint enough that the rfifind Fourier detector does not flag the
    # pulsar itself as periodic interference (per-block normalized power
    # ~7 vs the freq_sigma=4 threshold ~16.6) — at 1.2 sigma/channel the
    # whole band got masked and the pipeline went dark (the coverage
    # warning in ops/rfifind.py exists because of this test)
    for c in range(C):
        phase = ((t - delays[c] * DT) / P_TRUE) % 1.0
        data[c] += 0.8 * np.exp(
            -0.5 * ((phase - 0.5) / 0.03) ** 2).astype(np.float32)
    data[RFI_ROW] *= 18.0  # loud channel the mask must remove
    hdr = dict(telescope_id=6, machine_id=2, source_name="PIPE",
               src_raj=0.0, src_dej=0.0, tstart=56000.0, tsamp=DT,
               fch1=1500.0, foff=-4.0, nchans=C, nbits=32, nifs=1)
    write_filterbank(str(d / "obs.fil"), hdr, data.T)
    return d


def test_stage1_rfifind(obs_dir, monkeypatch):
    from pypulsar_tpu.cli.rfifind import main as rfifind_main

    monkeypatch.chdir(obs_dir)
    assert rfifind_main(["obs.fil", "-o", "obs", "-t", "2.0"]) == 0
    from pypulsar_tpu.io.rfimask import RfifindMask

    mask = RfifindMask("obs_rfifind.mask")
    assert C - 1 - RFI_ROW in mask.mask_zap_chans_set
    # the pulsar must NOT have been mistaken for periodic RFI: the mask
    # leaves most of the band alive
    assert float(mask._zap_table.mean()) < 0.3


def test_stage2_sweep_masked(obs_dir, monkeypatch):
    from pypulsar_tpu.cli.sweep import main as sweep_main

    monkeypatch.chdir(obs_dir)
    assert os.path.exists("obs_rfifind.mask"), "stage 1 must run first"
    assert sweep_main(["obs.fil", "--lodm", "0", "--dmstep", "10",
                       "--numdms", "13", "--mask", "obs_rfifind.mask",
                       "--write-dats", "-o", "obs",
                       "--threshold", "5"]) == 0
    # the per-DM series exist; the DM-60 one carries the strongest
    # periodicity (checked properly by the next stage)
    assert os.path.exists("obs_DM60.00.dat")
    assert os.path.exists("obs_DM60.00.inf")


def test_stage3_accelsearch(obs_dir, monkeypatch):
    from pypulsar_tpu.cli.accelsearch import main as accel_main

    monkeypatch.chdir(obs_dir)
    assert accel_main(["obs_DM60.00.dat", "-z", "8", "-n", "4",
                       "--sigma", "5"]) == 0
    txt = open("obs_DM60.00_ACCEL_8.txtcand").read()
    freqs = [float(line.split()[6]) for line in txt.splitlines()
             if line and not line.startswith("#")]
    assert freqs, "no candidates found"
    # the fundamental (or a recognized harmonic fold) of 20 Hz
    assert any(abs(f - 1.0 / P_TRUE) < 0.05
               or abs(f - 0.5 / P_TRUE) < 0.05 for f in freqs), freqs[:5]


def test_stage4_sift(obs_dir, monkeypatch):
    from pypulsar_tpu.cli.sift import main as sift_main
    from pypulsar_tpu.io.accelcands import parse_candlist

    monkeypatch.chdir(obs_dir)
    assert sift_main(["obs_DM60.00_ACCEL_8.cand", "-o",
                      "obs.accelcands"]) == 0
    cands = parse_candlist("obs.accelcands")
    assert len(cands) >= 1
    best = cands[0]
    # Candidate.period is seconds (ms on disk, converted by the parser)
    assert abs(best.period - P_TRUE) < 2e-3 \
        or abs(best.period - 2 * P_TRUE) < 4e-3, best.period


def test_stage5_prepfold_and_snr(obs_dir, monkeypatch, capsys):
    import matplotlib

    matplotlib.use("Agg", force=True)
    from pypulsar_tpu.cli.pfd_snr import main as snr_main
    from pypulsar_tpu.cli.prepfold import main as fold_main
    from pypulsar_tpu.io.prestopfd import PfdFile

    monkeypatch.chdir(obs_dir)
    assert fold_main(["obs.fil", "-p", str(P_TRUE), "--dm", str(DM_TRUE),
                      "-n", "40", "--npart", "8", "--nsub", "8",
                      "-o", "obs.pfd"]) == 0
    pfd = PfdFile("obs.pfd")
    assert pfd.bestdm == DM_TRUE
    assert snr_main(["obs.pfd", "--on-pulse", "0.35", "0.65"]) == 0
    out = capsys.readouterr().out
    snr = float([ln for ln in out.splitlines()
                 if ln.startswith("SNR:")][0].split()[1])
    # ~1310 pulses x 64 channels of a 0.8-sigma pulse: strong detection
    assert snr > 20.0, snr
