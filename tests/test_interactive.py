"""Interactive picker layer (utils/interactive.py): the handler logic is
display-free by design, so these tests drive it with synthesized events
— the matplotlib wiring itself is exercised with the Agg backend."""

import numpy as np
import pytest

from pypulsar_tpu.utils.interactive import (
    AxisCycler,
    NearestPointPicker,
    OnPulsePicker,
)


class TestOnPulsePicker:
    def test_select_normalizes_and_evaluates(self):
        calls = []
        picker = OnPulsePicker(lambda lo, hi: calls.append((lo, hi)) or 42)
        # reversed + out-of-range drag clamps to [0, 1] and reorders
        assert picker.on_select(0.7, -0.1) == 42
        assert picker.region == (0.0, 0.7)
        assert picker.result == 42
        assert calls == [(0.0, 0.7)]

    def test_zero_width_selection_ignored(self):
        picker = OnPulsePicker(lambda lo, hi: 1)
        assert picker.on_select(0.5, 0.5) is None
        assert picker.region is None and picker.result is None


class TestNearestPointPicker:
    def test_finds_nearest_in_normalized_space(self):
        # x spans 1000 units, y spans 1: un-normalized distance would
        # pick index 0; normalized picks index 1
        picker = NearestPointPicker([0.0, 500.0, 1000.0], [0.0, 0.5, 1.0],
                                    ["a", "b", "c"])
        i, label = picker.on_click(480.0, 0.52)
        assert (i, label) == (1, "b")
        assert picker.picked == [1]

    def test_far_click_returns_none(self):
        picker = NearestPointPicker([0.0, 1.0], [0.0, 1.0], ["a", "b"],
                                    max_dist=0.05)
        assert picker.on_click(0.5, 0.5) is None
        assert picker.picked == []

    def test_callback_invoked(self):
        hits = []
        picker = NearestPointPicker([0.0, 1.0], [0.0, 1.0], ["a", "b"],
                                    callback=lambda i, n: hits.append(n))
        picker.on_click(0.99, 0.98)
        assert hits == ["b"]

    def test_nan_points_skipped(self):
        picker = NearestPointPicker([0.0, np.nan, 1.0], [0.0, np.nan, 1.0],
                                    ["a", "bad", "c"])
        assert picker.on_click(0.01, 0.01)[1] == "a"


class TestAxisCycler:
    def test_cycles_and_redraws(self):
        drawn = []
        cyc = AxisCycler(("mjd", "numtoa"), ("phase", "usec", "sec"),
                         "mjd", "phase",
                         redraw=lambda x, y: drawn.append((x, y)))
        assert cyc.on_key("x") and cyc.xaxis == "numtoa"
        assert cyc.on_key("x") and cyc.xaxis == "mjd"  # wraps
        assert cyc.on_key("y") and cyc.yaxis == "usec"
        assert not cyc.on_key("q")  # unknown keys ignored, no redraw
        assert drawn == [("numtoa", "phase"), ("mjd", "phase"),
                         ("mjd", "usec")]


def test_pyppdot_picker_uses_log_space():
    from pypulsar_tpu.cli.pyppdot import Pulsar, make_picker

    mk = lambda name, p, pdot: Pulsar(name, p, pdot, "00:00:00",
                                      "00:00:00", 10.0, None, None, None)
    psrs = [mk("slow", 1.0, 1e-15), mk("msp", 3e-3, 1e-20),
            mk("nopdot", 0.5, None)]
    picker = make_picker(psrs)
    assert len(picker.labels) == 2  # pdot-less pulsar excluded
    i, name = picker.on_click(np.log10(3.2e-3), np.log10(1.2e-20))
    assert name == "msp"


def test_pfd_snr_interactive_without_display(tmp_path, monkeypatch):
    """interactive_snr with show=False exposes the picker path headless:
    build a tiny .pfd via the prepfold CLI, then evaluate a selection."""
    import matplotlib

    matplotlib.use("Agg", force=True)
    from tests.test_cli_prepfold import synth_pulsar_fil
    from pypulsar_tpu.cli import prepfold as cli_fold
    from pypulsar_tpu.cli.pfd_snr import interactive_snr
    from pypulsar_tpu.io.prestopfd import PfdFile
    from pypulsar_tpu.utils.interactive import OnPulsePicker

    monkeypatch.chdir(tmp_path)
    synth_pulsar_fil("psr.fil", period=0.0517, dm=35.0)
    assert cli_fold.main(["psr.fil", "-p", "0.0517", "--dm", "35.0",
                          "-n", "32", "--npart", "4", "--nsub", "8",
                          "-o", "psr.pfd"]) == 0
    pfd = PfdFile("psr.pfd")
    assert interactive_snr(pfd, show=False) is None  # nothing picked
    # the profile shown (and scored with dedisperse=False) must be the
    # dedispersed, period-adjusted one — selecting on the raw profile
    # would put the on-pulse window at the wrong phase
    assert pfd.currdm == pfd.bestdm

    # drive the same evaluate callback the UI wires to the SpanSelector
    got = {}

    def capture(lo, hi):
        from pypulsar_tpu.fold import profile_snr

        res = profile_snr.pfd_snr(
            pfd, regions=[(int(lo * pfd.proflen),
                           int(np.ceil(hi * pfd.proflen)))])
        got.update(res)
        return res

    picker = OnPulsePicker(capture)
    picker.on_select(0.35, 0.65)  # the synthetic pulse sits at phase 0.5
    assert got["snr"] > 5.0


def test_pyplotres_interactive_smoke(tmp_path, monkeypatch, capsys):
    import matplotlib

    matplotlib.use("Agg", force=True)
    from pypulsar_tpu.cli import pyplotres
    from pypulsar_tpu.io.residuals import write_residuals

    monkeypatch.chdir(tmp_path)
    n = 12
    rng = np.random.RandomState(0)
    write_residuals("resid2.tmp",
                    bary_TOA=55000 + np.arange(n, dtype=float),
                    postfit_phs=rng.randn(n) * 1e-3,
                    postfit_sec=rng.randn(n) * 1e-6,
                    prefit_sec=rng.randn(n) * 1e-6)
    rc = pyplotres.main(["--interactive", "-o", "out.png"])
    assert rc == 0
    assert (tmp_path / "out.png").exists()
