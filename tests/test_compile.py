"""Round-22 compilation plane: the bucket-size ladder, the plane_jit
AOT executable registry, warm-pool precompile hooks, bucket-crossing
checkpoint resume, and the cross-process persistent XLA cache."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pypulsar_tpu.compile import (
    bucket_floor, bucket_rows, bucket_size, buckets_enabled, plane_jit,
    register_warmer, warm_stage, warmable_stages,
)
from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.parallel import make_sweep_plan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SPAWN_PROBE: list = []  # cached (ok, detail), once per session


def _require_spawn():
    """Capability gate (same as test_multihost): spawn-less sandboxes
    skip the subprocess integration tests instead of failing red."""
    if not _SPAWN_PROBE:
        env = dict(os.environ)
        env["PYTHONPATH"] = (_REPO + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        env["JAX_PLATFORMS"] = "cpu"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import pypulsar_tpu; print('OK')"],
                env=env, capture_output=True, text=True, timeout=120)
            _SPAWN_PROBE.append(
                (proc.returncode == 0 and "OK" in proc.stdout,
                 proc.stderr.strip().splitlines()[-1][-200:]
                 if proc.stderr.strip() else ""))
        except (OSError, subprocess.TimeoutExpired) as e:
            _SPAWN_PROBE.append((False, f"{type(e).__name__}: {e}"))
    ok, detail = _SPAWN_PROBE[0]
    if not ok:
        pytest.skip("environment capability: cannot spawn python "
                    f"subprocesses ({detail})")


# ---------------------------------------------------------------------------
# the bucket ladder


def test_bucket_ladder_values():
    assert buckets_enabled()
    # ceil to {2^k} U {3*2^k}; floor is the same ladder rounded down
    for n, (floor, ceil) in {1: (1, 1), 2: (2, 2), 3: (3, 3), 4: (4, 4),
                             5: (4, 6), 6: (6, 6), 7: (6, 8), 9: (8, 12),
                             13: (12, 16), 17: (16, 24), 23: (16, 24),
                             100: (96, 128)}.items():
        assert bucket_size(n) == ceil, n
        assert bucket_floor(n) == floor, n
    # idempotent: every ladder value maps to itself
    for v in (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128):
        assert bucket_size(v) == v == bucket_floor(v)


def test_bucket_rows_respects_multiple():
    # ladder first, then up to the mesh multiple
    assert bucket_rows(5) == 6
    assert bucket_rows(5, multiple=4) == 8
    assert bucket_rows(9, multiple=8) == 16
    assert bucket_rows(0) == 0


def test_bucket_disable_knob(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_COMPILE_BUCKETS", "0")
    assert not buckets_enabled()
    # bucket_size stays the pure ladder function; the knob gates the
    # call sites (bucket_rows / bucket_floor)
    assert bucket_floor(5) == 5
    # disabled, bucket_rows degrades to the plain multiple round-up
    assert bucket_rows(5, multiple=4) == 8
    assert bucket_rows(5) == 5


# ---------------------------------------------------------------------------
# plane_jit AOT registry


def test_plane_jit_second_dispatch_is_registry_hit():
    f = plane_jit(lambda x: (x * 2.0 + 1.0).sum(), name="t_second")
    x = jnp.ones((8, 16), jnp.float32)
    with telemetry.session() as tlm:
        first = np.asarray(f(x))
        t1 = tlm.counter_totals()
    assert t1.get("compile.cache_miss", 0) == 1
    assert t1.get("compile.cache_hit", 0) == 0
    assert t1.get("compile.ms", 0) > 0
    with telemetry.session() as tlm:
        second = np.asarray(f(x))
        t2 = tlm.counter_totals()
    assert t2.get("compile.cache_miss", 0) == 0  # the warm-leg contract
    assert t2.get("compile.cache_hit", 0) == 1
    np.testing.assert_array_equal(first, second)
    assert f.cache_size() == 1


def test_plane_jit_warm_precompiles_without_dispatch():
    f = plane_jit(lambda x: jnp.fft.rfft(x).real.sum(axis=-1),
                  name="t_warm")
    spec = jax.ShapeDtypeStruct((4, 64), np.float32)
    with telemetry.session() as tlm:
        assert f.warm(spec) is True
        assert f.warm(spec) is False  # already resident
        t1 = tlm.counter_totals()
    assert t1.get("compile.cache_miss", 0) == 1
    # the real dispatch at the warmed geometry never compiles
    with telemetry.session() as tlm:
        f(jnp.ones((4, 64), jnp.float32))
        t2 = tlm.counter_totals()
    assert t2.get("compile.cache_miss", 0) == 0
    assert t2.get("compile.cache_hit", 0) == 1


def test_plane_jit_positional_and_kwarg_calls_share_one_entry():
    f = plane_jit(lambda x, n: x * n, static_argnames=("n",),
                  name="t_bind")
    x = jnp.ones((4,), jnp.float32)
    np.testing.assert_array_equal(np.asarray(f(x, 3)),
                                  np.asarray(f(x, n=3)))
    assert f.cache_size() == 1  # sig.bind canonicalizes the call forms


def test_plane_jit_aot_knob_off_falls_back_to_plain_jit(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_COMPILE_AOT", "0")
    f = plane_jit(lambda x: x + 1.0, name="t_off")
    np.testing.assert_array_equal(
        np.asarray(f(jnp.zeros(3, jnp.float32))), np.ones(3, np.float32))
    assert f.cache_size() == 0


def test_plane_jit_traced_input_falls_back():
    inner = plane_jit(lambda x: x * 2.0, name="t_traced")

    @jax.jit
    def outer(x):
        return inner(x) + 1.0  # tracers are unkeyable -> plain jit

    with telemetry.session() as tlm:
        y = np.asarray(outer(jnp.ones(4, jnp.float32)))
        totals = tlm.counter_totals()
    np.testing.assert_array_equal(y, np.full(4, 3.0, np.float32))
    assert totals.get("compile.aot_fallback", 0) >= 1
    assert inner.cache_size() == 0


# ---------------------------------------------------------------------------
# warm-pool registry


def test_warm_stage_registry_and_error_accounting():
    # the production warmers self-register at module import
    import pypulsar_tpu.fold.engine  # noqa: F401
    import pypulsar_tpu.parallel.sweep  # noqa: F401

    assert {"fold", "sweep"} <= set(warmable_stages())
    assert warm_stage("no_such_stage", n_samples=1) == 0

    from pypulsar_tpu.compile import plane

    def _boom(**_geometry):
        raise RuntimeError("boom")

    register_warmer("_test_boom", _boom)
    try:
        with telemetry.session() as tlm:
            assert warm_stage("_test_boom") == 0  # never raises
            assert tlm.counter_totals().get("compile.warm_error", 0) == 1
    finally:
        with plane._warmers_lock:
            plane._warmers.pop("_test_boom", None)


def test_fold_warmer_covers_the_real_dispatch():
    from pypulsar_tpu.fold.engine import fold_parts_batch

    T, nbins, npart, batch = 4096, 16, 4, 5
    with telemetry.session() as tlm:
        n = warm_stage("fold", n_samples=T, downsamp=1, fold_nbins=nbins,
                       fold_npart=npart, fold_batch=batch)
        warmed = tlm.counter_totals().get("compile.cache_miss", 0)
    assert n >= 0 and warmed == n
    # real dispatch at the warmed geometry: bucket_rows(batch) rows
    series = np.random.RandomState(0).randn(T).astype(np.float32)
    K = bucket_rows(batch)
    bins = np.random.RandomState(1).randint(0, nbins, (K, T)).astype(np.int32)
    with telemetry.session() as tlm:
        fold_parts_batch(jnp.asarray(series), jnp.asarray(bins),
                         nbins, npart)
        totals = tlm.counter_totals()
    assert totals.get("compile.cache_miss", 0) == 0
    assert totals.get("compile.cache_hit", 0) >= 1


# ---------------------------------------------------------------------------
# end-to-end: sweeps and checkpoints


def _toy_obs(C=16, T=9000, seed=3):
    rng = np.random.RandomState(seed)
    freqs = (1500.0 - 4.0 * np.arange(C)).astype(np.float64)
    data = rng.randn(C, T).astype(np.float32)
    return freqs, data


def _block_gen(data, plan, payload):
    ov = plan.min_overlap
    T = data.shape[1]
    pos = 0
    while pos < T:
        n = min(payload + ov, T - pos)
        yield pos, data[:, pos:pos + n]
        pos += payload


def test_sweep_second_run_has_zero_compile_miss():
    """The headline contract: a second run at an already-seen geometry
    never compiles on the critical path."""
    from pypulsar_tpu.parallel.sweep import sweep_stream

    freqs, data = _toy_obs()
    dms = np.linspace(0.0, 40.0, 12)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=8, group_size=4)
    baseline = data.mean(axis=1, keepdims=True).astype(np.float32)
    payload = 2048

    with telemetry.session():
        r1 = sweep_stream(plan, _block_gen(data, plan, payload), payload,
                          chan_major=True, baseline=baseline)
    with telemetry.session() as tlm:
        r2 = sweep_stream(plan, _block_gen(data, plan, payload), payload,
                          chan_major=True, baseline=baseline)
        totals = tlm.counter_totals()
    assert totals.get("compile.cache_miss", 0) == 0
    assert totals.get("compile.cache_hit", 0) >= 1
    np.testing.assert_array_equal(r1.snr, r2.snr)
    np.testing.assert_array_equal(r1.peak_sample, r2.peak_sample)


def test_checkpoint_resume_across_bucket_shapes(tmp_path):
    """A checkpoint written under one padded group count resumes under
    another byte-identically: the fingerprint hashes real trials only,
    and padded trials replicate the last real DM, so the bucket ladder
    is an execution detail a resume may legally change."""
    from pypulsar_tpu.parallel.sweep import (
        SweepCheckpoint, padded_group_count, sweep_stream,
    )

    freqs, data = _toy_obs()
    dms = np.linspace(0.0, 40.0, 20)  # 5 groups of 4
    baseline = data.mean(axis=1, keepdims=True).astype(np.float32)
    payload = 2048
    kw = dict(nsub=8, group_size=4)
    # what the bucketing callers would pick (5 -> ladder 6) vs natural
    assert padded_group_count(5, 1) == 6
    plan_bkt = make_sweep_plan(dms, freqs, 1e-3, pad_groups_to=6, **kw)
    plan_nat = make_sweep_plan(dms, freqs, 1e-3, **kw)
    assert plan_bkt.n_trials != plan_nat.n_trials
    assert plan_bkt.n_real_trials == plan_nat.n_real_trials == 20

    ref = sweep_stream(plan_nat, _block_gen(data, plan_nat, payload),
                       payload, chan_major=True, baseline=baseline)

    class Killed(Exception):
        pass

    def killing_blocks(plan, n_before_kill):
        for i, (pos, blk) in enumerate(_block_gen(data, plan, payload)):
            if i >= n_before_kill:
                raise Killed()
            yield pos, blk

    ck = str(tmp_path / "bucket.ckpt.npz")
    with pytest.raises(Killed):
        sweep_stream(plan_bkt, killing_blocks(plan_bkt, 4), payload,
                     chan_major=True, baseline=baseline,
                     checkpoint=SweepCheckpoint(ck, every=1),
                     max_pending=1)
    assert os.path.exists(ck)

    res = sweep_stream(plan_nat, _block_gen(data, plan_nat, payload),
                       payload, chan_major=True, baseline=baseline,
                       checkpoint=SweepCheckpoint(ck, every=1))
    np.testing.assert_array_equal(res.snr, ref.snr)
    np.testing.assert_array_equal(res.peak_sample, ref.peak_sample)
    np.testing.assert_array_equal(res.mean, ref.mean)
    np.testing.assert_array_equal(res.std, ref.std)


# ---------------------------------------------------------------------------
# cross-process persistent cache

_CHILD = """
import json
import jax.numpy as jnp
from pypulsar_tpu.compile import plane_jit
from pypulsar_tpu.obs import telemetry

@plane_jit
def f(x):
    return (x * 2.0 + 1.0).sum()

with telemetry.session() as tlm:
    f(jnp.ones((16, 8), jnp.float32))
    print("TOTALS " + json.dumps(tlm.counter_totals()))
"""


def test_persistent_cache_shared_across_processes(tmp_path):
    """Two processes pointed at one PYPULSAR_TPU_COMPILE_CACHE: the
    second one's compile is a cross-host persistent hit."""
    _require_spawn()
    env = dict(os.environ)
    env["PYTHONPATH"] = (_REPO + os.pathsep
                         + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYPULSAR_TPU_COMPILE_CACHE"] = str(tmp_path / "xla")

    def run():
        proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("TOTALS ")][-1]
        return json.loads(line[len("TOTALS "):])

    t1 = run()
    assert t1.get("compile.cache_miss", 0) == 1
    assert t1.get("compile.persistent_hit", 0) == 0
    t2 = run()
    # fresh process: the in-process registry is cold (one miss), but the
    # executable comes off the shared persistent cache
    assert t2.get("compile.cache_miss", 0) == 1
    assert t2.get("compile.persistent_hit", 0) >= 1
