"""Sweep-engine tests: NumPy twin parity, chunked-streaming consistency,
multi-device sharding on the virtual CPU mesh, and end-to-end pulse recovery
(SURVEY.md §4 strategies 1-3)."""

import os

import numpy as np
import pytest

import jax

from pypulsar_tpu.core.spectra import Spectra
from pypulsar_tpu.ops import numpy_ref
from pypulsar_tpu.parallel import make_mesh, make_sweep_plan, sweep_spectra


def make_obs(C=64, T=4096, dt=1e-3, dm=80.0, seed=1, amp=6.0, t0=700):
    rng = np.random.RandomState(seed)
    freqs = (1500.0 - 2.0 * np.arange(C)).astype(np.float64)
    data = rng.randn(C, T).astype(np.float32)
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for c in range(C):
        idx = t0 + bins[c]
        if idx < T:
            data[c, idx] += amp
            if idx + 1 < T:
                data[c, idx + 1] += amp * 0.5
    return freqs, data


def twin_sweep_stats(data, plan, chunk_is_whole_T):
    """Float64 twin of _sweep_chunk_impl for a single whole-series chunk.

    Implements the sweep_stream SNR accumulation-order contract: per-channel
    baseline subtraction first (SNR is exactly invariant; end-of-data padding
    then sits at the baseline level), everything else in float64."""
    data = data - data.mean(axis=1, keepdims=True)
    C, T = data.shape
    W = max(plan.widths)
    out_len = T + W
    slack2 = plan.max_shift2
    need = out_len + slack2 + plan.max_shift1
    padded = np.zeros((C, need))
    padded[:, :T] = data
    per = C // plan.nsub
    D = plan.n_trials
    L1 = out_len + slack2
    s = np.zeros(D)
    ss = np.zeros(D)
    mb = np.zeros((D, len(plan.widths)))
    ab = np.zeros((D, len(plan.widths)), dtype=int)
    for gi in range(plan.n_groups):
        sliced = np.stack(
            [padded[c, plan.stage1_bins[gi, c] : plan.stage1_bins[gi, c] + L1] for c in range(C)]
        )
        sub = sliced.reshape(plan.nsub, per, L1).sum(axis=1)
        for ti in range(plan.group_size):
            d = gi * plan.group_size + ti
            ts = np.zeros(out_len)
            for si in range(plan.nsub):
                st = plan.stage2_bins[gi, ti, si]
                ts += sub[si, st : st + out_len]
            payload = ts[:T]
            s[d] = payload.sum()
            ss[d] = (payload ** 2).sum()
            cs = np.concatenate([[0.0], np.cumsum(ts)])
            for wi, w in enumerate(plan.widths):
                box = cs[w : w + T] - cs[:T]
                mb[d, wi] = box.max()
                ab[d, wi] = box.argmax()
    mean = s / T
    std = np.sqrt(np.maximum(ss / T - mean ** 2, 0.0))
    ws = np.array(plan.widths, dtype=np.float64)
    snr = (mb - ws[None, :] * mean[:, None]) / (
        np.sqrt(ws)[None, :] * np.where(std > 0, std, 1.0)[:, None]
    )
    return snr, ab


def test_sweep_matches_numpy_twin():
    # bound documented in the sweep_stream SNR accumulation-order contract:
    # f32-ulp-scale agreement with the float64 twin (measured ~1e-6 rel)
    freqs, data = make_obs()
    dms = np.linspace(0.0, 160.0, 48)
    spec = Spectra(freqs, 1e-3, data)
    res = sweep_spectra(spec, dms, nsub=16, group_size=8)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=16, group_size=8)
    ref_snr, ref_ab = twin_sweep_stats(data, plan, True)
    np.testing.assert_allclose(res.snr, ref_snr[: len(dms)], rtol=5e-6, atol=1e-4)
    np.testing.assert_array_equal(res.peak_sample, ref_ab[: len(dms)])


def test_sweep_snr_parity_with_dc_offset():
    """The contract bound must hold for realistic offset data (8-bit PSRFITS
    levels ~100x sigma), not just zero-mean noise: the engine's internal
    per-channel baseline subtraction makes f32 rounding relative to the
    fluctuation scale. Without it the deviation is ~0.2 SNR units."""
    freqs, data = make_obs()
    data = data + np.float32(96.0)  # constant DC: SNR exactly invariant
    dms = np.linspace(0.0, 160.0, 48)
    res = sweep_spectra(Spectra(freqs, 1e-3, data), dms, nsub=16, group_size=8)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=16, group_size=8)
    ref_snr, ref_ab = twin_sweep_stats(data.astype(np.float64), plan, True)
    np.testing.assert_allclose(res.snr, ref_snr[: len(dms)], rtol=5e-6, atol=1e-4)
    np.testing.assert_array_equal(res.peak_sample, ref_ab[: len(dms)])
    # reported moments stay in original units
    assert abs(res.mean.mean() - 96.0 * len(freqs)) < 1.0


def test_sweep_recovers_injection():
    dm_true, t0 = 80.0, 700
    freqs, data = make_obs(dm=dm_true, t0=t0)
    dms = np.linspace(0.0, 160.0, 81)  # 2 pc/cm^3 steps
    res = sweep_spectra(Spectra(freqs, 1e-3, data), dms, nsub=16, group_size=8)
    best = res.best(1)[0]
    assert abs(best["dm"] - dm_true) <= 4.0
    assert abs(best["sample"] - t0) <= 2
    assert best["snr"] > 15.0


def test_chunked_equals_unchunked():
    freqs, data = make_obs(T=4096)
    dms = np.linspace(0.0, 120.0, 32)
    spec = Spectra(freqs, 1e-3, data)
    full = sweep_spectra(spec, dms, nsub=16, group_size=8)
    chunked = sweep_spectra(spec, dms, nsub=16, group_size=8, chunk_payload=1024)
    np.testing.assert_allclose(chunked.snr, full.snr, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(chunked.peak_sample, full.peak_sample)


def test_sharded_sweep_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    freqs, data = make_obs()
    dms = np.linspace(0.0, 120.0, 64)
    spec = Spectra(freqs, 1e-3, data)
    single = sweep_spectra(spec, dms, nsub=16, group_size=8)
    mesh = make_mesh(axis_names=("dm",))
    sharded = sweep_spectra(spec, dms, nsub=16, group_size=8, mesh=mesh)
    np.testing.assert_allclose(sharded.snr, single.snr, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(sharded.peak_sample, single.peak_sample)


def test_plan_geometry():
    freqs = 1400.0 - 0.5 * np.arange(128)
    plan = make_sweep_plan(np.arange(100, dtype=float), freqs, 64e-6, nsub=32,
                           group_size=16, pad_groups_to=8)
    assert plan.n_groups == 8
    assert plan.n_trials == 128
    assert plan.n_real_trials == 100
    assert plan.stage1_bins.shape == (8, 128)
    assert plan.stage2_bins.shape == (8, 16, 32)
    assert (plan.stage1_bins >= 0).all() and (plan.stage2_bins >= 0).all()
    # higher DM -> larger max shift
    assert plan.stage2_bins[-1].max() >= plan.stage2_bins[0].max()


def test_sharded_2d_matches_single_device():
    """dm x time mesh with ppermute halo exchange == single-device result."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from pypulsar_tpu.parallel.sweep import make_sharded_sweep_chunk_2d, sweep_chunk

    freqs, data = make_obs(C=32, T=2048, dt=1e-3, dm=60.0)
    dms = np.linspace(0.0, 120.0, 32)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=8, group_size=8, pad_groups_to=4)
    mesh = make_mesh([4, 2], ("dm", "time"))
    T = data.shape[1]
    nt = 2
    local_payload = T // nt
    W = max(plan.widths)
    overlap = plan.min_overlap
    assert overlap < local_payload

    fn2d = make_sharded_sweep_chunk_2d(mesh, plan.nsub, local_payload, overlap,
                                       plan.max_shift2, plan.widths)
    darr = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P(None, "time")))
    s1 = jax.device_put(jnp.asarray(plan.stage1_bins), NamedSharding(mesh, P("dm")))
    s2 = jax.device_put(jnp.asarray(plan.stage2_bins), NamedSharding(mesh, P("dm")))
    s, ss, mb, ab = fn2d(darr, s1, s2)

    # single-device reference on the zero-padded whole series
    out_len = T + W
    need = out_len + plan.max_shift2 + plan.max_shift1
    padded = jnp.pad(jnp.asarray(data), ((0, 0), (0, need - T)))
    s0, ss0, mb0, ab0 = sweep_chunk(
        padded, jnp.asarray(plan.stage1_bins), jnp.asarray(plan.stage2_bins),
        plan.nsub, out_len, plan.max_shift2, plan.widths, T)
    np.testing.assert_allclose(np.asarray(s), np.asarray(s0), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(ss), np.asarray(ss0), rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(np.asarray(mb), np.asarray(mb0), rtol=1e-4, atol=1e-3)
    np.testing.assert_array_equal(np.asarray(ab), np.asarray(ab0))


def test_stream_rejects_short_interior_block():
    # interior blocks lacking the required overlap must raise, not silently
    # zero-pad (seam SNRs would be depressed with no error)
    from pypulsar_tpu.parallel.sweep import make_sweep_plan, sweep_stream

    freqs, data = make_obs(T=4096)
    dms = np.linspace(0.0, 120.0, 16)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=16, group_size=8)
    chunk = 1024

    def bad_blocks():  # no overlap at all
        for pos in range(0, 4096, chunk):
            yield pos, data[:, pos : pos + chunk].T

    with pytest.raises(ValueError, match="interior block"):
        sweep_stream(plan, bad_blocks(), chunk)


def test_chunked_short_remainder():
    # T % chunk smaller than min_overlap: the penultimate block is short but
    # contains all remaining data, which is legal (end-of-data padding)
    freqs, data = make_obs(T=3 * 1024 + 32)
    dms = np.linspace(0.0, 120.0, 16)
    spec = Spectra(freqs, 1e-3, data)
    full = sweep_spectra(spec, dms, nsub=16, group_size=8)
    chunked = sweep_spectra(spec, dms, nsub=16, group_size=8, chunk_payload=1024)
    np.testing.assert_allclose(chunked.snr, full.snr, rtol=1e-4, atol=1e-4)


def test_shift_segment_sum_matches_slice_rows():
    """The scan-based fused shift+segment-sum equals the vmapped gather."""
    import jax.numpy as jnp
    from pypulsar_tpu.parallel.sweep import _shift_segment_sum, _slice_rows

    rng = np.random.RandomState(7)
    N, L, length, seg = 32, 500, 300, 8
    rows = jnp.asarray(rng.randn(N, L).astype(np.float32))
    starts = jnp.asarray(rng.randint(0, L - length, size=N).astype(np.int32))
    ref = np.asarray(_slice_rows(rows, starts, length)).reshape(
        N // seg, seg, length).sum(axis=1)
    got = np.asarray(_shift_segment_sum(rows, starts, length, seg))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("engine", ["scan", "fourier", "tree"])
def test_sweep_engine_parity(engine):
    """Every chunk-kernel engine reproduces the gather formulation."""
    import jax.numpy as jnp
    from pypulsar_tpu.parallel.sweep import _sweep_chunk_impl

    rng = np.random.RandomState(3)
    C, T, nsub, group = 32, 2048, 8, 4
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    dms = np.linspace(0.0, 60.0, 8)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=nsub, group_size=group)
    W = max(plan.widths)
    out_len = 1024 + W
    need = out_len + plan.max_shift2 + plan.max_shift1
    padded = jnp.asarray(np.pad(data, ((0, 0), (0, max(need - T, 0)))))
    args = (padded, jnp.asarray(plan.stage1_bins),
            jnp.asarray(plan.stage2_bins))
    kw = dict(nsub=plan.nsub, out_len=out_len, slack2=plan.max_shift2,
              widths=plan.widths, stat_len=1024)
    from pypulsar_tpu.parallel.sweep import sweep_chunk

    ref = [np.asarray(x) for x in _sweep_chunk_impl(*args, **kw)]
    # dispatch through the public wrapper: the tree engine builds its
    # host merge tables there (a traced impl cannot host them)
    got = [np.asarray(x) for x in sweep_chunk(*args, engine=engine, **kw)]
    for a, b in zip(ref, got):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("mode", ["direct", "lut"])
def test_fourier_phase_mode_parity(mode):
    """The factored (default), direct, and lut phase formulations agree to
    f32 rounding — all share the exact int32-wraparound index math and
    differ only by one extra complex multiply (~3e-7 relative)."""
    import jax.numpy as jnp
    from pypulsar_tpu.ops.fourier_dedisperse import (
        fourier_chunk_len, sweep_chunk_fourier_impl)

    rng = np.random.RandomState(5)
    C, nsub, group = 32, 8, 4
    freqs = 1500.0 - 4.0 * np.arange(C)
    dms = np.linspace(0.0, 60.0, 8)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=nsub, group_size=group)
    W = max(plan.widths)
    out_len = 1024 + W
    need = out_len + plan.max_shift2 + plan.max_shift1
    data = jnp.asarray(rng.randn(C, need).astype(np.float32))
    args = (data, jnp.asarray(plan.stage1_bins),
            jnp.asarray(plan.stage2_bins), plan.nsub, out_len, plan.widths,
            1024, fourier_chunk_len(need))
    kw = dict(max_shift1=plan.max_shift1, max_shift2=plan.max_shift2)
    ref = [np.asarray(x) for x in
           sweep_chunk_fourier_impl(*args, phase_mode="factored", **kw)]
    got = [np.asarray(x) for x in
           sweep_chunk_fourier_impl(*args, phase_mode=mode, **kw)]
    for a, b in zip(ref, got):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-4)


def test_sweep_stream_fourier_engine_end_to_end():
    """Streamed multi-chunk sweep under engine='fourier' matches 'gather'."""
    from pypulsar_tpu.core.spectra import Spectra

    rng = np.random.RandomState(7)
    C, T = 32, 6000
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    dms = np.linspace(0.0, 60.0, 16)
    spec = Spectra(freqs, 1e-3, data)
    a = sweep_spectra(spec, dms, nsub=8, group_size=4, chunk_payload=2048,
                      engine="gather")
    b = sweep_spectra(spec, dms, nsub=8, group_size=4, chunk_payload=2048,
                      engine="fourier")
    np.testing.assert_allclose(b.snr, a.snr, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(b.peak_sample, a.peak_sample)
    np.testing.assert_allclose(b.mean, a.mean, rtol=1e-5, atol=1e-5)


def test_fourier_engine_snr_tolerance():
    """The PUBLISHED parity contract (README "Golden parity"; bench JSON
    ``fourier_snr_rel_tol``; ops/fourier_dedisperse.py docstring): engine=
    'gather' is the bit-exact-SNR reference formulation; the TPU-default
    fourier engine agrees to <=2e-6 relative SNR (measured worst case 5e-7
    across seeds/geometries; ~1e-6 on-chip under chunk-dependent XLA
    fusion). This test pins the documented number itself (VERDICT r4
    item 7 — one value cited everywhere)."""
    from pypulsar_tpu.core.spectra import Spectra

    rng = np.random.RandomState(19)
    C, T = 64, 8192
    freqs = 1500.0 - 2.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    data[:, 4000:4004] += 4.0  # a real pulse so peak SNRs are O(10)
    dms = np.linspace(0.0, 80.0, 32)
    spec = Spectra(freqs, 1e-3, data)
    a = sweep_spectra(spec, dms, nsub=16, group_size=8, engine="gather")
    b = sweep_spectra(spec, dms, nsub=16, group_size=8, engine="fourier")
    rel = np.abs(b.snr - a.snr) / np.maximum(np.abs(a.snr), 1.0)
    assert rel.max() <= 2e-6, f"fourier SNR rel err {rel.max():.2e} > 2e-6"


def test_checkpoint_kill_and_resume_bit_exact(tmp_path):
    """A sweep killed mid-stream and resumed from its checkpoint reproduces
    the uninterrupted result bit-for-bit (VERDICT r2 item 7)."""
    from pypulsar_tpu.parallel.sweep import SweepCheckpoint, sweep_stream

    rng = np.random.RandomState(11)
    C, T, payload = 32, 9000, 2048
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    dms = np.linspace(0.0, 60.0, 16)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=8, group_size=4)
    baseline = data.mean(axis=1, keepdims=True).astype(np.float32)

    def blocks():
        ov = plan.min_overlap
        pos = 0
        while pos < T:
            n = min(payload + ov, T - pos)
            yield pos, data[:, pos:pos + n]
            pos += payload

    ref = sweep_stream(plan, blocks(), payload, chan_major=True,
                       baseline=baseline)

    class Killed(Exception):
        pass

    def killing_blocks(n_before_kill):
        for i, (pos, blk) in enumerate(blocks()):
            if i >= n_before_kill:
                raise Killed()
            yield pos, blk

    ck_path = str(tmp_path / "sweep.ckpt.npz")
    ckpt = SweepCheckpoint(ck_path, every=1)
    with pytest.raises(Killed):
        # max_pending=1 so at least one chunk drains (and checkpoints)
        # before the stream dies
        sweep_stream(plan, killing_blocks(4), payload, chan_major=True,
                     baseline=baseline, checkpoint=ckpt, max_pending=1)
    assert os.path.exists(ck_path), "checkpoint file not written"

    res = sweep_stream(plan, blocks(), payload, chan_major=True,
                       baseline=baseline,
                       checkpoint=SweepCheckpoint(ck_path, every=1))
    np.testing.assert_array_equal(res.snr, ref.snr)
    np.testing.assert_array_equal(res.peak_sample, ref.peak_sample)
    np.testing.assert_array_equal(res.mean, ref.mean)
    np.testing.assert_array_equal(res.std, ref.std)
    assert not os.path.exists(ck_path), "checkpoint not cleaned up"


def test_choose_group_size_scales_with_trial_density():
    from pypulsar_tpu.parallel import choose_group_size

    freqs = (1500.0 - 300.0 / 1024 * np.arange(1024)).astype(np.float64)
    dt = 64e-6
    # dDM ~ 0.031 / 0.12 / 7.9 pc/cm^3
    denser = np.linspace(0.0, 500.0, 16384)
    dense = np.linspace(0.0, 500.0, 4096)
    sparse = np.linspace(0.0, 500.0, 64)
    g_denser = choose_group_size(denser, freqs, dt, nsub=64)
    g_dense = choose_group_size(dense, freqs, dt, nsub=64)
    g_sparse = choose_group_size(sparse, freqs, dt, nsub=64)
    assert g_denser > g_dense > g_sparse  # monotone in trial density
    assert g_denser == 128  # hits max_group
    assert g_sparse <= 4
    assert choose_group_size([10.0], freqs, dt) == 1  # single trial
    # the chosen group's own smearing respects the bound
    from pypulsar_tpu.core import psrmath

    bw_sub = 300.0 / 64
    for g, dms in ((g_dense, dense), (g_sparse, sparse)):
        ddm = float(np.diff(dms)[0])
        # worst trial sits ((g-1)/2) steps from the group mean DM
        assert psrmath.dm_smear(((g - 1) / 2) * ddm, bw_sub,
                                float(freqs.min())) <= 1.0 * dt


def test_checkpoint_resume_with_chunk_peaks(tmp_path):
    """keep_chunk_peaks persists through a kill-and-resume: the multi-
    event list matches the uninterrupted run exactly, and a checkpoint
    written without peaks is not resumed into a peak run."""
    from pypulsar_tpu.parallel.sweep import SweepCheckpoint, sweep_stream

    rng = np.random.RandomState(13)
    C, T, payload = 32, 9000, 2048
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    data[:, 1000] += 4.0  # chunk-0 event
    data[:, 7000] += 4.0  # chunk-3 event
    # 14 trials with group_size 4 -> padded to 16: n_real < n_trials
    # exercises the chunk-peak slice against the padded moment arrays
    dms = np.linspace(0.0, 60.0, 14)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=8, group_size=4)
    baseline = data.mean(axis=1, keepdims=True).astype(np.float32)

    def blocks():
        ov = plan.min_overlap
        pos = 0
        while pos < T:
            n = min(payload + ov, T - pos)
            yield pos, data[:, pos:pos + n]
            pos += payload

    ref = sweep_stream(plan, blocks(), payload, chan_major=True,
                       baseline=baseline, keep_chunk_peaks=True)
    ref_events = ref.events(5.0)
    assert len({e["sample"] // payload for e in ref_events}) >= 2

    class Killed(Exception):
        pass

    def killing_blocks(n):
        for i, (pos, blk) in enumerate(blocks()):
            if i >= n:
                raise Killed()
            yield pos, blk

    ck = str(tmp_path / "pk.ckpt.npz")
    with pytest.raises(Killed):
        sweep_stream(plan, killing_blocks(3), payload, chan_major=True,
                     baseline=baseline, keep_chunk_peaks=True,
                     checkpoint=SweepCheckpoint(ck, every=1),
                     max_pending=1)
    assert os.path.exists(ck)
    res = sweep_stream(plan, blocks(), payload, chan_major=True,
                       baseline=baseline, keep_chunk_peaks=True,
                       checkpoint=SweepCheckpoint(ck, every=1))
    np.testing.assert_array_equal(res.chunk_snr, ref.chunk_snr)
    np.testing.assert_array_equal(res.chunk_sample, ref.chunk_sample)
    assert res.events(5.0) == ref_events

    # a peak-less checkpoint must not satisfy a keep_chunk_peaks resume
    ck2 = str(tmp_path / "nopk.ckpt.npz")
    with pytest.raises(Killed):
        sweep_stream(plan, killing_blocks(3), payload, chan_major=True,
                     baseline=baseline,
                     checkpoint=SweepCheckpoint(ck2, every=1),
                     max_pending=1)
    res2 = sweep_stream(plan, blocks(), payload, chan_major=True,
                        baseline=baseline, keep_chunk_peaks=True,
                        checkpoint=SweepCheckpoint(ck2, every=1))
    np.testing.assert_array_equal(res2.chunk_snr, ref.chunk_snr)


def test_checkpoint_fingerprint_mismatch_restarts(tmp_path):
    """A checkpoint from different sweep parameters is ignored."""
    from pypulsar_tpu.parallel.sweep import SweepCheckpoint, sweep_stream

    rng = np.random.RandomState(12)
    C, T, payload = 32, 5000, 2048
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    plan_a = make_sweep_plan(np.linspace(0, 60, 8), freqs, 1e-3,
                             nsub=8, group_size=4)
    plan_b = make_sweep_plan(np.linspace(0, 80, 8), freqs, 1e-3,
                             nsub=8, group_size=4)

    def blocks(plan):
        ov = plan.min_overlap
        pos = 0
        while pos < T:
            n = min(payload + ov, T - pos)
            yield pos, data[:, pos:pos + n]
            pos += payload

    ck = str(tmp_path / "x.npz")
    sweep_stream(plan_a, blocks(plan_a), payload, chan_major=True,
                 checkpoint=SweepCheckpoint(ck, every=1, cleanup=False))
    ref_b = sweep_stream(plan_b, blocks(plan_b), payload, chan_major=True)
    got_b = sweep_stream(plan_b, blocks(plan_b), payload, chan_major=True,
                         checkpoint=SweepCheckpoint(ck, every=1))
    np.testing.assert_array_equal(got_b.snr, ref_b.snr)


def test_ddplan_staged_checkpoint_resume(tmp_path):
    """Killing a staged DDplan sweep mid-plan resumes completed steps from
    their done markers and reproduces the uninterrupted result."""
    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.parallel import staged
    from pypulsar_tpu.plan.ddplan import Observation

    rng = np.random.RandomState(13)
    C, T = 32, 16384
    dt = 1e-3
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    spec = Spectra(freqs, dt, data)
    obs = Observation(dt=dt, fctr=float(freqs.mean()),
                      BW=float(freqs.max() - freqs.min() + 4.0), numchan=C)
    plan = obs.gen_ddplan(0.0, 400.0)
    assert len(plan.DDsteps) >= 2, "test needs a multi-step plan"

    ref = staged.sweep_ddplan(spec, plan, nsub=8, group_size=4)

    base = str(tmp_path / "stg")
    # interrupt after the first step by making the second step fail once
    calls = {"n": 0}
    orig = staged._run_step

    def failing_run_step(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise KeyboardInterrupt()
        return orig(*a, **kw)

    staged._run_step = failing_run_step
    try:
        with pytest.raises(KeyboardInterrupt):
            staged.sweep_ddplan(spec, plan, nsub=8, group_size=4,
                                checkpoint_path=base)
    finally:
        staged._run_step = orig
    assert os.path.exists(base + ".step0.done.npz")

    got = staged.sweep_ddplan(spec, plan, nsub=8, group_size=4,
                              checkpoint_path=base)
    assert len(got.steps) == len(ref.steps)
    for sa, sb in zip(got.steps, ref.steps):
        np.testing.assert_array_equal(sa.result.snr, sb.result.snr)
        np.testing.assert_array_equal(sa.result.peak_sample,
                                      sb.result.peak_sample)
    assert not os.path.exists(base + ".step0.done.npz"), "markers not cleared"


def test_sweep_resident_matches_streamed():
    """The single-dispatch resident sweep is bit-identical to the streamed
    path at the same chunking (same per-chunk kernels, same host-order
    f64 accumulation)."""
    from pypulsar_tpu.parallel.sweep import sweep_resident

    freqs, data = make_obs(T=4096)
    dms = np.linspace(0.0, 120.0, 32)
    spec = Spectra(freqs, 1e-3, data)
    streamed = sweep_spectra(spec, dms, nsub=16, group_size=8,
                             chunk_payload=1024)
    resident = sweep_resident(spec, dms, nsub=16, group_size=8,
                              chunk_payload=1024)
    np.testing.assert_array_equal(resident.snr, streamed.snr)
    np.testing.assert_array_equal(resident.peak_sample, streamed.peak_sample)
    np.testing.assert_array_equal(resident.mean, streamed.mean)


def test_sweep_resident_sharded_matches():
    from pypulsar_tpu.parallel.sweep import sweep_resident

    freqs, data = make_obs(T=4096)
    dms = np.linspace(0.0, 120.0, 64)
    spec = Spectra(freqs, 1e-3, data)
    mesh = make_mesh(axis_names=("dm",))
    single = sweep_resident(spec, dms, nsub=16, group_size=8,
                            chunk_payload=2048)
    sharded = sweep_resident(spec, dms, nsub=16, group_size=8,
                             chunk_payload=2048, mesh=mesh)
    np.testing.assert_allclose(sharded.snr, single.snr, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(sharded.peak_sample, single.peak_sample)


def test_bench_budget_shapes():
    """bench.py's HBM budgeting: fits in the budget, power-of-two FFT
    lengths, sane pending depth (VERDICT r2 item 1)."""
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "bench", _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    C = 1024
    freqs = (1500.0 - 300.0 / C * np.arange(C)).astype(np.float64)
    dms = np.linspace(0.0, 500.0, 1024)
    plan = make_sweep_plan(dms, freqs, 64e-6, nsub=64, group_size=32)
    T, payload, n, max_pending = bench.budget_shapes(C, 1 << 21, plan, 16e9)
    assert n & (n - 1) == 0  # power of two
    assert payload == n - plan.min_overlap
    assert 1 <= max_pending <= 4
    # accounting: dataset + pending chunks + workspace within 75% of HBM
    total = 4 * C * T + max_pending * 4 * C * n + 3 * 4 * C * n
    assert total <= 0.80 * 16e9
    # a tiny budget still returns a usable (min-sized) configuration
    T2, payload2, n2, mp2 = bench.budget_shapes(C, 1 << 21, plan, 2e9)
    assert T2 >= payload2 and mp2 >= 1

    # analytic traffic is positive and scales with T
    b1 = bench.sweep_bytes(plan, C, T, payload, n, "fourier")
    b2 = bench.sweep_bytes(plan, C, 2 * T, payload, n, "fourier")
    assert 0 < b1 < b2


def test_multi_event_chunk_peaks():
    """keep_chunk_peaks records one event per (chunk, trial, width): two
    injected pulses in different chunks both appear in events(), while the
    single-best fields keep only the stronger."""
    rng = np.random.RandomState(51)
    C, T, dt, dm = 32, 8192, 1e-3, 60.0
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for t0, amp in ((1000, 10.0), (6000, 7.0)):
        for c in range(C):
            idx = t0 + bins[c]
            if idx < T:
                data[c, idx] += amp

    from pypulsar_tpu.parallel.sweep import sweep_stream

    dms = np.linspace(0.0, 120.0, 16)
    plan = make_sweep_plan(dms, freqs, dt, nsub=8, group_size=4)
    payload = 2048
    baseline = data.mean(axis=1, keepdims=True).astype(np.float32)

    def blocks():
        ov = plan.min_overlap
        pos = 0
        while pos < T:
            n = min(payload + ov, T - pos)
            yield pos, data[:, pos:pos + n]
            pos += payload

    res = sweep_stream(plan, blocks(), payload, chan_major=True,
                       baseline=baseline, keep_chunk_peaks=True)
    events = res.events(8.0)
    assert events
    # both pulses present at a near-true DM
    near = [e for e in events if abs(e["dm"] - dm) <= 16.0]
    samples = {e["sample"] // 1000 for e in near}
    assert 1 in samples and 6 in samples, near
    # the single-best surface keeps only the stronger pulse
    di = int(np.argmin(np.abs(res.dms - dm)))
    wi = int(np.argmax(res.snr[di]))
    assert abs(res.peak_sample[di, wi] - 1000) < 50

    # without the flag, events() refuses
    res2 = sweep_stream(plan, blocks(), payload, chan_major=True,
                        baseline=baseline)
    with pytest.raises(ValueError):
        res2.events(8.0)


# ---------------------------------------------------------------------------
# tree dedispersion engine (round 16): exact-shift merge tree + snap
# ---------------------------------------------------------------------------


def test_tree_engine_snr_tolerance():
    """The tree engine's PUBLISHED parity contract, pinned at the SAME
    contract geometry as test_fourier_engine_snr_tolerance: engine=
    'gather' is the bit-exact-SNR reference; the tree engine's balanced
    pairwise summation agrees to <=2e-6 relative SNR (measured ~1.0e-6
    here — tighter than the fourier engine's 2.0e-6 at this geometry,
    because the per-channel shifts are byte-for-bit the same s1+s2 and
    only the f32 add ORDER differs)."""
    from pypulsar_tpu.core.spectra import Spectra

    rng = np.random.RandomState(19)
    C, T = 64, 8192
    freqs = 1500.0 - 2.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    data[:, 4000:4004] += 4.0  # a real pulse so peak SNRs are O(10)
    dms = np.linspace(0.0, 80.0, 32)
    spec = Spectra(freqs, 1e-3, data)
    a = sweep_spectra(spec, dms, nsub=16, group_size=8, engine="gather")
    b = sweep_spectra(spec, dms, nsub=16, group_size=8, engine="tree")
    rel = np.abs(b.snr - a.snr) / np.maximum(np.abs(a.snr), 1.0)
    assert rel.max() <= 2e-6, f"tree SNR rel err {rel.max():.2e} > 2e-6"
    np.testing.assert_array_equal(b.peak_sample, a.peak_sample)


def test_tree_exact_shift_snap():
    """The tentpole's exactness claim: every trial's tree series applies
    BYTE-FOR-BIT the same per-channel integer shift s1+s2 the direct
    engine applies — checked against an f64 direct-shift sum (agreement
    at f32 rounding of the SUM, with zero shift/index error: a
    one-sample shift slip would show up as O(1) differences)."""
    from pypulsar_tpu.parallel.sweep import dedisperse_series_chunk

    rng = np.random.RandomState(7)
    C, nsub, group = 48, 8, 4  # non-pow2 nchan: odd-carry merge levels
    freqs = 1500.0 - 4.0 * np.arange(C)
    dms = np.linspace(0.0, 60.0, 10)  # pads to 12 trials
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=nsub, group_size=group)
    out_len = 512
    need = out_len + plan.max_shift2 + plan.max_shift1
    data = rng.randn(C, need).astype(np.float32)
    got = np.asarray(dedisperse_series_chunk(
        data, plan.stage1_bins, plan.stage2_bins, plan.nsub, out_len,
        plan.max_shift2, "tree"))
    per = C // plan.nsub
    tot = (plan.stage1_bins[:, None, :]
           + np.repeat(plan.stage2_bins, per, axis=2)).reshape(-1, C)
    d64 = data.astype(np.float64)
    for d in range(plan.n_trials):
        exact = np.zeros(out_len)
        for c in range(C):
            exact += d64[c, tot[d, c]:tot[d, c] + out_len]
        np.testing.assert_allclose(got[d], exact, rtol=2e-5, atol=2e-4)


def test_tree_streamed_nonpow2_chunks_match_gather():
    """Streamed multi-chunk tree sweep — non-power-of-two chunk payload
    AND a trailing partial chunk — matches the gather engine within the
    engine-parity tolerance, with identical peak samples."""
    from pypulsar_tpu.core.spectra import Spectra

    rng = np.random.RandomState(7)
    C, T = 32, 6100  # 6100 / 1000 -> trailing partial chunk
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    dms = np.linspace(0.0, 60.0, 16)
    spec = Spectra(freqs, 1e-3, data)
    a = sweep_spectra(spec, dms, nsub=8, group_size=4, chunk_payload=1000,
                      engine="gather")
    b = sweep_spectra(spec, dms, nsub=8, group_size=4, chunk_payload=1000,
                      engine="tree")
    np.testing.assert_allclose(b.snr, a.snr, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(b.peak_sample, a.peak_sample)
    np.testing.assert_allclose(b.mean, a.mean, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n_dms", [64, 44])
def test_tree_sharded_bit_identical(n_dms):
    """'dm'-mesh tree sweep is BIT-identical to the unsharded tree sweep
    — a per-trial row's merge structure is fixed, so per-device tables
    cannot change any value (a stronger contract than the other engines'
    allclose). n_dms=44 with group 8 exercises the 6-groups-on-4-devices
    padding case."""
    import jax

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    freqs, data = make_obs()
    dms = np.linspace(0.0, 120.0, n_dms)
    spec = Spectra(freqs, 1e-3, data)
    single = sweep_spectra(spec, dms, nsub=16, group_size=8, engine="tree")
    mesh = make_mesh([4], ("dm",), devices=jax.devices()[:4])
    sharded = sweep_spectra(spec, dms, nsub=16, group_size=8,
                            engine="tree", mesh=mesh)
    np.testing.assert_array_equal(sharded.snr, single.snr)
    np.testing.assert_array_equal(sharded.peak_sample, single.peak_sample)
    np.testing.assert_array_equal(sharded.mean, single.mean)


def test_tree_checkpoint_kill_and_resume_bit_exact(tmp_path):
    """Kill+resume under engine='tree' reproduces the uninterrupted
    result bit-for-bit through the EXISTING checkpoint machinery (the
    engine is part of the checkpoint fingerprint context, so a tree
    checkpoint can only resume a tree run)."""
    from pypulsar_tpu.parallel.sweep import SweepCheckpoint, sweep_stream

    rng = np.random.RandomState(11)
    C, T, payload = 32, 9000, 2048
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(C, T).astype(np.float32)
    dms = np.linspace(0.0, 60.0, 16)
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=8, group_size=4)
    baseline = data.mean(axis=1, keepdims=True).astype(np.float32)

    def blocks():
        ov = plan.min_overlap
        pos = 0
        while pos < T:
            n = min(payload + ov, T - pos)
            yield pos, data[:, pos:pos + n]
            pos += payload

    ref = sweep_stream(plan, blocks(), payload, chan_major=True,
                       baseline=baseline, engine="tree")

    class Killed(Exception):
        pass

    def killing_blocks(n_before_kill):
        for i, (pos, blk) in enumerate(blocks()):
            if i >= n_before_kill:
                raise Killed()
            yield pos, blk

    ck_path = str(tmp_path / "tree.ckpt.npz")
    with pytest.raises(Killed):
        sweep_stream(plan, killing_blocks(3), payload, chan_major=True,
                     baseline=baseline, engine="tree", max_pending=1,
                     checkpoint=SweepCheckpoint(ck_path, every=1))
    assert os.path.exists(ck_path)
    # a GATHER run must NOT resume the tree checkpoint (engine is in the
    # fingerprint context) — it restarts and still matches its own ref
    g_ref = sweep_stream(plan, blocks(), payload, chan_major=True,
                         baseline=baseline, engine="gather")
    g_got = sweep_stream(plan, blocks(), payload, chan_major=True,
                         baseline=baseline, engine="gather",
                         checkpoint=SweepCheckpoint(ck_path, every=1,
                                                    cleanup=False))
    np.testing.assert_array_equal(g_got.snr, g_ref.snr)
    res = sweep_stream(plan, blocks(), payload, chan_major=True,
                       baseline=baseline, engine="tree",
                       checkpoint=SweepCheckpoint(ck_path, every=1))
    np.testing.assert_array_equal(res.snr, ref.snr)
    np.testing.assert_array_equal(res.peak_sample, ref.peak_sample)
    np.testing.assert_array_equal(res.mean, ref.mean)


def test_tree_plan_structure_and_cache():
    """TreePlan structural invariants: exact add accounting beats the
    two-stage direct count at a dense trial grid, the level count is
    ceil(log2(nchan)) with odd carries, and the digest cache returns the
    SAME object for repeated (even device-array) table inputs."""
    import jax.numpy as jnp

    from pypulsar_tpu.ops.tree_dedisperse import plan_from_bins

    C = 64
    freqs = 1500.0 - 2.0 * np.arange(C)
    dms = np.linspace(0.0, 120.0, 256)  # dense: heavy profile sharing
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=16, group_size=8)
    tp = plan_from_bins(plan.stage1_bins, plan.stage2_bins)
    assert tp.n_levels == 6  # ceil(log2(64))
    assert len(tp.rows_per_level) == tp.n_levels
    assert tp.rows == max(C, max(tp.rows_per_level))
    G, g, S = plan.stage2_bins.shape
    direct_adds = G * (C - S) + plan.n_trials * (S - 1)
    assert 0 < tp.adds_per_sample < direct_adds
    # snap offsets: within the exact total-shift bound, and the top
    # reference channel pins the minimum at zero
    assert tp.trial_off.min() == 0
    assert tp.trial_off.max() <= tp.pad
    # digest cache: same tables -> same plan object, device arrays too
    assert plan_from_bins(plan.stage1_bins, plan.stage2_bins) is tp
    assert plan_from_bins(jnp.asarray(plan.stage1_bins),
                          jnp.asarray(plan.stage2_bins)) is tp


def test_tree_engine_guards():
    """The tree engine's explicit non-goals fail loudly: the resident
    single-program sweep, the dm x time 2-D mesh, and a traced
    _sweep_chunk_impl all raise instead of silently falling back."""
    from pypulsar_tpu.parallel.sweep import (
        _sweep_chunk_impl,
        make_sharded_sweep_chunk_2d,
        sweep_resident,
    )

    freqs, data = make_obs(T=2048)
    dms = np.linspace(0.0, 120.0, 16)
    spec = Spectra(freqs, 1e-3, data)
    with pytest.raises(ValueError, match="streamed"):
        sweep_resident(spec, dms, nsub=16, group_size=8, engine="tree")
    mesh = make_mesh([4, 2], ("dm", "time"))
    plan = make_sweep_plan(dms, freqs, 1e-3, nsub=16, group_size=8,
                           pad_groups_to=4)
    with pytest.raises(ValueError, match="1-D 'dm' mesh"):
        make_sharded_sweep_chunk_2d(mesh, plan.nsub, 1024,
                                    plan.min_overlap, plan.max_shift2,
                                    plan.widths, engine="tree")
    with pytest.raises(ValueError, match="traced"):
        _sweep_chunk_impl(np.zeros((4, 64), np.float32),
                          plan.stage1_bins, plan.stage2_bins, nsub=16,
                          out_len=32, slack2=0, widths=(1,), stat_len=32,
                          engine="tree")


def test_cli_engine_validation(tmp_path, capsys):
    """--engine is validated at ARGPARSE time against the ENGINES
    registry with a difflib closest-match hint (the cli/__main__
    unknown-tool pattern), and PYPULSAR_TPU_SWEEP_ENGINE gets the same
    early validation — neither reaches resolve_engine mid-run."""
    from pypulsar_tpu.cli import sweep as cli_sweep

    with pytest.raises(SystemExit) as e:
        cli_sweep.main(["x.fil", "--numdms", "4", "--engine", "fourrier"])
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "did you mean 'fourier'?" in err
    assert "tree" in err  # the registry listing includes the new engine

    os.environ["PYPULSAR_TPU_SWEEP_ENGINE"] = "tre"
    try:
        with pytest.raises(SystemExit) as e:
            cli_sweep.main(["x.fil", "--numdms", "4"])
        assert e.value.code == 2
        assert "did you mean 'tree'?" in capsys.readouterr().err
        # an explicit (valid) --engine never consults the env knob, so
        # the typo must NOT abort such a run at the parse stage: the run
        # proceeds PAST argparse and the env check, and dies only when
        # the (nonexistent) input is opened — anything but exit 2
        with pytest.raises(Exception) as e:
            cli_sweep.main(["x.fil", "--numdms", "4", "--engine",
                            "gather"])
        assert not isinstance(e.value, SystemExit)
        assert "SWEEP_ENGINE" not in capsys.readouterr().err
    finally:
        del os.environ["PYPULSAR_TPU_SWEEP_ENGINE"]


def test_dedisp_roofline_tool():
    """tools/dedisp_roofline.py (round 16): the structural work
    accounting behind the BENCHNOTES complexity claims — tree adds/cell
    beat the two-stage direct engine at a dense grid and grow ~log2
    with nchan at a fixed DM grid while naive grows ~nchan."""
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "dedisp_roofline", _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            "tools", "dedisp_roofline.py"))
    roof = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(roof)

    dm = roof.diagonal_dm(128, 64e-6, 1500.0, 300.0)
    rec = roof.analyze(128, 256, 4096, dm, nsub=32, group_size=16)
    a = rec["adds_per_cell"]
    assert a["tree"] < a["direct_two_stage"] < a["naive"]
    assert rec["tree"]["merge_levels"] == 7  # ceil(log2(128))
    assert sum(rec["tree"]["rows_per_level"]) \
        >= rec["tree"]["adds_per_sample_all_trials"]
    s = roof.scaling_sweep([64, 128, 256], 256, 4096, dm, 32, 16,
                           64e-6, 1500.0, 300.0)
    g = s["growth"]
    assert g["naive"] > 3.5  # ~nchan over a 4x range
    assert g["tree"] < 2.0   # ~log2(nchan)


def test_default_chunk_payload_bounds():
    """Round-5 regression: the streaming default payload is BOUNDED
    (DEFAULT_CHUNK_FFT_LEN-derived) — the old whole-file default made a
    --chunk-less sweep of an hour-scale file try to build one ~2^26-
    sample chunk (a ~275 GB device buffer). The helper must also grow
    past overlaps that don't fit half the FFT."""
    from pypulsar_tpu.parallel.sweep import (DEFAULT_CHUNK_FFT_LEN,
                                             default_chunk_payload)

    p = default_chunk_payload(8122)
    assert p == DEFAULT_CHUNK_FFT_LEN - 8122
    big = default_chunk_payload(DEFAULT_CHUNK_FFT_LEN)  # overlap >= n/2
    assert big > 0 and (big + DEFAULT_CHUNK_FFT_LEN
                        ) & (big + DEFAULT_CHUNK_FFT_LEN - 1) == 0
