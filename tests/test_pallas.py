"""Pallas boxcar-stats kernel: interpret-mode parity vs the lax twin."""

import numpy as np
import pytest

from pypulsar_tpu.ops.pallas_kernels import boxcar_stats


@pytest.mark.parametrize("D,T,stat_len", [(8, 256, 224), (13, 512, 480),
                                          (3, 160, 128)])
def test_boxcar_stats_interpret_matches_lax(D, T, stat_len):
    rng = np.random.RandomState(0)
    ts = rng.randn(D, T).astype(np.float32)
    ts[1, 50:58] += 25.0  # strong pulse in trial 1
    widths = (1, 2, 4, 8, 16, 32)
    s_l, ss_l, mb_l, ab_l = boxcar_stats(ts, widths, stat_len,
                                         backend="lax")
    s_p, ss_p, mb_p, ab_p = boxcar_stats(ts, widths, stat_len,
                                         backend="interpret")
    np.testing.assert_allclose(np.asarray(s_p), np.asarray(s_l),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ss_p), np.asarray(ss_l),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(mb_p), np.asarray(mb_l),
                               rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(ab_p), np.asarray(ab_l))


def test_boxcar_stats_finds_pulse():
    rng = np.random.RandomState(1)
    D, T, stat_len = 8, 512, 480
    ts = rng.randn(D, T).astype(np.float32)
    ts[3, 100:116] += 12.0
    widths = (1, 4, 16, 32)
    s, ss, mb, ab = boxcar_stats(ts, widths, stat_len, backend="interpret")
    # trial 3's width-16 boxcar peaks at the injected pulse
    assert int(np.argmax(np.asarray(mb)[:, 2])) == 3
    assert abs(int(np.asarray(ab)[3, 2]) - 100) <= 1
    # sums match the straightforward computation
    np.testing.assert_allclose(np.asarray(s),
                               ts[:, :stat_len].sum(axis=1), rtol=1e-5)


def test_boxcar_stats_validates_length():
    ts = np.zeros((4, 100), dtype=np.float32)
    with pytest.raises(ValueError):
        boxcar_stats(ts, (64,), 100, backend="lax")
