"""PSRFITS reader/writer + rfifind mask tests (SURVEY.md §4 strategy 4:
byte-level round trips; parity targets reference formats/psrfits.py)."""

import numpy as np
import pytest

from pypulsar_tpu.io import psrfits, rfimask


def _mkdata(nchan=16, nspec=200, seed=0, lo=True):
    rng = np.random.RandomState(seed)
    data = rng.randint(0, 200, size=(nchan, nspec)).astype(np.float32)
    freqs = 1400.0 + np.arange(nchan) * 2.0  # low->high in channel index
    return data, freqs


def test_unpack_4bit_roundtrip():
    vals = np.arange(16, dtype=np.uint8)
    packed = (vals[0::2] & 15) | (vals[1::2] << 4)
    assert np.array_equal(psrfits.unpack_4bit(packed), vals)


def test_unpack_2bit_1bit():
    b = np.array([0b11100100], dtype=np.uint8)
    assert np.array_equal(psrfits.unpack_2bit(b), [0, 1, 2, 3])
    b = np.array([0b10110001], dtype=np.uint8)
    assert np.array_equal(psrfits.unpack_1bit(b), [1, 0, 0, 0, 1, 1, 0, 1])


@pytest.mark.parametrize("nbits", [8, 32, 4])
def test_roundtrip_get_spectra(tmp_path, nbits):
    data, freqs = _mkdata()
    if nbits == 4:
        data = np.mod(data, 16).astype(np.float32)
    fn = str(tmp_path / "fake.fits")
    psrfits.write_psrfits(fn, data, freqs, tsamp=1e-3, nsamp_per_subint=64,
                          nbits=nbits)
    with psrfits.PsrfitsFile(fn) as pf:
        assert pf.nchan == 16
        assert pf.nbits == nbits
        assert pf.tsamp == 1e-3
        spec = pf.get_spectra(0, 200)
    # Spectra is high-frequency-first; our data was low-first
    np.testing.assert_allclose(np.asarray(spec.data), data[::-1, :], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(spec.freqs), freqs[::-1])


def test_get_spectra_subint_spanning_and_offsets(tmp_path):
    data, freqs = _mkdata(nchan=8, nspec=300)
    fn = str(tmp_path / "fake.fits")
    psrfits.write_psrfits(fn, data, freqs, tsamp=5e-4, nsamp_per_subint=64,
                          nbits=32)
    with psrfits.PsrfitsFile(fn) as pf:
        # span three subints with odd start
        spec = pf.get_spectra(50, 150)
        np.testing.assert_allclose(np.asarray(spec.data), data[::-1, 50:200],
                                   rtol=1e-6)
        assert spec.starttime == pytest.approx(50 * 5e-4)
        with pytest.raises(ValueError):
            pf.get_spectra(300, 100)  # past EOF (file padded to 320 is not
            # exposed: nspec = nsub*nsblk = 320) -> valid; ask beyond that
        with pytest.raises(ValueError):
            pf.get_spectra(0, 10_000)


def test_scales_offsets_weights_applied(tmp_path):
    data, freqs = _mkdata(nchan=4, nspec=64)
    fn = str(tmp_path / "fake.fits")
    scales = np.array([1.0, 2.0, 0.5, 1.5], np.float32)
    offsets = np.array([0.0, 10.0, -5.0, 1.0], np.float32)
    weights = np.array([1.0, 1.0, 0.0, 1.0], np.float32)
    psrfits.write_psrfits(fn, data, freqs, tsamp=1e-3, nsamp_per_subint=64,
                          nbits=32, scales=scales, offsets=offsets,
                          weights=weights)
    with psrfits.PsrfitsFile(fn) as pf:
        si = pf.specinfo
        assert si.need_scale and si.need_offset and si.need_weight
        raw = pf.read_subint(0, apply_weights=False, apply_scales=False,
                             apply_offsets=False)
        np.testing.assert_allclose(raw.T, data, rtol=1e-6)
        cooked = pf.read_subint(0)
        expect = ((data.T * scales) + offsets) * weights
        np.testing.assert_allclose(cooked, expect, rtol=1e-6)


def test_specinfo_fields_and_str(tmp_path):
    data, freqs = _mkdata()
    fn = str(tmp_path / "fake.fits")
    psrfits.write_psrfits(fn, data, freqs, tsamp=1e-3, start_mjd=56123.5,
                          src_name="J0000+0000", ra_str="12:30:00.0",
                          dec_str="-05:15:00.0")
    assert psrfits.is_PSRFITS(fn)
    si = psrfits.SpectraInfo([fn])
    assert si.source == "J0000+0000"
    assert si.start_MJD[0] == pytest.approx(56123.5, abs=1e-9)
    assert si.num_channels == 16
    assert si.ra2000 == pytest.approx(12.5 * 15.0)
    assert si.dec2000 == pytest.approx(-(5 + 15 / 60.0))
    assert not si.need_flipband  # stored lo->hi
    assert si.summed_polns
    s = str(si)
    assert "J0000+0000" in s and "Number of channels = 16" in s


def test_dateobs_to_mjd():
    imjd, fmjd = psrfits.DATEOBS_to_MJD("2012-06-20T12:00:00")
    assert imjd == 56098
    assert fmjd == pytest.approx(0.5)


def test_nsuboffs_shifts_start_mjd(tmp_path):
    data, freqs = _mkdata(nchan=4, nspec=64)
    fn = str(tmp_path / "fake.fits")
    psrfits.write_psrfits(fn, data, freqs, tsamp=1e-3, nsamp_per_subint=64,
                          nbits=32, start_mjd=56000.0, nsuboffs=10)
    si = psrfits.SpectraInfo([fn])
    # 10 subints * 64 samples * 1 ms
    assert (si.start_MJD[0] - 56000.0) * 86400.0 == pytest.approx(0.64, abs=1e-6)


def test_rfimask_roundtrip_and_expansion(tmp_path):
    fn = str(tmp_path / "test.mask")
    per_int = [[0, 3], [], [1]]
    rfimask.write_mask(
        fn, nchan=8, nint=3, ptsperint=100,
        zap_chans=[5], zap_ints=[1], zap_chans_per_int=per_int,
        dtint=0.1, lofreq=1400.0, df=2.0,
    )
    m = rfimask.RfifindMask(fn)
    assert m.nchan == 8 and m.nint == 3 and m.ptsperint == 100
    assert list(m.mask_zap_chans) == [5]
    assert list(m.mask_zap_ints) == [1]
    assert [list(a) for a in m.mask_zap_chans_per_int] == [[0, 3], [], [1]]

    sm = m.get_sample_mask(0, 300)
    assert sm.shape == (8, 300)
    # globally zapped channel is masked in every interval
    assert sm[5].all()
    # interval 0: chans 0,3 zapped
    assert sm[0, 0] and sm[3, 50] and not sm[1, 0]
    # interval 1: fully zapped (zap_ints)
    assert sm[:, 150].all()
    # interval 2: chan 1
    assert sm[1, 250] and not sm[0, 250]
    # beyond the mask reuses the last interval
    sm2 = m.get_sample_mask(290, 30)
    assert sm2[1, -1] and not sm2[0, -1]
    # flipped orientation
    cm = m.get_chan_mask(0, 100, hifreq_first=True)
    assert cm[7, 0] and cm[4, 0]  # chans 0,3 -> rows 7,4 after flip


def test_psrfits_4bit_even_channel_packing(tmp_path):
    data = np.mod(np.arange(6 * 64).reshape(6, 64), 16).astype(np.float32)
    freqs = 1400.0 + np.arange(6) * 1.0
    fn = str(tmp_path / "fourbit.fits")
    psrfits.write_psrfits(fn, data, freqs, tsamp=1e-3, nsamp_per_subint=64,
                          nbits=4)
    with psrfits.PsrfitsFile(fn) as pf:
        spec = pf.get_spectra(0, 64)
    np.testing.assert_allclose(np.asarray(spec.data), data[::-1, :])
