"""Tests for pypulsar_tpu.astro: angles, calendar, sidereal time, transforms.

Golden values from standard references (Meeus worked examples, Duffett-Smith
section 12 example, known pulsar positions) — independent of the reference
implementation.
"""

import datetime

import numpy as np
import pytest

from pypulsar_tpu.astro import calendar, clock, coordconv, protractor, sextant
from pypulsar_tpu.astro import telescope_to_id, id_to_telescope, telescope_to_maxha


class TestProtractor:
    def test_roundtrip_deg(self):
        vals = np.array([0.0, 12.5, 180.0, 359.9])
        assert np.allclose(
            protractor.convert(protractor.convert(vals, "deg", "rad"), "rad", "deg"),
            vals,
        )

    def test_hmsstr_to_rad(self):
        # 06:00:00 hours = 90 deg = pi/2
        assert np.allclose(protractor.hmsstr_to_rad("06:00:00"), np.pi / 2)
        # negative sign
        assert np.allclose(protractor.hmsstr_to_rad("-06:00:00"), -np.pi / 2)

    def test_dmsstr_to_rad(self):
        assert np.allclose(protractor.dmsstr_to_rad("90:00:00"), np.pi / 2)
        assert np.allclose(
            protractor.dmsstr_to_rad("-45:30:00"), -45.5 * np.pi / 180.0
        )

    def test_rad_to_hmsstr_format(self):
        (s,) = protractor.rad_to_hmsstr(np.pi / 2)
        assert s == "06:00:00.0000"
        # seconds < 10 are zero-padded ("0x.xxxx")
        (s,) = protractor.rad_to_hmsstr(protractor.hmsstr_to_rad("01:02:03.5")[0])
        assert s == "01:02:03.5000"

    def test_rad_to_dmsstr_negative(self):
        (s,) = protractor.rad_to_dmsstr(-np.pi / 4)
        assert s == "-45:00:00.0000"

    def test_invalid_string_warns_nan(self):
        with pytest.warns(UserWarning):
            out = protractor.hmsstr_to_rad("garbage")
        assert np.isnan(out[0])

    def test_hms_dms_triples(self):
        assert np.allclose(protractor.hms_to_rad(6, 0, 0), np.pi / 2)
        assert np.allclose(protractor.dms_to_rad(-45, 30, 0), -45.5 * np.pi / 180)

    def test_convert_unknown_raises(self):
        with pytest.raises(ValueError):
            protractor.convert(1.0, "parsec", "rad")


class TestCalendar:
    def test_meeus_sputnik(self):
        # Meeus example 7.a: 1957 Oct 4.81 -> JD 2436116.31
        assert np.allclose(calendar.date_to_JD(1957, 10, 4.81), 2436116.31)

    def test_meeus_333(self):
        # Meeus example 7.b: 333 Jan 27.5 (Julian calendar) -> JD 1842713.0
        assert np.allclose(
            calendar.date_to_JD(333, 1, 27.5, gregorian=False), 1842713.0
        )

    def test_jd_to_date_inverse(self):
        y, m, d = calendar.JD_to_date(2436116.31)
        assert (y, m) == (1957, 10)
        assert np.allclose(d, 4.81)

    def test_mjd_roundtrip(self):
        mjd = 55000.123
        assert np.allclose(calendar.JD_to_MJD(calendar.MJD_to_JD(mjd)), mjd)

    def test_j2000_epoch(self):
        # J2000.0 = 2000 Jan 1.5 = JD 2451545.0 = MJD 51544.5
        assert np.allclose(calendar.date_to_MJD(2000, 1, 1.5), 51544.5)

    def test_leap_years(self):
        assert calendar.is_leap_year(2000)
        assert not calendar.is_leap_year(1900)
        assert calendar.is_leap_year(2004)
        assert calendar.is_leap_year(1900, gregorian=False)

    def test_day_of_year(self):
        assert calendar.day_of_year(2023, 1, 1) == 1
        assert calendar.day_of_year(2023, 12, 31) == 365
        assert calendar.day_of_year(2024, 12, 31) == 366

    def test_fraction_and_year_roundtrip(self):
        mjd = calendar.date_to_MJD(2010, 7, 2.0)
        year = calendar.MJD_to_year(mjd)
        assert 2010.0 < year < 2010.6
        assert np.allclose(calendar.year_to_MJD(year), mjd)

    def test_month_names(self):
        assert calendar.month_to_num("Feb") == 2
        assert calendar.num_to_month(2) == "February"
        with pytest.raises(ValueError):
            calendar.month_to_num("J")  # ambiguous

    def test_datetime_roundtrip(self):
        dt = datetime.datetime(2015, 6, 1, 12, 30, 15)
        mjd = calendar.datetime_to_MJD(dt)
        back = calendar.MJD_to_datetime(mjd)
        assert abs((back - dt).total_seconds()) < 1e-3

    def test_interval(self):
        assert calendar.interval_in_days(2000, 1, 1, 2000, 1, 31) == 30


class TestClock:
    def test_duffett_smith_example(self):
        # Duffett-Smith sec. 12: 1980 April 22 at 14:36:51.67 UT
        # -> GST 4h 40m 5.17s = 4.668103 h
        jd = calendar.date_to_JD(1980, 4, 22 + (14 + 36 / 60.0 + 51.67 / 3600.0) / 24.0)
        gst = clock.JD_to_GST(jd)
        assert np.allclose(gst, 4.668103, atol=2e-4)

    def test_lst_longitude(self):
        mjd = 55000.0
        gst = clock.MJD_to_GST(mjd)
        lst = clock.MJD_lon_to_LST(mjd, -75.0)  # 75 deg West = -5 h
        assert np.allclose(lst, (gst - 5.0) % 24.0)


class TestSextant:
    def test_precess_roundtrip(self):
        ra, dec = 1.2, 0.3  # rad
        ra2, dec2 = sextant.precess_B1950_to_J2000(ra, dec, input="rad", output="rad")
        ra3, dec3 = sextant.precess_J2000_to_B1950(ra2, dec2, input="rad", output="rad")
        assert np.allclose([ra3, dec3], [ra, dec], atol=1e-9)

    def test_galactic_center(self):
        # Galactic center J2000: RA 17:45:37.2, Dec -28:56:10 -> l~0, b~0
        l, b = sextant.equatorial_to_galactic(
            "17:45:37.2", "-28:56:10", input="sexigesimal", output="deg"
        )
        assert abs(float(b)) < 0.2
        assert min(float(l), 360 - float(l)) < 0.2

    def test_galactic_pole(self):
        # North galactic pole J2000: RA 12:51:26.28, Dec +27:07:41.7 -> b=90
        _l, b = sextant.equatorial_to_galactic(
            "12:51:26.28", "+27:07:41.7", input="sexigesimal", output="deg"
        )
        assert abs(float(b) - 90.0) < 0.1

    def test_ecliptic_roundtrip(self):
        ra, dec = 2.0, -0.5
        lon, lat = sextant.equatorial_to_ecliptic(ra, dec, input="rad", output="rad")
        ra2, dec2 = sextant.ecliptic_to_equatorial(lon, lat, input="rad", output="rad")
        assert np.allclose(np.mod([ra2, dec2], 2 * np.pi), np.mod([ra, dec], 2 * np.pi), atol=1e-9)

    def test_ecliptic_pole(self):
        # Ecliptic north pole: lat = +90 - obliquity at ra=18h... simpler:
        # a point on the ecliptic (the vernal equinox) has lat 0
        lon, lat = sextant.equatorial_to_ecliptic(0.0, 0.0, input="rad", output="rad")
        assert np.allclose([lon, lat], [0.0, 0.0], atol=1e-12)

    def test_angsep(self):
        assert np.allclose(sextant.angsep(0.0, 0.0, np.pi, 0.0, input="rad", output="deg"), 180.0)
        assert np.allclose(
            sextant.angsep(0.0, np.pi / 2, 1.0, np.pi / 2, input="rad", output="deg"),
            0.0,
            atol=1e-6,
        )

    def test_hadec_altaz_roundtrip(self):
        # The two functions use different azimuth conventions (from-north with
        # arccos fold vs from-south; reference parity) so they compose to
        # az -> pi - az, while altitude roundtrips exactly.
        obslat = 0.6  # rad
        alt0, az0 = 0.8, 2.1
        ha, dec = sextant.altaz_to_hadec(alt0, az0, obslat, input="rad", output="rad")
        alt, az = sextant.hadec_to_altaz(ha, dec, obslat, input="rad", output="rad")
        assert np.allclose(np.mod(alt, 2 * np.pi), alt0, atol=1e-9)
        assert np.allclose(np.mod(az, 2 * np.pi), np.pi - az0, atol=1e-9)
        # forward spherical-triangle identity holds for the inverse transform
        lhs = np.sin(alt0)
        rhs = np.sin(obslat) * np.sin(dec) + np.cos(obslat) * np.cos(dec) * np.cos(ha)
        assert np.allclose(lhs, rhs, atol=1e-12)

    def test_hadec_to_altaz_duffett_smith(self):
        # Duffett-Smith sec. 25 worked example: ha = 5h51m44s, dec = 23d13'10",
        # lat = 52N -> alt = 19d20'04", az = 283d16'16" (arccos folds to 360-az)
        ha = protractor.hmsstr_to_rad("05:51:44")[0]
        dec = protractor.dmsstr_to_rad("23:13:10")[0]
        alt, az = sextant.hadec_to_altaz(
            ha, dec, np.deg2rad(52.0), input="rad", output="deg"
        )
        assert np.allclose(alt, 19.0 + 20.0 / 60 + 4.0 / 3600, atol=1e-3)
        assert np.allclose(az, 360.0 - (283.0 + 16.0 / 60 + 16.0 / 3600), atol=1e-3)

    def test_zenith(self):
        # source at dec=obslat, ha=0 is at zenith
        obslat = 0.7
        alt, _az = sextant.hadec_to_altaz(0.0, obslat, obslat, input="rad", output="deg")
        assert np.allclose(alt, 90.0, atol=1e-8)


class TestCoordconv:
    def test_parse_decstr(self):
        assert coordconv.parse_decstr("-123456.78") == ("-", "12", "34", "56.78")
        # float stringification keeps a trailing .0 (reference parity)
        assert coordconv.parse_decstr("123456") == ("+", "12", "34", "56.0")
        assert coordconv.parse_decstr("0") == ("+", "00", "00", "00")
        assert coordconv.parse_decstr("-1234") == ("-", "00", "12", "34.0")

    def test_decstr_to_rad(self):
        assert np.allclose(
            coordconv.decstr_to_rad("900000"), np.pi / 2
        )
        assert np.allclose(coordconv.decstr_to_deg("-453000"), -45.5)

    def test_rastr(self):
        assert coordconv.parse_rastr("063015.5") == ("06", "30", "15.5")
        assert np.allclose(coordconv.rastr_to_deg("060000"), 90.0)
        assert coordconv.rastr_to_fmrastr("063015.5") == "06:30:15.5"
        assert coordconv.fmrastr_to_rastr("06:30:15.5") == "63015.5"

    def test_fm_roundtrip(self):
        assert coordconv.decstr_to_fmdecstr("-123456.78") == "-12:34:56.78"
        assert coordconv.fmdecstr_to_decstr("-12:34:56.78") == "-123456.78"

    def test_galactic_degrees(self):
        l, b = coordconv.eqdeg_to_galdeg(266.405, -28.936)  # galactic center
        assert min(abs(l), abs(360 - l)) < 0.2
        assert abs(b) < 0.2


class TestTelescopes:
    def test_tables(self):
        assert telescope_to_id["Arecibo"] == "3"
        assert id_to_telescope["1"] == "GBT"
        assert telescope_to_maxha["Arecibo"] == 3
        # every telescope with an id has a maxha
        for name in telescope_to_id:
            assert name in telescope_to_maxha
