"""Tests for the obs telemetry subsystem: span nesting/attributes,
counters/gauges/events, JSONL round-trip through tlmsum, the zero-overhead
inactive path, device snapshots on CPU-only backends, and the hot-path
instrumentation (sweep chunk records, H2D/D2H byte accounting)."""

import json

import numpy as np
import pytest

from pypulsar_tpu.obs import summarize, telemetry


def _read_jsonl(path):
    return [json.loads(line) for line in open(path) if line.strip()]


# ---------------------------------------------------------------------------
# core collector
# ---------------------------------------------------------------------------


def test_inactive_is_noop():
    from pypulsar_tpu.obs import flightrec

    assert not telemetry.is_active()
    assert telemetry.current() is None
    flightrec.configure(0)  # recorder off: the truly-zero-overhead path
    try:
        with telemetry.span("x", a=1) as sp:
            assert sp is None  # inactive: nothing collected
        telemetry.counter("c", 5)
        telemetry.gauge("g", 2.0)
        telemetry.event("e", detail="ignored")
        telemetry.record_span("x", 1.0)
    finally:
        flightrec.configure(None)  # back to the env-resolved default
    assert telemetry.device_snapshot() is None
    assert not telemetry.is_active()  # nothing leaked a session


def test_inactive_span_feeds_flight_recorder():
    """With no session but the (default-on) flight recorder enabled,
    span() yields a live handle and the record lands in the ring —
    round 21's always-on crash context."""
    from pypulsar_tpu.obs import flightrec

    assert not telemetry.is_active()
    flightrec.configure(8)
    try:
        flightrec.clear()
        with telemetry.span("ring.x", a=1) as sp:
            assert sp is not None  # ring handle, attrs attachable
            sp.set(rows=3)
        recs = flightrec.snapshot()
        spans = [r for r in recs if r.get("type") == "span"
                 and r.get("name") == "ring.x"]
        assert len(spans) == 1
        assert spans[0]["attrs"] == {"a": 1, "rows": 3}
        assert "tw" in spans[0]  # wall-stamped for cross-host alignment
    finally:
        flightrec.clear()
        flightrec.configure(None)
    assert not telemetry.is_active()


def test_span_nesting_attrs_and_jsonl(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path, tool="test") as tlm:
        assert telemetry.is_active()
        with telemetry.span("outer", kind="a"):
            with telemetry.span("inner", n=3) as sp:
                sp.set(rows=7)  # attrs attachable mid-flight
        with telemetry.span("outer"):
            pass
        assert tlm.stages["outer"][1] == 2
        assert tlm.stages["inner"][1] == 1
    assert not telemetry.is_active()
    recs = _read_jsonl(path)
    assert recs[0]["type"] == "meta" and recs[0]["tool"] == "test"
    spans = [r for r in recs if r["type"] == "span"]
    inner = next(r for r in spans if r["name"] == "inner")
    outers = [r for r in spans if r["name"] == "outer"]
    assert inner["parent"] == "outer"
    assert inner["depth"] == 1
    assert inner["attrs"] == {"n": 3, "rows": 7}
    assert len(outers) == 2
    assert all("parent" not in r for r in outers)
    # the first outer span encloses inner, so its duration dominates
    assert max(r["dur"] for r in outers) >= inner["dur"]
    assert recs[-1]["type"] == "end" and recs[-1]["wall"] > 0
    # end-of-run flushes carry the aggregates
    stages = next(r for r in recs if r["type"] == "stages")["stages"]
    assert stages["outer"][1] == 2


def test_counters_gauges_events(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path) as tlm:
        telemetry.counter("h2d.bytes", 100)
        telemetry.counter("h2d.bytes", 150)
        telemetry.counter("chunks")
        telemetry.gauge("depth", 2)
        telemetry.gauge("depth", 5)
        telemetry.gauge("depth", 3)
        telemetry.event("fallback", n=4, error="RuntimeError")
        assert tlm.counter_totals() == {"h2d.bytes": 250, "chunks": 1}
        assert tlm.gauge_values()["depth"] == {"last": 3, "max": 5}
    recs = _read_jsonl(path)
    ev = next(r for r in recs if r["type"] == "event")
    assert ev["name"] == "fallback"
    assert ev["attrs"] == {"n": 4, "error": "RuntimeError"}
    counters = next(r for r in recs if r["type"] == "counters")
    assert counters["counters"]["h2d.bytes"] == 250
    assert counters["gauges"]["depth"]["max"] == 5
    assert counters["events"]["fallback"] == 1


def test_nested_session_reuses_outer(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path) as outer:
        with telemetry.session(str(tmp_path / "ignored.jsonl")) as inner:
            assert inner is outer  # one trace per process
            telemetry.counter("c")
        assert telemetry.is_active()  # inner exit must not close outer
        assert outer.counter_totals() == {"c": 1}
    assert not telemetry.is_active()
    assert not (tmp_path / "ignored.jsonl").exists()


def test_session_from_flag_none_is_inactive():
    with telemetry.session_from_flag(None) as tlm:
        assert tlm is None
        assert not telemetry.is_active()


def test_device_snapshot_cpu_only(tmp_path):
    """Snapshots must work (not raise) on a backend with no memory_stats
    — the CPU-only guard of the issue's acceptance criteria."""
    import jax

    jax.devices()  # ensure the backend exists
    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path):
        devs = telemetry.device_snapshot(tag="probe")
    assert isinstance(devs, list) and devs
    assert devs[0]["platform"] == "cpu"
    recs = _read_jsonl(path)
    tags = [r["tag"] for r in recs if r["type"] == "device"]
    assert "probe" in tags and "session_end" in tags


def test_threaded_counters_race_free(tmp_path):
    import threading

    with telemetry.session() as tlm:
        def work():
            for _ in range(1000):
                telemetry.counter("n")

        ts = [threading.Thread(target=work) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert tlm.counter_totals()["n"] == 4000


# ---------------------------------------------------------------------------
# hot-path instrumentation
# ---------------------------------------------------------------------------


@pytest.fixture
def small_sweep_trace(tmp_path):
    """Run a tiny chunked sweep under a telemetry session; returns
    (jsonl path, counter totals, gauge values)."""
    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.parallel import sweep_spectra

    rng = np.random.RandomState(0)
    freqs = 1500.0 - 2.0 * np.arange(32)
    spec = Spectra(freqs, 1e-3, rng.randn(32, 4096).astype(np.float32))
    path = str(tmp_path / "sweep.jsonl")
    with telemetry.session(path, tool="sweep-test") as tlm:
        sweep_spectra(spec, np.linspace(0, 50, 8), nsub=8, group_size=4,
                      chunk_payload=1024)
        counters = tlm.counter_totals()
        gauges = tlm.gauge_values()
    return path, counters, gauges


def test_sweep_stream_chunk_records(small_sweep_trace):
    path, counters, gauges = small_sweep_trace
    assert counters["sweep.chunks"] == 4  # 4096 / 1024
    assert counters["sweep.payload_samples"] == 4096
    assert counters["sweep.trials_completed"] == 8
    assert counters["d2h.bytes"] > 0 and counters["d2h.pulls"] >= 1
    assert gauges["sweep.pending_depth"]["max"] >= 1
    recs = _read_jsonl(path)
    chunk_events = [r for r in recs
                    if r["type"] == "event" and r["name"] == "sweep.chunk"]
    assert len(chunk_events) == 4
    starts = [e["attrs"]["start"] for e in chunk_events]
    assert starts == [0, 1024, 2048, 3072]
    assert all(e["attrs"]["stat_len"] == 1024 for e in chunk_events)
    assert all(e["attrs"]["pending"] >= 1 for e in chunk_events)
    span_names = {r["name"] for r in recs if r["type"] == "span"}
    assert {"dispatch_sweep_chunk", "device_wait+accumulate"} <= span_names


def test_staged_sweep_step_span(tmp_path):
    """sweep_flat wraps each DDstep in a sweep_step span carrying the
    step geometry. (Spectra data is device-resident from construction,
    so no H2D is — correctly — accounted on this path; the streamed
    reader path is covered by test_ship_ahead_counts_h2d_bytes.)"""
    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.parallel.staged import sweep_flat

    rng = np.random.RandomState(1)
    freqs = 1500.0 - 4.0 * np.arange(16)
    spec = Spectra(freqs, 1e-3, rng.randn(16, 2048).astype(np.float32))
    path = str(tmp_path / "flat.jsonl")
    with telemetry.session(path) as tlm:
        sweep_flat(spec, np.linspace(0, 30, 4), nsub=8, group_size=2,
                   chunk_payload=512)
        assert tlm.counter_totals()["sweep.chunks"] == 4
    recs = _read_jsonl(path)
    steps = [r for r in recs if r["type"] == "span"
             and r["name"] == "sweep_step"]
    assert len(steps) == 1
    assert steps[0]["attrs"]["n_trials"] == 4


def test_ship_ahead_counts_h2d_bytes():
    """The streamed reader path's background host->device ship accounts
    every shipped block's bytes (the wire is the measured streamed-sweep
    ceiling — the counter is the evidence trail)."""
    from pypulsar_tpu.parallel.staged import _ship_ahead

    blocks = [(0, np.zeros((128, 64), np.uint8)),
              (128, np.zeros((128, 64), np.uint8))]
    with telemetry.session() as tlm:
        out = list(_ship_ahead(iter(blocks)))
        assert tlm.counter_totals()["h2d.bytes"] == 2 * 128 * 64
    assert [pos for pos, _ in out] == [0, 128]


def test_fold_engine_counters():
    from pypulsar_tpu.fold.engine import fold_bins

    data = np.random.RandomState(2).randn(4, 256).astype(np.float32)
    bins = (np.arange(256) % 16).astype(np.int32)
    with telemetry.session() as tlm:
        fold_bins(data, bins, 16)
        assert tlm.counter_totals()["fold.samples"] == 4 * 256
        assert "fold_bins" in tlm.stages


def test_rfifind_intervals_counter():
    from pypulsar_tpu.ops.rfifind import rfifind

    rng = np.random.RandomState(3)
    data = rng.randn(8, 2048).astype(np.float32)
    with telemetry.session() as tlm:
        rfifind(data, dt=1e-3, time=0.256)
        counters = tlm.counter_totals()
    assert counters["rfifind.intervals"] == 8  # 2048 / 256
    assert counters["d2h.bytes"] > 0


# ---------------------------------------------------------------------------
# tlmsum round-trip
# ---------------------------------------------------------------------------


def test_tlmsum_roundtrip(small_sweep_trace, capsys):
    path, counters, _ = small_sweep_trace
    from pypulsar_tpu.cli.__main__ import main as cli_main

    assert cli_main(["tlmsum", path]) == 0
    out = capsys.readouterr().out
    # per-stage wall breakdown
    assert "stage breakdown" in out
    assert "dispatch_sweep_chunk" in out and "%" in out
    # transfer byte totals and chunk counts (acceptance criteria)
    assert "d2h.bytes" in out
    assert "sweep.chunks" in out
    assert "sweep.pending_depth" in out
    assert "device snapshot" in out


def test_incremental_counter_flush(tmp_path, monkeypatch):
    """Counter totals flush incrementally (piggybacked on events) so a
    killed run's trace still answers 'where did the bytes go' even
    though close() never wrote the final counters record."""
    monkeypatch.setattr(telemetry, "COUNTER_FLUSH_INTERVAL", 0.0)
    path = str(tmp_path / "t.jsonl")
    with telemetry.session(path):
        telemetry.counter("h2d.bytes", 111)
        telemetry.event("sweep.chunk", start=0)
        telemetry.counter("h2d.bytes", 222)
        telemetry.event("sweep.chunk", start=1)
        # simulate the kill: drop everything after the incremental records
        lines_mid_run = open(path).read().splitlines()
    kept = [ln for ln in lines_mid_run]
    trunc = str(tmp_path / "killed.jsonl")
    open(trunc, "w").write("\n".join(kept) + "\n")
    partials = [json.loads(ln) for ln in kept
                if json.loads(ln)["type"] == "counters"]
    assert partials and all(p.get("partial") for p in partials)
    s = summarize.summarize(summarize.load_records(trunc))
    assert s.counters["h2d.bytes"] == 333  # last partial flush wins


def test_tlmsum_tree_dedispersion_rollup(tmp_path, capsys):
    """The round-16 tree-engine counters get their own tlmsum roll-up
    line (merge depth, shared-work adds, merge-state bytes), and the
    per-device stamps land in the per-device section — a trace without
    tree counters renders no such line."""
    path = str(tmp_path / "tree.jsonl")
    with telemetry.session(path, tool="sweep"):
        telemetry.gauge("tree.merge_levels", 10)
        telemetry.counter("tree.adds_total", 24491 * 16384)
        telemetry.counter("tree.bytes_on_device", 290_000_000)
        telemetry.counter("device0.tree.adds_total", 200_000_000)
    from pypulsar_tpu.obs.summarize import main as tlmsum_main

    assert tlmsum_main([path]) == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines() if "tree dedispersion" in ln]
    assert line, out
    assert "merge levels=10" in line[0]
    assert "shared-work adds=" in line[0]
    assert "merge-state bytes on device=" in line[0]
    dev = [ln for ln in out.splitlines() if ln.startswith("#   device 0")]
    assert dev and "tree.adds_total" in dev[0]

    plain = str(tmp_path / "plain.jsonl")
    with telemetry.session(plain, tool="sweep"):
        telemetry.counter("sweep.chunks", 1)
    assert tlmsum_main([plain]) == 0
    assert "tree dedispersion" not in capsys.readouterr().out


def test_tlmsum_autotuning_rollup(tmp_path, capsys):
    """The round-17 tune.* telemetry contract gets its own tlmsum
    roll-up: trials/hit/miss counters plus the winning config per stage
    from the tune.winner (search) and tune.applied (cache-hit) event
    attrs — and a trace without tune records renders no such section."""
    path = str(tmp_path / "tune.jsonl")
    with telemetry.session(path, tool="sweep"):
        telemetry.counter("tune.trials", 7)
        telemetry.counter("tune.cache_miss", 1)
        telemetry.counter("tune.cache_hit", 2)
        telemetry.event("tune.winner", stage="sweep",
                        config={"PYPULSAR_TPU_SWEEP_CHUNK": 131072},
                        n_trials=7, baseline_s=0.9, best_s=0.7)
        telemetry.event("tune.applied", stage="accel",
                        config={"PYPULSAR_TPU_ACCEL_BATCH": 8})
    from pypulsar_tpu.obs.summarize import main as tlmsum_main

    assert tlmsum_main([path]) == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines() if "auto-tuning" in ln]
    assert line, out
    assert "trials=7" in line[0]
    assert "cache hits=2" in line[0]
    assert "cache misses=1" in line[0]
    sweep = [ln for ln in out.splitlines() if "SWEEP_CHUNK=131072" in ln]
    assert sweep and "7 trials" in sweep[0], out
    accel = [ln for ln in out.splitlines() if "ACCEL_BATCH=8" in ln]
    assert accel, out

    plain = str(tmp_path / "plain.jsonl")
    with telemetry.session(plain, tool="sweep"):
        telemetry.counter("sweep.chunks", 1)
    assert tlmsum_main([plain]) == 0
    assert "auto-tuning" not in capsys.readouterr().out


def test_tlmsum_truncated_trace(small_sweep_trace, capsys):
    """A killed run's trace (no end-of-run flush records) still
    summarizes from the incremental span/event records."""
    path, _, _ = small_sweep_trace
    lines = open(path).read().splitlines()
    kept = [ln for ln in lines
            if json.loads(ln)["type"] not in ("counters", "stages", "end")]
    trunc = path + ".trunc"
    with open(trunc, "w") as f:
        f.write("\n".join(kept) + "\n" + '{"type": "span", "na')  # torn line
    s = summarize.summarize(summarize.load_records(trunc))
    assert s.wall > 0
    assert "dispatch_sweep_chunk" in s.stages
    assert s.events.get("sweep.chunk") == 4
    from pypulsar_tpu.obs.summarize import main as tlmsum_main

    assert tlmsum_main([trunc]) == 0
    assert "dispatch_sweep_chunk" in capsys.readouterr().out


def test_tlmsum_multi_trace_fleet_rollup(tmp_path, capsys):
    """tlmsum over several traces (paths or a quoted glob) renders one
    section per trace plus a combined fleet roll-up with summed stage
    seconds/calls, counters and events — the survey orchestrator's
    --telemetry-dir consumer. The single-file contract is unchanged (no
    section headers)."""
    import glob as _glob

    for i in range(2):
        path = str(tmp_path / f"obs{i}.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({"type": "meta", "tool": "survey-obs",
                                "obs": f"obs{i}"}) + "\n")
            f.write(json.dumps({"type": "span", "name": "survey.stage.x",
                                "t": 0.0, "dur": 1.0 + i}) + "\n")
            f.write(json.dumps({"type": "counters",
                                "counters": {"h2d.bytes": 100.0 * (i + 1),
                                             "sweep.chunks": 3.0},
                                "gauges": {"g": {"last": i, "max": i + 1}},
                                "events": {"e": 2}}) + "\n")
            f.write(json.dumps({"type": "end", "wall": 2.0}) + "\n")
    from pypulsar_tpu.obs.summarize import (
        combine_summaries,
        load_records,
        main as tlmsum_main,
    )

    paths = sorted(str(p) for p in _glob.glob(str(tmp_path / "obs*.jsonl")))
    assert tlmsum_main(paths) == 0
    out = capsys.readouterr().out
    assert out.count("# ===== trace:") == 2
    assert "# ===== fleet roll-up: 2 traces =====" in out
    # combined totals: counters summed, walls summed, stage calls summed
    combined = combine_summaries(
        [summarize.summarize(load_records(p)) for p in paths])
    assert combined.counters["h2d.bytes"] == 300.0
    assert combined.counters["sweep.chunks"] == 6.0
    assert combined.events["e"] == 4
    assert combined.wall == 4.0
    assert combined.stages["survey.stage.x"] == [3.0, 2]
    assert combined.gauges["g"]["max"] == 2
    # quoted-glob form expands (the CLI surface the survey docs show)
    assert tlmsum_main([str(tmp_path / "obs*.jsonl")]) == 0
    assert "fleet roll-up" in capsys.readouterr().out
    # single-file behavior unchanged: no section headers
    assert tlmsum_main([paths[0]]) == 0
    assert "=====" not in capsys.readouterr().out
    # one unreadable path among several: others still render, rc 1
    assert tlmsum_main([paths[0], str(tmp_path / "missing.jsonl")]) == 1
