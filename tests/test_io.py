"""File-format conformance tests (SURVEY.md §4 strategy 4): byte-level
round-trips for the SIGPROC header codec, filterbank reader/writer, .inf
and .dat/.inf pairs."""

import numpy as np
import pytest

from pypulsar_tpu.io import sigproc
from pypulsar_tpu.io.filterbank import FilterbankFile, write_filterbank
from pypulsar_tpu.io.infodata import InfoData
from pypulsar_tpu.io.datfile import Datfile, write_dat

RNG = np.random.RandomState(7)


HDR = dict(
    telescope_id=1,
    machine_id=2,
    source_name="J0000+0000",
    src_raj=123456.789,
    src_dej=-123456.789,
    tstart=59000.5,
    tsamp=64e-6,
    fch1=1500.0,
    foff=-0.5,
    nchans=64,
    nbits=32,
    nifs=1,
)


def test_sigproc_header_roundtrip(tmp_path):
    fn = tmp_path / "t.fil"
    data = RNG.randn(100, 64).astype(np.float32)
    write_filterbank(str(fn), HDR, data)
    with open(fn, "rb") as f:
        hdr, order, size = sigproc.read_header(f)
    for k, v in HDR.items():
        if isinstance(v, float):
            assert hdr[k] == pytest.approx(v)
        else:
            assert hdr[k] == v


def test_filterbank_read_write_roundtrip(tmp_path):
    fn = tmp_path / "t.fil"
    data = RNG.randn(256, 64).astype(np.float32)
    write_filterbank(str(fn), HDR, data)
    fil = FilterbankFile(str(fn))
    assert fil.nchans == 64
    assert fil.number_of_samples == 256
    assert fil.is_hifreq_first
    np.testing.assert_allclose(
        fil.frequencies, 1500.0 - 0.5 * np.arange(64), rtol=1e-12
    )
    # full read
    got = fil.get_samples(0, 256)
    np.testing.assert_array_equal(got, data)
    # random window, Spectra orientation [chan, time]
    spec = fil.get_spectra(17, 100)
    np.testing.assert_array_equal(spec.to_numpy(), data[17:117].T)
    assert spec.starttime == pytest.approx(17 * 64e-6)
    assert spec.dt == pytest.approx(64e-6)
    fil.close()


def test_filterbank_8bit(tmp_path):
    fn = tmp_path / "t8.fil"
    hdr = dict(HDR, nbits=8)
    data = RNG.randint(0, 255, size=(50, 64)).astype(np.uint8)
    write_filterbank(str(fn), hdr, data)
    fil = FilterbankFile(str(fn))
    np.testing.assert_array_equal(fil.get_samples(0, 50), data.astype(np.float32))
    fil.close()


def test_filterbank_iter_blocks(tmp_path):
    fn = tmp_path / "t.fil"
    data = RNG.randn(1000, 64).astype(np.float32)
    write_filterbank(str(fn), HDR, data)
    fil = FilterbankFile(str(fn))
    seen = []
    for start, block in fil.iter_blocks(256, overlap=32):
        assert block.shape[0] <= 256 + 32
        np.testing.assert_array_equal(block, data[start : start + block.shape[0]])
        seen.append(start)
    assert seen == [0, 256, 512, 768]
    fil.close()


def test_filterbank_out_of_range(tmp_path):
    fn = tmp_path / "t.fil"
    write_filterbank(str(fn), HDR, RNG.randn(10, 64).astype(np.float32))
    fil = FilterbankFile(str(fn))
    with pytest.raises(ValueError):
        fil.get_samples(5, 10)
    fil.close()


def test_infodata_roundtrip(tmp_path):
    inf = InfoData()
    inf.basenm = "testobs"
    inf.telescope = "Parkes"
    inf.instrument = "WAPP"
    inf.object = "J1234+5678"
    inf.RA = "12:34:56.7000"
    inf.DEC = "-56:07:08.9000"
    inf.observer = "Nobody"
    inf.epoch = 59123.456789012345
    inf.bary = 0
    inf.N = 123456
    inf.dt = 64e-6
    inf.breaks = 0
    inf.DM = 42.42
    inf.lofreq = 1182.0
    inf.BW = 320.0
    inf.numchan = 1024
    inf.chan_width = 0.3125
    inf.notes.append("    a note line")
    fn = tmp_path / "testobs.inf"
    inf.to_file(str(fn))
    back = InfoData(str(fn))
    assert back.basenm == "testobs"
    assert back.telescope == "Parkes"
    assert back.epoch == pytest.approx(59123.456789012345, abs=1e-12)
    assert back.N == 123456
    assert back.dt == pytest.approx(64e-6)
    assert back.DM == pytest.approx(42.42)
    assert back.numchan == 1024
    # labels containing '=' (e.g. "(1=yes, 0=no)") must parse to ints
    assert back.bary == 0 and isinstance(back.bary, int)
    assert back.breaks == 0 and isinstance(back.breaks, int)
    assert back.mjd_i == 59123
    assert any("a note line" in n for n in back.notes)


def _write_dat_pair(tmp_path, N=10000, dt=1e-3, epoch=59000.0):
    data = RNG.randn(N).astype(np.float32)
    inf = InfoData()
    inf.telescope = "Parkes"
    inf.instrument = "FAKE"
    inf.epoch = epoch
    inf.dt = dt
    inf.DM = 10.0
    inf.lofreq = 1400.0
    inf.BW = 256.0
    inf.numchan = 1
    inf.chan_width = 256.0
    base = str(tmp_path / "series")
    write_dat(base, data, inf)
    return base, data


def test_datfile_read(tmp_path):
    base, data = _write_dat_pair(tmp_path)
    df = Datfile(base + ".dat")
    assert df.inf.N == 10000
    np.testing.assert_array_equal(df.read_all(), data)
    df.rewind()
    np.testing.assert_array_equal(df.read_Nsamples(100), data[:100])
    np.testing.assert_array_equal(df.read_Nsamples(50), data[100:150])
    # dual clocks: desired time accumulates requests, actual integer samples
    df.rewind()
    df.read_Tseconds(0.0015)  # 1.5 samples -> reads 2, desired=0.0015
    assert df.currsample == 2
    assert df.currtime_desired == pytest.approx(0.0015)
    assert df.currtime_actual == pytest.approx(0.002)
    # next request accounts for the fraction already consumed
    df.read_Tseconds(0.0015)  # desired end 0.003 -> sample 3 -> reads 1
    assert df.currsample == 3
    df.close()


def test_datfile_pulses_generator(tmp_path):
    base, data = _write_dat_pair(tmp_path, N=1000, dt=1e-3)
    df = Datfile(base + ".dat")
    period = 0.0237  # seconds, non-integer number of samples
    pulses = list(df.pulses(lambda mjd: period))
    # ~1000*0.001/0.0237 = 42 full pulses
    assert len(pulses) == 42
    assert pulses[0].number == 1
    total = sum(len(p.profile) for p in pulses)
    assert abs(total - 42 * period / 1e-3) <= len(pulses)  # rounding only
    # profiles tile the series in order
    np.testing.assert_array_equal(
        np.concatenate([p.profile for p in pulses]), data[:total]
    )
    df.close()


def test_datfile_rejects_bad_name(tmp_path):
    with pytest.raises(ValueError):
        Datfile(str(tmp_path / "nope.txt"))


@pytest.mark.parametrize("nbits", [4, 2, 1])
def test_filterbank_subbyte_roundtrip(tmp_path, nbits):
    """4/2/1-bit .fil write -> read round-trip (VERDICT r4 item 2): values
    survive packing exactly, get_spectra orientation matches, raw
    iter_blocks yields PACKED rows of nchans*nbits//8 bytes while
    unpacked blocks equal the 8-bit expansion."""
    fn = tmp_path / f"t{nbits}.fil"
    hdr = dict(HDR, nbits=nbits)
    hi = 1 << nbits
    data = RNG.randint(0, hi, size=(200, 64)).astype(np.uint8)
    write_filterbank(str(fn), hdr, data)
    assert (fn.stat().st_size - FilterbankFile(str(fn)).header_size
            ) == 200 * 64 * nbits // 8
    fil = FilterbankFile(str(fn))
    assert fil.nbits == nbits
    assert fil.number_of_samples == 200
    np.testing.assert_array_equal(fil.get_samples(0, 200),
                                  data.astype(np.float32))
    np.testing.assert_array_equal(fil.get_spectra(13, 100).to_numpy(),
                                  data[13:113].T.astype(np.float32))
    # unpacked streaming equals the expansion; raw streaming stays packed
    for start, block in fil.iter_blocks(64, overlap=16):
        np.testing.assert_array_equal(
            block, data[start:start + block.shape[0]].astype(np.float32))
    for start, block in fil.iter_blocks(64, overlap=16, raw=True):
        assert block.dtype == np.uint8
        assert block.shape[1] == 64 * nbits // 8
    fil.close()


def test_filterbank_subbyte_rejects_ragged_channels(tmp_path):
    fn = tmp_path / "t4r.fil"
    hdr = dict(HDR, nbits=4, nchans=63)
    data = np.zeros((16, 63), np.uint8)
    with pytest.raises(ValueError, match="not divisible"):
        write_filterbank(str(fn), hdr, data)  # refuses at pack time
