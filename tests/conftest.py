"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; per SURVEY.md §4 strategy 3 we
exercise the sharded sweep on N virtual CPU devices via
--xla_force_host_platform_device_count. Must run before the first jax import.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin's sitecustomize imports jax at interpreter startup, so
# env vars alone are too late here — override through jax.config as well
# (must happen before the first backend init, which is lazy).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _lockdep_strict(monkeypatch):
    """Round 19: the whole suite (and every subprocess it spawns —
    children inherit the env) runs under PYPULSAR_TPU_LOCKDEP=strict,
    so ANY lock-acquisition-order cycle the survey/multihost/prefetch
    paths produce raises LockOrderError instead of warning. An explicit
    operator setting wins (so `PYPULSAR_TPU_LOCKDEP=off make test`
    still works); lockdep-mode tests monkeypatch their own value."""
    from pypulsar_tpu.resilience import locks

    if "PYPULSAR_TPU_LOCKDEP" not in os.environ:
        monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "strict")
    locks.reset()  # per-test: re-resolve mode, isolate the order graph
    yield


@pytest.fixture(autouse=True)
def _hermetic_tuning(tmp_path_factory, monkeypatch):
    """Round 17: the CLIs consult the persisted tuning cache by default.
    Point every test at a throwaway cache file (never the developer's
    ~/.cache winners — a tuned chunk length would silently change the
    geometry under golden tests) and start from an empty tuned overlay,
    unless the test pins the knob itself."""
    from pypulsar_tpu.tune import knobs

    # unconditional: a developer's exported PYPULSAR_TPU_TUNE_CACHE must
    # not leak their real winners into golden tests (tests that need a
    # specific cache path monkeypatch it themselves, which overrides)
    monkeypatch.setenv(
        "PYPULSAR_TPU_TUNE_CACHE",
        str(tmp_path_factory.mktemp("tune") / "tune.json"))
    knobs.clear_tuned()
    yield
    knobs.clear_tuned()


@pytest.fixture(autouse=True, scope="session")
def _hermetic_compile_cache(tmp_path_factory):
    """Round 22: plane_jit wires the persistent XLA compilation cache
    once per process (latched on first dispatch). Point it at a
    throwaway directory before any test dispatches, so the suite never
    reads — or pollutes — the developer's ~/.cache markers (a stale
    marker would flip compile.persistent_hit in exact-counter tests).
    Subprocess children inherit it, keeping them hermetic too."""
    os.environ["PYPULSAR_TPU_COMPILE_CACHE"] = \
        str(tmp_path_factory.mktemp("xla"))
    yield
