"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; per SURVEY.md §4 strategy 3 we
exercise the sharded sweep on N virtual CPU devices via
--xla_force_host_platform_device_count. Must run before the first jax import.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin's sitecustomize imports jax at interpreter startup, so
# env vars alone are too late here — override through jax.config as well
# (must happen before the first backend init, which is lazy).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
