"""Cross-path pipeline coverage: input variants the headline tests skip —
multi-file fbobs sources through the sweep, PSRFITS through the sweep CLI,
.fft inputs and zaplist masking through accelsearch."""

import os

import numpy as np

from pypulsar_tpu.io import filterbank
from pypulsar_tpu.ops import numpy_ref


def _dispersed_fil(path, freqs, data_tc, dt):
    hdr = dict(nchans=data_tc.shape[1], tsamp=dt, fch1=float(freqs[0]),
               foff=float(freqs[1] - freqs[0]), tstart=55000.0, nbits=32,
               nifs=1, source_name="PATHS")
    filterbank.write_filterbank(path, hdr, data_tc)


def test_fbobs_multifile_through_sweep(tmp_path):
    """A FilterbankObs spanning two .fil files sweeps identically to the
    same data in one file (the cross-file read path, reference
    fbobs.py:66-105, feeding the engine through the non-marker branch)."""
    from pypulsar_tpu.io.fbobs import FilterbankObs
    from pypulsar_tpu.parallel.staged import sweep_flat

    rng = np.random.RandomState(31)
    C, T, dt, dm = 32, 8192, 1e-3, 45.0
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(T, C).astype(np.float32)
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for c in range(C):
        idx = 3000 + bins[c]
        if idx < T:
            data[idx, c] += 9.0

    whole = str(tmp_path / "whole.fil")
    _dispersed_fil(whole, freqs, data, dt)
    # same data split at an arbitrary boundary; second file starts later
    a = str(tmp_path / "part1.fil")
    b = str(tmp_path / "part2.fil")
    cut = 5000
    hdr = dict(nchans=C, tsamp=dt, fch1=float(freqs[0]),
               foff=float(freqs[1] - freqs[0]), tstart=55000.0, nbits=32,
               nifs=1, source_name="PATHS")
    filterbank.write_filterbank(a, hdr, data[:cut])
    hdr2 = dict(hdr, tstart=55000.0 + cut * dt / 86400.0)
    filterbank.write_filterbank(b, hdr2, data[cut:])

    dms = np.linspace(0.0, 90.0, 16)
    ref = sweep_flat(filterbank.FilterbankFile(whole), dms, nsub=8,
                     group_size=4, chunk_payload=2048)
    obs = FilterbankObs([a, b])
    got = sweep_flat(obs, dms, nsub=8, group_size=4, chunk_payload=2048)
    rbest, gbest = ref.best(1)[0], got.best(1)[0]
    assert gbest["dm"] == rbest["dm"]
    assert gbest["sample"] == rbest["sample"]
    np.testing.assert_allclose(gbest["snr"], rbest["snr"], rtol=1e-5)


def test_psrfits_through_sweep_cli(tmp_path, monkeypatch):
    """PSRFITS input end-to-end through the sweep CLI (the is_PSRFITS
    dispatch + subint scale/offset/weight ingest path)."""
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.io.psrfits import write_psrfits

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(33)
    C, T, dt, dm = 32, 4096, 1e-3, 40.0
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(T, C) * 4.0 + 40.0
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for c in range(C):
        idx = 1500 + bins[c]
        if idx < T:
            data[idx, c] += 30.0
    write_psrfits("obs.fits", np.ascontiguousarray(data.T), freqs, dt,
                  nsamp_per_subint=256)
    rc = cli_sweep.main(["obs.fits", "-o", "pf", "--lodm", "0",
                         "--dmstep", "8", "--numdms", "12", "-s", "8",
                         "--group-size", "4", "--threshold", "7"])
    assert rc == 0
    rows = open("pf.cands").read().splitlines()[1:]
    assert rows, "no detections from the PSRFITS path"
    best = max(rows, key=lambda r: float(r.split()[1]))
    assert abs(float(best.split()[0]) - dm) <= 8.0


def test_accelsearch_fft_input_and_zaplist(tmp_path, monkeypatch):
    """accelsearch on a pre-computed .fft, with a zaplist masking a strong
    RFI tone: the tone dominates unzapped and disappears when zapped."""
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.fourier.prestofft import write_fft
    from pypulsar_tpu.io.infodata import InfoData
    from pypulsar_tpu.io.prestocand import read_rzwcands

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(37)
    N, dt = 1 << 15, 1e-3
    T = N * dt
    t = np.arange(N) * dt
    f_rfi, f_psr = 60.0, 37.7
    ts = rng.standard_normal(N).astype(np.float32)
    ts += 1.5 * np.sin(2 * np.pi * f_rfi * t).astype(np.float32)
    ts += 0.25 * np.cos(2 * np.pi * f_psr * t).astype(np.float32)
    inf = InfoData()
    inf.epoch = 55000.0
    inf.dt = dt
    inf.N = N
    inf.telescope = "Fake"
    inf.lofreq = 1400.0
    inf.BW = 100.0
    inf.numchan = 1
    inf.chan_width = 100.0
    inf.object = "ZAP"
    write_fft("zap.fft", np.fft.rfft(ts).astype(np.complex64), inf)

    rc = cli_accel.main(["zap.fft", "-z", "0", "-n", "1", "-s", "5"])
    assert rc == 0
    cands = read_rzwcands("zap_ACCEL_0.cand")
    assert abs(cands[0].r / T - f_rfi) < 1.0 / T  # RFI tone dominates

    with open("lines.zaplist", "w") as f:
        f.write("# freq width\n")
        f.write(f"{f_rfi} 1.0\n")
    rc = cli_accel.main(["zap.fft", "-z", "0", "-n", "1", "-s", "5",
                         "--zapfile", "lines.zaplist", "-o", "zapped"])
    assert rc == 0
    zcands = read_rzwcands("zapped_ACCEL_0.cand")
    assert zcands, "pulsar lost after zapping"
    assert abs(zcands[0].r / T - f_psr) < 1.0 / T  # pulsar now on top
    assert all(abs(c.r / T - f_rfi) > 0.5 for c in zcands)


def test_accelsearch_cli_batch_matches_serial(tmp_path, monkeypatch):
    """`accelsearch --batch N` (one device dispatch per stage for a group
    of same-geometry spectra) writes the same .cand files as the serial
    loop — the CLI face of accel_search_batch's parity contract."""
    from pypulsar_tpu.cli import accelsearch as cli_accel
    from pypulsar_tpu.fourier.prestofft import write_fft
    from pypulsar_tpu.io.infodata import InfoData
    from pypulsar_tpu.io.prestocand import read_rzwcands

    monkeypatch.chdir(tmp_path)
    rng = np.random.RandomState(53)
    N, dt = 1 << 14, 1e-3
    t = np.arange(N) * dt
    for i, f_psr in enumerate((23.3, 41.9, 67.1)):
        ts = rng.standard_normal(N).astype(np.float32)
        ts += 0.35 * np.cos(2 * np.pi * f_psr * t).astype(np.float32)
        inf = InfoData()
        inf.epoch = 55000.0
        inf.dt = dt
        inf.N = N
        inf.telescope = "Fake"
        inf.lofreq = 1400.0
        inf.BW = 100.0
        inf.numchan = 1
        inf.chan_width = 100.0
        inf.object = f"B{i}"
        write_fft(f"dm{i}.fft", np.fft.rfft(ts).astype(np.complex64), inf)

    files = [f"dm{i}.fft" for i in range(3)]
    assert cli_accel.main(files + ["-z", "20", "-n", "2", "-s", "3"]) == 0
    serial = [read_rzwcands(f"dm{i}_ACCEL_20.cand") for i in range(3)]
    for i in range(3):
        os.remove(f"dm{i}_ACCEL_20.cand")
    assert cli_accel.main(files + ["-z", "20", "-n", "2", "-s", "3",
                                   "--batch", "2"]) == 0
    batch = [read_rzwcands(f"dm{i}_ACCEL_20.cand") for i in range(3)]
    for s, b, f_psr in zip(serial, batch, (23.3, 41.9, 67.1)):
        assert len(s) == len(b) and len(s) >= 1
        T = N * dt
        assert abs(s[0].r / T - f_psr) < 1.0 / T
        for cs, cb in zip(s, b):
            assert abs(cs.r - cb.r) < 1e-4
            assert abs(cs.z - cb.z) < 1e-4
            assert abs(cs.sig - cb.sig) < 1e-2


def test_ascending_band_filterbank_through_sweep(tmp_path):
    """A foff>0 (low-frequency-first) filterbank sweeps identically to the
    same data stored high-first: the block sources normalize channel
    order to the plan's convention instead of silently clamping negative
    shifts."""
    from pypulsar_tpu.parallel.staged import sweep_flat

    rng = np.random.RandomState(41)
    C, T, dt, dm = 32, 6144, 1e-3, 50.0
    freqs_hi = 1500.0 - 4.0 * np.arange(C)  # descending
    data = rng.randn(T, C).astype(np.float32)  # columns follow freqs_hi
    bins = numpy_ref.bin_delays(dm, freqs_hi, dt)
    for c in range(C):
        idx = 2500 + bins[c]
        if idx < T:
            data[idx, c] += 9.0

    hi = str(tmp_path / "hi.fil")
    filterbank.write_filterbank(hi, dict(
        nchans=C, tsamp=dt, fch1=1500.0, foff=-4.0, tstart=55000.0,
        nbits=32, nifs=1, source_name="HI"), data)
    lo = str(tmp_path / "lo.fil")
    filterbank.write_filterbank(lo, dict(
        nchans=C, tsamp=dt, fch1=float(freqs_hi[-1]), foff=4.0,
        tstart=55000.0, nbits=32, nifs=1, source_name="LO"),
        data[:, ::-1])  # same samples, stored ascending

    dms = np.linspace(0.0, 100.0, 16)
    a = sweep_flat(filterbank.FilterbankFile(hi), dms, nsub=8, group_size=4)
    b = sweep_flat(filterbank.FilterbankFile(lo), dms, nsub=8, group_size=4)
    ba, bb = a.best(1)[0], b.best(1)[0]
    assert bb["dm"] == ba["dm"] and bb["sample"] == ba["sample"]
    np.testing.assert_allclose(bb["snr"], ba["snr"], rtol=1e-5)
    assert abs(ba["dm"] - dm) <= 8.0  # and it is the injected pulse
