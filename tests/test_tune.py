"""Auto-tuning subsystem (round 17): knob registry precedence, the
persisted geometry-keyed cache's durability contract, the bounded
deterministic searcher, and the science-invariance acceptance gate
(candidate/.pfd artifacts byte-identical across tuned configs of the
same engine — tuning may only move throughput knobs, never results)."""

import glob
import json
import os
import threading

import numpy as np
import pytest

from pypulsar_tpu import tune
from pypulsar_tpu.tune import cache as tcache
from pypulsar_tpu.tune import knobs
from pypulsar_tpu.tune.search import coordinate_search


@pytest.fixture
def cache(tmp_path, monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_TUNE_CACHE",
                       str(tmp_path / "tune.json"))
    return tune.TuneCache()


# ---------------------------------------------------------------------------
# knob registry: the read-path precedence contract


def _distinct_values(k):
    """(env_string, tuned_value) both distinct from the declared
    default, typed for knob ``k``."""
    if k.ktype == "int":
        base = int(k.default or 0)
        return str(base + 3), base + 7
    if k.ktype == "float":
        base = float(k.default or 0.0)
        return str(base + 3.5), base + 7.5
    return "envv", "tunedv"


def test_env_beats_tuned_beats_default_for_every_knob(monkeypatch):
    """The acceptance bullet: env var > cache (tuned) > default, pinned
    for EVERY registered knob. Non-invariant (results-affecting) knobs
    additionally REFUSE tuned values — a cache file can never flip an
    engine or a mode."""
    for k in knobs.all_knobs():
        monkeypatch.delenv(k.env, raising=False)
        knobs.clear_tuned()
        assert knobs.env_value(k.env) == k.default, k.env

        envs, tuned = _distinct_values(k)
        applied = knobs.apply_tuned({k.env: tuned})
        if k.invariant:
            assert applied == {k.env: tuned}, k.env
            assert knobs.env_value(k.env) == tuned, k.env
        else:
            assert applied == {}, k.env
            assert knobs.env_value(k.env) == k.default, k.env

        monkeypatch.setenv(k.env, envs)
        got = knobs.env_value(k.env)
        expect = k.parse(envs) if k.ktype != "str" else envs
        assert got == expect, k.env  # env wins over tuned AND default
        knobs.clear_tuned()


def test_typo_tolerant_numeric_fallthrough(monkeypatch):
    """A garbage numeric env value falls through to tuned, then to the
    default — the fleet-wide 'a bad knob must never abort' contract."""
    monkeypatch.setenv("PYPULSAR_TPU_SWEEP_CHUNK", "not-a-number")
    assert knobs.env_int("PYPULSAR_TPU_SWEEP_CHUNK") == 1 << 18
    knobs.apply_tuned({"PYPULSAR_TPU_SWEEP_CHUNK": 65536})
    assert knobs.env_int("PYPULSAR_TPU_SWEEP_CHUNK") == 65536
    knobs.clear_tuned()


def test_trial_overlay_is_thread_local_and_scoped():
    knobs.apply_tuned({"PYPULSAR_TPU_ACCEL_BATCH": 16})
    seen = {}
    with knobs.trial_overrides({"PYPULSAR_TPU_ACCEL_BATCH": 4}):
        assert knobs.env_int("PYPULSAR_TPU_ACCEL_BATCH") == 4

        def other():
            seen["other"] = knobs.env_int("PYPULSAR_TPU_ACCEL_BATCH")

        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert seen["other"] == 16  # the overlay never escapes its thread
    assert knobs.env_int("PYPULSAR_TPU_ACCEL_BATCH") == 16
    knobs.clear_tuned()


def test_unregistered_name_keeps_env_float_compat(monkeypatch):
    """health.env_float is now a re-export: unregistered names keep the
    historical (raw env, default argument) behavior."""
    from pypulsar_tpu.resilience.health import env_float

    monkeypatch.delenv("X_TUNE_COMPAT", raising=False)
    assert env_float("X_TUNE_COMPAT", 3.0) == 3.0
    monkeypatch.setenv("X_TUNE_COMPAT", "junk")
    assert env_float("X_TUNE_COMPAT", 3.0) == 3.0
    monkeypatch.setenv("X_TUNE_COMPAT", "1.5")
    assert env_float("X_TUNE_COMPAT", 3.0) == 1.5


def test_chunk_knob_resolves_pow2(monkeypatch):
    """PYPULSAR_TPU_SWEEP_CHUNK: registry default == the historical
    constant; odd values round UP to a power of two; a degenerate value
    floors at 2^12."""
    from pypulsar_tpu.parallel.sweep import (DEFAULT_CHUNK_FFT_LEN,
                                             chunk_fft_len)

    assert knobs.knob("PYPULSAR_TPU_SWEEP_CHUNK").default \
        == DEFAULT_CHUNK_FFT_LEN
    monkeypatch.delenv("PYPULSAR_TPU_SWEEP_CHUNK", raising=False)
    assert chunk_fft_len() == DEFAULT_CHUNK_FFT_LEN
    monkeypatch.setenv("PYPULSAR_TPU_SWEEP_CHUNK", "100000")
    assert chunk_fft_len() == 131072
    monkeypatch.setenv("PYPULSAR_TPU_SWEEP_CHUNK", "8")
    assert chunk_fft_len() == 1 << 12


# ---------------------------------------------------------------------------
# search-domain policy (the science-invariance contract's enforcement)


def test_fourier_engine_excludes_chunk_from_search(monkeypatch):
    """Measured (round 17): .dat bytes are chunk-length-invariant for
    gather/tree but NOT for fourier (FFT rounding is chunk-length-
    dependent, the fact staged.py fingerprints). The searcher must
    therefore never move the chunk under fourier."""
    monkeypatch.delenv("PYPULSAR_TPU_SWEEP_CHUNK", raising=False)
    gather = {k.env for k in knobs.searchable_knobs("sweep", "gather")}
    tree = {k.env for k in knobs.searchable_knobs("sweep", "tree")}
    fourier = {k.env for k in knobs.searchable_knobs("sweep", "fourier")}
    assert "PYPULSAR_TPU_SWEEP_CHUNK" in gather
    assert "PYPULSAR_TPU_SWEEP_CHUNK" in tree
    assert "PYPULSAR_TPU_SWEEP_CHUNK" not in fourier


def test_env_pinned_knob_is_never_searched(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_ACCEL_BATCH", "16")
    names = {k.env for k in knobs.searchable_knobs("accel")}
    assert "PYPULSAR_TPU_ACCEL_BATCH" not in names
    monkeypatch.delenv("PYPULSAR_TPU_ACCEL_BATCH")
    names = {k.env for k in knobs.searchable_knobs("accel")}
    assert "PYPULSAR_TPU_ACCEL_BATCH" in names


def test_results_affecting_knobs_have_no_domain():
    """Selection knobs (engine, specfuse mode, shift backend …) are
    declared non-invariant and must never carry a search domain."""
    for k in knobs.all_knobs():
        if not k.invariant:
            assert not k.domain, k.env


# ---------------------------------------------------------------------------
# bounded deterministic search


class _FakeClock:
    """Deterministic stand-in for the searcher's ``time`` module: the
    measure advances it by the table value, so trial 'walls' are exact
    regardless of machine load."""

    def __init__(self):
        self.t = 0.0

    def perf_counter(self):
        return self.t


def _table_measure(table, calls, clock=None):
    """A pure 'wall time' read from a lookup table — what the searcher
    sees, minus the noise. With ``clock`` the duration is injected
    exactly; without it the measure just records the probe."""

    def measure():
        cfg = {k.env: knobs.env_value(k.env)
               for k in knobs.all_knobs("accel")}
        key = (cfg["PYPULSAR_TPU_ACCEL_BATCH"],
               cfg["PYPULSAR_TPU_ACCEL_HBM"])
        calls.append(key)
        if clock is not None:
            clock.t += table(key)

    return measure


def test_coordinate_search_is_bounded_and_deterministic(monkeypatch):
    for env in ("PYPULSAR_TPU_ACCEL_BATCH", "PYPULSAR_TPU_ACCEL_HBM"):
        monkeypatch.delenv(env, raising=False)
    knobs.clear_tuned()

    import pypulsar_tpu.tune.search as search_mod

    def table(key):
        batch, hbm = key
        return 0.02 * abs(batch - 8) / 8 + 0.04 + \
            (0.0 if hbm == 2e9 else 0.02)

    runs = []
    for _ in range(2):
        clock = _FakeClock()
        monkeypatch.setattr(search_mod, "time", clock)
        calls = []
        res = coordinate_search(
            "accel", _table_measure(table, calls, clock), budget=10,
            repeats=1)
        assert res.n_trials <= 10
        runs.append((res.best, res.n_trials, calls))
    assert runs[0] == runs[1]  # deterministic end to end
    best = runs[0][0]
    assert best["PYPULSAR_TPU_ACCEL_BATCH"] == 8
    assert best["PYPULSAR_TPU_ACCEL_HBM"] == 2e9
    # tuned_config stores only knobs moved OFF baseline
    clock = _FakeClock()
    monkeypatch.setattr(search_mod, "time", clock)
    res = coordinate_search("accel", _table_measure(table, [], clock),
                            budget=10, repeats=1)
    assert set(res.tuned_config()) == {"PYPULSAR_TPU_ACCEL_BATCH",
                                       "PYPULSAR_TPU_ACCEL_HBM"}


def test_search_early_cutoff_abandons_regressing_direction(monkeypatch):
    """A steep regression past ``cutoff x best`` must stop that
    direction without spending the rest of its domain values."""
    import pypulsar_tpu.tune.search as search_mod

    for env in ("PYPULSAR_TPU_ACCEL_BATCH", "PYPULSAR_TPU_ACCEL_HBM"):
        monkeypatch.delenv(env, raising=False)
    knobs.clear_tuned()

    def table(key):
        batch, _ = key
        return 0.002 if batch == 32 else 0.02  # everything else awful

    calls = []
    clock = _FakeClock()
    monkeypatch.setattr(search_mod, "time", clock)
    coordinate_search("accel", _table_measure(table, calls, clock),
                      budget=50, repeats=1, cutoff=1.35)
    batches = [b for b, _ in calls]
    # direction above 32: 64 regresses 10x -> cutoff; below: 16
    # regresses -> cutoff; 8 never probed
    assert 8 not in batches


# ---------------------------------------------------------------------------
# the persisted cache: durability contract


def test_cache_roundtrip_and_key_components(cache):
    key = tune.make_key("sweep", nchan=64, nsamp=60000, dtype="nbits32",
                        engine="gather")
    cache.store(key, {"PYPULSAR_TPU_SWEEP_CHUNK": 65536},
                {"n_trials": 5})
    ent = cache.lookup(key)
    assert ent["config"]["PYPULSAR_TPU_SWEEP_CHUNK"] == 65536
    # nsamp buckets to the next pow2: nearby lengths share the entry
    assert tune.make_key("sweep", nchan=64, nsamp=65536, dtype="nbits32",
                         engine="gather") == key
    # EVERY changed key component forces a re-search (lookup misses)
    for other in (
            tune.make_key("sweep", nchan=128, nsamp=60000,
                          dtype="nbits32", engine="gather"),
            tune.make_key("sweep", nchan=64, nsamp=90000,
                          dtype="nbits32", engine="gather"),
            tune.make_key("sweep", nchan=64, nsamp=60000,
                          dtype="nbits8", engine="gather"),
            tune.make_key("sweep", nchan=64, nsamp=60000,
                          dtype="nbits32", engine="tree"),
            tune.make_key("accel", nchan=64, nsamp=60000,
                          dtype="nbits32", engine="gather"),
    ):
        assert other != key
        assert cache.lookup(other) is None


def test_cache_key_embeds_jax_and_schema_version(cache, monkeypatch):
    key = tune.make_key("sweep", nchan=64, nsamp=60000)
    cache.store(key, {"PYPULSAR_TPU_SWEEP_CHUNK": 65536})
    monkeypatch.setattr(tcache, "_jax_version", lambda: "9.9.99")
    assert tune.make_key("sweep", nchan=64, nsamp=60000) != key
    monkeypatch.undo()
    monkeypatch.setattr(tcache, "SCHEMA_VERSION", 2)
    assert tune.make_key("sweep", nchan=64, nsamp=60000) != key


@pytest.mark.parametrize("garbage", [
    "{torn", "[]", '{"schema": 99, "entries": {}}',
    '{"entries": "nope"}', ""])
def test_corrupt_cache_is_rebuilt_not_crashed(cache, garbage):
    key = tune.make_key("accel", nsamp=8192, zmax=20)
    cache.store(key, {"PYPULSAR_TPU_ACCEL_BATCH": 8})
    with open(cache.path, "w") as f:
        f.write(garbage)
    assert cache.lookup(key) is None  # miss, not crash
    cache.store(key, {"PYPULSAR_TPU_ACCEL_BATCH": 16})  # rebuilds
    assert cache.lookup(key)["config"]["PYPULSAR_TPU_ACCEL_BATCH"] == 16
    data = json.load(open(cache.path))
    assert data["schema"] == tcache.SCHEMA_VERSION


def test_concurrent_writers_do_not_clobber(cache):
    """N threads storing distinct keys: the file ends valid JSON with
    ALL entries present (read-merge-write under the lock + atomic
    replace), not last-writer-wins."""
    keys = [tune.make_key("accel", nsamp=1 << (10 + i), zmax=20)
            for i in range(8)]
    threads = [threading.Thread(
        target=cache.store, args=(k, {"PYPULSAR_TPU_ACCEL_BATCH": 8 + i}))
        for i, k in enumerate(keys)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    entries = cache.entries()
    assert set(keys) <= set(entries)
    for i, k in enumerate(keys):
        assert entries[k]["config"]["PYPULSAR_TPU_ACCEL_BATCH"] == 8 + i


def test_apply_cached_installs_hit_and_survives_broken_cache(
        cache, monkeypatch):
    knobs.clear_tuned()
    key = tune.make_key("accel", nsamp=16384, zmax=20)
    cache.store(key, {"PYPULSAR_TPU_ACCEL_BATCH": 8,
                      "PYPULSAR_TPU_SPECFUSE_MODE": "decimate"})
    applied = tune.apply_cached("accel", nsamp=16384, zmax=20)
    # the throughput knob lands; the results-affecting one is REFUSED
    assert applied == {"PYPULSAR_TPU_ACCEL_BATCH": 8}
    assert knobs.env_int("PYPULSAR_TPU_ACCEL_BATCH") == 8
    knobs.clear_tuned()
    # tuning off: no consult at all
    monkeypatch.setenv("PYPULSAR_TPU_TUNE", "off")
    assert tune.apply_cached("accel", nsamp=16384, zmax=20) == {}
    monkeypatch.delenv("PYPULSAR_TPU_TUNE")
    # unreadable cache directory: defaults, never a raise
    monkeypatch.setenv("PYPULSAR_TPU_TUNE_CACHE", "/dev/null/nope.json")
    assert tune.apply_cached("accel", nsamp=16384, zmax=20) == {}


def test_autotune_cache_hit_runs_zero_trials(cache, monkeypatch):
    """The bench's structural gate in miniature: a search populates the
    key, the second consult serves it with ZERO trials and bumps
    tune.cache_hit."""
    from pypulsar_tpu.obs import telemetry

    monkeypatch.setenv("PYPULSAR_TPU_TUNE", "search")
    knobs.clear_tuned()
    calls = []

    def table(key):
        return 0.001

    with telemetry.session() as s:
        tune.autotune("accel", nsamp=4096, zmax=20,
                      measure=_table_measure(table, calls), cache=cache,
                      budget=5)
        trials_after_search = s.counter_totals().get("tune.trials", 0)
        assert 0 < trials_after_search <= 5
        assert s.counter_totals().get("tune.cache_miss", 0) == 1
        knobs.clear_tuned()
        tune.autotune("accel", nsamp=4096, zmax=20,
                      measure=_table_measure(table, calls), cache=cache)
        assert s.counter_totals().get("tune.trials", 0) \
            == trials_after_search  # zero new trials
        assert s.counter_totals().get("tune.cache_hit", 0) == 1
    knobs.clear_tuned()


# ---------------------------------------------------------------------------
# science invariance: the acceptance gate


def _pulsar_fil(tmp_path, C=32, T=16384, dt=5e-4, dm=40.0,
                period=0.1024, amp=10.0, seed=5):
    from pypulsar_tpu.io import filterbank
    from pypulsar_tpu.ops import numpy_ref

    rng = np.random.RandomState(seed)
    freqs = 1500.0 - 4.0 * np.arange(C)
    data = rng.randn(T, C).astype(np.float32) * 2.0 + 30.0
    bins = numpy_ref.bin_delays(dm, freqs, dt)
    for t0 in np.arange(0.01, T * dt, period):
        s = int(t0 / dt)
        for c in range(C):
            idx = s + bins[c]
            if idx < T:
                data[idx, c] += amp
    fn = str(tmp_path / "psr.fil")
    hdr = dict(nchans=C, tsamp=dt, fch1=float(freqs[0]),
               foff=float(freqs[1] - freqs[0]), tstart=55000.0, nbits=32,
               nifs=1, source_name="PSR")
    filterbank.write_filterbank(fn, hdr, data)
    return fn


def _run_chain(fil, outbase, tuned_config, fold=False):
    """sweep --accel-search --write-dats under ``tuned_config``
    (installed exactly as a cache hit would), then optionally foldbatch
    the DM-40 fundamental. Returns {relpath: bytes} of every candidate
    and .pfd artifact."""
    from pypulsar_tpu.cli import foldbatch as cli_fold
    from pypulsar_tpu.cli import sweep as cli_sweep

    knobs.clear_tuned()
    knobs.apply_tuned(tuned_config)
    try:
        assert cli_sweep.main(
            [fil, "-o", outbase, "--lodm", "0", "--dmstep", "10",
             "--numdms", "8", "-s", "8", "--group-size", "4",
             "--threshold", "8", "--engine", "gather", "--write-dats",
             "--accel-search", "--accel-zmax", "20", "--accel-numharm",
             "2", "--accel-sigma", "3"]) == 0
        if fold:
            candfile = outbase + "_cands.txt"
            with open(candfile, "w") as f:
                f.write("0.1024 40.0\n")
            assert cli_fold.main(
                ["--cands", candfile, "--datbase", outbase, "-o",
                 outbase, "-n", "32", "--npart", "8"]) == 0
    finally:
        knobs.clear_tuned()
    out = {}
    for pat in ("_DM*.cand", "_DM*.txtcand", ".cands", "*.pfd"):
        for fn in sorted(glob.glob(outbase + pat)):
            out[os.path.basename(fn)[len(os.path.basename(outbase)):]] \
                = open(fn, "rb").read()
    return out


def test_science_invariant_across_tuned_configs(tmp_path, monkeypatch):
    """THE acceptance gate: two different tuned configs drawn from the
    legal search domain (chunk + batch + budgets moved) produce
    BYTE-identical candidate tables and .pfd archives for the same
    engine — tuning moves throughput only, never results."""
    monkeypatch.chdir(tmp_path)
    for env in ("PYPULSAR_TPU_SWEEP_CHUNK", "PYPULSAR_TPU_ACCEL_BATCH",
                "PYPULSAR_TPU_ACCEL_HBM"):
        monkeypatch.delenv(env, raising=False)
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    fil = _pulsar_fil(tmp_path)
    cfg_a = {"PYPULSAR_TPU_SWEEP_CHUNK": 4096,
             "PYPULSAR_TPU_ACCEL_BATCH": 4,
             "PYPULSAR_TPU_ACCEL_HBM": 2e9}
    cfg_b = {"PYPULSAR_TPU_SWEEP_CHUNK": 8192,
             "PYPULSAR_TPU_ACCEL_BATCH": 8,
             "PYPULSAR_TPU_ACCEL_HBM": 8e9}
    # same BASENAME in two directories: the .pfd header embeds the .dat
    # basename, so equal names isolate the comparison to the science
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    arts_a = _run_chain(fil, str(tmp_path / "a" / "x"), cfg_a, fold=True)
    arts_b = _run_chain(fil, str(tmp_path / "b" / "x"), cfg_b, fold=True)
    assert set(arts_a) == set(arts_b) and arts_a
    assert any(k.endswith(".cand") for k in arts_a)
    assert any(k.endswith(".pfd") for k in arts_a)
    for name in sorted(arts_a):
        assert arts_a[name] == arts_b[name], \
            f"{name} differs across tuned configs"


def test_cli_sweep_consults_cache_at_run_geometry(tmp_path, monkeypatch,
                                                  capsys):
    """The entry-point contract: a cache entry at the file's actual
    geometry is applied by the sweep CLI automatically (no flags), and
    the applied chunk shows up in the effective payload."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("PYPULSAR_TPU_SWEEP_CHUNK", raising=False)
    cache_fn = str(tmp_path / "cache.json")
    monkeypatch.setenv("PYPULSAR_TPU_TUNE_CACHE", cache_fn)
    fil = _pulsar_fil(tmp_path, T=8192)
    c = tune.TuneCache()
    key = tune.make_key("sweep", nchan=32, nsamp=8192, dtype="nbits32",
                        engine="gather")
    c.store(key, {"PYPULSAR_TPU_SWEEP_CHUNK": 4096})
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.parallel import sweep as psweep

    seen = {}
    orig = psweep.default_chunk_payload

    def spy(min_overlap, **kw):
        out = orig(min_overlap, **kw)
        if kw.get("tuned", True):  # the series/handoff (tuned) path
            seen["payload"] = out + min_overlap  # the resolved fft len
        return out

    monkeypatch.setattr(psweep, "default_chunk_payload", spy)
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    assert cli_sweep.main([fil, "-o", "t", "--lodm", "0", "--dmstep",
                           "10", "--numdms", "4", "-s", "8",
                           "--group-size", "4", "--threshold", "8",
                           "--engine", "gather", "--write-dats"]) == 0
    assert seen.get("payload") == 4096, seen
    knobs.clear_tuned()


def test_cli_sweep_online_search_mode_populates_cache(tmp_path,
                                                      monkeypatch):
    """PYPULSAR_TPU_TUNE=search: a stage's FIRST run at a new geometry
    pays the bounded trial budget and persists the winner; the second
    run at the same key is a pure cache hit with zero trials."""
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.obs import telemetry

    monkeypatch.chdir(tmp_path)
    cache_fn = str(tmp_path / "cache.json")
    monkeypatch.setenv("PYPULSAR_TPU_TUNE_CACHE", cache_fn)
    monkeypatch.setenv("PYPULSAR_TPU_TUNE", "search")
    monkeypatch.setenv("PYPULSAR_TPU_TUNE_TRIALS", "2")
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    fil = _pulsar_fil(tmp_path, T=4096)
    argv = [fil, "-o", "t", "--lodm", "0", "--dmstep", "10",
            "--numdms", "4", "-s", "8", "--group-size", "4",
            "--threshold", "8", "--engine", "gather", "--write-dats"]
    with telemetry.session() as s:
        assert cli_sweep.main(argv) == 0
        first = s.counter_totals()
        assert 0 < first.get("tune.trials", 0) <= 2
        entries = tune.TuneCache().entries()
        assert any("stage=sweep" in k for k in entries)
        knobs.clear_tuned()
        assert cli_sweep.main(argv) == 0
        second = s.counter_totals()
        assert second.get("tune.trials", 0) == first.get("tune.trials")
        assert second.get("tune.cache_hit", 0) \
            > first.get("tune.cache_hit", 0)
    knobs.clear_tuned()


def test_tune_cli_warm_then_sweep_consume_key_contract(tmp_path,
                                                       monkeypatch):
    """The warm-the-cache workflow: `tune --search --file obs.fil`
    must store keys cli/sweep's consult actually HITS (same nchan,
    nsamp bucket, dtype, engine derivation) — the round-17 drive
    caught a dtype mismatch here."""
    from pypulsar_tpu.cli import sweep as cli_sweep
    from pypulsar_tpu.cli import tune as cli_tune
    from pypulsar_tpu.obs import telemetry

    monkeypatch.chdir(tmp_path)
    cache_fn = str(tmp_path / "cache.json")
    monkeypatch.setenv("PYPULSAR_TPU_TUNE_CACHE", cache_fn)
    monkeypatch.setenv("PYPULSAR_TPU_DATS_RESIDENT_LIMIT", "0")
    fil = _pulsar_fil(tmp_path, T=4096)
    assert cli_tune.main(["--search", "--file", fil, "--stage", "sweep",
                          "--engine", "gather", "--trials", "2",
                          "--dm-count", "4", "--json"]) == 0
    knobs.clear_tuned()
    with telemetry.session() as s:
        assert cli_sweep.main(
            [fil, "-o", "t", "--lodm", "0", "--dmstep", "10",
             "--numdms", "4", "-s", "8", "--group-size", "4",
             "--threshold", "8", "--engine", "gather",
             "--write-dats"]) == 0
        assert s.counter_totals().get("tune.cache_hit", 0) >= 1, \
            "sweep consult missed the CLI-warmed entry (key drift)"
    knobs.clear_tuned()


def test_accelsearch_batch_auto_resolves_through_registry(monkeypatch,
                                                          tmp_path):
    """--batch auto takes the tuned registry default; a bad value exits
    2 at parse time; an explicit number stays untouched."""
    from pypulsar_tpu.cli import accelsearch as cli_accel

    p = cli_accel.build_parser()
    assert p.parse_args(["x.dat"]).batch == 1
    assert p.parse_args(["x.dat", "--batch", "7"]).batch == 7
    assert p.parse_args(["x.dat", "--batch", "auto"]).batch == "auto"
    with pytest.raises(SystemExit) as e:
        p.parse_args(["x.dat", "--batch", "thirty"])
    assert e.value.code == 2
    # 'auto' resolves through env > tuned > default in _apply_tuning
    args = p.parse_args([str(tmp_path / "missing.dat"), "--batch",
                         "auto"])
    knobs.apply_tuned({"PYPULSAR_TPU_ACCEL_BATCH": 16})
    try:
        cli_accel._apply_tuning(args)
        assert args.batch == 16
    finally:
        knobs.clear_tuned()


def test_accelpipe_default_batch_comes_from_registry():
    """sweep_accel_stream(batch=None) resolves the hand-pinned 32
    through the knob registry (satellite: tuned-default routing)."""
    import inspect

    from pypulsar_tpu.parallel.accelpipe import sweep_accel_stream

    sig = inspect.signature(sweep_accel_stream)
    assert sig.parameters["batch"].default is None
    assert knobs.knob("PYPULSAR_TPU_ACCEL_BATCH").default == 32
