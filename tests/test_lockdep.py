"""psrrace dynamic half: the lockdep wrappers (resilience/locks.py) and
the watchdog's defer-interrupt-while-locked contract.

Covers the round-19 acceptance surface: cycle detection across 3 locks,
reentrant-RLock no-false-positive, strict-vs-warn modes, hold-time gauge
emission into the telemetry session, the cross-thread held-set the
deferral rides on, the Condition-over-tracked-lock integration the
scheduler uses, the async-interrupt deferral regression (a stage parked
INSIDE a held lock is not shot; delivery lands after release), and the
slow-marked long-seed twin of ``bench.py --race``.
"""

import os
import threading
import time

import pytest

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.resilience import health, locks


@pytest.fixture(autouse=True)
def _clean_lockdep():
    locks.reset()
    yield
    locks.configure_race(None)
    locks.reset()


def test_cycle_detected_across_three_locks(monkeypatch):
    """A -> B -> C held orderings, then C -> A closes the 3-cycle: the
    violation names the full cycle, and under warn mode the acquire
    still succeeds (nothing strands)."""
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "warn")
    a = locks.TrackedLock("t3.A")
    b = locks.TrackedLock("t3.B")
    c = locks.TrackedLock("t3.C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:  # warn mode: recorded, not raised
            pass
    (v,) = locks.violations()
    assert v["acquiring"] == "t3.A" and v["held"] == "t3.C"
    assert v["cycle"] == ["t3.A", "t3.B", "t3.C", "t3.A"]
    # all three locks released cleanly despite the violation
    for lk in (a, b, c):
        assert lk.acquire(False)
        lk.release()


def test_strict_mode_raises_and_never_holds(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "strict")
    a = locks.TrackedLock("ts.A")
    b = locks.TrackedLock("ts.B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locks.LockOrderError) as ei:
            a.acquire()
    assert "ts.A" in str(ei.value) and "ts.B" in str(ei.value)
    # the offending lock was never left held
    assert a.acquire(False)
    a.release()
    assert len(locks.violations()) == 1


def test_rlock_reentrancy_no_false_positive(monkeypatch):
    """A reentrant re-acquire must not self-edge (no violation), and
    the held entry survives until the LAST release."""
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "strict")
    r = locks.TrackedRLock("tr.R")
    tid = threading.get_ident()
    with r:
        with r:
            assert locks.thread_holds_lock(tid)
        assert locks.thread_holds_lock(tid)
    assert not locks.thread_holds_lock(tid)
    assert locks.violations() == []


def test_off_mode_disables_tracking(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "off")
    locks.reset()  # re-resolve the cached mode under the new env
    a = locks.TrackedLock("toff.A")
    with a:
        assert not locks.thread_holds_lock(threading.get_ident())
    assert locks.snapshot() == {}


def test_hold_time_gauge_and_contention_counter(monkeypatch):
    """A non-quiet lock emits lock.<name>.hold_ms on release and a
    contended counter + wait gauge when a blocking acquire had to
    wait — the tlmsum 'lock health' roll-up's inputs."""
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "warn")
    lk = locks.TrackedLock("tg.L")
    got_it = threading.Event()

    def worker():
        with lk:
            got_it.set()
            time.sleep(0.05)

    with telemetry.session() as tlm:
        with lk:
            time.sleep(0.02)
        t = threading.Thread(target=worker)
        t.start()
        assert got_it.wait(5)  # the worker definitely holds it now
        with lk:  # contended
            pass
        t.join(timeout=5)
        gauges = tlm.gauge_values()
        counters = tlm.counter_totals()
    assert gauges["lock.tg.L.hold_ms"]["max"] >= 20.0 * 0.5
    assert counters.get("lock.tg.L.contended", 0) >= 1
    assert gauges["lock.tg.L.wait_ms"]["max"] > 0
    snap = locks.snapshot()["tg.L"]
    assert snap["acquires"] >= 3 and snap["contentions"] >= 1


def test_quiet_lock_tracks_but_never_emits(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "warn")
    lk = locks.TrackedLock("tq.L", quiet=True)
    with telemetry.session() as tlm:
        with lk:
            pass
        assert not any(k.startswith("lock.tq.L")
                       for k in tlm.gauge_values())
    assert locks.snapshot()["tq.L"]["acquires"] == 1


def test_held_set_is_cross_thread_queryable(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "warn")
    lk = locks.TrackedLock("tc.L")
    holding = threading.Event()
    release = threading.Event()
    tids = []

    def hold():
        tids.append(threading.get_ident())
        with lk:
            holding.set()
            release.wait(5)

    t = threading.Thread(target=hold)
    t.start()
    assert holding.wait(5)
    assert locks.thread_holds_lock(tids[0])
    assert not locks.thread_holds_lock(threading.get_ident())
    release.set()
    t.join(timeout=5)
    assert not locks.thread_holds_lock(tids[0])


def test_condition_over_tracked_lock(monkeypatch):
    """The scheduler's shape: one TrackedLock behind both the bare lock
    and the Condition. wait() must drop the held entry while parked
    (the watchdog may interrupt a waiter) and re-add it on wake."""
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "warn")
    mu = locks.TrackedLock("tcv.L")
    cv = locks.TrackedCondition("tcv.L", lock=mu)
    seen = {}

    def waiter():
        tid = threading.get_ident()
        with cv:
            seen["held_before"] = locks.thread_holds_lock(tid)
            cv.wait(1.0)
            seen["held_after"] = locks.thread_holds_lock(tid)
        seen["held_outside"] = locks.thread_holds_lock(tid)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.15)
    with cv:
        cv.notify_all()
    t.join(timeout=5)
    assert seen == {"held_before": True, "held_after": True,
                    "held_outside": False}
    assert locks.violations() == []


def test_interrupt_thread_defers_while_locked(monkeypatch):
    """The raw channel: interrupt_thread returns DEFERRED (truthy, not
    False) while the target holds a tracked lock, then delivers after
    release."""
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "warn")
    lk = locks.TrackedLock("ti.L")
    state = {"interrupted": False}
    holding = threading.Event()
    release = threading.Event()
    tids = []

    def victim():
        tids.append(threading.get_ident())
        try:
            with lk:
                holding.set()
                deadline = time.monotonic() + 5
                while not release.is_set() \
                        and time.monotonic() < deadline:
                    time.sleep(0.01)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                time.sleep(0.01)
        except health.StageTimeout:
            state["interrupted"] = True

    t = threading.Thread(target=victim)
    t.start()
    assert holding.wait(5)
    res = health.interrupt_thread(tids[0], health.StageStalled)
    assert res is health.DEFERRED and res  # truthy by design
    release.set()
    deadline = time.monotonic() + 5
    delivered = False
    while time.monotonic() < deadline and not delivered:
        r = health.interrupt_thread(tids[0], health.StageStalled)
        if r is not health.DEFERRED:
            delivered = bool(r)
            break
        time.sleep(0.01)
    t.join(timeout=10)
    assert delivered and state["interrupted"]
    assert lk.acquire(False), "the deferred interrupt stranded the lock"
    lk.release()


def test_watchdog_defers_interrupt_inside_held_lock(monkeypatch):
    """End-to-end regression (the round-19 satellite): a stage parked
    INSIDE a held tracked lock outruns its deadline — the watchdog must
    emit survey.interrupt_deferred (not shoot), then deliver after the
    stage releases; the verdict lands as an ordinary quarantine and the
    lock is NOT stranded."""
    from pypulsar_tpu.survey.dag import StageSpec, SurveyConfig
    from pypulsar_tpu.survey.scheduler import FleetScheduler
    from pypulsar_tpu.survey.state import Observation

    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "warn")
    stage_lock = locks.TrackedLock("twd.stage")

    def run(o, c):
        with stage_lock:
            # well past the 0.2 s deadline, in interruptible slices —
            # every tick the watchdog fires it must choose deferral
            t_end = time.monotonic() + 0.8
            while time.monotonic() < t_end:
                time.sleep(0.01)
        # unlocked runway for the retried delivery to land on
        t_end = time.monotonic() + 10.0
        while time.monotonic() < t_end:
            time.sleep(0.01)
        return 0

    def _tmp_obs(tmp_path):
        raw = os.path.join(str(tmp_path), "o0.raw")
        with open(raw, "wb") as f:
            f.write(b"x" * 64)
        return [Observation("o0", raw, os.path.join(str(tmp_path), "o0"))]

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        obs = _tmp_obs(td)
        spec = StageSpec("dev1", "stub", True, (), lambda o, c: [],
                         lambda o, c: [], run=run)
        with telemetry.session() as tlm:
            sched = FleetScheduler(obs, SurveyConfig(), stages=[spec],
                                   retries=0, stage_deadline=0.2)
            res = sched.run()
        assert "o0" in res.quarantined, res
        assert res.timeouts == 1
        deferred = tlm.event_counts.get("survey.interrupt_deferred", 0)
        assert deferred >= 1, (
            f"no deferral recorded: {tlm.event_counts}")
    assert stage_lock.acquire(False), "watchdog stranded the stage lock"
    stage_lock.release()


def test_race_pause_injection_is_seeded_and_counted(monkeypatch):
    monkeypatch.setenv("PYPULSAR_TPU_LOCKDEP", "warn")
    locks.configure_race(7, pause_us=10.0)
    lk = locks.TrackedLock("trp.L")
    for _ in range(5):
        with lk:
            pass
    n = locks.race_pauses()
    assert n >= 10  # acquire + release per pass
    locks.configure_race(None)
    with lk:
        pass
    assert locks.race_pauses() == n  # disarmed: no further pauses


@pytest.mark.slow
def test_race_harness_long_seed_twin():
    """The slow twin of `make test-race`'s quick bench leg: more seeds
    through the full bench.py --race harness (in-process)."""
    import bench

    args = bench.parse_args(["--race", "--quick", "--race-seeds", "3",
                             "--child"])
    rec = bench.run_race(args)
    assert rec["value"] == 1.0
    assert all(p["order_violations"] == 0 for p in rec["race_per_seed"])
    assert sum(p["watchdog_interrupts"]
               for p in rec["race_per_seed"]) >= 3
