#!/usr/bin/env python
"""Benchmark: DM-trials/sec of the sweep engine vs single-core NumPy.

Metric (BASELINE.md): DM-trials/sec on a 1024-channel filterbank at 64 us
sampling; one "DM trial" = dedispersing + boxcar-detecting the full segment at
one DM. ``vs_baseline`` is the speedup over a single-core NumPy implementation
doing the reference's brute-force per-channel-roll dedispersion
(reference formats/spectra.py:229-260 semantics) with the same detection step,
measured on a slice and scaled linearly (NumPy cost is linear in trials).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Usage: python bench.py [--quick] [--trials D] [--nsamp T] [--nchan C]
"""

import argparse
import json
import sys
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small shapes for smoke tests")
    ap.add_argument("--trials", type=int, default=None, help="number of DM trials")
    ap.add_argument("--nchan", type=int, default=None)
    ap.add_argument("--nsamp", type=int, default=None)
    ap.add_argument("--dm-max", type=float, default=500.0)
    ap.add_argument("--baseline-trials", type=int, default=None,
                    help="NumPy trials to actually run before extrapolating")
    args = ap.parse_args()

    if args.quick:
        C = args.nchan or 128
        T = args.nsamp or 1 << 15
        D = args.trials or 64
        nb = args.baseline_trials or 2
        nsub, group = 32, 16
        chunk = 1 << 14
    else:
        C = args.nchan or 1024
        T = args.nsamp or 1 << 21  # ~134 s at 64 us
        D = args.trials or 1024
        nb = args.baseline_trials or 4
        nsub, group = 64, 32
        chunk = 1 << 18

    import jax
    import jax.numpy as jnp
    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.parallel import make_sweep_plan, sweep_spectra
    from pypulsar_tpu.parallel.sweep import sweep_chunk

    dt = 64e-6
    dev = jax.devices()[0]
    print(f"# device: {dev}, C={C} chans, T={T} samples ({T*dt:.0f}s), "
          f"D={D} DM trials 0-{args.dm_max}", file=sys.stderr)

    freqs = (1500.0 - 300.0 / C * np.arange(C)).astype(np.float64)
    # generate the dataset directly on device: the measured quantity is the
    # sweep engine, not the axon tunnel's host->device transfer rate
    key = jax.random.PRNGKey(0)
    data = jax.random.normal(key, (C, T), dtype=jnp.float32)
    data.block_until_ready()
    dms = np.linspace(0.0, args.dm_max, D)
    spec = Spectra(freqs, dt, data)

    # --- JAX sweep: warm up compile on one chunk, then time the full run ---
    plan = make_sweep_plan(dms, freqs, dt, nsub=nsub, group_size=group)
    if plan.min_overlap >= chunk:
        chunk = int(2 ** np.ceil(np.log2(plan.min_overlap * 2)))
        print(f"# chunk raised to {chunk} (overlap {plan.min_overlap})", file=sys.stderr)

    # warmup: compile exactly the stat_len variants the timed run will hit.
    # A single block of length L takes the tail path with stat_len=min(chunk,L)
    # and is padded to the same shape as interior blocks, so warming on slices
    # of length chunk and T%chunk covers both jit cache entries.
    warm_lens = {min(T, chunk)}
    if T > chunk and T % chunk:
        warm_lens.add(T % chunk)
    for wl in warm_lens:
        warm = Spectra(freqs, dt, data[:, :wl])
        sweep_spectra(warm, dms, nsub=nsub, group_size=group, chunk_payload=chunk)

    t0 = time.perf_counter()
    res = sweep_spectra(spec, dms, nsub=nsub, group_size=group, chunk_payload=chunk)
    jax_time = time.perf_counter() - t0
    trials_per_sec = D / jax_time

    # --- NumPy single-core baseline: reference-style brute force, nb trials ---
    bl_T = min(T, 1 << 17)  # slice; scale linearly
    rng = np.random.RandomState(1)
    bl_data = rng.standard_normal((C, bl_T))  # same distribution; cost is data-independent
    t0 = time.perf_counter()
    for dm in dms[:: max(1, D // nb)][:nb]:
        bins = numpy_ref.bin_delays(dm, freqs, dt)
        ts = numpy_ref.dedispersed_timeseries(bl_data, bins)
        numpy_ref.boxcar_snr(ts, plan.widths)
    bl_time = time.perf_counter() - t0
    bl_trials_per_sec = nb / (bl_time * (T / bl_T))
    speedup = trials_per_sec / bl_trials_per_sec

    print(f"# jax: {jax_time:.3f}s for {D} trials; numpy: {bl_time:.3f}s for {nb} "
          f"trials on {bl_T/T:.3f} of data; best cand: {res.best(1)[0]}", file=sys.stderr)
    print(json.dumps({
        "metric": "dm_trials_per_sec",
        "value": round(trials_per_sec, 2),
        "unit": f"DM-trials/s ({C}-chan, {T*dt:.0f}s @ 64us, nsub={nsub})",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
