#!/usr/bin/env python
"""Benchmark: DM-trials/sec of the sweep engine vs single-core NumPy.

Metric (BASELINE.md): DM-trials/sec on a 1024-channel filterbank at 64 us
sampling; one "DM trial" = dedispersing + boxcar-detecting the full segment at
one DM. ``vs_baseline`` is the speedup over a single-core NumPy implementation
doing the reference's brute-force per-channel-roll dedispersion
(reference formats/spectra.py:229-260 semantics) with the same detection step,
measured on a slice and scaled linearly (NumPy cost is linear in trials).

Robustness contract (round-1 postmortem): this script ALWAYS prints exactly one
JSON line of the required shape and exits 0, whatever the TPU tunnel does.
Backend acquisition retries with bounded backoff; if the accelerator backend
cannot initialize, the benchmark re-execs itself on the CPU backend (reduced
shapes) so the round still records a measured number, with the fallback noted
in ``unit``.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Usage: python bench.py [--quick] [--trials D] [--nsamp T] [--nchan C]
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small shapes for smoke tests")
    ap.add_argument("--trials", type=int, default=None, help="number of DM trials")
    ap.add_argument("--nchan", type=int, default=None)
    ap.add_argument("--nsamp", type=int, default=None)
    ap.add_argument("--dm-max", type=float, default=500.0)
    ap.add_argument("--baseline-trials", type=int, default=None,
                    help="NumPy trials to actually run before extrapolating")
    ap.add_argument("--profile", action="store_true",
                    help="print a per-stage timing breakdown to stderr")
    ap.add_argument("--cpu-fallback", action="store_true",
                    help="(internal) run on the CPU backend with reduced shapes")
    ap.add_argument("--child", action="store_true",
                    help="(internal) run the measurement in this process")
    return ap.parse_args(argv)


def acquire_backend(retries=3, backoff=20.0):
    """jax.devices() with bounded retry; returns the device list or raises."""
    last = None
    for attempt in range(retries):
        try:
            import jax

            devs = jax.devices()
            # a device list can exist while the tunnel is wedged; prove
            # liveness with a tiny round-trip before committing to the run
            import jax.numpy as jnp

            val = float(jnp.ones((8, 8)).sum())
            assert val == 64.0
            return devs
        except Exception as e:  # noqa: BLE001 - any backend failure retries
            last = e
            print(f"# backend attempt {attempt + 1}/{retries} failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            if attempt + 1 < retries:
                time.sleep(backoff)
                try:
                    import jax.extend.backend

                    jax.extend.backend.clear_backends()
                except Exception:
                    pass
    raise RuntimeError(f"backend unavailable after {retries} attempts: {last}")


def run_benchmark(args):
    if args.cpu_fallback or args.quick:
        C = args.nchan or 128
        T = args.nsamp or 1 << 15
        D = args.trials or 64
        nb = args.baseline_trials or 2
        nsub, group = 32, 16
        chunk = 1 << 14
    else:
        C = args.nchan or 1024
        T = args.nsamp or 1 << 21  # ~134 s at 64 us
        D = args.trials or 1024
        nb = args.baseline_trials or 4
        nsub, group = 64, 32
        chunk = 1 << 18

    devs = acquire_backend()

    import jax
    import jax.numpy as jnp
    from pypulsar_tpu.core.spectra import Spectra
    from pypulsar_tpu.ops import numpy_ref
    from pypulsar_tpu.parallel import make_sweep_plan, sweep_spectra

    dt = 64e-6
    dev = devs[0]
    print(f"# device: {dev}, C={C} chans, T={T} samples ({T*dt:.0f}s), "
          f"D={D} DM trials 0-{args.dm_max}", file=sys.stderr)

    freqs = (1500.0 - 300.0 / C * np.arange(C)).astype(np.float64)
    # generate the dataset directly on device: the measured quantity is the
    # sweep engine, not the axon tunnel's host->device transfer rate
    key = jax.random.PRNGKey(0)
    data = jax.random.normal(key, (C, T), dtype=jnp.float32)
    data.block_until_ready()
    dms = np.linspace(0.0, args.dm_max, D)
    spec = Spectra(freqs, dt, data)

    # --- JAX sweep: warm up compile on one chunk, then time the full run ---
    plan = make_sweep_plan(dms, freqs, dt, nsub=nsub, group_size=group)
    if plan.min_overlap >= chunk:
        chunk = int(2 ** np.ceil(np.log2(plan.min_overlap * 2)))
        print(f"# chunk raised to {chunk} (overlap {plan.min_overlap})", file=sys.stderr)

    # warmup: compile exactly the stat_len variants the timed run will hit.
    # A single block of length L takes the tail path with stat_len=min(chunk,L)
    # and is padded to the same shape as interior blocks, so warming on slices
    # of length chunk and T%chunk covers both jit cache entries.
    warm_lens = {min(T, chunk)}
    if T > chunk and T % chunk:
        warm_lens.add(T % chunk)
    for wl in warm_lens:
        warm = Spectra(freqs, dt, data[:, :wl])
        sweep_spectra(warm, dms, nsub=nsub, group_size=group, chunk_payload=chunk)

    if args.profile:
        from pypulsar_tpu.utils.profiling import stage_report

        profile_ctx = stage_report(file=sys.stderr)
    else:
        import contextlib

        profile_ctx = contextlib.nullcontext()
    with profile_ctx:
        t0 = time.perf_counter()
        res = sweep_spectra(spec, dms, nsub=nsub, group_size=group,
                            chunk_payload=chunk)
        jax_time = time.perf_counter() - t0
    trials_per_sec = D / jax_time

    # --- NumPy single-core baseline: reference-style brute force, nb trials ---
    bl_T = min(T, 1 << 17)  # slice; scale linearly
    rng = np.random.RandomState(1)
    bl_data = rng.standard_normal((C, bl_T))  # same distribution; cost is data-independent
    t0 = time.perf_counter()
    for dm in dms[:: max(1, D // nb)][:nb]:
        bins = numpy_ref.bin_delays(dm, freqs, dt)
        ts = numpy_ref.dedispersed_timeseries(bl_data, bins)
        numpy_ref.boxcar_snr(ts, plan.widths)
    bl_time = time.perf_counter() - t0
    bl_trials_per_sec = nb / (bl_time * (T / bl_T))
    speedup = trials_per_sec / bl_trials_per_sec

    print(f"# jax: {jax_time:.3f}s for {D} trials; numpy: {bl_time:.3f}s for {nb} "
          f"trials on {bl_T/T:.3f} of data; best cand: {res.best(1)[0]}", file=sys.stderr)
    unit = f"DM-trials/s ({C}-chan, {T*dt:.0f}s @ 64us, nsub={nsub})"
    if args.cpu_fallback:
        unit += " [CPU FALLBACK: accelerator backend unavailable]"
    return {
        "metric": "dm_trials_per_sec",
        "value": round(trials_per_sec, 2),
        "unit": unit,
        "vs_baseline": round(speedup, 2),
    }


def run_child(args, cpu: bool, timeout: float):
    """Run the measurement in a child interpreter; return its JSON record.

    The accelerator attempt keeps the full environment; the CPU attempt pins
    ``JAX_PLATFORMS=cpu`` and strips the axon sitecustomize trigger vars so
    the child cannot touch (or hang on) the TPU tunnel at interpreter start.
    A child is the only way to bound a backend that hangs instead of raising
    — ``jax.devices()`` on a wedged tunnel blocks in native code."""
    env = dict(os.environ)
    argv = [sys.executable, os.path.abspath(__file__), "--child"]
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
        for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
            env.pop(var, None)
        argv.append("--cpu-fallback")
    for flag, val in (("--trials", args.trials), ("--nchan", args.nchan),
                      ("--nsamp", args.nsamp),
                      ("--baseline-trials", args.baseline_trials)):
        if val is not None:
            argv += [flag, str(val)]
    argv += ["--dm-max", str(args.dm_max)]
    if args.quick:
        argv.append("--quick")
    if args.profile:
        argv.append("--profile")
    proc = subprocess.run(argv, env=env, capture_output=True, text=True,
                          timeout=timeout)
    sys.stderr.write(proc.stderr[-6000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError(f"bench child produced no JSON (rc={proc.returncode})")


def main():
    args = parse_args()
    if args.child:
        # measurement mode: run in this interpreter, print JSON, propagate rc
        print(json.dumps(run_benchmark(args)))
        return
    record = None
    try:
        record = run_child(args, cpu=False, timeout=2400)
    except Exception as e:  # noqa: BLE001 - the JSON line must happen
        print(f"# benchmark failed on primary backend: {type(e).__name__}: {e}",
              file=sys.stderr)
        try:
            record = run_child(args, cpu=True, timeout=1800)
        except Exception as e2:  # noqa: BLE001
            print(f"# cpu fallback failed too: {type(e2).__name__}: {e2}",
                  file=sys.stderr)
    if record is None:
        record = {
            "metric": "dm_trials_per_sec",
            "value": 0.0,
            "unit": "DM-trials/s [FAILED: no backend produced a measurement]",
            "vs_baseline": 0.0,
        }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
